"""tpulint concurrency tier — lock graphs, guarded-by, async safety.

The verification data plane (PRs 11-19) is deeply concurrent: pipeline
accumulator Conditions, supervisor watchdog threads, governor tick vs
API-thread reads, DeferredVerdict continuations with a documented
"callbacks fire outside the lock" contract.  Those invariants used to
live only in prose; this module makes them statically enforced on top
of the engine's per-module index.

Three rules share one interprocedural ``ConcurrencyIndex``:

lock-order (error)
    Builds a lock-acquisition graph — lock objects resolved through
    ``self._lock``-style attributes (including base classes and
    attr-typed neighbours like ``self._pipeline._lock``) and
    module-level constants; acquisition edges come from nested ``with``
    scopes and from direct calls made while holding a lock (the
    callee's transitive acquisitions).  Cycles are reported as
    potential deadlocks; re-acquiring a plain (non-reentrant)
    ``threading.Lock`` already held on the same call path is a
    self-deadlock.  ``RLock``/``Condition`` are reentrant and exempt
    from the self-acquire check.

guarded-by (warning)
    Infers guarded-by sets: an attribute whose non-``__init__`` writes
    consistently happen under one class-owned lock is "guarded by" that
    lock; a lock-free read or write of it in a method reachable from a
    DIFFERENT thread/task root (spawned thread, executor submit,
    future done-callback, clock-tick callback, async handler, external
    caller) is a race finding.  Lock context propagates into private
    helpers whose every resolvable call site holds the lock, so the
    repo's ``*_locked`` convention checks out instead of flooding.

async-lock-safety (error)
    The contracts the soundness ledgers document: no blocking call
    (device dispatch, ``.result()``, file IO, ``time.sleep``) while
    holding a threading lock; no user-callback invocation (``on_*``
    hooks, callback ctor params, future ``set_result``/``set_exception``
    — done-callbacks run synchronously) inside a ``with lock:`` body;
    no threading lock acquired at all where the acquiring frame is a
    coroutine.

Known blind spots (by design — name-based, never-imported analysis):
locks passed as function arguments are untracked (the helper acquires
an unknowable lock; no false edges either); ``lock.acquire()`` /
``lock.release()`` call pairs outside ``with`` are invisible; lambda
and nested-def bodies do not inherit the lexical lock context (they
are deferred work — exactly why the swap-and-fire callback pattern
stays clean); guard inference only binds attributes to locks defined
in the same class hierarchy, so cross-object guards (the aggregator's
fields guarded by the pipeline's Condition) are documented, not
enforced.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .engine import Finding, FunctionInfo, Module, Project

# (owner, name): owner is "mod:Class" for instance locks, "mod" for
# module-level locks
LockId = Tuple[str, str]

_LOCK_KINDS = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
}
# reentrant (or not-a-mutex) kinds: self-acquire on the same path is
# legal — threading.Condition wraps an RLock by default
_REENTRANT = {"rlock", "condition", "semaphore"}

# blocking sinks while holding a lock (async-lock-safety); device
# dispatch entry points mirror rules._DEVICE_DISPATCH_FNS (kept local:
# rules.py imports this module)
_BLOCKING_ATTRS = {
    "result": "`.result()` (synchronous future wait)",
    "exception": "`.exception()` (synchronous future wait)",
    "block_until_ready": "`.block_until_ready()`",
    "read_text": "file IO (`.read_text()`)",
    "write_text": "file IO (`.write_text()`)",
    "read_bytes": "file IO (`.read_bytes()`)",
    "write_bytes": "file IO (`.write_bytes()`)",
}
_DEVICE_DISPATCH = {
    "verify_each_device",
    "verify_each_device_wire",
    "verify_batch_device",
    "verify_batch_device_wire",
    "verify_batch_device_wire_grouped",
    "aggregate_g2_sum_device",
    "load_or_export",
    "export_and_save",
}
_CLOCK_METHOD_NAMES = {"on_slot", "on_clock_slot", "on_tick_slot"}


def _is_callback_name(name: str) -> bool:
    """User-callback naming convention: `on_*` hooks and `*_cb` /
    `*_callback` / `*_hook` params.  A bare Callable annotation is NOT
    enough — time sources (`clock: Callable[[], float]`) and key
    functions are utility callables, fine to invoke under a lock."""
    return name.startswith("on_") or name.endswith(
        ("_cb", "_callback", "_hook")
    )


@dataclass
class ClassInfo:
    key: str  # "mod:Qualname"
    modname: str
    qualname: str
    node: ast.ClassDef
    base_keys: List[str] = field(default_factory=list)
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fn key
    lock_attrs: Dict[str, str] = field(default_factory=dict)  # attr -> kind
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class
    callback_attrs: Set[str] = field(default_factory=set)


@dataclass
class Access:
    owner: str  # root class key the attribute lives on
    attr: str
    is_store: bool
    node: ast.AST
    held: Tuple[LockId, ...]


@dataclass
class Acquire:
    lock: LockId
    kind: str
    node: ast.AST
    held_before: Tuple[LockId, ...]


@dataclass
class CallSite:
    callee: str  # fn key
    node: ast.AST
    held: Tuple[LockId, ...]


@dataclass
class Event:
    etype: str  # "await" | "blocking" | "callback" | "settle"
    desc: str
    node: ast.AST
    held: Tuple[LockId, ...]


@dataclass
class FnScan:
    info: FunctionInfo
    cls: Optional[ClassInfo]
    is_async: bool
    acquires: List[Acquire] = field(default_factory=list)
    calls: List[CallSite] = field(default_factory=list)
    accesses: List[Access] = field(default_factory=list)
    events: List[Event] = field(default_factory=list)
    # `*_locked`-suffix method with no resolvable caller: assumed to run
    # under an unknowable caller-held lock — excluded from inference
    assume_held_unknown: bool = False


class ConcurrencyIndex:
    """Shared lock/thread-root model, built once per Project and reused
    by all three concurrency rules (cached on the project)."""

    def __init__(self, project: Project):
        self.project = project
        self.classes: Dict[str, ClassInfo] = {}
        self.module_locks: Dict[LockId, str] = {}  # id -> kind
        self.lock_kinds: Dict[LockId, str] = {}
        self.lock_sites: Dict[LockId, Tuple[str, int]] = {}  # modname, line
        self.scans: Dict[str, FnScan] = {}  # fn key -> scan
        self.context_locks: Dict[str, FrozenSet[LockId]] = {}
        self.tags: Dict[str, FrozenSet[str]] = {}  # fn key -> root tags
        self._method_class: Dict[str, ClassInfo] = {}  # fn key -> class
        self._build()

    # -- construction -------------------------------------------------------

    def _build(self) -> None:
        for modname in sorted(self.project.modules):
            mod = self.project.modules[modname]
            self._collect_classes(mod)
            self._collect_module_locks(mod)
        for cls in self.classes.values():
            self._collect_class_details(cls)
        for modname in sorted(self.project.modules):
            mod = self.project.modules[modname]
            for qual in mod.functions:
                info = mod.functions[qual]
                self.scans[info.key] = self._scan_function(mod, info)
        self._compute_context_locks()
        self._compute_root_tags()

    def _collect_classes(self, mod: Module) -> None:
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            key = f"{mod.modname}:{node.name}"
            cls = ClassInfo(
                key=key, modname=mod.modname, qualname=node.name, node=node
            )
            for item in node.body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    fqual = f"{node.name}.{item.name}"
                    if fqual in mod.functions:
                        fkey = mod.functions[fqual].key
                        cls.methods[item.name] = fkey
                        self._method_class[fkey] = cls
            self.classes[key] = cls

    def _resolve_class_name(
        self, mod: Module, name: str
    ) -> Optional[str]:
        if f"{mod.modname}:{name}" in self.classes:
            return f"{mod.modname}:{name}"
        fi = mod.from_imports.get(name)
        if fi is not None:
            src_mod, orig = fi
            if f"{src_mod}:{orig}" in self.classes:
                return f"{src_mod}:{orig}"
        return None

    def _resolve_class_expr(
        self, mod: Module, expr: ast.AST
    ) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self._resolve_class_name(mod, expr.id)
        if isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            target_mod = mod.module_aliases.get(expr.value.id)
            if target_mod and f"{target_mod}:{expr.attr}" in self.classes:
                return f"{target_mod}:{expr.attr}"
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            # string annotation: "BlsVerificationPipeline"
            return self._resolve_class_name(
                mod, expr.value.split(".")[-1].strip()
            )
        return None

    def _lock_ctor_kind(self, mod: Module, expr: ast.AST) -> Optional[str]:
        if not isinstance(expr, ast.Call):
            return None
        fn = expr.func
        if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            if mod.module_aliases.get(fn.value.id) == "threading":
                return _LOCK_KINDS.get(fn.attr)
        if isinstance(fn, ast.Name):
            fi = mod.from_imports.get(fn.id)
            if fi is not None and fi[0] == "threading":
                return _LOCK_KINDS.get(fi[1])
        return None

    def _collect_module_locks(self, mod: Module) -> None:
        for node in mod.tree.body:
            if not isinstance(node, ast.Assign):
                continue
            kind = self._lock_ctor_kind(mod, node.value)
            if kind is None:
                continue
            for t in node.targets:
                if isinstance(t, ast.Name):
                    lid = (mod.modname, t.id)
                    self.module_locks[lid] = kind
                    self.lock_kinds[lid] = kind
                    self.lock_sites[lid] = (mod.modname, node.lineno)

    def _collect_class_details(self, cls: ClassInfo) -> None:
        mod = self.project.modules[cls.modname]
        # resolvable base classes (single-inheritance chain is what the
        # repo uses; multiple resolvable bases are all recorded)
        for b in cls.node.bases:
            bk = self._resolve_class_expr(mod, b)
            if bk:
                cls.base_keys.append(bk)
        # lock attrs, attr types and callback attrs from method bodies
        # (locks are conventionally created in __init__, but any method
        # assigning `self.X = threading.Lock()` declares one)
        init_key = cls.methods.get("__init__")
        init_info = self.project.function(init_key) if init_key else None
        param_anns: Dict[str, Optional[ast.AST]] = {}
        if init_info is not None:
            a = init_info.node.args
            for arg in a.posonlyargs + a.args + a.kwonlyargs:
                param_anns[arg.arg] = arg.annotation
        for mname, fkey in cls.methods.items():
            info = self.project.function(fkey)
            if info is None:
                continue
            for node in Project._fn_body_nodes(info):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = self._lock_ctor_kind(mod, node.value)
                    if kind is not None:
                        cls.lock_attrs[t.attr] = kind
                        lid = (cls.key, t.attr)
                        self.lock_kinds[lid] = kind
                        self.lock_sites[lid] = (cls.modname, node.lineno)
                        continue
                    if mname != "__init__":
                        continue
                    v = node.value
                    if isinstance(v, ast.Call):
                        ck = self._resolve_class_expr(mod, v.func)
                        if ck:
                            cls.attr_types[t.attr] = ck
                    elif isinstance(v, ast.Name):
                        pname = v.id
                        if pname in param_anns:
                            ann = param_anns[pname]
                            ck = (
                                self._resolve_class_expr(mod, ann)
                                if ann is not None
                                else None
                            )
                            if ck:
                                cls.attr_types[t.attr] = ck
                            elif _is_callback_name(pname):
                                cls.callback_attrs.add(t.attr)

    # -- MRO-ish helpers ----------------------------------------------------

    def mro(self, key: str) -> List[ClassInfo]:
        out: List[ClassInfo] = []
        seen: Set[str] = set()
        work = [key]
        while work:
            k = work.pop(0)
            if k in seen:
                continue
            seen.add(k)
            cls = self.classes.get(k)
            if cls is None:
                continue
            out.append(cls)
            work.extend(cls.base_keys)
        return out

    def root_class(self, key: str) -> str:
        """Base-most resolvable ancestor: a subclass and its base share
        one instance attribute namespace, so accesses group there."""
        chain = self.mro(key)
        return chain[-1].key if chain else key

    def lock_attr_of(self, class_key: str, attr: str) -> Optional[LockId]:
        for cls in self.mro(class_key):
            if attr in cls.lock_attrs:
                return (cls.key, attr)
        return None

    def attr_type_of(self, class_key: str, attr: str) -> Optional[str]:
        for cls in self.mro(class_key):
            if attr in cls.attr_types:
                return cls.attr_types[attr]
        return None

    def method_of(self, class_key: str, name: str) -> Optional[str]:
        for cls in self.mro(class_key):
            if name in cls.methods:
                return cls.methods[name]
        return None

    def callback_attr_of(self, class_key: str, attr: str) -> bool:
        for cls in self.mro(class_key):
            if attr in cls.callback_attrs:
                return True
        # hook attrs assigned from outside (node.py: `sup.on_trip = …`)
        # follow the on_* naming convention and are not methods
        return attr.startswith("on_") and self.method_of(
            class_key, attr
        ) is None

    def lock_name(self, lid: LockId) -> str:
        owner, attr = lid
        if ":" in owner:
            return f"{owner.split(':', 1)[1]}.{attr}"
        return f"{owner.rsplit('.', 1)[-1]}.{attr}"

    # -- per-function scan --------------------------------------------------

    def _scan_function(self, mod: Module, info: FunctionInfo) -> FnScan:
        cls = self._method_class.get(info.key)
        scan = FnScan(
            info=info,
            cls=cls,
            is_async=isinstance(info.node, ast.AsyncFunctionDef),
        )
        mname = info.qualname.rsplit(".", 1)[-1]
        if mname.endswith("_locked"):
            scan.assume_held_unknown = True  # cleared if callers resolve
        local_binds = Project.local_binds(info)
        # one-level local typing: `p = self._pipeline` lets later
        # `p._lock` / `p._pending` resolve through the attr-type table
        local_types: Dict[str, str] = {}
        local_callbacks: Set[str] = set()
        param_anns: Dict[str, Optional[ast.AST]] = {}
        a = info.node.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            param_anns[arg.arg] = arg.annotation
        consumed: Set[int] = set()

        def chain_parts(node: ast.AST) -> Optional[Tuple[str, List[str]]]:
            """Unfold `base.a.b…` into (base name, [a, b, …])."""
            parts: List[str] = []
            cur = node
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                consumed.add(id(cur))
                cur = cur.value
            if not isinstance(cur, ast.Name):
                return None
            parts.reverse()
            return (cur.id, parts)

        def owner_for(base: str, parts: List[str]) -> Optional[str]:
            """Class key owning parts[-1], walking attr types."""
            if base == "self" and cls is not None:
                cur: Optional[str] = cls.key
            elif base in local_types:
                cur = local_types[base]
            else:
                return None
            for attr in parts[:-1]:
                cur = self.attr_type_of(cur, attr)
                if cur is None:
                    return None
            return cur

        def resolve_lock(expr: ast.AST) -> Optional[Tuple[LockId, str]]:
            if isinstance(expr, ast.Name):
                if expr.id in local_binds:
                    return None
                lid = (mod.modname, expr.id)
                if lid in self.module_locks:
                    return (lid, self.module_locks[lid])
                fi = mod.from_imports.get(expr.id)
                if fi is not None:
                    lid = (fi[0], fi[1])
                    if lid in self.module_locks:
                        return (lid, self.module_locks[lid])
                return None
            if isinstance(expr, ast.Attribute):
                cp = chain_parts(expr)
                if cp is None:
                    return None
                base, parts = cp
                if base not in ("self",) and base not in local_types:
                    # module-attr lock: `mod_alias._METRICS_LOCK`
                    if len(parts) == 1:
                        target = mod.module_aliases.get(base)
                        if target:
                            lid = (target, parts[0])
                            if lid in self.module_locks:
                                return (lid, self.module_locks[lid])
                    return None
                owner = owner_for(base, parts)
                if owner is None:
                    return None
                lid = self.lock_attr_of(owner, parts[-1])
                if lid is not None:
                    return (lid, self.lock_kinds[lid])
            return None

        def resolve_call(node: ast.Call) -> Optional[str]:
            fn = node.func
            if isinstance(fn, ast.Attribute):
                cp = chain_parts(fn)
                if cp is not None:
                    base, parts = cp
                    owner = owner_for(base, parts)
                    if owner is not None:
                        return self.method_of(owner, parts[-1])
            return self.project.resolve_callee(mod, info, fn)

        def classify_call(node: ast.Call, held) -> None:
            fn = node.func
            # future settlement: done-callbacks run synchronously on
            # the settling thread, i.e. under any lock currently held
            if isinstance(fn, ast.Attribute) and fn.attr in (
                "set_result",
                "set_exception",
            ):
                scan.events.append(
                    Event(
                        "settle",
                        f"`.{fn.attr}()` settles a future",
                        node,
                        held,
                    )
                )
                return
            if isinstance(fn, ast.Attribute):
                if fn.attr in _BLOCKING_ATTRS:
                    scan.events.append(
                        Event(
                            "blocking", _BLOCKING_ATTRS[fn.attr], node, held
                        )
                    )
                    return
                if fn.attr == "sleep" and isinstance(fn.value, ast.Name):
                    if mod.module_aliases.get(fn.value.id) == "time":
                        scan.events.append(
                            Event("blocking", "`time.sleep()`", node, held)
                        )
                        return
                if fn.attr in _DEVICE_DISPATCH:
                    scan.events.append(
                        Event(
                            "blocking",
                            f"device dispatch `{fn.attr}()`",
                            node,
                            held,
                        )
                    )
                    return
                # user-callback hooks: `self.on_drop(…)` where on_drop
                # is a callback attr / non-method on_* hook
                cp = chain_parts(fn)
                if cp is not None:
                    base, parts = cp
                    owner = owner_for(base, parts)
                    if owner is not None and self.callback_attr_of(
                        owner, parts[-1]
                    ):
                        scan.events.append(
                            Event(
                                "callback",
                                f"user callback `{parts[-1]}`",
                                node,
                                held,
                            )
                        )
                        return
            if isinstance(fn, ast.Name):
                name = fn.id
                if name in _DEVICE_DISPATCH and name not in local_binds:
                    scan.events.append(
                        Event(
                            "blocking",
                            f"device dispatch `{name}()`",
                            node,
                            held,
                        )
                    )
                    return
                if name == "open" and name not in local_binds:
                    scan.events.append(
                        Event("blocking", "file IO (`open()`)", node, held)
                    )
                    return
                if name in local_callbacks or (
                    name in param_anns and _is_callback_name(name)
                ):
                    scan.events.append(
                        Event(
                            "callback", f"user callback `{name}`", node, held
                        )
                    )

        def record_chain(
            base: str,
            parts: List[str],
            node: ast.AST,
            held,
            final_store: bool,
        ) -> None:
            """Record an access per resolvable chain level; only the
            outermost attribute can be a store."""
            if base == "self" and cls is not None:
                cur: Optional[str] = cls.key
            elif base in local_types:
                cur = local_types[base]
            else:
                return
            for i, attr in enumerate(parts):
                if cur is None:
                    break
                scan.accesses.append(
                    Access(
                        owner=self.root_class(cur),
                        attr=attr,
                        is_store=final_store and i == len(parts) - 1,
                        node=node,
                        held=held,
                    )
                )
                cur = self.attr_type_of(cur, attr)

        def record_accesses(node: ast.AST, held) -> None:
            if id(node) in consumed or not isinstance(node, ast.Attribute):
                return
            cp = chain_parts(node)
            if cp is None:
                return
            base, parts = cp
            record_chain(
                base,
                parts,
                node,
                held,
                isinstance(node.ctx, (ast.Store, ast.Del)),
            )

        def visit(node: ast.AST, held: Tuple[LockId, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested defs are deferred work: no lexical lock context
                for d in node.decorator_list:
                    visit(d, held)
                return
            if isinstance(node, ast.Lambda):
                return  # lambda bodies run later, outside the lock
            if isinstance(node, ast.With):
                new_held = held
                for item in node.items:
                    visit(item.context_expr, new_held)
                    rl = resolve_lock(item.context_expr)
                    if rl is not None:
                        lid, kind = rl
                        scan.acquires.append(
                            Acquire(lid, kind, item.context_expr, new_held)
                        )
                        new_held = new_held + (lid,)
                    if item.optional_vars is not None:
                        visit(item.optional_vars, new_held)
                for s in node.body:
                    visit(s, new_held)
                return
            if isinstance(node, ast.Await):
                scan.events.append(Event("await", "`await`", node, held))
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t, v = node.targets[0], node.value
                if isinstance(t, ast.Name):
                    cp = (
                        chain_parts(v)
                        if isinstance(v, ast.Attribute)
                        else None
                    )
                    if cp is not None:
                        base, parts = cp
                        owner = owner_for(base, parts)
                        if owner is not None:
                            ck = self.attr_type_of(owner, parts[-1])
                            if ck is not None:
                                local_types[t.id] = ck
                            elif self.callback_attr_of(owner, parts[-1]):
                                local_callbacks.add(t.id)
            if isinstance(node, ast.Call):
                callee = resolve_call(node)
                if callee is not None:
                    scan.calls.append(CallSite(callee, node, held))
                classify_call(node, held)
                # the receiver of a method call is an access too
                # (`self._items.popleft()` reads — and mutates — _items)
                if isinstance(node.func, ast.Attribute):
                    cp = chain_parts(node.func)
                    if cp is not None:
                        record_chain(
                            cp[0], cp[1][:-1], node.func, held, False
                        )
            record_accesses(node, held)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in info.node.body:
            visit(stmt, ())
        return scan

    # -- context locks (call-site lock propagation) -------------------------

    def _compute_context_locks(self) -> None:
        """A PRIVATE method whose every resolvable call site holds lock
        L runs under L (the `_flush_bucket_locked` convention).  Public
        methods never inherit context — external callers are unknown."""
        context: Dict[str, FrozenSet[LockId]] = {}
        for _round in range(3):
            incoming: Dict[str, List[FrozenSet[LockId]]] = {}
            for key, scan in self.scans.items():
                eff = frozenset(context.get(key, frozenset()))
                for cs in scan.calls:
                    held = frozenset(cs.held) | eff
                    incoming.setdefault(cs.callee, []).append(held)
            new_context: Dict[str, FrozenSet[LockId]] = {}
            for key, scan in self.scans.items():
                mname = scan.info.qualname.rsplit(".", 1)[-1]
                if not mname.startswith("_") or mname.startswith("__"):
                    continue
                sites = incoming.get(key)
                if not sites:
                    continue
                inter = frozenset.intersection(*sites)
                if inter:
                    new_context[key] = inter
            if new_context == context:
                break
            context = new_context
        self.context_locks = context
        for key, scan in self.scans.items():
            if key in context:
                scan.assume_held_unknown = False

    def effective_held(self, scan: FnScan, held) -> FrozenSet[LockId]:
        return frozenset(held) | self.context_locks.get(
            scan.info.key, frozenset()
        )

    # -- thread/task-root classification ------------------------------------

    def _fn_ref_key(
        self, mod: Module, scope: Optional[FunctionInfo], expr: ast.AST
    ) -> Optional[str]:
        """Resolve a function REFERENCE (Thread target, submit arg,
        done-callback) to a FunctionInfo key, through attr-typed
        chains (`self.chain.governor.on_slot`)."""
        if isinstance(expr, ast.Attribute):
            parts: List[str] = []
            cur: ast.AST = expr
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            parts.reverse()
            if isinstance(cur, ast.Name) and cur.id == "self":
                cls = (
                    self._method_class.get(scope.key)
                    if scope is not None
                    else None
                )
                if cls is None:
                    return None
                owner: Optional[str] = cls.key
                for attr in parts[:-1]:
                    owner = self.attr_type_of(owner, attr)
                    if owner is None:
                        return None
                return self.method_of(owner, parts[-1])
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return self.project.resolve_callee(mod, scope, expr) or (
                self.project.resolve_name(mod, scope, expr.id)
                if isinstance(expr, ast.Name)
                else None
            )
        return None

    def _compute_root_tags(self) -> None:
        entries: Dict[str, Set[str]] = {}

        def add(key: Optional[str], tag: str) -> None:
            if key is not None and key in self.scans:
                entries.setdefault(key, set()).add(tag)

        for modname in sorted(self.project.modules):
            mod = self.project.modules[modname]
            short = modname.rsplit(".", 1)[-1]
            for scope, node, _prefix in self.project._walk_scoped(mod):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                callee = (
                    fn.attr
                    if isinstance(fn, ast.Attribute)
                    else fn.id
                    if isinstance(fn, ast.Name)
                    else None
                )
                if callee in ("Thread", "Timer"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            add(
                                self._fn_ref_key(mod, scope, kw.value),
                                f"thread:{short}:{node.lineno}",
                            )
                elif callee == "submit" and node.args:
                    add(
                        self._fn_ref_key(mod, scope, node.args[0]),
                        "executor",
                    )
                elif callee == "add_done_callback" and node.args:
                    add(
                        self._fn_ref_key(mod, scope, node.args[0]),
                        "future-callback",
                    )
        for key, scan in self.scans.items():
            mname = scan.info.qualname.rsplit(".", 1)[-1]
            if scan.is_async:
                entries.setdefault(key, set()).add("async")
            if mname in _CLOCK_METHOD_NAMES:
                entries.setdefault(key, set()).add("clock")
            # externally callable surface: public functions/methods and
            # container dunders — the caller's own thread is a root
            if not mname.startswith("_") or (
                mname.startswith("__")
                and mname.endswith("__")
                and mname not in ("__init__", "__del__", "__new__")
            ):
                entries.setdefault(key, set()).add("external")
        tags: Dict[str, Set[str]] = {
            k: set(v) for k, v in entries.items()
        }
        work = list(entries)
        while work:
            key = work.pop()
            scan = self.scans.get(key)
            if scan is None:
                continue
            src = tags.get(key, set())
            for cs in scan.calls:
                dst = tags.setdefault(cs.callee, set())
                if not src <= dst:
                    dst |= src
                    work.append(cs.callee)
        self.tags = {k: frozenset(v) for k, v in tags.items()}

    # -- shared lookup ------------------------------------------------------

    def module_of(self, scan: FnScan) -> Module:
        return self.project.modules[scan.info.modname]

    def ordered_scans(self) -> List[FnScan]:
        return [self.scans[k] for k in self.scans]


def get_index(project: Project) -> ConcurrencyIndex:
    idx = getattr(project, "_concurrency_index", None)
    if idx is None:
        idx = ConcurrencyIndex(project)
        project._concurrency_index = idx
    return idx


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


class _ConcurrencyRule:
    name = "concurrency"
    severity = "error"

    def finding(
        self, mod: Module, node: ast.AST, message: str, severity=None
    ) -> Finding:
        return Finding(
            rule=self.name,
            path=mod.display_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            severity=severity or self.severity,
            message=message,
        )


class LockOrderRule(_ConcurrencyRule):
    name = "lock-order"
    severity = "error"

    def run(self, project: Project) -> Iterable[Finding]:
        idx = get_index(project)
        out: List[Finding] = []
        # transitive acquisitions per function (fixpoint over the call
        # graph): "calling f while holding L" orders L before everything
        # f can acquire
        closure: Dict[str, Set[LockId]] = {
            k: {a.lock for a in s.acquires} for k, s in idx.scans.items()
        }
        callers: Dict[str, Set[str]] = {}
        for key, scan in idx.scans.items():
            for cs in scan.calls:
                callers.setdefault(cs.callee, set()).add(key)
        work = [k for k, locks in closure.items() if locks]
        while work:
            key = work.pop()
            locks = closure.get(key)
            if not locks:
                continue
            for caller in callers.get(key, ()):
                cur = closure[caller]
                if not locks <= cur:
                    cur |= locks
                    work.append(caller)
        # edges + self-deadlocks
        edges: Dict[Tuple[LockId, LockId], Tuple[FnScan, ast.AST, str]] = {}
        self_dead: Dict[Tuple[str, LockId], Tuple[FnScan, ast.AST, str]] = {}
        for key, scan in idx.scans.items():
            ctx = idx.context_locks.get(key, frozenset())
            for a in scan.acquires:
                eff = frozenset(a.held_before) | ctx
                for h in eff:
                    if h == a.lock:
                        if idx.lock_kinds.get(a.lock) == "lock":
                            self_dead.setdefault(
                                (key, a.lock), (scan, a.node, "directly")
                            )
                    else:
                        edges.setdefault(
                            (h, a.lock), (scan, a.node, "")
                        )
            for cs in scan.calls:
                eff = frozenset(cs.held) | ctx
                if not eff:
                    continue
                callee_scan = idx.scans.get(cs.callee)
                via = (
                    f"via call to `{callee_scan.info.qualname}`"
                    if callee_scan
                    else "via call"
                )
                for lock in closure.get(cs.callee, ()):
                    for h in eff:
                        if h == lock:
                            if idx.lock_kinds.get(lock) == "lock":
                                self_dead.setdefault(
                                    (key, lock), (scan, cs.node, via)
                                )
                        else:
                            edges.setdefault((h, lock), (scan, cs.node, via))
        for (key, lock), (scan, node, via) in sorted(
            self_dead.items(), key=lambda kv: kv[0]
        ):
            mod = idx.module_of(scan)
            out.append(
                self.finding(
                    mod,
                    node,
                    f"self-deadlock: non-reentrant `{idx.lock_name(lock)}` "
                    f"re-acquired {via} while already held in "
                    f"`{scan.info.qualname}` — a plain threading.Lock "
                    f"blocks its own thread; use an RLock or restructure",
                )
            )
        # 2-cycles: both orders observed for a pair of locks
        reported_pairs: Set[FrozenSet[LockId]] = set()
        for (a, b), (scan, node, via) in sorted(
            edges.items(),
            key=lambda kv: (idx.lock_name(kv[0][0]), idx.lock_name(kv[0][1])),
        ):
            if (b, a) not in edges:
                continue
            pair = frozenset((a, b))
            if pair in reported_pairs:
                continue
            reported_pairs.add(pair)
            o_scan, o_node, o_via = edges[(b, a)]
            o_mod = idx.module_of(o_scan)
            mod = idx.module_of(scan)
            via_s = f" {via}" if via else ""
            o_via_s = f" {o_via}" if o_via else ""
            out.append(
                self.finding(
                    mod,
                    node,
                    f"lock-order inversion: `{idx.lock_name(a)}` is held "
                    f"while acquiring `{idx.lock_name(b)}`{via_s} in "
                    f"`{scan.info.qualname}`, but "
                    f"`{o_scan.info.qualname}` "
                    f"({o_mod.display_path}:{getattr(o_node, 'lineno', 1)}) "
                    f"acquires them in the opposite order{o_via_s} — "
                    f"concurrent callers can deadlock; pick one order",
                )
            )
        # longer cycles (no 2-cycle inside): SCCs of the remaining graph
        out.extend(self._scc_findings(idx, edges, reported_pairs))
        return out

    def _scc_findings(self, idx, edges, reported_pairs) -> List[Finding]:
        graph: Dict[LockId, Set[LockId]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        index_of: Dict[LockId, int] = {}
        low: Dict[LockId, int] = {}
        on_stack: Set[LockId] = set()
        stack: List[LockId] = []
        sccs: List[List[LockId]] = []
        counter = [0]

        def strongconnect(v: LockId) -> None:
            work = [(v, iter(sorted(graph.get(v, ()))))]
            index_of[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on_stack.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index_of:
                        index_of[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on_stack.add(w)
                        work.append((w, iter(sorted(graph.get(w, ())))))
                        advanced = True
                        break
                    elif w in on_stack:
                        low[node] = min(low[node], index_of[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index_of[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(comp)

        for v in sorted(graph, key=idx.lock_name):
            if v not in index_of:
                strongconnect(v)
        out: List[Finding] = []
        for comp in sccs:
            comp_set = set(comp)
            if any(
                frozenset((a, b)) in reported_pairs
                for a in comp
                for b in comp
                if a != b
            ):
                continue  # already reported as an inversion pair
            names = sorted(idx.lock_name(l) for l in comp)
            site = min(
                (
                    (scan, node)
                    for (a, b), (scan, node, _via) in edges.items()
                    if a in comp_set and b in comp_set
                ),
                key=lambda sn: (
                    idx.module_of(sn[0]).display_path,
                    getattr(sn[1], "lineno", 1),
                ),
            )
            scan, node = site
            out.append(
                self.finding(
                    idx.module_of(scan),
                    node,
                    "lock-order cycle across "
                    + ", ".join(f"`{n}`" for n in names)
                    + " — the acquisition graph is cyclic; impose a "
                    "global order",
                )
            )
        return out


class GuardedByRule(_ConcurrencyRule):
    name = "guarded-by"
    severity = "warning"

    def run(self, project: Project) -> Iterable[Finding]:
        idx = get_index(project)
        out: List[Finding] = []
        # class family: every class sharing a root shares the instance
        # attribute namespace (subclass methods touch base attrs)
        family_locks: Dict[str, Dict[str, LockId]] = {}
        for ckey, cls in idx.classes.items():
            root = idx.root_class(ckey)
            fam = family_locks.setdefault(root, {})
            for attr in cls.lock_attrs:
                fam.setdefault(attr, (ckey, attr))
        groups: Dict[Tuple[str, str], List[Tuple[FnScan, Access]]] = {}
        for key, scan in idx.scans.items():
            mname = scan.info.qualname.rsplit(".", 1)[-1]
            if mname in ("__init__", "__del__"):
                continue
            for acc in scan.accesses:
                if acc.attr in family_locks.get(acc.owner, {}):
                    continue  # the lock attr itself
                groups.setdefault((acc.owner, acc.attr), []).append(
                    (scan, acc)
                )
        for (owner, attr) in sorted(groups):
            fam = family_locks.get(owner)
            if not fam:
                continue  # no lock anywhere in this hierarchy
            cand = set(fam.values())
            accesses = groups[(owner, attr)]
            stores = [
                (s, a)
                for (s, a) in accesses
                if a.is_store and not s.assume_held_unknown
            ]
            locked_holds = [
                idx.effective_held(s, a.held) & cand
                for (s, a) in stores
                if idx.effective_held(s, a.held) & cand
            ]
            if not locked_holds:
                continue  # never written under a class lock
            guard_set = frozenset.intersection(*locked_holds)
            if not guard_set:
                continue  # inconsistent locks; no single guard inferred
            lock = sorted(
                guard_set, key=lambda l: (l[1] != "_lock", l)
            )[0]
            writers = [
                (s, a)
                for (s, a) in stores
                if lock in idx.effective_held(s, a.held)
            ]
            if not writers:
                continue
            writer_tags: Set[str] = set()
            for (s, _a) in writers:
                writer_tags |= idx.tags.get(s.info.key, frozenset())
            writer_names = sorted(
                {s.info.qualname for (s, _a) in writers}
            )
            seen_methods: Set[str] = set()
            for (s, a) in accesses:
                if s.assume_held_unknown:
                    continue
                if lock in idx.effective_held(s, a.held):
                    continue
                acc_tags = idx.tags.get(s.info.key, frozenset())
                if not acc_tags:
                    continue  # unreachable from any classified root
                if len(acc_tags | writer_tags) <= 1:
                    continue  # same single root as every locked writer
                if s.info.key in seen_methods:
                    continue
                seen_methods.add(s.info.key)
                verb = "written" if a.is_store else "read"
                roots = ", ".join(sorted(acc_tags))
                out.append(
                    self.finding(
                        idx.module_of(s),
                        a.node,
                        f"`self.{attr}` is guarded by "
                        f"`{idx.lock_name(lock)}` (written under it in "
                        f"{', '.join(writer_names[:3])}) but {verb} "
                        f"lock-free in `{s.info.qualname}` (reachable "
                        f"from: {roots}) — take the lock or suppress "
                        f"with the benign-race rationale",
                    )
                )
        return out


class AsyncLockSafetyRule(_ConcurrencyRule):
    name = "async-lock-safety"
    severity = "error"

    _MESSAGES = {
        "await": (
            "{desc} while holding `{lock}` — the event loop parks every "
            "task behind a threading lock"
        ),
        "blocking": (
            "{desc} while holding `{lock}` — blocks every thread "
            "contending for the lock; move the slow work outside the "
            "critical section"
        ),
        "callback": (
            "{desc} invoked while holding `{lock}` — user callbacks "
            "must fire outside the lock (the DeferredVerdict "
            "swap-and-fire contract); capture under the lock, call "
            "after release"
        ),
        "settle": (
            "{desc} while holding `{lock}` — done-callbacks run "
            "synchronously on the settling thread, i.e. inside this "
            "critical section; settle after release"
        ),
    }

    def run(self, project: Project) -> Iterable[Finding]:
        idx = get_index(project)
        out: List[Finding] = []
        for key in idx.scans:
            scan = idx.scans[key]
            mod = idx.module_of(scan)
            if scan.is_async and scan.acquires:
                for a in scan.acquires:
                    kind = idx.lock_kinds.get(a.lock, "lock")
                    out.append(
                        self.finding(
                            mod,
                            a.node,
                            f"threading {kind} `{idx.lock_name(a.lock)}` "
                            f"acquired in coroutine "
                            f"`{scan.info.qualname}` — a contended "
                            f"acquire stalls the whole event loop; use "
                            f"asyncio primitives or hand off to a "
                            f"thread",
                        )
                    )
                continue  # the acquisition finding covers the body
            ctx = idx.context_locks.get(key, frozenset())
            seen: Set[Tuple[int, str]] = set()
            for ev in scan.events:
                eff = frozenset(ev.held) | ctx
                if not eff:
                    continue
                lock = ev.held[-1] if ev.held else sorted(eff)[0]
                line = getattr(ev.node, "lineno", 1)
                dk = (line, ev.desc)
                if dk in seen:
                    continue
                seen.add(dk)
                out.append(
                    self.finding(
                        mod,
                        ev.node,
                        self._MESSAGES[ev.etype].format(
                            desc=ev.desc, lock=idx.lock_name(lock)
                        )
                        + f" (in `{scan.info.qualname}`)",
                    )
                )
        return out
