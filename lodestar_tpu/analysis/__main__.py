"""CLI: python -m lodestar_tpu.analysis [--json|--sarif] [--changed]
                                        [--profile-rules] [paths]

Exit codes: 0 clean, 1 non-suppressed findings, 2 usage/internal error.

`--changed` is the pre-push mode: the full tree is parsed (cross-module
rules need it) but only findings in git-touched files (staged, unstaged,
untracked) are considered, and of those only findings NEW relative to a
baseline lint of the HEAD revision of each touched file are reported —
pre-existing debt in a file you edited does not fail your push.  Exits
nonzero on new findings only; the hidden pre-existing count goes to
stderr.  dev/lint.sh forwards to this.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from collections import Counter
from pathlib import Path
from typing import Dict, List, Optional, Set, Tuple

from . import (
    ALL_RULES,
    Finding,
    analyze,
    findings_to_json,
    findings_to_sarif,
    render_findings,
)


def _git_toplevel() -> Optional[Path]:
    try:
        res = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            capture_output=True,
            text=True,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if res.returncode != 0:
        return None
    return Path(res.stdout.strip())


def _git_changed_files() -> Optional[Set[str]]:
    # git prints paths relative to the repo TOPLEVEL; anchor there, not
    # at the process cwd, or a subdirectory run filters everything out
    top = _git_toplevel()
    if top is None:
        return None
    cmds = [
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ]
    out: Set[str] = set()
    for cmd in cmds:
        try:
            res = subprocess.run(
                cmd, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if res.returncode != 0:
            return None
        for line in res.stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(str((top / line).resolve()))
    return out


def _baseline_overrides(
    changed: Set[str],
) -> Optional[Dict[str, Optional[str]]]:
    """HEAD-revision source for every changed file (None when the file
    did not exist at HEAD — it is skipped in the baseline lint)."""
    top = _git_toplevel()
    if top is None:
        return None
    overrides: Dict[str, Optional[str]] = {}
    for p in sorted(changed):
        rel = os.path.relpath(p, top).replace(os.sep, "/")
        try:
            res = subprocess.run(
                ["git", "show", f"HEAD:{rel}"],
                capture_output=True,
                text=True,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        overrides[p] = res.stdout if res.returncode == 0 else None
    return overrides


def _finding_key(f: Finding) -> Tuple:
    # line/col excluded on purpose: an unrelated edit above a
    # pre-existing finding must not make it look new
    return (f.rule, f.path, f.severity, f.message, f.suppressed)


def _subtract_baseline(
    findings: List[Finding], baseline: List[Finding]
) -> Tuple[List[Finding], int]:
    """Multiset difference: drop each finding matched by an identical
    baseline finding (returning the count of hidden ACTIVE ones)."""
    remaining = Counter(_finding_key(f) for f in baseline)
    out: List[Finding] = []
    hidden_active = 0
    for f in findings:
        k = _finding_key(f)
        if remaining[k] > 0:
            remaining[k] -= 1
            if not f.suppressed:
                hidden_active += 1
        else:
            out.append(f)
    return out, hidden_active


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m lodestar_tpu.analysis")
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--sarif",
        action="store_true",
        help="emit SARIF 2.1.0 (CI/code-review annotation format)",
    )
    ap.add_argument(
        "--changed",
        action="store_true",
        help="report only NEW findings in git-changed files "
        "(baseline: the HEAD revision of each touched file)",
    )
    ap.add_argument(
        "--profile-rules",
        action="store_true",
        help="print per-rule wall-clock timings to stderr",
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.as_json and args.sarif:
        print("tpulint: --json and --sarif are exclusive", file=sys.stderr)
        return 2

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name} [{rule.severity}]")
        print("bad-suppression [error]")
        return 0

    paths = args.paths or ["lodestar_tpu"]
    only: Optional[Set[str]] = None
    if args.changed:
        only = _git_changed_files()
        if only is None:
            print(
                "tpulint: --changed needs a working git; running full",
                file=sys.stderr,
            )

    timings: Optional[Dict[str, float]] = (
        {} if args.profile_rules else None
    )
    try:
        findings = analyze(paths, only_files=only, rule_timings=timings)
    except FileNotFoundError as e:
        print(f"tpulint: no such path: {e}", file=sys.stderr)
        return 2

    if args.changed and only is not None:
        overrides = _baseline_overrides(only)
        if overrides is None:
            print(
                "tpulint: --changed baseline unavailable; "
                "reporting all findings in changed files",
                file=sys.stderr,
            )
        else:
            baseline = analyze(
                paths, only_files=only, source_overrides=overrides
            )
            findings, hidden = _subtract_baseline(findings, baseline)
            if hidden:
                print(
                    f"tpulint: --changed: {hidden} pre-existing "
                    f"finding(s) hidden (baseline HEAD)",
                    file=sys.stderr,
                )

    if timings is not None:
        print("tpulint: rule timings:", file=sys.stderr)
        for name, dt in sorted(
            timings.items(), key=lambda kv: -kv[1]
        ):
            print(f"  {name:28s} {dt:7.3f}s", file=sys.stderr)

    if args.as_json:
        print(findings_to_json(findings))
    elif args.sarif:
        print(findings_to_sarif(findings))
    else:
        print(render_findings(findings))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
