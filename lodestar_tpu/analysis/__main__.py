"""CLI: python -m lodestar_tpu.analysis [--json] [--changed] [paths]

Exit codes: 0 clean, 1 non-suppressed findings, 2 usage/internal error.
`--changed` parses the full tree (cross-module rules need it) but only
reports findings in files touched per git (staged, unstaged, untracked)
— the fast local-iteration mode behind dev/lint.sh.
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import Optional, Set

from . import ALL_RULES, analyze, findings_to_json, render_findings


def _git_changed_files() -> Optional[Set[str]]:
    # git prints paths relative to the repo TOPLEVEL; anchor there, not
    # at the process cwd, or a subdirectory run filters everything out
    cmds = [
        ["git", "rev-parse", "--show-toplevel"],
        ["git", "diff", "--name-only", "HEAD", "--"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ]
    results = []
    for cmd in cmds:
        try:
            res = subprocess.run(
                cmd, capture_output=True, text=True, timeout=30
            )
        except (OSError, subprocess.TimeoutExpired):
            return None
        if res.returncode != 0:
            return None
        results.append(res.stdout)
    top = Path(results[0].strip())
    out: Set[str] = set()
    for stdout in results[1:]:
        for line in stdout.splitlines():
            line = line.strip()
            if line.endswith(".py"):
                out.add(str((top / line).resolve()))
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m lodestar_tpu.analysis")
    ap.add_argument("paths", nargs="*", default=None)
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--changed",
        action="store_true",
        help="report only findings in git-changed files",
    )
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name} [{rule.severity}]")
        print("bad-suppression [error]")
        return 0

    paths = args.paths or ["lodestar_tpu"]
    only: Optional[Set[str]] = None
    if args.changed:
        only = _git_changed_files()
        if only is None:
            print(
                "tpulint: --changed needs a working git; running full",
                file=sys.stderr,
            )

    try:
        findings = analyze(paths, only_files=only)
    except FileNotFoundError as e:
        print(f"tpulint: no such path: {e}", file=sys.stderr)
        return 2

    if args.as_json:
        print(findings_to_json(findings))
    else:
        print(render_findings(findings))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
