"""tpulint — AST-based invariant checker for the lodestar-tpu tree.

The kernel surface (15 modules, ~150 kernels, plus standalone export
entries like slasher/device.py) rests on invariants no general-purpose
linter knows about: pallas kernel bodies must stay shape-stable,
gather-free and constant-capture-free or the Mosaic export path breaks
(dev/NOTES.md "Mosaic failure modes"); export-cache artifacts must
fingerprint every source module they trace or a stale artifact runs
silently.  This package encodes those invariants as static rules and
runs them on every tier-1 pass (tests/test_tpulint.py).

Usage:
    python -m lodestar_tpu.analysis [--json|--sarif] [--changed]
                                    [--profile-rules] [paths]

Suppressions are inline, with a mandatory reason:
    x = TABLE[idx]  # tpulint: disable=gather-hazard -- host-side numpy

Rule catalog: see analysis/rules.py docstrings or --list-rules.
"""

from .engine import (  # noqa: F401
    Finding,
    Project,
    analyze,
    render_findings,
    findings_to_json,
    findings_to_sarif,
)
from .rules import ALL_RULES, RULE_NAMES  # noqa: F401

__all__ = [
    "Finding",
    "Project",
    "analyze",
    "render_findings",
    "findings_to_json",
    "findings_to_sarif",
    "ALL_RULES",
    "RULE_NAMES",
]
