"""tpulint rule engine — project model, reachability, suppressions.

The engine parses every module into an index (imports, module-level
constants, function defs with lexical nesting), computes two
reachability tiers over the static call graph, and hands the `Project`
to each rule:

  - ``mosaic`` tier: functions reachable from a `pl.pallas_call` kernel
    argument (or a `launch.tiled` kernel argument).  These bodies lower
    through Mosaic; captured array constants and gathers break the
    export path there (dev/NOTES.md "Mosaic failure modes").
  - ``traced`` tier: the mosaic tier plus everything reachable from
    `jax.jit` roots and export-cache entries.  These bodies run under
    tracing: host-only operations (`.item()`, `int()` on traced values,
    Python `if` on traced truthiness) and dtype-sloppy constructors are
    hazards here.

Resolution is name-based and best-effort — a static tool cannot chase
every first-class-function indirection — but it is conservative in the
direction that matters: over-approximating reachability only ever adds
lint coverage, never unsoundness.

Everything is plain `ast`; the analyzed code is NEVER imported, so
fixtures and broken modules lint fine.
"""

from __future__ import annotations

import ast
import json
import re
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    rule: str
    path: str  # as given on the command line (repo-relative in CI)
    line: int
    col: int
    severity: str  # "error" | "warning"
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


# ---------------------------------------------------------------------------
# suppressions — "tpulint: disable=<rule>[,<rule>] -- <reason>" comments
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*\S))?\s*$"
)
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclass
class Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: Optional[str]  # None => invalid (mandatory reason missing)


def parse_suppressions(source_lines: Sequence[str]) -> Dict[int, Suppression]:
    out: Dict[int, Suppression] = {}
    for i, text in enumerate(source_lines, start=1):
        if "tpulint" not in text:
            continue
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = tuple(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        out[i] = Suppression(line=i, rules=rules, reason=m.group(2))
    return out


# ---------------------------------------------------------------------------
# module index
# ---------------------------------------------------------------------------

# numpy-ish scalar constructors: capturing these is NOT an array capture
_SCALAR_FNS = frozenset(
    {
        "int8", "int16", "int32", "int64", "intp",
        "uint8", "uint16", "uint32", "uint64",
        "float16", "float32", "float64", "bool_",
        "dtype", "iinfo", "finfo",
    }
)


@dataclass
class FunctionInfo:
    key: str  # "modname:qualname"
    modname: str
    qualname: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    params: Tuple[str, ...]
    static_params: frozenset  # annotated/defaulted as python scalars
    parent: Optional[str]  # enclosing function key
    children: List[str] = field(default_factory=list)


@dataclass
class ExportEntry:
    """A `register_entry(name, builder, sources=...)` or
    `bucketed_entry(name, builder, buckets, sources=...)` call site."""

    name: Optional[str]  # None when not a string literal
    modname: str
    line: int
    col: int
    sources: Tuple[str, ...]  # statically-resolved dotted module names
    unresolved_sources: bool  # a source expr we could not read statically
    traced_fn: Optional[str]  # FunctionInfo key of the traced computation
    # bucketed_entry only: the statically-resolved shape-bucket table
    # (None for plain register_entry calls, and for bucketed calls
    # whose table could not be read — unresolved_buckets marks those)
    buckets: Optional[Tuple[int, ...]] = None
    unresolved_buckets: bool = False


class Module:
    def __init__(
        self,
        modname: str,
        path: Path,
        display_path: str,
        source: Optional[str] = None,
    ):
        self.modname = modname
        self.path = path
        self.display_path = display_path
        # `source` overrides the on-disk content (--changed baselines
        # lint the HEAD revision of a file under its working-tree path)
        self.source = (
            path.read_text(encoding="utf-8", errors="replace")
            if source is None
            else source
        )
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        self.suppressions = parse_suppressions(self.lines)
        # alias -> dotted module (both `import x as a` and resolvable
        # `from pkg import submodule as a`); module- and function-level
        # imports are merged into one namespace (good enough for lint)
        self.module_aliases: Dict[str, str] = {}
        # name -> (dotted module, original name) for `from mod import name`
        self.from_imports: Dict[str, Tuple[str, str]] = {}
        # module-level names whose value expression builds an np/jnp array
        self.array_consts: Set[str] = set()
        # alias -> "numpy" | "jax.numpy"
        self.np_aliases: Dict[str, str] = {}
        self.functions: Dict[str, FunctionInfo] = {}  # qualname -> info


def _rel_module(base: str, level: int, name: Optional[str]) -> Optional[str]:
    """Resolve a relative import against a dotted module name."""
    if level == 0:
        return name
    parts = base.split(".")
    # level 1 = current package; the module's own name is the last part
    if len(parts) < level:
        return None
    prefix = parts[: len(parts) - level]
    if name:
        prefix = prefix + name.split(".")
    return ".".join(prefix) if prefix else None


class Project:
    """Every analyzed module plus the cross-module resolution tables."""

    def __init__(self) -> None:
        self.modules: Dict[str, Module] = {}
        self.mosaic: Set[str] = set()  # FunctionInfo keys
        self.traced: Set[str] = set()
        self.export_entries: List[ExportEntry] = []
        # unparseable files become findings, never a crashed run (one
        # half-saved file must not abort linting everything else)
        self.parse_errors: List[Finding] = []

    # -- loading -----------------------------------------------------------

    @staticmethod
    def _iter_py(path: Path) -> Iterable[Path]:
        if path.is_file():
            if path.suffix == ".py":
                yield path
            return
        for p in sorted(path.rglob("*.py")):
            if "__pycache__" in p.parts:
                continue
            yield p

    @staticmethod
    def _module_root(path: Path) -> Path:
        """Directory that dotted module names are computed from: walk up
        while the directory is a package (has __init__.py)."""
        d = path if path.is_dir() else path.parent
        while (d / "__init__.py").exists() and d.parent != d:
            d = d.parent
        return d

    def load_paths(
        self,
        paths: Sequence[str],
        source_overrides: Optional[Dict[str, Optional[str]]] = None,
    ) -> None:
        """`source_overrides` maps resolved path strings to replacement
        source text (the --changed baseline lints HEAD revisions under
        working-tree paths); a None value skips the file entirely (it
        did not exist at the baseline revision)."""
        for raw in paths:
            p = Path(raw)
            if not p.exists():
                raise FileNotFoundError(raw)
            root = self._module_root(p)
            for f in self._iter_py(p):
                rel = f.relative_to(root)
                parts = list(rel.with_suffix("").parts)
                if parts[-1] == "__init__":
                    parts = parts[:-1]
                modname = ".".join(parts) if parts else f.stem
                if modname in self.modules:
                    continue
                src: Optional[str] = None
                if source_overrides is not None:
                    key = str(f.resolve())
                    if key in source_overrides:
                        src = source_overrides[key]
                        if src is None:
                            continue
                try:
                    display = str(f.relative_to(Path.cwd()))
                except ValueError:
                    display = str(f)
                try:
                    self.modules[modname] = Module(
                        modname, f, display, source=src
                    )
                except SyntaxError as e:
                    self.parse_errors.append(
                        Finding(
                            rule="parse-error",
                            path=display,
                            line=e.lineno or 1,
                            col=(e.offset or 1) - 1,
                            severity="error",
                            message=f"file does not parse: {e.msg}",
                        )
                    )
        for mod in self.modules.values():
            self._index_module(mod)
        self._compute_reachability()
        self._collect_export_entries()

    # -- per-module indexing ------------------------------------------------

    def _index_module(self, mod: Module) -> None:
        self._collect_imports(mod, mod.tree)
        self._collect_functions(mod, mod.tree, parent=None, prefix="")
        self._collect_array_consts(mod)

    def _collect_imports(self, mod: Module, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    mod.module_aliases[alias] = target
                    if a.name == "numpy":
                        mod.np_aliases[alias] = "numpy"
                    elif a.name == "jax.numpy":
                        mod.np_aliases[alias] = "jax.numpy"
            elif isinstance(node, ast.ImportFrom):
                base = _rel_module(mod.modname, node.level, node.module)
                if base is None:
                    continue
                for a in node.names:
                    alias = a.asname or a.name
                    if a.name == "*":
                        continue
                    sub = f"{base}.{a.name}"
                    if base == "jax" and a.name == "numpy":
                        mod.np_aliases[alias] = "jax.numpy"
                    # `from pkg import submodule` binds a module object
                    mod.module_aliases.setdefault(alias, sub)
                    mod.from_imports[alias] = (base, a.name)

    def _collect_functions(
        self, mod: Module, tree: ast.AST, parent: Optional[str], prefix: str
    ) -> None:
        body = getattr(tree, "body", [])
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + node.name
                key = f"{mod.modname}:{qual}"
                args = node.args
                names = [
                    a.arg
                    for a in (
                        args.posonlyargs + args.args + args.kwonlyargs
                    )
                ]
                static = set()
                for a in args.posonlyargs + args.args + args.kwonlyargs:
                    ann = a.annotation
                    if (
                        isinstance(ann, ast.Name)
                        and ann.id in ("int", "bool", "float", "str", "bytes")
                    ):
                        static.add(a.arg)
                defaults = list(args.defaults)
                pos = args.posonlyargs + args.args
                for a, d in zip(pos[len(pos) - len(defaults):], defaults):
                    if isinstance(d, ast.Constant) and isinstance(
                        d.value, (bool, int, float, str, bytes, type(None))
                    ):
                        static.add(a.arg)
                info = FunctionInfo(
                    key=key,
                    modname=mod.modname,
                    qualname=qual,
                    node=node,
                    params=tuple(names),
                    static_params=frozenset(static),
                    parent=parent,
                )
                mod.functions[qual] = info
                if parent is not None:
                    pmod, pqual = parent.split(":", 1)
                    self.modules[pmod].functions[pqual].children.append(key)
                self._collect_functions(
                    mod, node, parent=key, prefix=qual + "."
                )
            elif isinstance(node, ast.ClassDef):
                self._collect_functions(
                    mod, node, parent=parent, prefix=prefix + node.name + "."
                )

    def _expr_builds_array(self, mod: Module, expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id in mod.np_aliases
                and fn.attr not in _SCALAR_FNS
            ):
                return True
        return False

    def _collect_array_consts(self, mod: Module) -> None:
        for node in mod.tree.body:
            targets: List[ast.expr] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if value is None or not self._expr_builds_array(mod, value):
                continue
            for t in targets:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                for e in elts:
                    if isinstance(e, ast.Name):
                        mod.array_consts.add(e.id)

    # -- resolution ---------------------------------------------------------

    def function(self, key: str) -> Optional[FunctionInfo]:
        modname, qual = key.split(":", 1)
        mod = self.modules.get(modname)
        return mod.functions.get(qual) if mod else None

    def resolve_name(
        self, mod: Module, scope: Optional[FunctionInfo], name: str
    ) -> Optional[str]:
        """Resolve a bare name reference to a FunctionInfo key."""
        # lexically enclosing nested defs
        s = scope
        while s is not None:
            cand = f"{s.qualname}.{name}"
            if cand in mod.functions:
                return f"{mod.modname}:{cand}"
            s = self.function(s.parent) if s.parent else None
        if name in mod.functions:
            return f"{mod.modname}:{name}"
        fi = mod.from_imports.get(name)
        if fi is not None:
            src_mod, orig = fi
            target = self.modules.get(src_mod)
            if target and orig in target.functions:
                return f"{src_mod}:{orig}"
        return None

    def resolve_callee(
        self, mod: Module, scope: Optional[FunctionInfo], func: ast.AST
    ) -> Optional[str]:
        if isinstance(func, ast.Name):
            return self.resolve_name(mod, scope, func.id)
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            target_mod = mod.module_aliases.get(func.value.id)
            if target_mod is not None:
                tm = self.modules.get(target_mod)
                if tm and func.attr in tm.functions:
                    return f"{target_mod}:{func.attr}"
        return None

    def is_array_const_ref(
        self, mod: Module, scope_locals: Set[str], node: ast.AST
    ) -> Optional[str]:
        """Is `node` a read of a module-level np/jnp array constant?
        Returns a description of the constant, or None."""
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in scope_locals:
                return None
            if node.id in mod.array_consts:
                return f"{mod.modname}.{node.id}"
            fi = mod.from_imports.get(node.id)
            if fi is not None:
                src_mod, orig = fi
                target = self.modules.get(src_mod)
                if target and orig in target.array_consts:
                    return f"{src_mod}.{orig}"
        elif isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ):
            target_mod = mod.module_aliases.get(node.value.id)
            if target_mod is not None:
                tm = self.modules.get(target_mod)
                if tm and node.attr in tm.array_consts:
                    return f"{target_mod}.{node.attr}"
        return None

    # -- reachability -------------------------------------------------------

    @staticmethod
    def _is_jit_expr(node: ast.AST) -> bool:
        """`jax.jit`, `jit`, `ops_jit` (the instrumented dispatcher in
        kernels/jit_dispatch.py), `partial(jax.jit, ...)`, or a direct
        decorator call `ops_jit(name=...)`."""
        if isinstance(node, ast.Name) and node.id in ("jit", "ops_jit"):
            return True
        if isinstance(node, ast.Attribute) and node.attr in (
            "jit", "ops_jit",
        ):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            is_partial = (
                isinstance(fn, ast.Name) and fn.id == "partial"
            ) or (isinstance(fn, ast.Attribute) and fn.attr == "partial")
            if is_partial and node.args:
                return Project._is_jit_expr(node.args[0])
            # `@ops_jit(name=..., static_argnums=...)` configures and
            # returns the jit wrapper directly
            if Project._is_jit_expr(fn):
                return True
        return False

    def _fn_ref_arg(
        self, mod: Module, scope: Optional[FunctionInfo], arg: ast.AST
    ) -> Optional[str]:
        if isinstance(arg, (ast.Name, ast.Attribute)):
            return self.resolve_callee(mod, scope, arg) or (
                self.resolve_name(mod, scope, arg.id)
                if isinstance(arg, ast.Name)
                else None
            )
        return None

    def _walk_scoped(self, mod: Module):
        """Yield (scope FunctionInfo | None, node, prefix) over the whole
        module: scope is the innermost enclosing FUNCTION; prefix is the
        full qualname prefix (classes included) at this point, so a
        def's qualname is prefix + node.name."""

        def rec(tree: ast.AST, scope, prefix: str):
            for node in ast.iter_child_nodes(tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    qual = prefix + node.name
                    info = mod.functions.get(qual)
                    yield (scope, node, prefix)
                    yield from rec(node, info or scope, qual + ".")
                elif isinstance(node, ast.ClassDef):
                    yield (scope, node, prefix)
                    yield from rec(node, scope, prefix + node.name + ".")
                else:
                    yield (scope, node, prefix)
                    yield from rec(node, scope, prefix)

        yield from rec(mod.tree, None, "")

    def _builder_traced_fn(
        self, builder_key: str, depth: int = 0
    ) -> Optional[str]:
        """Chase a spec-builder's returns to the traced function: a
        builder returns `(fn, specs)` or delegates to another builder."""
        if depth > 3:
            return None
        info = self.function(builder_key)
        if info is None:
            return None
        mod = self.modules[info.modname]
        for node in ast.walk(info.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Tuple) and v.elts:
                ref = self._fn_ref_arg(mod, info, v.elts[0])
                if ref:
                    return ref
            elif isinstance(v, ast.Call):
                target = self.resolve_callee(mod, info, v.func)
                if target:
                    found = self._builder_traced_fn(target, depth + 1)
                    if found:
                        return found
        return None

    def _compute_reachability(self) -> None:
        mosaic_roots: Set[str] = set()
        traced_roots: Set[str] = set()
        for mod in self.modules.values():
            for scope, node, prefix in self._walk_scoped(mod):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    info = mod.functions.get(prefix + node.name)
                    if info and any(
                        self._is_jit_expr(d) for d in node.decorator_list
                    ):
                        traced_roots.add(info.key)
                    continue
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                callee = (
                    fn.attr
                    if isinstance(fn, ast.Attribute)
                    else fn.id
                    if isinstance(fn, ast.Name)
                    else None
                )
                if callee == "pallas_call" and node.args:
                    ref = self._fn_ref_arg(mod, scope, node.args[0])
                    if ref:
                        mosaic_roots.add(ref)
                elif callee == "tiled" and node.args:
                    ref = self._fn_ref_arg(mod, scope, node.args[0])
                    if ref:
                        mosaic_roots.add(ref)
                elif callee in ("load_or_export", "export_and_save") and len(
                    node.args
                ) >= 2:
                    ref = self._fn_ref_arg(mod, scope, node.args[1])
                    if ref:
                        traced_roots.add(ref)
                elif callee in (
                    "register_entry", "bucketed_entry"
                ) and len(node.args) >= 2:
                    ref = self._fn_ref_arg(mod, scope, node.args[1])
                    if ref:
                        traced = self._builder_traced_fn(ref)
                        if traced:
                            traced_roots.add(traced)
                elif self._is_jit_expr(fn) and node.args:
                    ref = self._fn_ref_arg(mod, scope, node.args[0])
                    if ref:
                        traced_roots.add(ref)
        self.mosaic = self._closure(mosaic_roots)
        self.traced = self._closure(traced_roots | mosaic_roots)

    def _closure(self, roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        work = list(roots)
        while work:
            key = work.pop()
            if key in seen:
                continue
            info = self.function(key)
            if info is None:
                continue
            seen.add(key)
            # nested defs are the kernel bodies/closures of their parent
            work.extend(info.children)
            mod = self.modules[info.modname]
            for node in self._fn_body_nodes(info):
                if isinstance(node, ast.Call):
                    target = self.resolve_callee(mod, info, node.func)
                    if target:
                        work.append(target)
                if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load
                ):
                    target = self.resolve_name(mod, info, node.id)
                    if target and target != key:
                        work.append(target)
        return seen

    @staticmethod
    def _fn_body_nodes(info: FunctionInfo) -> Iterable[ast.AST]:
        """Walk a function body, excluding nested def bodies (they are
        separate FunctionInfos) but including lambdas."""

        def rec(tree: ast.AST):
            for node in ast.iter_child_nodes(tree):
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    # decorators/defaults evaluate in this scope
                    for d in node.decorator_list:
                        yield d
                        yield from rec(d)
                    continue
                yield node
                yield from rec(node)

        yield from rec(info.node)

    @staticmethod
    def local_binds(info: FunctionInfo) -> Set[str]:
        """Names bound inside the function (params, assigns, loops,
        comprehensions, withs, walrus) — these shadow module constants."""
        out: Set[str] = set(info.params)
        for node in Project._fn_body_nodes(info):
            if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)
            ):
                out.add(node.id)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    out.add((a.asname or a.name).split(".")[0])
        return out

    # -- export entries (fingerprint-completeness inputs) -------------------

    def _collect_export_entries(self) -> None:
        for mod in self.modules.values():
            for scope, node, _prefix in self._walk_scoped(mod):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                callee = (
                    fn.attr
                    if isinstance(fn, ast.Attribute)
                    else fn.id
                    if isinstance(fn, ast.Name)
                    else None
                )
                if callee not in (
                    "register_entry", "bucketed_entry"
                ) or len(node.args) < 2:
                    continue
                name = (
                    node.args[0].value
                    if isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    else None
                )
                sources: List[str] = []
                unresolved = False
                for kw in node.keywords:
                    if kw.arg not in ("source", "sources"):
                        continue
                    exprs = (
                        list(kw.value.elts)
                        if isinstance(kw.value, (ast.Tuple, ast.List))
                        else [kw.value]
                    )
                    for e in exprs:
                        if isinstance(e, ast.Constant) and isinstance(
                            e.value, str
                        ):
                            sources.append(e.value)
                        else:
                            unresolved = True
                buckets: Optional[Tuple[int, ...]] = None
                unresolved_buckets = False
                if callee == "bucketed_entry":
                    bexpr = (
                        node.args[2] if len(node.args) >= 3 else None
                    )
                    if bexpr is None:
                        for kw in node.keywords:
                            if kw.arg == "buckets":
                                bexpr = kw.value
                                break
                    buckets = (
                        self._static_int_tuple(mod, bexpr)
                        if bexpr is not None
                        else None
                    )
                    unresolved_buckets = buckets is None
                builder = self._fn_ref_arg(mod, scope, node.args[1])
                traced = (
                    self._builder_traced_fn(builder) if builder else None
                )
                self.export_entries.append(
                    ExportEntry(
                        name=name,
                        modname=mod.modname,
                        line=node.lineno,
                        col=node.col_offset,
                        sources=tuple(sources),
                        unresolved_sources=unresolved,
                        traced_fn=traced,
                        buckets=buckets,
                        unresolved_buckets=unresolved_buckets,
                    )
                )

    # -- static constant resolution (bucket tables) -------------------------

    def _module_const_expr(
        self, mod: Module, name: str
    ) -> Optional[ast.AST]:
        """The value expression of a MODULE-LEVEL assignment to `name`
        (last one wins, matching runtime semantics)."""
        found: Optional[ast.AST] = None
        for node in mod.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == name:
                        found = node.value
            elif (
                isinstance(node, ast.AnnAssign)
                and isinstance(node.target, ast.Name)
                and node.target.id == name
                and node.value is not None
            ):
                found = node.value
        return found

    def _static_int(
        self, mod: Module, expr: ast.AST, depth: int = 0
    ) -> Optional[int]:
        """Evaluate `expr` to an int using only literals, arithmetic
        over them, and module-level constants (local or imported) —
        None when anything dynamic appears."""
        if depth > 6:
            return None
        if isinstance(expr, ast.Constant):
            v = expr.value
            return v if type(v) is int else None
        if isinstance(expr, ast.UnaryOp) and isinstance(
            expr.op, ast.USub
        ):
            v = self._static_int(mod, expr.operand, depth + 1)
            return -v if v is not None else None
        if isinstance(expr, ast.BinOp):
            left = self._static_int(mod, expr.left, depth + 1)
            right = self._static_int(mod, expr.right, depth + 1)
            if left is None or right is None:
                return None
            if isinstance(expr.op, ast.Add):
                return left + right
            if isinstance(expr.op, ast.Sub):
                return left - right
            if isinstance(expr.op, ast.Mult):
                return left * right
            if isinstance(expr.op, ast.FloorDiv):
                return left // right if right else None
            if isinstance(expr.op, ast.LShift):
                return left << right
            if isinstance(expr.op, ast.Pow):
                return left**right if 0 <= right <= 64 else None
            return None
        resolved = self._resolve_const_ref(mod, expr)
        if resolved is not None:
            target_mod, value = resolved
            return self._static_int(target_mod, value, depth + 1)
        return None

    def _resolve_const_ref(
        self, mod: Module, expr: ast.AST
    ) -> Optional[Tuple[Module, ast.AST]]:
        """Chase a Name/Attribute reference to a module-level constant's
        value expression (following `from mod import NAME` and module
        aliases), returning (defining module, value expr)."""
        if isinstance(expr, ast.Name):
            local = self._module_const_expr(mod, expr.id)
            if local is not None:
                return (mod, local)
            fi = mod.from_imports.get(expr.id)
            if fi is not None:
                src_mod, orig = fi
                target = self.modules.get(src_mod)
                if target is not None:
                    value = self._module_const_expr(target, orig)
                    if value is not None:
                        return (target, value)
        elif isinstance(expr, ast.Attribute) and isinstance(
            expr.value, ast.Name
        ):
            target_mod = mod.module_aliases.get(expr.value.id)
            target = (
                self.modules.get(target_mod) if target_mod else None
            )
            if target is not None:
                value = self._module_const_expr(target, expr.attr)
                if value is not None:
                    return (target, value)
        return None

    def _static_int_tuple(
        self, mod: Module, expr: ast.AST, depth: int = 0
    ) -> Optional[Tuple[int, ...]]:
        """Resolve `expr` to a tuple of ints: a tuple/list display of
        statically-evaluable int expressions, a module-level constant
        reference to one, or a `+` concatenation of resolvable tuples."""
        if depth > 6:
            return None
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: List[int] = []
            for e in expr.elts:
                v = self._static_int(mod, e, depth + 1)
                if v is None:
                    return None
                out.append(v)
            return tuple(out)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left = self._static_int_tuple(mod, expr.left, depth + 1)
            right = self._static_int_tuple(mod, expr.right, depth + 1)
            if left is None or right is None:
                return None
            return left + right
        resolved = self._resolve_const_ref(mod, expr)
        if resolved is not None:
            target_mod, value = resolved
            return self._static_int_tuple(target_mod, value, depth + 1)
        return None

    def transitive_imports(
        self, modname: str, expand=None
    ) -> Set[str]:
        """Project modules transitively imported by `modname` (AST
        imports at any nesting, skipping TYPE_CHECKING blocks).
        `expand(modname) -> bool` gates which discovered modules have
        their OWN imports walked (the fingerprint rule stops at
        kernels/ modules: the kernels package is fingerprinted
        wholesale, so its internal deps are a global concern, not a
        per-entry one).  Package `__init__` side effects are NOT
        chased — the fingerprint contract covers modules whose CODE
        the traced function can reach, which explicit imports name."""
        seen: Set[str] = set()
        work = [modname]
        while work:
            cur = work.pop()
            if cur in seen:
                continue
            seen.add(cur)
            if expand is not None and cur != modname and not expand(cur):
                continue
            mod = self.modules.get(cur)
            if mod is None:
                continue
            for node in self._walk_no_type_checking(mod.tree):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if a.name in self.modules:
                            work.append(a.name)
                elif isinstance(node, ast.ImportFrom):
                    base = _rel_module(cur, node.level, node.module)
                    if base is None:
                        continue
                    if base in self.modules:
                        work.append(base)
                    for a in node.names:
                        sub = f"{base}.{a.name}"
                        if sub in self.modules:
                            work.append(sub)
        seen.discard(modname)
        return {m for m in seen if m in self.modules}

    @staticmethod
    def _walk_no_type_checking(tree: ast.AST) -> Iterable[ast.AST]:
        def guarded(node: ast.If) -> bool:
            t = node.test
            return (
                isinstance(t, ast.Name) and t.id == "TYPE_CHECKING"
            ) or (
                isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
            )

        def rec(n: ast.AST):
            for child in ast.iter_child_nodes(n):
                if isinstance(child, ast.If) and guarded(child):
                    for e in child.orelse:
                        yield e
                        yield from rec(e)
                    continue
                yield child
                yield from rec(child)

        yield from rec(tree)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _apply_suppressions(
    mod: Module, findings: List[Finding]
) -> List[Finding]:
    """Mark findings suppressed; emit bad-suppression findings for
    reason-less or unknown-rule suppressions."""
    from .rules import RULE_NAMES

    out: List[Finding] = []
    for f in findings:
        sup = mod.suppressions.get(f.line)
        if sup is None:
            prev = mod.suppressions.get(f.line - 1)
            if prev is not None and f.line - 1 >= 1:
                prev_text = mod.lines[f.line - 2]
                if _COMMENT_ONLY_RE.match(prev_text):
                    sup = prev
        if sup is not None and f.rule in sup.rules and sup.reason:
            f.suppressed = True
            f.suppress_reason = sup.reason
        out.append(f)
    for sup in mod.suppressions.values():
        if not sup.reason:
            out.append(
                Finding(
                    rule="bad-suppression",
                    path=mod.display_path,
                    line=sup.line,
                    col=0,
                    severity="error",
                    message=(
                        "suppression without a reason — write "
                        "'# tpulint: disable=<rule> -- <why>'"
                    ),
                )
            )
            continue
        for r in sup.rules:
            if r not in RULE_NAMES:
                out.append(
                    Finding(
                        rule="bad-suppression",
                        path=mod.display_path,
                        line=sup.line,
                        col=0,
                        severity="error",
                        message=f"unknown rule in suppression: {r!r}",
                    )
                )
    return out


def analyze(
    paths: Sequence[str],
    only_files: Optional[Set[str]] = None,
    rule_timings: Optional[Dict[str, float]] = None,
    source_overrides: Optional[Dict[str, Optional[str]]] = None,
) -> List[Finding]:
    """Run every rule over `paths`.  `only_files` (resolved-path strings)
    restricts REPORTING to those files; the whole tree is still parsed
    so cross-module rules keep full context (--changed mode).
    `rule_timings`, when given, is filled with per-rule wall-clock
    seconds (plus a "(parse+index)" entry; the first concurrency rule
    to run also pays the shared concurrency-index build).
    `source_overrides` is forwarded to Project.load_paths (--changed
    baseline runs)."""
    from .rules import ALL_RULES

    project = Project()
    t0 = time.monotonic()
    project.load_paths(paths, source_overrides=source_overrides)
    if rule_timings is not None:
        rule_timings["(parse+index)"] = time.monotonic() - t0
    display_to_mod = {
        m.display_path: m for m in project.modules.values()
    }
    findings: List[Finding] = []
    for rule in ALL_RULES:
        t0 = time.monotonic()
        findings.extend(rule.run(project))
        if rule_timings is not None:
            rule_timings[rule.name] = time.monotonic() - t0
    out: List[Finding] = list(project.parse_errors)
    grouped: Dict[str, List[Finding]] = {}
    for f in findings:
        grouped.setdefault(f.path, []).append(f)
    for path, fs in grouped.items():
        mod = display_to_mod.get(path)
        out.extend(_apply_suppressions(mod, fs) if mod else fs)
    # modules with no rule findings can still hold bad suppressions
    for mod in project.modules.values():
        if mod.display_path not in grouped:
            out.extend(_apply_suppressions(mod, []))
    if only_files is not None:
        out = [
            f
            for f in out
            if str(Path(f.path).resolve()) in only_files
        ]
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def render_findings(findings: List[Finding]) -> str:
    lines = []
    active = [f for f in findings if not f.suppressed]
    for f in active:
        lines.append(
            f"{f.path}:{f.line}:{f.col}: {f.rule} {f.severity}: {f.message}"
        )
    n_sup = sum(1 for f in findings if f.suppressed)
    lines.append(
        f"tpulint: {len(active)} finding(s), {n_sup} suppressed"
    )
    return "\n".join(lines)


def findings_to_sarif(findings: List[Finding]) -> str:
    """SARIF 2.1.0 — the interchange shape CI annotators and code-review
    bots consume.  Suppressed findings are emitted as results carrying
    an `inSource` suppression (with the mandatory reason as the
    justification) so reviewers see them without them failing gates;
    columns are converted to SARIF's 1-based convention."""
    from .rules import ALL_RULES

    severities = {r.name: r.severity for r in ALL_RULES}
    severities["bad-suppression"] = "error"
    severities["parse-error"] = "error"
    rules = [
        {
            "id": name,
            "defaultConfiguration": {
                "level": severities.get(name, "warning")
            },
        }
        for name in sorted(severities)
    ]
    results = []
    for f in findings:
        res = {
            "ruleId": f.rule,
            "level": f.severity,
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": f.line,
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        if f.suppressed:
            res["suppressions"] = [
                {
                    "kind": "inSource",
                    "justification": f.suppress_reason or "",
                }
            ]
        results.append(res)
    return json.dumps(
        {
            "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
            "version": "2.1.0",
            "runs": [
                {
                    "tool": {
                        "driver": {
                            "name": "tpulint",
                            "rules": rules,
                        }
                    },
                    "results": results,
                }
            ],
        },
        indent=2,
    )


def findings_to_json(findings: List[Finding]) -> str:
    active = [f for f in findings if not f.suppressed]
    return json.dumps(
        {
            "version": 1,
            "findings": [f.to_dict() for f in findings],
            "counts": {
                "active": len(active),
                "suppressed": len(findings) - len(active),
                "errors": sum(
                    1 for f in active if f.severity == "error"
                ),
                "warnings": sum(
                    1 for f in active if f.severity == "warning"
                ),
            },
        },
        indent=2,
    )
