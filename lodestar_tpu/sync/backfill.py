"""BackfillSync — verify history BACKWARD from a trusted checkpoint.

Mirror of the reference's backfill machinery (reference:
packages/beacon-node/src/sync/backfill/backfill.ts:1-883 and
backfill/verify.ts): after a checkpoint-sync bootstrap the node has a
trusted finalized state but no history; backfill walks the parent-root
chain backward from the anchor block, authenticating every block two
ways before archiving it:

  1. LINKAGE — the fetched block's hash_tree_root must equal the parent
     root declared by the already-trusted child (this alone makes the
     content authentic given a trusted anchor),
  2. PROPOSER SIGNATURES — batched through the injected BLS verifier
     (wire sets over validator indices, the same TPU batch path as
     gossip; reference: backfill/verify.ts verifyBlockProposerSignature).

Verified ranges are recorded in the db's backfilledRanges repository
(reference: db/repositories/backfilledRanges.ts) so a restart resumes
where it stopped.
"""

from __future__ import annotations

from typing import List, Optional

from .. import params
from ..bls.signature_set import WireSignatureSet
from ..bls.verifier import VerifyOptions
from ..db.beacon_db import _slot_key
from ..types import BeaconBlockAltair
from ..utils.logger import get_logger
from .range_sync import BlockSource

ZERO_ROOT = b"\x00" * 32


class BackfillError(Exception):
    pass


class BackfillSync:
    """Walks backward from (anchor_root, anchor_slot) to target_slot."""

    def __init__(self, config, db, verifier, batch_size: int = 32):
        self.config = config
        self.db = db
        self.verifier = verifier
        self.batch_size = batch_size
        self.log = get_logger("sync/backfill")
        self.verified_blocks = 0
        self.lowest_backfilled_slot: Optional[int] = None

    # -- signature sets (reference: backfill/verify.ts) --------------------

    def _proposer_set(self, signed: dict) -> WireSignatureSet:
        block = signed["message"]
        domain = self.config.get_domain(
            block["slot"], params.DOMAIN_BEACON_PROPOSER, block["slot"]
        )
        block_type = self.config.get_fork_types(block["slot"])[0]
        root = self.config.compute_signing_root(
            block_type.hash_tree_root(block), domain
        )
        return WireSignatureSet.single(
            int(block["proposer_index"]), root, signed["signature"]
        )

    def _verify_and_archive(self, batch: List[dict]) -> None:
        """All-or-nothing per batch: signatures verify as ONE batched
        job, then every block is archived."""
        if not batch:
            return
        sets = [self._proposer_set(s) for s in batch]
        ok = self.verifier.verify_signature_sets(
            sets, VerifyOptions(batchable=True)
        )
        if not ok:
            raise BackfillError(
                "backfill batch failed proposer-signature verification"
            )
        for signed in batch:
            block = signed["message"]
            root = self.config.get_fork_types(block["slot"])[0].hash_tree_root(
                block
            )
            self.db.archive_block(int(block["slot"]), signed, root=root)
            self.verified_blocks += 1
            self.lowest_backfilled_slot = int(block["slot"])

    # -- the backward walk (reference: backfill.ts syncBlockByRoot /
    # syncRange state machine, collapsed to the injected-source model) -----

    def backfill(
        self,
        source: BlockSource,
        anchor_parent_root: bytes,
        anchor_slot: int,
        target_slot: int = 0,
        genesis_root: bytes = None,
    ) -> int:
        """Fetch-verify-archive backward until target_slot (or the
        pre-genesis zero root / the genesis block root, which exists as
        a parent reference but never as a fetchable signed block).
        `anchor_parent_root` is the parent root declared by the TRUSTED
        anchor block (from the checkpoint state's latest block
        header); pass `genesis_root` when known so a chain with an
        empty slot 1 still terminates cleanly."""
        imported_before = self.verified_blocks
        expected = bytes(anchor_parent_root)
        batch: List[dict] = []
        prev_slot = anchor_slot
        genesis = bytes(genesis_root) if genesis_root is not None else None
        while expected != ZERO_ROOT and expected != genesis:
            blocks = source.get_blocks_by_root([expected])
            if not blocks:
                raise BackfillError(
                    f"source has no block {expected.hex()[:16]} "
                    "(history unavailable)"
                )
            signed = blocks[0]
            block = signed["message"]
            root = self.config.get_fork_types(block["slot"])[0].hash_tree_root(
                block
            )
            if root != expected:
                raise BackfillError(
                    f"linkage broken: fetched block roots to "
                    f"{root.hex()[:16]}, child declared {expected.hex()[:16]}"
                )
            if int(block["slot"]) >= prev_slot:
                raise BackfillError("backfill slots must strictly decrease")
            prev_slot = int(block["slot"])
            batch.append(signed)
            if len(batch) >= self.batch_size:
                self._verify_and_archive(batch)
                batch = []
            expected = bytes(block["parent_root"])
            # slot 1 is the lowest possible SIGNED block — its parent is
            # the genesis block header, which exists as a root but never
            # as a fetchable signed block, so the walk must stop here
            # even with target_slot=0 ("verify everything")
            if int(block["slot"]) <= max(target_slot, 1):
                break
        self._verify_and_archive(batch)
        # record the completed range (reference: backfilledRanges repo —
        # anchor slot -> lowest verified slot)
        if self.lowest_backfilled_slot is not None:
            self.db.backfilled_ranges.put(
                _slot_key(anchor_slot),
                _slot_key(self.lowest_backfilled_slot),
            )
        return self.verified_blocks - imported_before

    def status(self) -> dict:
        return {
            "verified_blocks": self.verified_blocks,
            "lowest_backfilled_slot": self.lowest_backfilled_slot,
        }


class ApiBlockSource:
    """BlockSource over a trusted node's REST API — the transport the
    checkpoint-sync bootstrap uses to backfill history (reference:
    backfill's reqresp beaconBlocksByRoot, carried over REST here since
    the libp2p wire is off the TPU path)."""

    def __init__(self, client):
        self.client = client

    @staticmethod
    def _absent(e: Exception) -> bool:
        """Only a definitive 404 means 'no such block'; transient
        transport/server errors must propagate so the caller can retry
        instead of mis-reading them as missing history."""
        return getattr(e, "status", None) == 404

    def get_blocks_by_root(self, roots) -> List[dict]:
        out = []
        for root in roots:
            try:
                out.append(self.client.get_block("0x" + bytes(root).hex()))
            except Exception as e:  # noqa: BLE001 - classify absent vs outage
                if not self._absent(e):
                    raise
        return out

    def get_blocks_by_range(self, start_slot: int, count: int) -> List[dict]:
        out = []
        for slot in range(start_slot, start_slot + count):
            try:
                out.append(self.client.get_block(str(slot)))
            except Exception as e:  # noqa: BLE001 - skip slots are empty
                if not self._absent(e):
                    raise
        return out
