"""Sync — transport-agnostic chain synchronization drivers.

Mirror of the reference's packages/beacon-node/src/sync/: RangeSync
(batched by-range download → import), UnknownBlockSync (fetch-by-root
parent resolution), and the sync state machine the node/API report.
The network transport itself is out of the TPU scope (SURVEY.md §2.4
P9); block sources are injected callables with the reqresp shapes
(get_blocks_by_range(start_slot, count), get_blocks_by_root(roots)).
"""

from .backfill import ApiBlockSource, BackfillError, BackfillSync  # noqa: F401
from .range_sync import (  # noqa: F401
    Batch,
    BatchState,
    BlockSource,
    RangeSync,
    SyncChain,
    SyncChainError,
    SyncState,
    UnknownBlockSync,
)
