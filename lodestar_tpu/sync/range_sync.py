"""RangeSync + UnknownBlockSync over injected block sources.

Reference: packages/beacon-node/src/sync/range/range.ts (SyncChain:
EPOCHS_PER_BATCH-sized by-range requests, sequential import, peer
scoring on bad batches) and sync/unknownBlock.ts (UnknownBlockSync:
fetch unknown parents by root, walk back to a known ancestor, import
forward).  Import goes through BeaconChain.process_block — the full
state transition, so a bad batch surfaces as a BlockProcessError the
same way the reference's processChainSegment rejects.
"""

from __future__ import annotations

import enum
from typing import Callable, List, Optional, Protocol, Sequence

from .. import params
from ..types import BeaconBlockAltair
from ..utils.logger import get_logger

P = params.ACTIVE_PRESET

# reference: EPOCHS_PER_BATCH = 1 (range/batch.ts) → one epoch per request
SLOTS_PER_BATCH = P.SLOTS_PER_EPOCH
MAX_PARENT_DEPTH = 32  # unknownBlock.ts walk-back bound


class BlockSource(Protocol):
    def get_blocks_by_range(
        self, start_slot: int, count: int
    ) -> List[dict]: ...

    def get_blocks_by_root(self, roots: Sequence[bytes]) -> List[dict]: ...


class SyncState(str, enum.Enum):
    stalled = "Stalled"
    syncing = "Syncing"
    synced = "Synced"


class RangeSync:
    """Pull batches from a source until the chain reaches target_slot."""

    def __init__(self, chain, batch_size: int = SLOTS_PER_BATCH):
        self.chain = chain
        self.batch_size = batch_size
        self.log = get_logger("sync/range")
        self.state = SyncState.stalled
        self.imported = 0
        self.failed_batches = 0

    def sync_to(self, source: BlockSource, target_slot: int) -> int:
        """Drive the chain head toward target_slot; returns blocks
        imported.  An empty batch is NOT a stall — it is a window of
        skip slots, and the cursor advances past it (reference
        range/batch.ts treats empty by-range responses as valid)."""
        self.state = SyncState.syncing
        imported_before = self.imported
        cursor = self.chain.head_state.slot + 1
        try:
            while cursor <= target_slot:
                count = min(self.batch_size, target_slot - cursor + 1)
                batch = source.get_blocks_by_range(cursor, count)
                for signed in batch:
                    self.chain.process_block(signed)
                    self.imported += 1
                cursor += count
        except Exception as e:  # bad batch: stop, report (peer scoring
            # is the transport layer's job in the reference)
            self.failed_batches += 1
            self.log.warn("batch import failed", error=str(e))
            self.state = SyncState.stalled
            raise
        # covered the whole range; synced if blocks actually arrived up
        # to the target's vicinity, stalled if the source was dry
        self.state = (
            SyncState.synced
            if self.imported > imported_before
            or self.chain.head_state.slot >= target_slot
            else SyncState.stalled
        )
        return self.imported - imported_before

    def status(self) -> dict:
        """The node API's syncing status shape (routes/node.ts)."""
        head_slot = self.chain.head_state.slot
        return {
            "head_slot": str(head_slot),
            "sync_distance": "0" if self.state == SyncState.synced else "1",
            "is_syncing": self.state == SyncState.syncing,
            "is_optimistic": False,
        }


class UnknownBlockSync:
    """Resolve a block whose parent chain is unknown: walk back by root
    to a known ancestor, then import forward."""

    def __init__(self, chain):
        self.chain = chain
        self.log = get_logger("sync/unknown-block")
        self.resolved = 0

    def on_unknown_block(self, source: BlockSource, root: bytes) -> int:
        chain_segment: List[dict] = []
        next_root = root
        for _ in range(MAX_PARENT_DEPTH):
            if self.chain.fork_choice.has_block(next_root.hex()):
                break  # found the known ancestor
            blocks = source.get_blocks_by_root([next_root])
            if not blocks:
                raise LookupError(
                    f"source has no block {next_root.hex()[:16]}"
                )
            signed = blocks[0]
            chain_segment.append(signed)
            next_root = signed["message"]["parent_root"]
        else:
            raise LookupError("parent chain exceeds walk-back bound")
        for signed in reversed(chain_segment):
            self.chain.process_block(signed)
            self.resolved += 1
        return len(chain_segment)
