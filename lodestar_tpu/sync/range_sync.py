"""RangeSync: batch state machine, multi-peer download, import overlap.

Reference: packages/beacon-node/src/sync/range/chain.ts (SyncChain:
EPOCHS_PER_BATCH-sized batches, batch buffer ahead of processing,
per-batch download/processing attempt tracking, peer rotation) and
sync/range/batch.ts (the batch state machine:
AwaitingDownload -> Downloading -> AwaitingProcessing -> Processing ->
AwaitingValidation, with maxDownloadAttempts/maxProcessingAttempts and
a record of which peers served failed attempts), plus
sync/unknownBlock.ts (UnknownBlockSync: fetch unknown parents by root,
walk back to a known ancestor, import forward).

Downloads run on worker threads while the caller thread imports
completed batches strictly in order — the reference's
download/processing overlap (chain.ts requestBatches vs processBatch)
without its event-loop framing.  Import goes through
BeaconChain.process_block — the full state transition, so a bad batch
surfaces as a BlockProcessError the same way the reference's
processChainSegment rejects.

Deneb: batches whose blocks carry blob commitments download the
matching sidecars (blob_sidecars_by_range), verify inclusion + KZG
proofs, and register availability with the chain before import — the
import-side DA gate is satisfied by the sync path itself.
"""

from __future__ import annotations

import enum
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Protocol, Sequence

from .. import params
from ..network.reqresp import (
    PeerDemotion,
    ReqRespTimeout,
    RetryPolicy,
    call_with_timeout,
)
from ..utils.logger import get_logger

P = params.ACTIVE_PRESET

# reference: EPOCHS_PER_BATCH = 1 (range/batch.ts) → one epoch per request
SLOTS_PER_BATCH = P.SLOTS_PER_EPOCH
MAX_PARENT_DEPTH = 32  # unknownBlock.ts walk-back bound
# reference: range/chain.ts BATCH_BUFFER_SIZE = 5 (downloads ahead of
# the processing cursor) and batch.ts MAX_BATCH_DOWNLOAD_ATTEMPTS = 5,
# MAX_BATCH_PROCESSING_ATTEMPTS = 3
BATCH_BUFFER_SIZE = 5
MAX_DOWNLOAD_ATTEMPTS = 5
MAX_PROCESSING_ATTEMPTS = 3


class BlockSource(Protocol):
    def get_blocks_by_range(
        self, start_slot: int, count: int
    ) -> List[dict]: ...

    def get_blocks_by_root(self, roots: Sequence[bytes]) -> List[dict]: ...

    # optional (deneb): sidecars for the same range
    # def get_blob_sidecars_by_range(self, start_slot, count) -> List[dict]


class SyncState(str, enum.Enum):
    stalled = "Stalled"
    syncing = "Syncing"
    synced = "Synced"


class BatchState(str, enum.Enum):
    """reference: batch.ts BatchStatus."""

    awaiting_download = "AwaitingDownload"
    downloading = "Downloading"
    awaiting_processing = "AwaitingProcessing"
    processing = "Processing"
    processed = "Processed"
    failed = "Failed"


class Batch:
    """One EPOCHS_PER_BATCH window of slots with attempt bookkeeping
    (reference: batch.ts Batch)."""

    def __init__(self, start_slot: int, count: int):
        self.start_slot = start_slot
        self.count = count
        self.state = BatchState.awaiting_download
        self.blocks: List[dict] = []
        self.sidecars: List[dict] = []
        self.download_attempts = 0
        self.processing_attempts = 0
        # peers that served attempts, in order — a retry prefers a peer
        # NOT on this list (batch.ts getFailedPeers)
        self.peers_tried: List[str] = []
        self.error: Optional[str] = None

    def failed_peers(self) -> set:
        return set(self.peers_tried)


class SyncChainError(Exception):
    pass


def verify_and_register_sidecar(chain, kzg_setup, sc, slot: int) -> None:
    """ONE sidecar through the sync-side validation (inclusion proof +
    optional KZG proof) into the chain's DA tracker — shared by the
    range and by-root paths so their verification can never diverge."""
    from ..chain import blobs as BL
    from ..crypto import kzg as K
    from ..types import BeaconBlockHeader

    body_type = chain.config.get_fork_types(slot)[2]
    if not BL.verify_blob_inclusion(sc, body_type):
        raise SyncChainError("sidecar inclusion proof invalid")
    if kzg_setup is not None and not K.verify_blob_kzg_proof(
        bytes(sc["blob"]),
        bytes(sc["kzg_commitment"]),
        bytes(sc["kzg_proof"]),
        kzg_setup,
    ):
        raise SyncChainError("sidecar KZG proof invalid")
    chain.on_blob_sidecar(
        BeaconBlockHeader.hash_tree_root(
            sc["signed_block_header"]["message"]
        ),
        int(sc["index"]),
        bytes(sc["kzg_commitment"]),
        slot=slot,
        sidecar=sc,
    )


class SyncChain:
    """Multi-peer batched sync toward a target slot.

    Peers register with their block sources; a downloader pool keeps up
    to `buffer_size` batches in flight ahead of the import cursor while
    the caller's thread imports strictly in order.  A failed download or
    import retries on a different peer; a batch exhausting its attempts
    fails the chain (reference: chain.ts SyncChain semantics).
    """

    def __init__(
        self,
        chain,
        start_slot: int,
        target_slot: int,
        batch_size: int = SLOTS_PER_BATCH,
        buffer_size: int = BATCH_BUFFER_SIZE,
        max_download_attempts: int = MAX_DOWNLOAD_ATTEMPTS,
        max_processing_attempts: int = MAX_PROCESSING_ATTEMPTS,
        kzg_setup=None,
        on_peer_fault: Optional[Callable[[str, str], None]] = None,
        download_timeout_s: Optional[float] = None,
        demotion: Optional[PeerDemotion] = None,
        retry_policy: Optional[RetryPolicy] = None,
        rng: Optional[random.Random] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self.chain = chain
        self.batch_size = batch_size
        self.buffer_size = buffer_size
        self.max_download_attempts = max_download_attempts
        self.max_processing_attempts = max_processing_attempts
        self.kzg_setup = kzg_setup
        self.on_peer_fault = on_peer_fault
        # timeout + demotion (ISSUE 14 satellite): a peer that stalls a
        # by-range request is abandoned after `download_timeout_s`,
        # demoted for a doubling cooldown, and the retry goes to a
        # DIFFERENT peer after a jittered backoff — never awaited
        # forever.  None = no deadline (in-process sources).
        self.download_timeout_s = download_timeout_s
        self.demotion = demotion or PeerDemotion()
        # NOTE: only the policy's backoff() schedule is used here — the
        # download loop bound is `max_download_attempts` (the batch
        # state machine's own counter), never RetryPolicy.attempts
        self.retry_policy = retry_policy or RetryPolicy()
        self._rng = rng or random.Random()
        self._sleep = sleep
        self.log = get_logger("sync/chain")
        self.peers: Dict[str, BlockSource] = {}
        self._peer_rr = 0  # round-robin cursor
        self.batches: List[Batch] = []
        slot = start_slot
        while slot <= target_slot:
            count = min(batch_size, target_slot - slot + 1)
            self.batches.append(Batch(slot, count))
            slot += count
        self.imported = 0
        self._lock = threading.Lock()

    # -- peers -------------------------------------------------------------

    def add_peer(self, peer_id: str, source: BlockSource) -> None:
        self.peers[peer_id] = source

    def remove_peer(self, peer_id: str) -> None:
        self.peers.pop(peer_id, None)

    def _pick_peer(self, batch: Batch) -> Optional[str]:
        """Round-robin over registered peers, preferring one that has
        not failed this batch (reference: chain.ts prefers idle peers
        not in batch.getFailedPeers) AND is not timeout-demoted — a
        demoted peer is only used when nothing healthier remains."""
        with self._lock:
            ids = list(self.peers)
            if not ids:
                return None
            failed = batch.failed_peers()
            fresh = [p for p in ids if p not in failed]
            healthy = [
                p for p in fresh if not self.demotion.is_demoted(p)
            ]
            pool = healthy or fresh or ids
            self._peer_rr += 1
            return pool[self._peer_rr % len(pool)]

    # -- download ----------------------------------------------------------

    def _download(self, batch: Batch) -> None:
        """One download attempt; runs on a worker thread."""
        peer = self._pick_peer(batch)
        if peer is None:
            batch.state = BatchState.awaiting_download
            return
        source = self.peers.get(peer)
        if source is None:
            batch.state = BatchState.awaiting_download
            return
        batch.download_attempts += 1
        batch.peers_tried.append(peer)

        def _fetch():
            blocks = source.get_blocks_by_range(
                batch.start_slot, batch.count
            )
            sidecars: List[dict] = []
            if any(
                b["message"].get("body", {}).get("blob_kzg_commitments")
                for b in blocks
            ):
                fetch = getattr(source, "get_blob_sidecars_by_range", None)
                if fetch is None:
                    raise SyncChainError(
                        f"peer {peer} serves deneb blocks but no blobs"
                    )
                sidecars = fetch(batch.start_slot, batch.count)
            return blocks, sidecars

        try:
            if self.download_timeout_s:
                blocks, sidecars = call_with_timeout(
                    _fetch,
                    self.download_timeout_s,
                    desc=f"by_range@{peer}[{batch.start_slot}]",
                )
            else:
                blocks, sidecars = _fetch()
            batch.blocks = blocks
            batch.sidecars = sidecars
            batch.state = BatchState.awaiting_processing
            self.demotion.restore(peer)
        except Exception as e:  # noqa: BLE001 — any download fault rotates
            self.log.warn(
                "batch download failed",
                start=batch.start_slot,
                peer=peer,
                error=str(e),
            )
            if isinstance(e, (ReqRespTimeout, TimeoutError)):
                # a stalling peer: demoted for a doubling cooldown so
                # the retry prefers someone else
                self.demotion.demote(peer)
            if self.on_peer_fault is not None:
                self.on_peer_fault(peer, f"download failed: {e}")
            if batch.download_attempts >= self.max_download_attempts:
                batch.state = BatchState.failed
                batch.error = f"download attempts exhausted: {e}"
            else:
                # jittered exponential backoff before the next attempt
                # (a flapping peer set must not busy-spin the workers)
                self._sleep(
                    self.retry_policy.backoff(
                        batch.download_attempts - 1, self._rng
                    )
                )
                batch.state = BatchState.awaiting_download

    def _schedule_downloads(self, cursor: int, threads: List) -> None:
        """Keep up to buffer_size batches past the cursor in flight."""
        window = self.batches[cursor : cursor + self.buffer_size]
        capacity = max(1, len(self.peers))
        active = sum(
            1 for b in window if b.state == BatchState.downloading
        )
        for batch in window:
            if active >= capacity:
                break
            if batch.state == BatchState.awaiting_download:
                batch.state = BatchState.downloading
                t = threading.Thread(
                    target=self._download, args=(batch,), daemon=True
                )
                t.start()
                threads.append(t)
                active += 1

    # -- blob verification (deneb sync path) -------------------------------

    def _register_batch_sidecars(self, batch: Batch) -> None:
        """Verify each downloaded sidecar (inclusion proof + KZG proof)
        and register availability so the import DA gate passes.  Header
        signatures are NOT re-checked here — the blocks themselves are
        fully verified at import, and the inclusion proof binds each
        sidecar to its block body (reference: sync imports check blob
        data against the block's own commitments)."""
        if not batch.sidecars:
            return
        for sc in batch.sidecars:
            slot = int(sc["signed_block_header"]["message"]["slot"])
            verify_and_register_sidecar(
                self.chain, self.kzg_setup, sc, slot
            )

    # -- the drive loop ----------------------------------------------------

    def run(self) -> int:
        """Download ahead + import in order until every batch lands or
        one fails permanently.  Returns blocks imported."""
        imported_before = self.imported
        threads: List = []
        cursor = 0  # next batch to import
        while cursor < len(self.batches):
            self._schedule_downloads(cursor, threads)
            batch = self.batches[cursor]
            if batch.state == BatchState.failed:
                raise SyncChainError(
                    f"batch @{batch.start_slot} failed: {batch.error}"
                )
            if batch.state != BatchState.awaiting_processing:
                # wait for the head batch's download to land; prune dead
                # threads so the list stays O(in-flight), not O(attempts)
                threads[:] = [t for t in threads if t.is_alive()]
                if not threads and batch.state in (
                    BatchState.awaiting_download,
                    BatchState.downloading,
                ):
                    # no worker will advance it: one inline attempt,
                    # then the loop re-evaluates.  A transient failure
                    # here is a normal retry (attempt accounting decides
                    # when to give up) — only a truly peerless chain
                    # aborts.
                    if not self.peers:
                        raise SyncChainError("no peers to sync from")
                    self._download(batch)
                else:
                    for t in threads[:1]:
                        t.join(timeout=5.0)
                continue
            batch.state = BatchState.processing
            batch.processing_attempts += 1
            try:
                self._register_batch_sidecars(batch)
                for signed in batch.blocks:
                    self.chain.process_block(signed)
                    self.imported += 1
                batch.state = BatchState.processed
                cursor += 1
            except Exception as e:  # noqa: BLE001 — a bad segment rotates
                peer = batch.peers_tried[-1] if batch.peers_tried else "?"
                self.log.warn(
                    "batch import failed",
                    start=batch.start_slot,
                    peer=peer,
                    error=str(e),
                )
                if self.on_peer_fault is not None:
                    self.on_peer_fault(peer, f"bad batch: {e}")
                if (
                    batch.processing_attempts
                    >= self.max_processing_attempts
                ):
                    batch.state = BatchState.failed
                    batch.error = f"processing attempts exhausted: {e}"
                    raise SyncChainError(
                        f"batch @{batch.start_slot} failed: {batch.error}"
                    ) from e
                # re-download from a different peer: the blocks may be
                # the problem, not just the import
                batch.blocks = []
                batch.sidecars = []
                batch.state = BatchState.awaiting_download
        return self.imported - imported_before


class RangeSync:
    """The sync facade: drive the chain toward a target via SyncChain.

    Accepts a single source (one implicit peer) or a {peer_id: source}
    mapping; state reporting matches the node API's syncing shape."""

    def __init__(
        self,
        chain,
        batch_size: int = SLOTS_PER_BATCH,
        kzg_setup=None,
        download_timeout_s: Optional[float] = None,
    ):
        self.chain = chain
        self.batch_size = batch_size
        self.kzg_setup = kzg_setup
        self.download_timeout_s = download_timeout_s
        # the demotion ledger outlives one SyncChain: a peer that
        # stalled the previous sync stays deprioritized for the next
        self.demotion = PeerDemotion()
        self.log = get_logger("sync/range")
        self.state = SyncState.stalled
        self.imported = 0
        self.failed_batches = 0
        self.on_peer_fault: Optional[Callable[[str, str], None]] = None

    def sync_to(self, source, target_slot: int) -> int:
        """Drive the chain head toward target_slot; returns blocks
        imported.  An empty batch is NOT a stall — it is a window of
        skip slots, and the cursor advances past it (reference
        range/batch.ts treats empty by-range responses as valid)."""
        self.state = SyncState.syncing
        start = self.chain.head_state.slot + 1
        if start > target_slot:
            self.state = SyncState.synced
            return 0
        sc = SyncChain(
            self.chain,
            start,
            target_slot,
            batch_size=self.batch_size,
            kzg_setup=self.kzg_setup,
            on_peer_fault=self.on_peer_fault,
            download_timeout_s=self.download_timeout_s,
            demotion=self.demotion,
        )
        if isinstance(source, dict):
            for peer_id, src in source.items():
                sc.add_peer(peer_id, src)
        else:
            sc.add_peer("peer-0", source)
        try:
            n = sc.run()
        except Exception as e:
            self.failed_batches += 1
            self.log.warn("range sync failed", error=str(e))
            self.state = SyncState.stalled
            raise
        self.imported += n
        self.state = (
            SyncState.synced
            if n > 0 or self.chain.head_state.slot >= target_slot
            else SyncState.stalled
        )
        return n

    def status(self) -> dict:
        """The node API's syncing status shape (routes/node.ts)."""
        head_slot = self.chain.head_state.slot
        return {
            "head_slot": str(head_slot),
            "sync_distance": "0" if self.state == SyncState.synced else "1",
            "is_syncing": self.state == SyncState.syncing,
            "is_optimistic": False,
        }


class UnknownBlockSync:
    """Resolve a block whose parent chain is unknown: walk back by root
    to a known ancestor, then import forward.  Deneb blocks in the
    segment fetch their sidecars by root (verified + registered) so the
    DA gate passes (reference: unknownBlock.ts fetches block inputs
    incl. blobs)."""

    def __init__(self, chain, kzg_setup=None):
        self.chain = chain
        self.kzg_setup = kzg_setup
        self.log = get_logger("sync/unknown-block")
        self.resolved = 0

    def _fetch_blobs(self, source, signed: dict, root: bytes) -> None:
        """`root` is the block root on_unknown_block fetched by — no
        rehash.  Skips the network when gossip already delivered the
        sidecars (the COMMON case for unknown-parent triggers)."""
        from ..types import BeaconBlockHeader

        block = signed["message"]
        commitments = block.get("body", {}).get("blob_kzg_commitments")
        if not commitments:
            return
        local = getattr(self.chain, "get_blob_sidecars", None)
        if local is not None:
            have = local(bytes(root))
            if have is not None and len(have) >= len(commitments):
                return  # gossip already registered this block's data
        fetch = getattr(source, "get_blob_sidecars_by_root", None)
        if fetch is None:
            raise LookupError(
                "deneb block needs sidecars but the source has no "
                "blob_sidecars_by_root"
            )
        slot = int(block["slot"])
        sidecars = fetch(
            [(bytes(root), i) for i in range(len(commitments))]
        )
        # response validation FIRST: a short answer or foreign sidecars
        # are a misbehaving peer, not a data-availability condition
        if len(sidecars) != len(commitments):
            raise LookupError(
                f"peer served {len(sidecars)}/{len(commitments)} sidecars"
            )
        for sc in sidecars:
            sc_root = BeaconBlockHeader.hash_tree_root(
                sc["signed_block_header"]["message"]
            )
            if bytes(sc_root) != bytes(root):
                raise LookupError(
                    "peer served a sidecar for a different block"
                )
        for sc in sidecars:
            try:
                verify_and_register_sidecar(
                    self.chain, self.kzg_setup, sc, slot
                )
            except SyncChainError as e:
                raise LookupError(str(e)) from e

    def on_unknown_block(self, source: BlockSource, root: bytes) -> int:
        chain_segment: List[tuple] = []  # (signed_block, its root)
        next_root = root
        for _ in range(MAX_PARENT_DEPTH):
            if self.chain.fork_choice.has_block(next_root.hex()):
                break  # found the known ancestor
            blocks = source.get_blocks_by_root([next_root])
            if not blocks:
                raise LookupError(
                    f"source has no block {next_root.hex()[:16]}"
                )
            signed = blocks[0]
            chain_segment.append((signed, bytes(next_root)))
            next_root = signed["message"]["parent_root"]
        else:
            raise LookupError("parent chain exceeds walk-back bound")
        for signed, blk_root in reversed(chain_segment):
            self._fetch_blobs(source, signed, blk_root)
            self.chain.process_block(signed)
            self.resolved += 1
        return len(chain_segment)
