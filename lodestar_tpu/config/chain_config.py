"""ChainConfig — runtime fork schedule + domain computation.

Reference: packages/config/src/chainConfig/ (fork versions/epochs per
network), config/src/forkConfig/index.ts (getForkInfo/getForkName),
config/src/genesisConfig/ (cached domains per fork).  Domain bytes follow
the consensus spec: compute_fork_data_root(version, genesis_validators_
root)[:28] appended to the 4-byte domain type.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from .. import params
from ..params import ForkName
from ..ssz import Bytes4, Bytes32, Container

# ForkData (consensus spec) for fork-data-root computation
ForkDataType = Container(
    (
        ("current_version", Bytes4),
        ("genesis_validators_root", Bytes32),
    ),
    name="ForkData",
)

SigningDataType = Container(
    (
        ("object_root", Bytes32),
        ("domain", Bytes32),
    ),
    name="SigningData",
)


@dataclass
class ChainConfig:
    """Fork schedule + genesis info for one chain."""

    config_name: str
    genesis_validators_root: bytes = b"\x00" * 32
    genesis_time: int = 0
    # version/epoch per fork, in FORK_ORDER
    fork_versions: Dict[ForkName, bytes] = field(default_factory=dict)
    fork_epochs: Dict[ForkName, int] = field(default_factory=dict)
    # Runtime (non-preset) spec values — reference keeps these in
    # chainConfig/presets/{mainnet,minimal}.ts rather than the preset.
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY: int = 256
    SHARD_COMMITTEE_PERIOD: int = 256
    INACTIVITY_SCORE_BIAS: int = 4
    INACTIVITY_SCORE_RECOVERY_RATE: int = 16
    MIN_PER_EPOCH_CHURN_LIMIT: int = 4
    CHURN_LIMIT_QUOTIENT: int = 65536
    # the eth1 deposit contract (reference: chainConfig DEPOSIT_CHAIN_ID
    # / DEPOSIT_CONTRACT_ADDRESS; served by /eth/v1/config/
    # deposit_contract).  Mainnet values by default.
    DEPOSIT_CHAIN_ID: int = 1
    DEPOSIT_CONTRACT_ADDRESS: str = (
        "0x00000000219ab540356cbb839cbe05303d7705fa"
    )

    def __post_init__(self):
        self._domain_cache: Dict[Tuple[bytes, bytes], bytes] = {}

    # -- fork schedule (reference: forkConfig/index.ts) --------------------

    def fork_schedule(self) -> List[ForkName]:
        return [
            f
            for f in params.FORK_ORDER
            if self.fork_epochs.get(f, params.FAR_FUTURE_EPOCH)
            != params.FAR_FUTURE_EPOCH
        ]

    def get_fork_name(self, slot: int) -> ForkName:
        epoch = max(slot, 0) // params.SLOTS_PER_EPOCH
        active = ForkName.phase0
        for f in params.FORK_ORDER:
            if self.fork_epochs.get(f, params.FAR_FUTURE_EPOCH) <= epoch:
                active = f
        return active

    def get_fork_types(self, slot: int):
        """(block, signed_block, body) SSZ containers for the fork at
        `slot` (reference: config.getForkTypes — the ONE fork->type
        dispatch every serializer/signer/hasher must use)."""
        from .. import types as T

        name = self.get_fork_name(slot)
        if name == ForkName.phase0:
            return T.BeaconBlock, T.SignedBeaconBlock, T.BeaconBlockBody
        if name == ForkName.altair:
            return (
                T.BeaconBlockAltair,
                T.SignedBeaconBlockAltair,
                T.BeaconBlockBodyAltair,
            )
        if name == ForkName.bellatrix:
            return (
                T.BeaconBlockBellatrix,
                T.SignedBeaconBlockBellatrix,
                T.BeaconBlockBodyBellatrix,
            )
        if name == ForkName.capella:
            return (
                T.BeaconBlockCapella,
                T.SignedBeaconBlockCapella,
                T.BeaconBlockBodyCapella,
            )
        return (
            T.BeaconBlockDeneb,
            T.SignedBeaconBlockDeneb,
            T.BeaconBlockBodyDeneb,
        )

    def get_blinded_fork_types(self, slot: int):
        """(blinded_block, signed_blinded_block, blinded_body) for the
        fork at `slot` (reference: config.getBlindedForkTypes).  Blinded
        containers exist from bellatrix on."""
        from .. import types as T

        name = self.get_fork_name(slot)
        if name in (ForkName.phase0, ForkName.altair):
            raise ValueError(f"no blinded containers before bellatrix ({name})")
        if name == ForkName.bellatrix:
            return (
                T.BlindedBeaconBlockBellatrix,
                T.SignedBlindedBeaconBlockBellatrix,
                T.BlindedBeaconBlockBodyBellatrix,
            )
        if name == ForkName.capella:
            return (
                T.BlindedBeaconBlockCapella,
                T.SignedBlindedBeaconBlockCapella,
                T.BlindedBeaconBlockBodyCapella,
            )
        return (
            T.BlindedBeaconBlockDeneb,
            T.SignedBlindedBeaconBlockDeneb,
            T.BlindedBeaconBlockBodyDeneb,
        )

    def get_fork_seq(self, slot: int) -> int:
        return params.FORK_SEQ[self.get_fork_name(slot)]

    def get_fork_version(self, slot: int) -> bytes:
        return self.fork_versions[self.get_fork_name(slot)]

    # -- domains (consensus spec compute_domain) ---------------------------

    def fork_data_root(self, version: bytes, genesis_validators_root=None) -> bytes:
        gvr = (
            self.genesis_validators_root
            if genesis_validators_root is None
            else genesis_validators_root
        )
        return ForkDataType.hash_tree_root(
            {"current_version": version, "genesis_validators_root": gvr}
        )

    def fork_digest(self, slot: int) -> bytes:
        """4-byte gossip fork digest (reference: forkConfig getForkDigest)."""
        return self.fork_data_root(self.get_fork_version(slot))[:4]

    def get_domain(
        self, state_slot: int, domain_type: bytes, message_slot: int = None
    ) -> bytes:
        """Domain at the fork active at `message_slot` (defaults to
        state_slot) — signature domains use the message's fork, matching
        the reference's config.getDomain(stateSlot, domainType, slot)."""
        slot = state_slot if message_slot is None else message_slot
        version = self.get_fork_version(slot)
        key = (domain_type, version)
        d = self._domain_cache.get(key)
        if d is None:
            d = domain_type + self.fork_data_root(version)[:28]
            self._domain_cache[key] = d
        return d

    def compute_domain(
        self,
        domain_type: bytes,
        fork_version: bytes,
        genesis_validators_root: bytes = None,
    ) -> bytes:
        """Domain pinned to an explicit fork version (spec compute_domain;
        used by fork-agnostic signatures: deposits, BLS-to-execution
        changes, and post-EIP-7044 voluntary exits)."""
        return (
            domain_type
            + self.fork_data_root(fork_version, genesis_validators_root)[:28]
        )

    def compute_signing_root(self, object_root: bytes, domain: bytes) -> bytes:
        """hash_tree_root(SigningData(object_root, domain)) — the 32-byte
        message every BLS signature in the protocol actually signs."""
        return SigningDataType.hash_tree_root(
            {"object_root": object_root, "domain": domain}
        )


MAINNET_CHAIN_CONFIG = ChainConfig(
    config_name="mainnet",
    genesis_validators_root=bytes.fromhex(
        "4b363db94e286120d76eb905340fdd4e54bfe9f06bf33ff6cf5ad27f511bfe95"
    ),
    genesis_time=1606824023,
    fork_versions={
        ForkName.phase0: bytes.fromhex("00000000"),
        ForkName.altair: bytes.fromhex("01000000"),
        ForkName.bellatrix: bytes.fromhex("02000000"),
        ForkName.capella: bytes.fromhex("03000000"),
        ForkName.deneb: bytes.fromhex("04000000"),
    },
    fork_epochs={
        ForkName.phase0: 0,
        ForkName.altair: 74240,
        ForkName.bellatrix: 144896,
        ForkName.capella: 194048,
        ForkName.deneb: params.FAR_FUTURE_EPOCH,
    },
)

MINIMAL_CHAIN_CONFIG = ChainConfig(
    config_name="minimal",
    SHARD_COMMITTEE_PERIOD=64,
    CHURN_LIMIT_QUOTIENT=32,
    fork_versions={
        ForkName.phase0: bytes.fromhex("00000001"),
        ForkName.altair: bytes.fromhex("01000001"),
        ForkName.bellatrix: bytes.fromhex("02000001"),
        ForkName.capella: bytes.fromhex("03000001"),
        ForkName.deneb: bytes.fromhex("04000001"),
    },
    fork_epochs={
        ForkName.phase0: 0,
        ForkName.altair: 0,
        ForkName.bellatrix: params.FAR_FUTURE_EPOCH,
        ForkName.capella: params.FAR_FUTURE_EPOCH,
        ForkName.deneb: params.FAR_FUTURE_EPOCH,
    },
)


def create_chain_config(
    base: ChainConfig,
    genesis_validators_root: bytes = None,
    genesis_time: int = None,
    fork_epochs: Dict[ForkName, int] = None,
) -> ChainConfig:
    """Derive a config (the reference's createBeaconConfig: chain config +
    genesis validators root -> cached domains)."""
    import dataclasses

    return dataclasses.replace(
        base,
        genesis_validators_root=(
            base.genesis_validators_root
            if genesis_validators_root is None
            else genesis_validators_root
        ),
        genesis_time=base.genesis_time if genesis_time is None else genesis_time,
        fork_versions=dict(base.fork_versions),
        fork_epochs={**base.fork_epochs, **(fork_epochs or {})},
    )
