"""Chain config: fork schedule, domains, fork digests.

Mirror of the reference's `@lodestar/config` (reference:
packages/config/src/beaconConfig.ts, config/src/forkConfig/index.ts,
config/src/chainConfig/): a runtime ChainConfig (fork versions/epochs,
genesis validators root) layered on the compile-time preset, exposing

    get_fork_name(slot)   — active fork at a slot
    get_domain(...)       — 32-byte signature domain (fork version mixed
                            with the genesis validators root)
    fork_digest(...)      — 4-byte gossip topic digest

Domain/digest math follows the consensus spec compute_domain /
compute_fork_data_root (the reference delegates to @lodestar/state-
transition util/domain.ts for the same).
"""

from .chain_config import (  # noqa: F401
    ChainConfig,
    MAINNET_CHAIN_CONFIG,
    MINIMAL_CHAIN_CONFIG,
    create_chain_config,
)
