"""flare — operator debug tool: intentionally self-slash test validators.

Mirror of the reference's packages/flare (cmds/selfSlashProposer.ts,
cmds/selfSlashAttester.ts): sign two conflicting messages with a
validator's own key and submit the resulting slashing object to a
beacon node's pool, to exercise the slashing pipeline end to end on
devnets.  Signing here deliberately bypasses the ValidatorStore's
slashing protection — producing the slashable pair IS the tool's job.
"""

from __future__ import annotations

from typing import List

from . import params
from . import types as T
from .config.chain_config import ChainConfig
from .crypto import bls as B


def make_proposer_slashing(
    config: ChainConfig, sk: int, proposer_index: int, slot: int
) -> dict:
    """Two different headers for the same slot, both validly signed."""

    def _signed(body_root: bytes) -> dict:
        header = {
            "slot": slot,
            "proposer_index": proposer_index,
            "parent_root": b"\x00" * 32,
            "state_root": b"\x00" * 32,
            "body_root": body_root,
        }
        root = config.compute_signing_root(
            T.BeaconBlockHeader.hash_tree_root(header),
            config.get_domain(slot, params.DOMAIN_BEACON_PROPOSER, slot),
        )
        return {"message": header, "signature": B.sign_bytes(sk, root)}

    return {
        "signed_header_1": _signed(b"\x01" * 32),
        "signed_header_2": _signed(b"\x02" * 32),
    }


def make_attester_slashing(
    config: ChainConfig,
    sks: List[int],
    indices: List[int],
    target_epoch: int,
) -> dict:
    """A double vote: same target epoch, different beacon block roots."""

    def _signed(block_root: bytes) -> dict:
        data = {
            "slot": target_epoch * params.SLOTS_PER_EPOCH,
            "index": 0,
            "beacon_block_root": block_root,
            "source": {"epoch": max(target_epoch - 1, 0), "root": b"\x00" * 32},
            "target": {"epoch": target_epoch, "root": block_root},
        }
        root = config.compute_signing_root(
            T.AttestationData.hash_tree_root(data),
            config.get_domain(
                data["slot"], params.DOMAIN_BEACON_ATTESTER, data["slot"]
            ),
        )
        sig = B.aggregate_signatures([B.sign(sk, root) for sk in sks])
        from .crypto import curves as C

        return {
            "attesting_indices": sorted(indices),
            "data": data,
            "signature": C.g2_compress(sig),
        }

    return {
        "attestation_1": _signed(b"\x0a" * 32),
        "attestation_2": _signed(b"\x0b" * 32),
    }


def self_slash_proposer(
    config: ChainConfig, api, sk: int, proposer_index: int, slot: int
) -> dict:
    slashing = make_proposer_slashing(config, sk, proposer_index, slot)
    api.submit_proposer_slashing(slashing)
    return slashing


def self_slash_attester(
    config: ChainConfig,
    api,
    sks: List[int],
    indices: List[int],
    target_epoch: int,
) -> dict:
    slashing = make_attester_slashing(config, sks, indices, target_epoch)
    api.submit_attester_slashing(slashing)
    return slashing
