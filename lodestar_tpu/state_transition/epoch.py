"""Epoch transition — altair, fully vectorized over the registry.

Reference: packages/state-transition/src/epoch/index.ts (processEpoch
order), epoch/processJustificationAndFinalization.ts,
processInactivityUpdates.ts, processRewardsAndPenalties.ts +
getRewardsAndPenalties.ts, processRegistryUpdates.ts,
processSlashings.ts, processEffectiveBalanceUpdates.ts,
processSyncCommitteeUpdates.ts, and cache/epochProcess.ts
(beforeProcessEpoch: the one-pass precomputation).

The reference walks the registry in JS loops with packed status flags
(epochProcess.ts `FLAG_*` bitmasks); here the same dataflow is numpy
column arithmetic — every per-validator rule is a masked vector
expression, so a 1M-validator epoch transition is ~30 array passes with
no Python-level loop (the only loops left are the rare sequential
queues: activations and exits, bounded by the churn limit).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .. import params
from ..types import HistoricalBatch
from .accessors import (
    active_mask,
    get_block_root,
    get_next_sync_committee,
    get_randao_mix,
    get_total_active_balance,
    get_validator_churn_limit,
    integer_squareroot,
)
from .util import compute_activation_exit_epoch, compute_epoch_at_slot

P = params.ACTIVE_PRESET
FAR_FUTURE = params.FAR_FUTURE_EPOCH
_I64 = np.int64


class EpochTransitionCache:
    """beforeProcessEpoch analog: shared per-epoch precomputation."""

    def __init__(self, state):
        self.current_epoch = compute_epoch_at_slot(state.slot)
        self.previous_epoch = max(self.current_epoch - 1, params.GENESIS_EPOCH)
        self.active_current = active_mask(state, self.current_epoch)
        self.active_previous = active_mask(state, self.previous_epoch)
        self.total_active_balance = get_total_active_balance(state)
        # spec get_eligible_validator_indices
        self.eligible = self.active_previous | (
            state.slashed
            & (self.previous_epoch + 1 < state.withdrawable_epoch)
        )
        # unslashed & participating masks per flag, for both epochs
        prev = state.previous_epoch_participation
        curr = state.current_epoch_participation
        self.prev_flag = [
            self.active_previous
            & (~state.slashed)
            & ((prev >> np.uint8(f)) & np.uint8(1)).astype(bool)
            for f in range(3)
        ]
        self.curr_flag = [
            self.active_current
            & (~state.slashed)
            & ((curr >> np.uint8(f)) & np.uint8(1)).astype(bool)
            for f in range(3)
        ]

    def participating_balance(self, state, mask) -> int:
        total = int(state.effective_balance[mask].sum())
        return max(P.EFFECTIVE_BALANCE_INCREMENT, total)

    def is_in_inactivity_leak(self, state) -> bool:
        finality_delay = self.previous_epoch - int(
            state.finalized_checkpoint["epoch"]
        )
        return finality_delay > P.MIN_EPOCHS_TO_INACTIVITY_PENALTY


# -- 1. justification & finalization ---------------------------------------


def process_justification_and_finalization(
    state, cache: EpochTransitionCache
) -> None:
    if cache.current_epoch <= params.GENESIS_EPOCH + 1:
        return
    prev_target = cache.participating_balance(
        state, cache.prev_flag[params.TIMELY_TARGET_FLAG_INDEX]
    )
    curr_target = cache.participating_balance(
        state, cache.curr_flag[params.TIMELY_TARGET_FLAG_INDEX]
    )
    weigh_justification_and_finalization(
        state, cache, cache.total_active_balance, prev_target, curr_target
    )


def weigh_justification_and_finalization(
    state,
    cache: EpochTransitionCache,
    total_balance: int,
    previous_target_balance: int,
    current_target_balance: int,
) -> None:
    previous_epoch = cache.previous_epoch
    current_epoch = cache.current_epoch
    old_previous_justified = dict(state.previous_justified_checkpoint)
    old_current_justified = dict(state.current_justified_checkpoint)

    state.previous_justified_checkpoint = dict(
        state.current_justified_checkpoint
    )
    bits = state.justification_bits
    state.justification_bits = [False] + bits[:-1]

    if previous_target_balance * 3 >= total_balance * 2:
        state.current_justified_checkpoint = {
            "epoch": previous_epoch,
            "root": get_block_root(state, previous_epoch),
        }
        state.justification_bits[1] = True
    if current_target_balance * 3 >= total_balance * 2:
        state.current_justified_checkpoint = {
            "epoch": current_epoch,
            "root": get_block_root(state, current_epoch),
        }
        state.justification_bits[0] = True

    bits = state.justification_bits
    # 2nd/3rd/4th most recent epochs justified → finalize accordingly
    if all(bits[1:4]) and old_previous_justified["epoch"] + 3 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[1:3]) and old_previous_justified["epoch"] + 2 == current_epoch:
        state.finalized_checkpoint = old_previous_justified
    if all(bits[0:3]) and old_current_justified["epoch"] + 2 == current_epoch:
        state.finalized_checkpoint = old_current_justified
    if all(bits[0:2]) and old_current_justified["epoch"] + 1 == current_epoch:
        state.finalized_checkpoint = old_current_justified


# -- 2. inactivity scores ---------------------------------------------------


def process_inactivity_updates(state, cache: EpochTransitionCache) -> None:
    if cache.current_epoch == params.GENESIS_EPOCH:
        return
    scores = state.inactivity_scores.astype(_I64)
    eligible = cache.eligible
    target_participant = cache.prev_flag[params.TIMELY_TARGET_FLAG_INDEX]
    bias = state.config.INACTIVITY_SCORE_BIAS
    recovery = state.config.INACTIVITY_SCORE_RECOVERY_RATE

    delta = np.where(
        target_participant, -np.minimum(scores, 1), _I64(bias)
    )
    if not cache.is_in_inactivity_leak(state):
        post = scores + delta
        delta = delta - np.minimum(post, _I64(recovery))
    scores = scores + np.where(eligible, delta, _I64(0))
    state.inactivity_scores = np.maximum(scores, 0).astype(np.uint64)


# -- 3. rewards & penalties -------------------------------------------------


def get_flag_index_deltas(
    state, cache: EpochTransitionCache, flag_index: int
):
    """Vectorized spec get_flag_index_deltas → (rewards, penalties) i64."""
    n = state.num_validators
    rewards = np.zeros(n, _I64)
    penalties = np.zeros(n, _I64)
    weight = params.PARTICIPATION_FLAG_WEIGHTS[flag_index]
    unslashed_participating = cache.prev_flag[flag_index]
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    participating_increments = (
        cache.participating_balance(state, unslashed_participating)
        // increment
    )
    active_increments = cache.total_active_balance // increment
    base_reward = get_base_rewards(state, cache)

    eligible = cache.eligible
    in_leak = cache.is_in_inactivity_leak(state)
    participate = eligible & unslashed_participating
    if not in_leak:
        reward_numerator = (
            base_reward * _I64(weight) * _I64(participating_increments)
        )
        rewards = np.where(
            participate,
            reward_numerator
            // _I64(active_increments * params.WEIGHT_DENOMINATOR),
            _I64(0),
        )
    if flag_index != params.TIMELY_HEAD_FLAG_INDEX:
        penalties = np.where(
            eligible & ~unslashed_participating,
            base_reward * _I64(weight) // _I64(params.WEIGHT_DENOMINATOR),
            _I64(0),
        )
    return rewards, penalties


def get_inactivity_penalty_deltas(state, cache: EpochTransitionCache):
    n = state.num_validators
    penalties = np.zeros(n, _I64)
    target = cache.prev_flag[params.TIMELY_TARGET_FLAG_INDEX]
    mask = cache.eligible & ~target
    numerator = state.effective_balance.astype(_I64) * state.inactivity_scores.astype(
        _I64
    )
    denominator = (
        state.config.INACTIVITY_SCORE_BIAS
        * P.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    )
    penalties = np.where(mask, numerator // _I64(denominator), _I64(0))
    return np.zeros(n, _I64), penalties


def get_base_rewards(state, cache: EpochTransitionCache) -> np.ndarray:
    """Per-validator get_base_reward as one vector."""
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    base_reward_per_increment = (
        increment
        * P.BASE_REWARD_FACTOR
        // integer_squareroot(cache.total_active_balance)
    )
    return (state.effective_balance.astype(_I64) // _I64(increment)) * _I64(
        base_reward_per_increment
    )


def process_rewards_and_penalties(state, cache: EpochTransitionCache) -> None:
    if cache.current_epoch == params.GENESIS_EPOCH:
        return
    n = state.num_validators
    rewards = np.zeros(n, _I64)
    penalties = np.zeros(n, _I64)
    for flag_index in range(len(params.PARTICIPATION_FLAG_WEIGHTS)):
        r, p = get_flag_index_deltas(state, cache, flag_index)
        rewards += r
        penalties += p
    r, p = get_inactivity_penalty_deltas(state, cache)
    rewards += r
    penalties += p
    balances = state.balances.astype(_I64) + rewards - penalties
    state.balances = np.maximum(balances, 0).astype(np.uint64)


# -- 4. registry updates ----------------------------------------------------


def initiate_validator_exit(state, index: int) -> None:
    """Spec initiate_validator_exit (sequential; exits are churn-rare)."""
    if int(state.exit_epoch[index]) != FAR_FUTURE:
        return
    exiting = state.exit_epoch[state.exit_epoch != np.uint64(FAR_FUTURE)]
    activation_exit = compute_activation_exit_epoch(
        compute_epoch_at_slot(state.slot)
    )
    exit_queue_epoch = max(
        int(exiting.max()) if len(exiting) else 0, activation_exit
    )
    exit_queue_churn = int((exiting == np.uint64(exit_queue_epoch)).sum())
    if exit_queue_churn >= get_validator_churn_limit(state):
        exit_queue_epoch += 1
    state.exit_epoch[index] = exit_queue_epoch
    state.withdrawable_epoch[index] = (
        exit_queue_epoch + state.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
    )


def process_registry_updates(state, cache: EpochTransitionCache) -> None:
    current_epoch = cache.current_epoch
    # eligibility for activation queue
    newly_eligible = (
        state.activation_eligibility_epoch == np.uint64(FAR_FUTURE)
    ) & (state.effective_balance == np.uint64(P.MAX_EFFECTIVE_BALANCE))
    state.activation_eligibility_epoch[newly_eligible] = current_epoch + 1

    # ejections
    eject = cache.active_current & (
        state.effective_balance <= np.uint64(P.EJECTION_BALANCE)
    )
    for idx in np.nonzero(eject)[0]:
        initiate_validator_exit(state, int(idx))

    # activation queue: eligible & not yet activated, finalized eligibility
    finalized_epoch = int(state.finalized_checkpoint["epoch"])
    queue_mask = (
        (state.activation_eligibility_epoch <= np.uint64(finalized_epoch))
        & (state.activation_epoch == np.uint64(FAR_FUTURE))
    )
    queue = np.nonzero(queue_mask)[0]
    if len(queue):
        order = np.lexsort(
            (queue, state.activation_eligibility_epoch[queue])
        )
        from .accessors import get_validator_activation_churn_limit

        churn = get_validator_activation_churn_limit(state)
        dequeued = queue[order][:churn]
        state.activation_epoch[dequeued] = compute_activation_exit_epoch(
            current_epoch
        )


# -- 5. slashings -----------------------------------------------------------


def process_slashings(state, cache: EpochTransitionCache) -> None:
    epoch = cache.current_epoch
    total_balance = cache.total_active_balance
    adjusted_total = min(
        int(state.slashings.sum())
        * P.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
        total_balance,
    )
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    target_withdrawable = epoch + P.EPOCHS_PER_SLASHINGS_VECTOR // 2
    mask = state.slashed & (
        state.withdrawable_epoch == np.uint64(target_withdrawable)
    )
    if not mask.any():
        return
    # penalty_numerator // total_balance * increment, per spec rounding
    numerator = (
        state.effective_balance.astype(object) // increment
    ) * adjusted_total
    penalty = numerator // total_balance * increment
    for idx in np.nonzero(mask)[0]:
        state.decrease_balance(int(idx), int(penalty[idx]))


# -- 6-12. resets & rotations ----------------------------------------------


def process_eth1_data_reset(state, cache: EpochTransitionCache) -> None:
    next_epoch = cache.current_epoch + 1
    if next_epoch % P.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(
    state, cache: EpochTransitionCache
) -> None:
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    hysteresis_increment = increment // P.HYSTERESIS_QUOTIENT
    downward = hysteresis_increment * P.HYSTERESIS_DOWNWARD_MULTIPLIER
    upward = hysteresis_increment * P.HYSTERESIS_UPWARD_MULTIPLIER
    balances = state.balances.astype(_I64)
    eff = state.effective_balance.astype(_I64)
    update = (balances + downward < eff) | (eff + upward < balances)
    new_eff = np.minimum(
        balances - balances % increment, P.MAX_EFFECTIVE_BALANCE
    )
    state.effective_balance = np.where(update, new_eff, eff).astype(np.uint64)


def process_slashings_reset(state, cache: EpochTransitionCache) -> None:
    next_epoch = cache.current_epoch + 1
    state.slashings[next_epoch % P.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(state, cache: EpochTransitionCache) -> None:
    current_epoch = cache.current_epoch
    next_epoch = current_epoch + 1
    state.randao_mixes[next_epoch % P.EPOCHS_PER_HISTORICAL_VECTOR] = (
        get_randao_mix(state, current_epoch)
    )


def process_historical_roots_update(
    state, cache: EpochTransitionCache
) -> None:
    next_epoch = cache.current_epoch + 1
    if next_epoch % (P.SLOTS_PER_HISTORICAL_ROOT // P.SLOTS_PER_EPOCH) == 0:
        if state.historical_summaries is not None:
            # capella (process_historical_summaries_update): summarize the
            # two root vectors separately so light proofs need no batch
            from ..ssz import Vector as _Vec
            from ..types import Root as _Root

            vec = _Vec(_Root, P.SLOTS_PER_HISTORICAL_ROOT)
            state.historical_summaries.append(
                {
                    "block_summary_root": vec.hash_tree_root(
                        list(state.block_roots)
                    ),
                    "state_summary_root": vec.hash_tree_root(
                        list(state.state_roots)
                    ),
                }
            )
        else:
            state.historical_roots.append(
                HistoricalBatch.hash_tree_root(
                    {
                        "block_roots": list(state.block_roots),
                        "state_roots": list(state.state_roots),
                    }
                )
            )


def process_participation_flag_updates(
    state, cache: EpochTransitionCache
) -> None:
    engine = getattr(state, "_root_engine", None)
    if engine is not None:
        # swap the incremental merkle caches with the rotation so the
        # previous-epoch field diffs clean against what current held; a
        # missing/wrong hint only costs extra hashing (state_root.py)
        engine.note_participation_rotation()
    state.previous_epoch_participation = state.current_epoch_participation
    state.current_epoch_participation = np.zeros(
        state.num_validators, np.uint8
    )


def process_sync_committee_updates(
    state, cache: EpochTransitionCache
) -> None:
    next_epoch = cache.current_epoch + 1
    if next_epoch % P.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        state.next_sync_committee = get_next_sync_committee(state)


# -- entry ------------------------------------------------------------------


def compute_unrealized_checkpoints(state) -> Dict[str, Dict]:
    """Pulled-up justification: the checkpoints the chain WOULD realize
    if the epoch transition ran right after this state's latest block
    (reference: state-transition/src/epoch/computeUnrealizedCheckpoints.ts:15).

    Runs justification-and-finalization on a clone; the fork-choice
    stores the result per node for the prev-epoch viability filter."""
    epoch = compute_epoch_at_slot(state.slot)
    if epoch <= params.GENESIS_EPOCH + 1:
        return {
            "justified": dict(state.current_justified_checkpoint),
            "finalized": dict(state.finalized_checkpoint),
        }
    # weigh_justification_and_finalization touches exactly four fields;
    # save/restore them instead of deep-cloning the whole registry —
    # this runs in the per-block import hot path
    saved = (
        dict(state.previous_justified_checkpoint),
        dict(state.current_justified_checkpoint),
        list(state.justification_bits),
        dict(state.finalized_checkpoint),
    )
    try:
        process_justification_and_finalization(
            state, EpochTransitionCache(state)
        )
        return {
            "justified": dict(state.current_justified_checkpoint),
            "finalized": dict(state.finalized_checkpoint),
        }
    finally:
        (
            state.previous_justified_checkpoint,
            state.current_justified_checkpoint,
            state.justification_bits,
            state.finalized_checkpoint,
        ) = saved


def process_epoch(state) -> Dict:
    """Run the full altair epoch transition in spec order; returns the
    cache for callers that want the precomputed masks (regen metrics)."""
    cache = EpochTransitionCache(state)
    process_justification_and_finalization(state, cache)
    process_inactivity_updates(state, cache)
    process_rewards_and_penalties(state, cache)
    process_registry_updates(state, cache)
    process_slashings(state, cache)
    process_eth1_data_reset(state, cache)
    process_effective_balance_updates(state, cache)
    process_slashings_reset(state, cache)
    process_randao_mixes_reset(state, cache)
    process_historical_roots_update(state, cache)
    process_participation_flag_updates(state, cache)
    process_sync_committee_updates(state, cache)
    return {"cache": cache}
