"""Incremental BeaconState merkleization — the per-slot state-root engine.

`BeaconState.hash_tree_root()` used to materialize the columnar state
into Python lists (`to_value()`) and recursively re-hash ALL of it —
O(state size) per slot, dominated by the per-validator lists.  The
reference never pays that: its ViewDU states keep a persistent merkle
tree and re-hash only dirty nodes (`@chainsafe/persistent-merkle-tree`
+ `as-sha256` level batching, SURVEY.md §2.3).  This module is the
struct-of-arrays equivalent:

  - the big per-validator fields (`validators`, `balances`,
    `inactivity_scores`, both participation arrays) and the big root
    vectors (`block_roots`, `state_roots`, `randao_mixes`, `slashings`)
    each own a `ChunkTree` (ssz/merkle_tree.py) whose leaf planes are
    packed STRAIGHT from the numpy columns — the hot path never calls
    `to_value()`;
  - dirty tracking is CONSERVATIVE by construction: a chunk re-hashes
    iff its packed bytes differ from the plane the tree last hashed, so
    an untracked mutation can cost extra hashing but can never yield a
    stale root (the invariant every mutation-surface change must keep);
  - every other field memoizes (serialized bytes -> root): serializing
    a sync committee or the eth1 vote list is memcpy-cheap next to
    re-hashing it, and a byte-equal serialization proves the cached
    root is current;
  - `clone()` shares the whole engine copy-on-write
    (state_transition's pre->post clone, regen replay, checkpoint
    states and block production all inherit warm trees for free).

The cold path (first hash of a deserialized state) costs one full
merkleization — the same work `to_value()`-based hashing paid every
slot — and every later root is O(touched validators · log n).
`LODESTAR_TPU_HTR=full` restores the old full recompute;
`LODESTAR_TPU_HTR=check` runs both and asserts bit-identity.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import params
from ..ssz import ChunkTree, hash_pairs_plane, merkleize_chunks
from ..ssz.core import _mix_in_length

P = params.ACTIVE_PRESET
_U8 = np.uint8


def _htr_device():
    """The opt-in device merkleization backend (None = PR 3 host path).
    Imported lazily — the state-root engine must stay importable on
    hosts without jax."""
    from ..ssz import device_backend

    return device_backend.maybe_backend()

# numeric validator-record columns in Validator-container chunk order
_VAL_COLS = (
    ("effective_balance", 2),
    ("activation_eligibility_epoch", 4),
    ("activation_epoch", 5),
    ("exit_epoch", 6),
    ("withdrawable_epoch", 7),
)


def _pack_u64(arr: np.ndarray) -> np.ndarray:
    """uint64 column -> (nchunks, 32) little-endian leaf plane (copy)."""
    n = arr.shape[0]
    out = np.zeros(((n + 3) // 4, 32), _U8)
    if n:
        raw = np.ascontiguousarray(arr, dtype="<u8").view(_U8)
        out.reshape(-1)[: raw.size] = raw
    return out


def _pack_u8(arr: np.ndarray) -> np.ndarray:
    """uint8 column -> (nchunks, 32) leaf plane (copy)."""
    n = arr.shape[0]
    out = np.zeros(((n + 31) // 32, 32), _U8)
    if n:
        out.reshape(-1)[:n] = np.ascontiguousarray(arr, dtype=_U8)
    return out


def _pack_roots(values) -> np.ndarray:
    """List of 32-byte values -> (n, 32) leaf plane."""
    if not values:
        return np.zeros((0, 32), _U8)
    return np.frombuffer(b"".join(values), _U8).reshape(-1, 32)


class _PackedCell:
    """ChunkTree over a packable field; `mixin` adds the list length."""

    def __init__(self, limit_chunks: int, mixin: bool):
        self.tree = ChunkTree(limit_chunks)
        self.mixin = mixin
        # element count as of the last root() — the mixin length a
        # plane-read proof must append (proofs/plane_reader.py)
        self.length = 0

    def root(self, plane: np.ndarray, length: int) -> bytes:
        self.tree.update(plane)
        self.length = length
        r = self.tree.root
        return _mix_in_length(r, length) if self.mixin else r

    def clone(self) -> "_PackedCell":
        out = _PackedCell.__new__(_PackedCell)
        out.tree = self.tree.clone()
        out.mixin = self.mixin
        out.length = self.length
        return out

    def plane_bytes(self, seen: set) -> int:
        return self.tree.plane_bytes(seen)

    def planes(self):
        return self.tree.planes()


class _ValidatorsCell:
    """Per-validator container roots, batch-hashed for dirty rows only.

    A validator's root is a fixed 8-chunk tree:
      [pubkey_root, withdrawal_credentials, effective_balance, slashed,
       activation_eligibility_epoch, activation_epoch, exit_epoch,
       withdrawable_epoch]
    Dirty rows come from vectorized column diffs (numpy columns) plus
    list comparison for the two byte-string columns; pubkey roots are
    cached separately (pubkeys are immutable once registered, so that
    plane only ever grows).
    """

    def __init__(self):
        self.tree = ChunkTree(P.VALIDATOR_REGISTRY_LIMIT)
        self.count = 0
        self.cols: Optional[Dict[str, np.ndarray]] = None
        self.pubkeys: List[bytes] = []
        self.creds: List[bytes] = []
        self.pk_roots = np.zeros((0, 32), _U8)
        self._shared = False

    def clone(self) -> "_ValidatorsCell":
        out = _ValidatorsCell.__new__(_ValidatorsCell)
        out.tree = self.tree.clone()
        out.count = self.count
        out.cols = self.cols
        out.pubkeys = self.pubkeys
        out.creds = self.creds
        out.pk_roots = self.pk_roots
        out._shared = True
        self._shared = True
        return out

    def _own(self) -> None:
        if self._shared:
            if self.cols is not None:
                self.cols = {k: v.copy() for k, v in self.cols.items()}
            self.pubkeys = list(self.pubkeys)
            self.creds = list(self.creds)
            self.pk_roots = self.pk_roots.copy()
            self._shared = False

    def plane_bytes(self, seen: set) -> int:
        """Tree node planes + the cached pubkey-root plane + the
        per-validator diff columns (all COW-shared across clones until
        the first owning mutation; the columns are a second full copy
        of the registry's numeric columns, same magnitude as the state's
        own — an owned engine that omitted them would under-count by
        ~7x8n bytes).  The pubkeys/creds pointer lists stay uncounted:
        their elements are shared bytes objects and the list copies are
        pointer-sized."""
        total = self.tree.plane_bytes(seen)
        for arr in self._aux_planes():
            if id(arr) not in seen:
                seen.add(id(arr))
                total += arr.nbytes
        return total

    def _aux_planes(self):
        out = [self.pk_roots]
        if self.cols is not None:
            out.extend(self.cols.values())
        return out

    def planes(self):
        return self.tree.planes() + self._aux_planes()

    @staticmethod
    def _list_mismatches(cached: List[bytes], current: List[bytes], m: int):
        """Indices in [0, m) where the byte-string columns differ.
        Fast path: one C-level list compare when nothing changed."""
        a = cached[:m]
        b = current[:m]
        if a == b:
            return ()
        return [i for i in range(m) if a[i] != b[i]]

    def root(self, state) -> bytes:
        n = len(state.pubkeys)
        cold = self.cols is None or n < self.count
        old_n = 0 if cold else self.count
        m = min(n, old_n)

        if cold:
            dirty = np.arange(n, dtype=np.intp)
            pk_dirty = dirty
        else:
            mask = np.zeros(m, bool)
            for name, _chunk in _VAL_COLS:
                cur = getattr(state, name)
                mask |= self.cols[name][:m] != cur[:m]
            mask |= self.cols["slashed"][:m] != state.slashed[:m]
            cred_mis = self._list_mismatches(
                self.creds, state.withdrawal_credentials, m
            )
            if cred_mis:
                mask[cred_mis] = True
            pk_mis = self._list_mismatches(self.pubkeys, state.pubkeys, m)
            if pk_mis:
                mask[pk_mis] = True
            dirty = np.nonzero(mask)[0].astype(np.intp)
            if n > old_n:
                dirty = np.concatenate(
                    [dirty, np.arange(old_n, n, dtype=np.intp)]
                )
            pk_dirty = (
                np.concatenate(
                    [
                        np.asarray(pk_mis, dtype=np.intp),
                        np.arange(old_n, n, dtype=np.intp),
                    ]
                )
                if (pk_mis or n > old_n)
                else np.zeros(0, np.intp)
            )

        if not (cold or dirty.size or pk_dirty.size):
            return _mix_in_length(self.tree.root, n)

        self._own()

        # pubkey roots: H(pk[0:32] || pk[32:48] + 16 zero bytes)
        if self.pk_roots.shape[0] < n:
            grown = np.zeros((max(n, self.pk_roots.shape[0] * 2, 8), 32), _U8)
            grown[: self.pk_roots.shape[0]] = self.pk_roots
            self.pk_roots = grown
        if pk_dirty.size:
            pk_plane = np.zeros((pk_dirty.size, 64), _U8)
            pk_plane[:, :48] = np.frombuffer(
                b"".join(state.pubkeys[int(i)] for i in pk_dirty), _U8
            ).reshape(-1, 48)
            self.pk_roots[pk_dirty] = hash_pairs_plane(pk_plane)

        if dirty.size:
            d = dirty.size
            cred_rows = np.frombuffer(
                b"".join(state.withdrawal_credentials[int(i)] for i in dirty),
                _U8,
            ).reshape(-1, 32)
            vroots = None
            backend = _htr_device()
            if backend is not None:
                # leaf packing + the fixed 8-chunk subtree in ONE device
                # dispatch (kernels/sha256.validator_roots_device); any
                # fault degrades to the host packing below, bit-identical
                vroots = backend.validator_roots(
                    self.pk_roots[dirty],
                    cred_rows,
                    [
                        np.ascontiguousarray(getattr(state, name)[dirty])
                        for name, _chunk in _VAL_COLS
                    ],
                    state.slashed[dirty],
                )
            if vroots is None:
                blk = np.zeros((d, 8, 32), _U8)
                blk[:, 0] = self.pk_roots[dirty]
                blk[:, 1] = cred_rows
                for name, chunk in _VAL_COLS:
                    blk[:, chunk, :8] = (
                        np.ascontiguousarray(
                            getattr(state, name)[dirty], "<u8"
                        )
                        .view(_U8)
                        .reshape(-1, 8)
                    )
                blk[:, 3, 0] = state.slashed[dirty].astype(_U8)
                # three batched levels: 8 chunks -> 4 -> 2 -> 1 root per row
                lvl = hash_pairs_plane(blk.reshape(d * 4, 64))
                lvl = hash_pairs_plane(lvl.reshape(d * 2, 64))
                vroots = hash_pairs_plane(lvl.reshape(d, 64))
            if cold:
                self.tree.reset(vroots)
            else:
                self.tree.apply(dirty, vroots, n)
        elif cold:
            # shrink-to-empty: the tree must forget stale leaves
            self.tree.reset(np.zeros((0, 32), _U8))

        # sync the caches to what the tree now reflects
        if self.cols is None:
            self.cols = {}
        for name in [c for c, _ in _VAL_COLS] + ["slashed"]:
            cur = getattr(state, name)
            cached = self.cols.get(name)
            if cached is None or cached.shape[0] != n:
                fresh = np.empty(n, cur.dtype)
                if cached is not None and m:
                    fresh[:m] = cached[:m]
                self.cols[name] = cached = fresh
            cached[dirty] = cur[dirty]
        self.pubkeys = list(state.pubkeys)
        self.creds = list(state.withdrawal_credentials)
        self.count = n

        return _mix_in_length(self.tree.root, n)


class StateRootEngine:
    """Per-field root cache composed through the fork's container."""

    def __init__(self):
        self.validators = _ValidatorsCell()
        self.cells: Dict[str, _PackedCell] = {}
        # fname -> (serialized bytes, root) for every non-columnar field
        self.memo: Dict[str, tuple] = {}
        # top-level tree over the per-field root chunks — the root-most
        # planes the proof-serving plane reads field branches from
        self.top: Optional[ChunkTree] = None

    def clone(self) -> "StateRootEngine":
        out = StateRootEngine.__new__(StateRootEngine)
        out.validators = self.validators.clone()
        out.cells = {k: v.clone() for k, v in self.cells.items()}
        out.memo = dict(self.memo)
        out.top = self.top.clone() if self.top is not None else None
        return out

    # -- mutation-surface hints (performance only, never correctness) ------

    def note_participation_rotation(self) -> None:
        """Epoch transition rotates current -> previous participation;
        swapping the cached trees keeps the rotated field's diff clean.
        A wrong or missing hint only costs extra hashing: the diff
        against whichever plane is cached still finds every change."""
        a = self.cells.pop("previous_epoch_participation", None)
        b = self.cells.pop("current_epoch_participation", None)
        if b is not None:
            self.cells["previous_epoch_participation"] = b
        if a is not None:
            self.cells["current_epoch_participation"] = a

    # -- per-field roots ---------------------------------------------------

    def _cell(self, fname: str, limit_chunks: int, mixin: bool) -> _PackedCell:
        cell = self.cells.get(fname)
        if cell is None:
            cell = self.cells[fname] = _PackedCell(limit_chunks, mixin)
        return cell

    def _field_root(self, state, fname: str, ftype) -> bytes:
        reg = P.VALIDATOR_REGISTRY_LIMIT
        if fname == "validators":
            return self.validators.root(state)
        if fname in ("balances", "inactivity_scores"):
            arr = getattr(state, fname)
            cell = self._cell(fname, (reg * 8 + 31) // 32, mixin=True)
            return cell.root(_pack_u64(arr), arr.shape[0])
        if fname in (
            "previous_epoch_participation",
            "current_epoch_participation",
        ):
            arr = getattr(state, fname)
            cell = self._cell(fname, (reg + 31) // 32, mixin=True)
            return cell.root(_pack_u8(arr), arr.shape[0])
        if fname in ("block_roots", "state_roots", "randao_mixes"):
            values = getattr(state, fname)
            cell = self._cell(fname, len(values), mixin=False)
            return cell.root(_pack_roots(values), len(values))
        if fname == "slashings":
            arr = state.slashings
            cell = self._cell(fname, (arr.shape[0] * 8 + 31) // 32, mixin=False)
            return cell.root(_pack_u64(arr), arr.shape[0])
        # serialize-memo: byte-equal serialization proves the cached
        # root is current (serialization is memcpy; hashing is not)
        value = getattr(state, fname)
        ser = ftype.serialize(value)
        hit = self.memo.get(fname)
        if hit is not None and hit[0] == ser:
            return hit[1]
        root = ftype.hash_tree_root(value)
        self.memo[fname] = (ser, root)
        return root

    def hash_tree_root(self, state) -> bytes:
        container = state._container()
        chunks = [
            self._field_root(state, fname, ftype)
            for fname, ftype in container.fields
        ]
        # ChunkTree(n) pads to the same next-pow2 leaf count
        # merkleize_chunks(chunks) does, so the root is bit-identical —
        # but the internal planes stay resident for O(log n) field
        # branches (proofs/plane_reader.py)
        top = self.top
        if top is None or top.limit_chunks != len(chunks):
            top = self.top = ChunkTree(len(chunks))
        top.update(np.frombuffer(b"".join(chunks), _U8).reshape(-1, 32))
        return top.root

    def leaf_cell(self, fname: str):
        """(tree, length, mixin) for a ChunkTree-backed field as of the
        last hash_tree_root(), or None for memo-backed fields."""
        if fname == "validators":
            v = self.validators
            return (v.tree, v.count, True)
        cell = self.cells.get(fname)
        if cell is None:
            return None
        return (cell.tree, cell.length, cell.mixin)

    def engine_bytes(self, seen: Optional[set] = None) -> int:
        """Live ChunkTree plane bytes held by this engine.  Thread one
        `seen` set across engines to count COW-shared planes once."""
        if seen is None:
            seen = set()
        total = self.validators.plane_bytes(seen)
        for cell in self.cells.values():
            total += cell.plane_bytes(seen)
        if self.top is not None:
            total += self.top.plane_bytes(seen)
        return total

    def iter_planes(self):
        """Every live node-plane array this engine holds (the exact set
        plane_bytes() walks, in the same id() identity space) — the
        residency ledger's per-state enumeration.  O(fields x levels)
        attribute reads, no hashing."""
        yield from self.validators.planes()
        for cell in self.cells.values():
            yield from cell.planes()
        if self.top is not None:
            yield from self.top.planes()

    def release_planes(self) -> int:
        """Tier-1 demotion (chain/memory_governor.py): free every
        ChunkTree node plane, the pubkey-root plane, the validators
        diff columns, and the serialize memos.  Returns the plane bytes
        freed.  The next hash_tree_root() through this engine rebuilds
        cold — one full merkleization, bit-identical by the PR 3
        incremental==full equivalence."""
        freed = self.engine_bytes()
        self.validators = _ValidatorsCell()
        self.cells = {}
        self.memo = {}
        self.top = None
        return freed


def state_root_engine_bytes(states) -> int:
    """Aggregate live engine plane bytes across `states` (e.g. the regen
    state-cache LRU + checkpoint cache): COW-shared planes — the normal
    case right after clone() — are counted ONCE, so the number tracks
    real residency, not per-state virtual size.  The first step toward
    bounding warm-engine memory (ROADMAP)."""
    seen: set = set()
    total = 0
    for st in states:
        engine = getattr(st, "_root_engine", None)
        if engine is not None:
            total += engine.engine_bytes(seen)
    return total
