"""stateTransition() — the top-level block STF.

Reference: packages/state-transition/src/stateTransition.ts:42-113
(clone → processSlots → verify proposer signature → processBlock →
verify state root).  Options mirror StateTransitionOpts
{verifyStateRoot, verifyProposer, verifySignatures}: in the import
pipeline all signatures (proposer included) are pre-verified in one
batched TPU job, so the defaults here match the reference's
"signatures already checked by chain/bls" call site
(beacon-node/src/chain/blocks/verifyBlock.ts flow).
"""

from __future__ import annotations

from typing import Dict

from .. import params
from ..types import BeaconBlock, BeaconBlockAltair, BeaconBlockHeader
from .block import BlockProcessError, process_block
from .slot import process_slots

P = params.ACTIVE_PRESET


def _block_type(config, slot: int):
    return config.get_fork_types(slot)[0]


def verify_proposer_signature(state, signed_block: Dict) -> bool:
    from ..crypto import bls as _bls

    block = signed_block["message"]
    block_type = _block_type(state.config, block["slot"])
    domain = state.config.get_domain(
        state.slot, params.DOMAIN_BEACON_PROPOSER, block["slot"]
    )
    root = state.config.compute_signing_root(
        block_type.hash_tree_root(block), domain
    )
    proposer = block["proposer_index"]
    if proposer >= state.num_validators:
        return False
    return _bls.verify_bytes(
        state.pubkeys[proposer], root, signed_block["signature"]
    )


def state_transition(
    state,
    signed_block: Dict,
    *,
    verify_state_root: bool = True,
    verify_proposer: bool = False,
    verify_signatures: bool = False,
):
    """Clone, advance, apply, verify; returns the post-state."""
    block = signed_block["message"]
    post = state.clone()

    if post.slot < block["slot"]:
        process_slots(post, block["slot"])

    if verify_proposer and not verify_proposer_signature(post, signed_block):
        raise BlockProcessError("invalid proposer signature")

    process_block(post, block, verify_signatures)

    if verify_state_root:
        actual = post.hash_tree_root()
        if block["state_root"] != actual:
            raise BlockProcessError(
                f"state root mismatch at slot {block['slot']}: "
                f"block {block['state_root'].hex()} != computed {actual.hex()}"
            )
    return post
