"""Slot processing + the multifork stateTransition entry.

Reference: packages/state-transition/src/slot/index.ts (processSlot),
stateTransition.ts (stateTransition / processSlots; the
eth2fastspec-style "cache roots then maybe epoch-transition" loop).
The canonical working state is the altair family (phase0 pre-states are
out of the replay window); the BELLATRIX fork upgrade runs at its
scheduled epoch boundary (reference: slot/upgradeStateToBellatrix.ts),
attaching the execution-payload header that process_execution_payload
maintains thereafter.
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import params
from ..params import ForkName
from ..types import BeaconBlockHeader, ExecutionPayloadHeader
from .epoch import process_epoch

P = params.ACTIVE_PRESET
ZERO_ROOT = b"\x00" * 32


def process_slot(state) -> None:
    """Cache the state/block roots for the slot being closed.

    The state root here is THE per-slot merkleization hot path; it runs
    through the incremental engine (state_transition/state_root.py), so
    a slot that touched k validators re-hashes O(k log n) chunks, not
    the whole registry."""
    previous_state_root = state.hash_tree_root()
    state.state_roots[state.slot % P.SLOTS_PER_HISTORICAL_ROOT] = (
        previous_state_root
    )
    if state.latest_block_header["state_root"] == ZERO_ROOT:
        state.latest_block_header["state_root"] = previous_state_root
    state.block_roots[state.slot % P.SLOTS_PER_HISTORICAL_ROOT] = (
        BeaconBlockHeader.hash_tree_root(state.latest_block_header)
    )


def process_slots(state, slot: int, metrics: Optional[Dict] = None) -> None:
    """Advance state (in place) through empty slots up to `slot`."""
    assert state.slot < slot, (
        f"process_slots target {slot} not beyond state slot {state.slot}"
    )
    while state.slot < slot:
        process_slot(state)
        if (state.slot + 1) % P.SLOTS_PER_EPOCH == 0:
            if state.previous_epoch_attestations is not None:
                # PendingAttestation era (reference: phase0 processEpoch)
                from .phase0 import process_epoch_phase0

                process_epoch_phase0(state)
            else:
                process_epoch(state)
        state.slot += 1
        maybe_upgrade_state(state)


def maybe_upgrade_state(state) -> None:
    """Run the scheduled fork upgrade when the state enters the fork's
    first slot (reference: stateTransition.ts processSlotsWithTransientCache
    -> upgradeStateToX at epoch boundaries)."""
    if state.slot % P.SLOTS_PER_EPOCH != 0:
        return
    epoch = state.slot // P.SLOTS_PER_EPOCH
    altair_epoch = state.config.fork_epochs.get(ForkName.altair)
    if (
        altair_epoch is not None
        and epoch == altair_epoch
        and state.previous_epoch_attestations is not None
    ):
        # reference: slot/upgradeStateToAltair.ts (pending attestations
        # translate into participation flags; sync committees start)
        from .phase0 import upgrade_to_altair

        upgrade_to_altair(state)
    bellatrix_epoch = state.config.fork_epochs.get(ForkName.bellatrix)
    if (
        bellatrix_epoch is not None
        and epoch == bellatrix_epoch
        and state.latest_execution_payload_header is None
    ):
        upgrade_to_bellatrix(state)
    capella_epoch = state.config.fork_epochs.get(ForkName.capella)
    if (
        capella_epoch is not None
        and epoch == capella_epoch
        and state.next_withdrawal_index is None
    ):
        upgrade_to_capella(state)
    deneb_epoch = state.config.fork_epochs.get(ForkName.deneb)
    if (
        deneb_epoch is not None
        and epoch == deneb_epoch
        and state.next_withdrawal_index is not None
        and "blob_gas_used" not in (state.latest_execution_payload_header or {})
    ):
        upgrade_to_deneb(state)


def _bump_fork(state, fork: ForkName) -> None:
    state.fork = {
        "previous_version": state.fork["current_version"],
        "current_version": state.config.fork_versions[fork],
        "epoch": state.slot // P.SLOTS_PER_EPOCH,
    }


def upgrade_to_bellatrix(state) -> None:
    """reference: slot/upgradeStateToBellatrix.ts — bump the fork record
    and attach the default (pre-merge) execution payload header."""
    _bump_fork(state, ForkName.bellatrix)
    state.latest_execution_payload_header = ExecutionPayloadHeader.default()


def upgrade_to_capella(state) -> None:
    """reference: slot/upgradeStateToCapella.ts — the payload header gains
    withdrawals_root; withdrawal bookkeeping + historical summaries start."""
    _bump_fork(state, ForkName.capella)
    header = dict(state.latest_execution_payload_header or {})
    if not header:
        header = ExecutionPayloadHeader.default()
    header["withdrawals_root"] = ZERO_ROOT
    state.latest_execution_payload_header = header
    state.next_withdrawal_index = 0
    state.next_withdrawal_validator_index = 0
    state.historical_summaries = []


def upgrade_to_deneb(state) -> None:
    """reference: slot/upgradeStateToDeneb.ts — the payload header gains
    the blob gas fields."""
    _bump_fork(state, ForkName.deneb)
    header = dict(state.latest_execution_payload_header)
    header["blob_gas_used"] = 0
    header["excess_blob_gas"] = 0
    state.latest_execution_payload_header = header
