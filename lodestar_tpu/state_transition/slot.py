"""Slot processing + the multifork stateTransition entry.

Reference: packages/state-transition/src/slot/index.ts (processSlot),
stateTransition.ts (stateTransition / processSlots; the
eth2fastspec-style "cache roots then maybe epoch-transition" loop).
Fork upgrades are a no-op here because the TPU build's canonical state
IS the altair family (minimal config activates altair at epoch 0);
phase0 pre-states are out of the replay window this framework targets.
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import params
from ..types import BeaconBlockHeader
from .epoch import process_epoch

P = params.ACTIVE_PRESET
ZERO_ROOT = b"\x00" * 32


def process_slot(state) -> None:
    """Cache the state/block roots for the slot being closed."""
    previous_state_root = state.hash_tree_root()
    state.state_roots[state.slot % P.SLOTS_PER_HISTORICAL_ROOT] = (
        previous_state_root
    )
    if state.latest_block_header["state_root"] == ZERO_ROOT:
        state.latest_block_header["state_root"] = previous_state_root
    state.block_roots[state.slot % P.SLOTS_PER_HISTORICAL_ROOT] = (
        BeaconBlockHeader.hash_tree_root(state.latest_block_header)
    )


def process_slots(state, slot: int, metrics: Optional[Dict] = None) -> None:
    """Advance state (in place) through empty slots up to `slot`."""
    assert state.slot < slot, (
        f"process_slots target {slot} not beyond state slot {state.slot}"
    )
    while state.slot < slot:
        process_slot(state)
        if (state.slot + 1) % P.SLOTS_PER_EPOCH == 0:
            process_epoch(state)
        state.slot += 1
