"""BeaconState — altair, struct-of-arrays working representation.

The reference keeps the state as a persistent SSZ tree-of-nodes ViewDU
(reference: packages/state-transition/src/cache/stateCache.ts, types
re-exported via types/src/altair/sszTypes.ts BeaconState).  On TPU-era
hardware the profitable layout is the opposite: the per-validator
columns (balances, effective balances, participation flags, inactivity
scores, activation/exit epochs) live as contiguous numpy vectors so the
whole epoch transition is a handful of vectorized array passes instead
of a per-validator interpreter loop.  SSZ view (serialize /
hash_tree_root) is materialized on demand from the columns.

Reference parity map:
  - field set:        types/src/altair/sszTypes.ts (BeaconState)
  - clone-on-write:   stateTransition.ts:59 (state.clone() before mutate)
  - hashTreeRoot:     stateTransition.ts:101-104 (verifyStateRoot)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from .. import params
from ..config.chain_config import ChainConfig
from ..ssz import Bitvector, Bytes32, Container, List as SszList, Vector, uint8, uint64
from ..types import (
    BeaconBlockHeader,
    Checkpoint,
    Eth1Data,
    Fork,
    SyncCommittee,
    Validator,
)

P = params.ACTIVE_PRESET

# Full altair BeaconState SSZ type (reference: types/src/altair/sszTypes.ts)
_altair_state_fields = (
        ("genesis_time", uint64),
        ("genesis_validators_root", Bytes32),
        ("slot", uint64),
        ("fork", Fork),
        ("latest_block_header", BeaconBlockHeader),
        ("block_roots", Vector(Bytes32, P.SLOTS_PER_HISTORICAL_ROOT)),
        ("state_roots", Vector(Bytes32, P.SLOTS_PER_HISTORICAL_ROOT)),
        ("historical_roots", SszList(Bytes32, P.HISTORICAL_ROOTS_LIMIT)),
        ("eth1_data", Eth1Data),
        (
            "eth1_data_votes",
            SszList(
                Eth1Data, P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH
            ),
        ),
        ("eth1_deposit_index", uint64),
        ("validators", SszList(Validator, P.VALIDATOR_REGISTRY_LIMIT)),
        ("balances", SszList(uint64, P.VALIDATOR_REGISTRY_LIMIT)),
        ("randao_mixes", Vector(Bytes32, P.EPOCHS_PER_HISTORICAL_VECTOR)),
        ("slashings", Vector(uint64, P.EPOCHS_PER_SLASHINGS_VECTOR)),
        (
            "previous_epoch_participation",
            SszList(uint8, P.VALIDATOR_REGISTRY_LIMIT),
        ),
        (
            "current_epoch_participation",
            SszList(uint8, P.VALIDATOR_REGISTRY_LIMIT),
        ),
        ("justification_bits", Bitvector(params.JUSTIFICATION_BITS_LENGTH)),
        ("previous_justified_checkpoint", Checkpoint),
        ("current_justified_checkpoint", Checkpoint),
        ("finalized_checkpoint", Checkpoint),
        ("inactivity_scores", SszList(uint64, P.VALIDATOR_REGISTRY_LIMIT)),
        ("current_sync_committee", SyncCommittee),
        ("next_sync_committee", SyncCommittee),
)

BeaconStateAltair = Container(_altair_state_fields, name="BeaconStateAltair")

# phase0 replaces the participation/inactivity/sync tail with the
# PendingAttestation record lists (reference: types/src/phase0/sszTypes.ts
# BeaconState)
from ..types import PendingAttestation as _PendingAttestation  # noqa: E402

_PENDING_ATT_LIMIT = P.MAX_ATTESTATIONS * P.SLOTS_PER_EPOCH

BeaconStatePhase0 = Container(
    _altair_state_fields[:15]  # ... through slashings
    + (
        (
            "previous_epoch_attestations",
            SszList(_PendingAttestation, _PENDING_ATT_LIMIT),
        ),
        (
            "current_epoch_attestations",
            SszList(_PendingAttestation, _PENDING_ATT_LIMIT),
        ),
    )
    + _altair_state_fields[17:21],  # justification bits + checkpoints
    name="BeaconStatePhase0",
)

# bellatrix appends the execution-payload header
# (reference: types/src/bellatrix/sszTypes.ts BeaconState)
from ..types import ExecutionPayloadHeader as _ExecutionPayloadHeader  # noqa: E402

BeaconStateBellatrix = Container(
    _altair_state_fields
    + (("latest_execution_payload_header", _ExecutionPayloadHeader),),
    name="BeaconStateBellatrix",
)

# capella appends withdrawal bookkeeping + historical summaries, and the
# payload header gains withdrawals_root
# (reference: types/src/capella/sszTypes.ts BeaconState)
from ..types import (  # noqa: E402
    ExecutionPayloadHeaderCapella as _HeaderCapella,
    ExecutionPayloadHeaderDeneb as _HeaderDeneb,
    HistoricalSummary as _HistoricalSummary,
)

_capella_extra_fields = (
    ("next_withdrawal_index", uint64),
    ("next_withdrawal_validator_index", uint64),
    (
        "historical_summaries",
        SszList(_HistoricalSummary, P.HISTORICAL_ROOTS_LIMIT),
    ),
)

BeaconStateCapella = Container(
    _altair_state_fields
    + (("latest_execution_payload_header", _HeaderCapella),)
    + _capella_extra_fields,
    name="BeaconStateCapella",
)

# deneb only swaps the payload header type (blob gas fields)
BeaconStateDeneb = Container(
    _altair_state_fields
    + (("latest_execution_payload_header", _HeaderDeneb),)
    + _capella_extra_fields,
    name="BeaconStateDeneb",
)

_U64 = np.uint64
FAR_FUTURE = params.FAR_FUTURE_EPOCH


@dataclass
class BeaconState:
    """Mutable working state; columns are numpy, the rest plain Python."""

    config: ChainConfig
    genesis_time: int = 0
    genesis_validators_root: bytes = b"\x00" * 32
    slot: int = 0
    fork: Dict = field(
        default_factory=lambda: Fork.default()
    )
    latest_block_header: Dict = field(
        default_factory=lambda: BeaconBlockHeader.default()
    )
    block_roots: List[bytes] = field(
        default_factory=lambda: [b"\x00" * 32] * P.SLOTS_PER_HISTORICAL_ROOT
    )
    state_roots: List[bytes] = field(
        default_factory=lambda: [b"\x00" * 32] * P.SLOTS_PER_HISTORICAL_ROOT
    )
    historical_roots: List[bytes] = field(default_factory=list)
    eth1_data: Dict = field(default_factory=lambda: Eth1Data.default())
    eth1_data_votes: List[Dict] = field(default_factory=list)
    eth1_deposit_index: int = 0
    # -- validator registry, struct-of-arrays ------------------------------
    pubkeys: List[bytes] = field(default_factory=list)
    withdrawal_credentials: List[bytes] = field(default_factory=list)
    effective_balance: np.ndarray = field(
        default_factory=lambda: np.zeros(0, _U64)
    )
    slashed: np.ndarray = field(default_factory=lambda: np.zeros(0, bool))
    activation_eligibility_epoch: np.ndarray = field(
        default_factory=lambda: np.zeros(0, _U64)
    )
    activation_epoch: np.ndarray = field(
        default_factory=lambda: np.zeros(0, _U64)
    )
    exit_epoch: np.ndarray = field(default_factory=lambda: np.zeros(0, _U64))
    withdrawable_epoch: np.ndarray = field(
        default_factory=lambda: np.zeros(0, _U64)
    )
    balances: np.ndarray = field(default_factory=lambda: np.zeros(0, _U64))
    # ----------------------------------------------------------------------
    randao_mixes: List[bytes] = field(
        default_factory=lambda: [b"\x00" * 32] * P.EPOCHS_PER_HISTORICAL_VECTOR
    )
    slashings: np.ndarray = field(
        default_factory=lambda: np.zeros(P.EPOCHS_PER_SLASHINGS_VECTOR, _U64)
    )
    previous_epoch_participation: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.uint8)
    )
    current_epoch_participation: np.ndarray = field(
        default_factory=lambda: np.zeros(0, np.uint8)
    )
    justification_bits: List[bool] = field(
        default_factory=lambda: [False] * params.JUSTIFICATION_BITS_LENGTH
    )
    previous_justified_checkpoint: Dict = field(
        default_factory=lambda: Checkpoint.default()
    )
    current_justified_checkpoint: Dict = field(
        default_factory=lambda: Checkpoint.default()
    )
    finalized_checkpoint: Dict = field(
        default_factory=lambda: Checkpoint.default()
    )
    inactivity_scores: np.ndarray = field(
        default_factory=lambda: np.zeros(0, _U64)
    )
    current_sync_committee: Dict = field(
        default_factory=lambda: SyncCommittee.default()
    )
    next_sync_committee: Dict = field(
        default_factory=lambda: SyncCommittee.default()
    )
    # phase0-era pending attestation records; None = altair or later
    # (the altair upgrade translates them into participation flags)
    previous_epoch_attestations: Optional[List[Dict]] = None
    current_epoch_attestations: Optional[List[Dict]] = None
    # None = pre-bellatrix state; set by upgrade_to_bellatrix
    latest_execution_payload_header: Optional[Dict] = None
    # None = pre-capella state; set by upgrade_to_capella
    next_withdrawal_index: Optional[int] = None
    next_withdrawal_validator_index: Optional[int] = None
    historical_summaries: Optional[List[Dict]] = None

    # -- fork identity ------------------------------------------------------

    @property
    def fork_name(self) -> params.ForkName:
        """The fork this state is in, from Fork.current_version (the
        reference's config.getForkName(state.slot) equivalent)."""
        version = bytes(self.fork["current_version"])
        for name, v in self.config.fork_versions.items():
            if bytes(v) == version:
                return name
        return (
            params.ForkName.phase0
            if self.previous_epoch_attestations is not None
            else params.ForkName.altair
        )

    def fork_at_least(self, fork: params.ForkName) -> bool:
        return params.FORK_SEQ[self.fork_name] >= params.FORK_SEQ[fork]

    # -- registry ----------------------------------------------------------

    @property
    def num_validators(self) -> int:
        return len(self.pubkeys)

    def pubkey_index(self, pubkey: bytes) -> Optional[int]:
        """O(1) pubkey → validator index (the pubkey2index cache; lazily
        built, incrementally maintained by add_validator)."""
        m = getattr(self, "_pubkey_map", None)
        if m is None or len(m) != len(self.pubkeys):
            m = {pk: i for i, pk in enumerate(self.pubkeys)}
            self._pubkey_map = m
        return m.get(bytes(pubkey))

    def add_validator(
        self,
        pubkey: bytes,
        withdrawal_credential: bytes,
        amount: int,
        *,
        effective_balance: Optional[int] = None,
        activation_eligibility_epoch: int = FAR_FUTURE,
        activation_epoch: int = FAR_FUTURE,
        exit_epoch: int = FAR_FUTURE,
        withdrawable_epoch: int = FAR_FUTURE,
    ) -> int:
        """Append a validator (spec add_validator_to_registry)."""
        if effective_balance is None:
            effective_balance = min(
                amount - amount % P.EFFECTIVE_BALANCE_INCREMENT,
                P.MAX_EFFECTIVE_BALANCE,
            )
        self.pubkeys.append(bytes(pubkey))
        self.withdrawal_credentials.append(bytes(withdrawal_credential))
        m = getattr(self, "_pubkey_map", None)
        if m is not None and len(m) == len(self.pubkeys) - 1:
            m[bytes(pubkey)] = len(self.pubkeys) - 1

        def _app(arr, v, dtype=_U64):
            return np.append(arr, np.asarray([v], dtype))

        self.effective_balance = _app(self.effective_balance, effective_balance)
        self.slashed = _app(self.slashed, False, bool)
        self.activation_eligibility_epoch = _app(
            self.activation_eligibility_epoch, activation_eligibility_epoch
        )
        self.activation_epoch = _app(self.activation_epoch, activation_epoch)
        self.exit_epoch = _app(self.exit_epoch, exit_epoch)
        self.withdrawable_epoch = _app(
            self.withdrawable_epoch, withdrawable_epoch
        )
        self.balances = _app(self.balances, amount)
        self.previous_epoch_participation = _app(
            self.previous_epoch_participation, 0, np.uint8
        )
        self.current_epoch_participation = _app(
            self.current_epoch_participation, 0, np.uint8
        )
        self.inactivity_scores = _app(self.inactivity_scores, 0)
        return self.num_validators - 1

    def increase_balance(self, index: int, delta: int) -> None:
        self.balances[index] = _U64(int(self.balances[index]) + int(delta))

    def decrease_balance(self, index: int, delta: int) -> None:
        self.balances[index] = _U64(
            max(0, int(self.balances[index]) - int(delta))
        )

    # -- clone / SSZ view --------------------------------------------------

    def clone(self) -> "BeaconState":
        """Deep copy (the reference's state.clone() before mutation)."""
        import copy

        out = BeaconState(config=self.config)
        out.genesis_time = self.genesis_time
        out.genesis_validators_root = self.genesis_validators_root
        out.slot = self.slot
        out.fork = copy.deepcopy(self.fork)
        out.latest_block_header = copy.deepcopy(self.latest_block_header)
        out.block_roots = list(self.block_roots)
        out.state_roots = list(self.state_roots)
        out.historical_roots = list(self.historical_roots)
        out.eth1_data = copy.deepcopy(self.eth1_data)
        out.eth1_data_votes = copy.deepcopy(self.eth1_data_votes)
        out.eth1_deposit_index = self.eth1_deposit_index
        out.pubkeys = list(self.pubkeys)
        out.withdrawal_credentials = list(self.withdrawal_credentials)
        for col in (
            "effective_balance",
            "slashed",
            "activation_eligibility_epoch",
            "activation_epoch",
            "exit_epoch",
            "withdrawable_epoch",
            "balances",
            "slashings",
            "previous_epoch_participation",
            "current_epoch_participation",
            "inactivity_scores",
        ):
            setattr(out, col, getattr(self, col).copy())
        out.randao_mixes = list(self.randao_mixes)
        out.justification_bits = list(self.justification_bits)
        out.previous_justified_checkpoint = dict(
            self.previous_justified_checkpoint
        )
        out.current_justified_checkpoint = dict(
            self.current_justified_checkpoint
        )
        out.finalized_checkpoint = dict(self.finalized_checkpoint)
        out.current_sync_committee = copy.deepcopy(self.current_sync_committee)
        out.next_sync_committee = copy.deepcopy(self.next_sync_committee)
        if self.previous_epoch_attestations is not None:
            out.previous_epoch_attestations = copy.deepcopy(
                self.previous_epoch_attestations
            )
            out.current_epoch_attestations = copy.deepcopy(
                self.current_epoch_attestations
            )
        out.latest_execution_payload_header = copy.deepcopy(
            self.latest_execution_payload_header
        )
        out.next_withdrawal_index = self.next_withdrawal_index
        out.next_withdrawal_validator_index = (
            self.next_withdrawal_validator_index
        )
        out.historical_summaries = (
            [dict(h) for h in self.historical_summaries]
            if self.historical_summaries is not None
            else None
        )
        # share the incremental-merkleization engine copy-on-write: the
        # clone inherits warm trees (state_transition pre->post, regen
        # replay, checkpoint states), and either side copies a plane
        # only when its first dirty path touches it
        engine = getattr(self, "_root_engine", None)
        if engine is not None:
            out._root_engine = engine.clone()
        return out

    def validators_value(self) -> List[Dict]:
        return [
            {
                "pubkey": self.pubkeys[i],
                "withdrawal_credentials": self.withdrawal_credentials[i],
                "effective_balance": int(self.effective_balance[i]),
                "slashed": bool(self.slashed[i]),
                "activation_eligibility_epoch": int(
                    self.activation_eligibility_epoch[i]
                ),
                "activation_epoch": int(self.activation_epoch[i]),
                "exit_epoch": int(self.exit_epoch[i]),
                "withdrawable_epoch": int(self.withdrawable_epoch[i]),
            }
            for i in range(self.num_validators)
        ]

    def to_value(self) -> Dict:
        """Materialize the SSZ container value."""
        out = {
            "genesis_time": self.genesis_time,
            "genesis_validators_root": self.genesis_validators_root,
            "slot": self.slot,
            "fork": self.fork,
            "latest_block_header": self.latest_block_header,
            "block_roots": list(self.block_roots),
            "state_roots": list(self.state_roots),
            "historical_roots": list(self.historical_roots),
            "eth1_data": self.eth1_data,
            "eth1_data_votes": list(self.eth1_data_votes),
            "eth1_deposit_index": self.eth1_deposit_index,
            "validators": self.validators_value(),
            "balances": [int(b) for b in self.balances],
            "randao_mixes": list(self.randao_mixes),
            "slashings": [int(s) for s in self.slashings],
            "previous_epoch_participation": [
                int(x) for x in self.previous_epoch_participation
            ],
            "current_epoch_participation": [
                int(x) for x in self.current_epoch_participation
            ],
            "justification_bits": list(self.justification_bits),
            "previous_justified_checkpoint": self.previous_justified_checkpoint,
            "current_justified_checkpoint": self.current_justified_checkpoint,
            "finalized_checkpoint": self.finalized_checkpoint,
            "inactivity_scores": [int(x) for x in self.inactivity_scores],
            "current_sync_committee": self.current_sync_committee,
            "next_sync_committee": self.next_sync_committee,
        }
        if self.previous_epoch_attestations is not None:
            # phase0 view: the pending-attestation lists replace the
            # participation/inactivity/sync tail
            out["previous_epoch_attestations"] = [
                dict(a) for a in self.previous_epoch_attestations
            ]
            out["current_epoch_attestations"] = [
                dict(a) for a in self.current_epoch_attestations
            ]
            for k in (
                "previous_epoch_participation",
                "current_epoch_participation",
                "inactivity_scores",
                "current_sync_committee",
                "next_sync_committee",
            ):
                del out[k]
        if self.latest_execution_payload_header is not None:
            out["latest_execution_payload_header"] = (
                self.latest_execution_payload_header
            )
        if self.next_withdrawal_index is not None:
            out["next_withdrawal_index"] = self.next_withdrawal_index
            out["next_withdrawal_validator_index"] = (
                self.next_withdrawal_validator_index
            )
            out["historical_summaries"] = list(self.historical_summaries)
        return out

    @classmethod
    def from_value(cls, value: Dict, config: ChainConfig) -> "BeaconState":
        st = cls(config=config)
        st.genesis_time = value["genesis_time"]
        st.genesis_validators_root = value["genesis_validators_root"]
        st.slot = value["slot"]
        st.fork = dict(value["fork"])
        st.latest_block_header = dict(value["latest_block_header"])
        st.block_roots = list(value["block_roots"])
        st.state_roots = list(value["state_roots"])
        st.historical_roots = list(value["historical_roots"])
        st.eth1_data = dict(value["eth1_data"])
        st.eth1_data_votes = [dict(v) for v in value["eth1_data_votes"]]
        st.eth1_deposit_index = value["eth1_deposit_index"]
        vals = value["validators"]
        st.pubkeys = [v["pubkey"] for v in vals]
        st.withdrawal_credentials = [
            v["withdrawal_credentials"] for v in vals
        ]
        st.effective_balance = np.asarray(
            [v["effective_balance"] for v in vals], _U64
        )
        st.slashed = np.asarray([v["slashed"] for v in vals], bool)
        st.activation_eligibility_epoch = np.asarray(
            [v["activation_eligibility_epoch"] for v in vals], _U64
        )
        st.activation_epoch = np.asarray(
            [v["activation_epoch"] for v in vals], _U64
        )
        st.exit_epoch = np.asarray([v["exit_epoch"] for v in vals], _U64)
        st.withdrawable_epoch = np.asarray(
            [v["withdrawable_epoch"] for v in vals], _U64
        )
        st.balances = np.asarray(value["balances"], _U64)
        st.randao_mixes = list(value["randao_mixes"])
        st.slashings = np.asarray(value["slashings"], _U64)
        n_val = len(vals)
        if "previous_epoch_attestations" in value:
            # phase0 value: pending lists in, flag columns defaulted
            st.previous_epoch_attestations = [
                dict(a) for a in value["previous_epoch_attestations"]
            ]
            st.current_epoch_attestations = [
                dict(a) for a in value["current_epoch_attestations"]
            ]
            st.previous_epoch_participation = np.zeros(n_val, np.uint8)
            st.current_epoch_participation = np.zeros(n_val, np.uint8)
        else:
            st.previous_epoch_participation = np.asarray(
                value["previous_epoch_participation"], np.uint8
            )
            st.current_epoch_participation = np.asarray(
                value["current_epoch_participation"], np.uint8
            )
        st.justification_bits = list(value["justification_bits"])
        st.previous_justified_checkpoint = dict(
            value["previous_justified_checkpoint"]
        )
        st.current_justified_checkpoint = dict(
            value["current_justified_checkpoint"]
        )
        st.finalized_checkpoint = dict(value["finalized_checkpoint"])
        if "inactivity_scores" in value:
            st.inactivity_scores = np.asarray(value["inactivity_scores"], _U64)
            st.current_sync_committee = dict(value["current_sync_committee"])
            st.next_sync_committee = dict(value["next_sync_committee"])
        else:
            st.inactivity_scores = np.zeros(n_val, _U64)
        if "latest_execution_payload_header" in value:
            st.latest_execution_payload_header = dict(
                value["latest_execution_payload_header"]
            )
        if "next_withdrawal_index" in value:
            st.next_withdrawal_index = value["next_withdrawal_index"]
            st.next_withdrawal_validator_index = value[
                "next_withdrawal_validator_index"
            ]
            st.historical_summaries = [
                dict(h) for h in value["historical_summaries"]
            ]
        return st

    # -- fork-aware container selection ------------------------------------

    @staticmethod
    def _container_for_fork(name: params.ForkName):
        seq = params.FORK_SEQ[name]
        if seq >= params.FORK_SEQ[params.ForkName.deneb]:
            return BeaconStateDeneb
        if seq >= params.FORK_SEQ[params.ForkName.capella]:
            return BeaconStateCapella
        if seq >= params.FORK_SEQ[params.ForkName.bellatrix]:
            return BeaconStateBellatrix
        if seq >= params.FORK_SEQ[params.ForkName.altair]:
            return BeaconStateAltair
        return BeaconStatePhase0

    def _container(self):
        # Prefer the schema implied by the materialized fields over the
        # fork version: tests build altair-shaped states with arbitrary
        # fork records, and a capella state always carries the fields.
        if self.next_withdrawal_index is not None:
            c = self._container_for_fork(self.fork_name)
            return c if c in (BeaconStateCapella, BeaconStateDeneb) else BeaconStateCapella
        if self.latest_execution_payload_header is not None:
            return BeaconStateBellatrix
        if self.previous_epoch_attestations is not None:
            return BeaconStatePhase0
        return BeaconStateAltair

    @staticmethod
    def _container_for_bytes(data: bytes, config: ChainConfig):
        """Pick the SSZ container from the fork version embedded in the
        serialized state (Fork.current_version at fixed offset 52:56 —
        genesis_time 8 + genesis_validators_root 32 + slot 8 +
        previous_version 4)."""
        version = bytes(data[52:56])
        for name, v in config.fork_versions.items():
            if v == version:
                return BeaconState._container_for_fork(name)
        return BeaconStateAltair

    def hash_tree_root(self) -> bytes:
        """State root via the incremental engine (state_root.py): cached
        per-field roots + dirty-chunk re-hash, O(touched validators) per
        slot.  `LODESTAR_TPU_HTR=full` restores the full recompute;
        `=check` runs both and asserts bit-identity.  Any engine fault
        falls back to the full recompute (and drops the engine, so the
        next call rebuilds cold)."""
        import os

        mode = os.environ.get("LODESTAR_TPU_HTR", "incremental")
        if mode == "full":
            return self._container().hash_tree_root(self.to_value())
        from .state_root import StateRootEngine

        engine = getattr(self, "_root_engine", None)
        if engine is None:
            engine = self._root_engine = StateRootEngine()
        try:
            root = engine.hash_tree_root(self)
        except Exception:
            if mode == "check":
                raise
            self._root_engine = None
            return self._container().hash_tree_root(self.to_value())
        if mode == "check":
            full = self._container().hash_tree_root(self.to_value())
            if root != full:  # not an assert: must survive python -O
                raise RuntimeError(
                    "incremental state root diverged from full recompute"
                )
        return root

    def invalidate_root_cache(self) -> None:
        """Drop the incremental-merkleization engine; the next
        hash_tree_root() rebuilds cold.  Correctness never requires
        this (dirty tracking is diff-based and conservative) — it is an
        escape hatch for memory pressure or debugging."""
        self._root_engine = None

    def serialize(self) -> bytes:
        return self._container().serialize(self.to_value())

    @classmethod
    def deserialize(cls, data: bytes, config: ChainConfig) -> "BeaconState":
        container = cls._container_for_bytes(data, config)
        return cls.from_value(container.deserialize(data), config)
