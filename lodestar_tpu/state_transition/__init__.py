"""State-transition layer — the full beacon state machine.

Mirror of the reference's `@lodestar/state-transition`
(packages/state-transition/src/):

  - `util`: epoch/slot math, swap-or-not shuffling (vectorized numpy —
    whole-registry batch shuffles instead of per-index loops),
  - `accessors`: spec get_* over the columnar state (seeds, committees,
    proposer/sync-committee rejection sampling),
  - `state`: BeaconState — altair, struct-of-arrays columns + SSZ view,
  - `slot` / `block` / `epoch` / `transition`: processSlots,
    processBlock (header/randao/eth1/operations/sync aggregate), the
    fully vectorized epoch transition, and stateTransition() itself
    (reference: stateTransition.ts:42-113, block/index.ts,
    epoch/index.ts),
  - `genesis`: interop-style genesis + the eth1 DepositTree,
  - `EpochCache`: committee assignments + validator pubkey table (the
    Index2PubkeyCache analog whose storage IS the device pubkey table),
  - `signature_sets`: getBlockSignatureSets and the per-object
    extractors feeding the TPU verifier
    (reference: state-transition/src/signatureSets/index.ts:26-73).
"""

from .epoch_cache import EpochCache  # noqa: F401
from .block import BlockProcessError, process_block  # noqa: F401
from .epoch import process_epoch  # noqa: F401
from .genesis import DepositTree, create_genesis_state  # noqa: F401
from .slot import process_slot, process_slots  # noqa: F401
from .state import BeaconState, BeaconStateAltair  # noqa: F401
from .transition import (  # noqa: F401
    state_transition,
    verify_proposer_signature,
)
from .signature_sets import (  # noqa: F401
    get_aggregate_and_proof_signature_set,
    get_attestation_signature_sets,
    get_attester_slashings_signature_sets,
    get_block_signature_sets,
    get_proposer_signature_set,
    get_proposer_slashings_signature_sets,
    get_randao_reveal_signature_set,
    get_sync_committee_signature_set,
    get_voluntary_exits_signature_sets,
)
from .util import (  # noqa: F401
    compute_committee_count_per_slot,
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    shuffle_list,
    unshuffle_list,
)
