"""State-transition layer: epoch caches + signature-set extraction.

The reference's `@lodestar/state-transition` is a 12.6k-LoC beacon state
machine; the TPU build reproduces the parts on the signature path
(SURVEY.md §7 scope guard):

  - `util`: epoch/slot math, swap-or-not shuffling (vectorized numpy —
    whole-registry batch shuffles instead of per-index loops),
  - `EpochCache`: committee assignments + validator pubkey table (the
    Index2PubkeyCache analog whose storage IS the device pubkey table),
  - `signature_sets`: getBlockSignatureSets and the per-object
    extractors feeding the TPU verifier
    (reference: state-transition/src/signatureSets/index.ts:26-73).
"""

from .epoch_cache import EpochCache  # noqa: F401
from .signature_sets import (  # noqa: F401
    get_aggregate_and_proof_signature_set,
    get_attestation_signature_sets,
    get_attester_slashings_signature_sets,
    get_block_signature_sets,
    get_proposer_signature_set,
    get_proposer_slashings_signature_sets,
    get_randao_reveal_signature_set,
    get_sync_committee_signature_set,
    get_voluntary_exits_signature_sets,
)
from .util import (  # noqa: F401
    compute_committee_count_per_slot,
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    shuffle_list,
    unshuffle_list,
)
