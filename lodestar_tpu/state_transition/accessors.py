"""State accessors — spec get_* helpers over the columnar BeaconState.

Reference: packages/state-transition/src/util/{seed,validator,balance}.ts
and cache/epochContext.ts (proposer/committee/sync-committee selection).
Everything registry-shaped is a vectorized numpy pass; the rejection-
sampling loops (proposer, sync committee) draw candidates from the
whole-epoch permutation computed once by `shuffled_positions`.
"""

from __future__ import annotations

import hashlib
import math
from typing import List, Sequence

import numpy as np

from .. import params
from .util import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    shuffled_positions,
)

P = params.ACTIVE_PRESET
FAR_FUTURE = params.FAR_FUTURE_EPOCH


def integer_squareroot(n: int) -> int:
    return math.isqrt(n)


def uint_to_bytes(n: int, length: int = 8) -> bytes:
    return int(n).to_bytes(length, "little")


# -- validator status (vectorized; spec is_active_validator et al) ----------


def active_mask(state, epoch: int) -> np.ndarray:
    return (state.activation_epoch <= epoch) & (epoch < state.exit_epoch)


def get_active_validator_indices(state, epoch: int) -> np.ndarray:
    return np.nonzero(active_mask(state, epoch))[0].astype(np.int64)


def is_slashable_validator_mask(state, epoch: int) -> np.ndarray:
    return (
        (~state.slashed)
        & (state.activation_epoch <= epoch)
        & (epoch < state.withdrawable_epoch)
    )


def get_total_balance(state, indices) -> int:
    """max(EFFECTIVE_BALANCE_INCREMENT, sum of effective balances)."""
    total = int(state.effective_balance[np.asarray(indices, np.int64)].sum())
    return max(P.EFFECTIVE_BALANCE_INCREMENT, total)


def get_total_active_balance(state) -> int:
    epoch = compute_epoch_at_slot(state.slot)
    return get_total_balance(state, get_active_validator_indices(state, epoch))


def get_validator_churn_limit(state) -> int:
    epoch = compute_epoch_at_slot(state.slot)
    active = int(active_mask(state, epoch).sum())
    return max(
        state.config.MIN_PER_EPOCH_CHURN_LIMIT,
        active // state.config.CHURN_LIMIT_QUOTIENT,
    )


def get_validator_activation_churn_limit(state) -> int:
    """deneb (EIP-7514): activations are additionally capped at
    MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT; exits keep the plain limit."""
    from .. import params as _params

    limit = get_validator_churn_limit(state)
    if state.fork_at_least(_params.ForkName.deneb):
        return min(_params.ACTIVE_PRESET.MAX_PER_EPOCH_ACTIVATION_CHURN_LIMIT, limit)
    return limit


# -- randao / seeds ---------------------------------------------------------


def get_randao_mix(state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % P.EPOCHS_PER_HISTORICAL_VECTOR]


def get_seed(state, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(
        state,
        (epoch + P.EPOCHS_PER_HISTORICAL_VECTOR - P.MIN_SEED_LOOKAHEAD - 1)
        % P.EPOCHS_PER_HISTORICAL_VECTOR,
    )
    return hashlib.sha256(domain_type + uint_to_bytes(epoch) + mix).digest()


# -- block roots ------------------------------------------------------------


def get_block_root_at_slot(state, slot: int) -> bytes:
    assert slot < state.slot <= slot + P.SLOTS_PER_HISTORICAL_ROOT, (
        "slot outside block-roots window"
    )
    return state.block_roots[slot % P.SLOTS_PER_HISTORICAL_ROOT]


def get_block_root(state, epoch: int) -> bytes:
    return get_block_root_at_slot(state, compute_start_slot_at_epoch(epoch))


# -- proposer selection (spec compute_proposer_index) -----------------------


def compute_proposer_index(state, indices: np.ndarray, seed: bytes) -> int:
    """Rejection-sample a proposer weighted by effective balance.

    The shuffled candidate order for ALL i is one `shuffled_positions`
    permutation (vectorized); the loop only walks it until acceptance
    (expected ~2 draws at full effective balance)."""
    total = len(indices)
    assert total > 0, "no active validators"
    perm = shuffled_positions(total, seed)
    eff = state.effective_balance
    max_eff = P.MAX_EFFECTIVE_BALANCE
    i = 0
    while True:
        candidate = int(indices[perm[i % total]])
        rand_bytes = hashlib.sha256(seed + uint_to_bytes(i // 32)).digest()
        random_byte = rand_bytes[i % 32]
        if int(eff[candidate]) * 255 >= max_eff * random_byte:
            return candidate
        i += 1


def get_beacon_proposer_index(state) -> int:
    epoch = compute_epoch_at_slot(state.slot)
    seed = hashlib.sha256(
        get_seed(state, epoch, params.DOMAIN_BEACON_PROPOSER)
        + uint_to_bytes(state.slot)
    ).digest()
    # Memoized per (slot, seed): block processing asks for the proposer
    # many times per block (header, randao, attestation rewards, sync
    # aggregate), each a full-registry shuffle without this.
    cache = getattr(state, "_proposer_cache", None)
    if cache and cache[0] == (state.slot, seed):
        return cache[1]
    indices = get_active_validator_indices(state, epoch)
    proposer = compute_proposer_index(state, indices, seed)
    state._proposer_cache = ((state.slot, seed), proposer)
    return proposer


def get_proposer_indices_for_epoch(state, epoch: int) -> List[int]:
    """All SLOTS_PER_EPOCH proposers from one epoch-aligned state.

    The per-slot seed only mixes the slot number into the epoch seed, so
    one state serves the whole epoch (reference:
    epochContext.ts proposers / computeProposers)."""
    assert compute_epoch_at_slot(state.slot) == epoch, (
        "state must be in the target epoch"
    )
    base_seed = get_seed(state, epoch, params.DOMAIN_BEACON_PROPOSER)
    indices = get_active_validator_indices(state, epoch)
    out = []
    start = compute_start_slot_at_epoch(epoch)
    for slot in range(start, start + P.SLOTS_PER_EPOCH):
        seed = hashlib.sha256(base_seed + uint_to_bytes(slot)).digest()
        out.append(compute_proposer_index(state, indices, seed))
    return out


# -- sync committee (spec get_next_sync_committee) --------------------------


def get_next_sync_committee_indices(state) -> List[int]:
    epoch = compute_epoch_at_slot(state.slot) + 1
    indices = get_active_validator_indices(state, epoch)
    total = len(indices)
    assert total > 0, "no active validators"
    seed = get_seed(state, epoch, params.DOMAIN_SYNC_COMMITTEE)
    perm = shuffled_positions(total, seed)
    eff = state.effective_balance
    max_eff = P.MAX_EFFECTIVE_BALANCE
    out: List[int] = []
    i = 0
    while len(out) < P.SYNC_COMMITTEE_SIZE:
        candidate = int(indices[perm[i % total]])
        rand_bytes = hashlib.sha256(seed + uint_to_bytes(i // 32)).digest()
        random_byte = rand_bytes[i % 32]
        if int(eff[candidate]) * 255 >= max_eff * random_byte:
            out.append(candidate)
        i += 1
    return out


def get_next_sync_committee(state) -> dict:
    """SyncCommittee value {pubkeys, aggregate_pubkey} for the next period."""
    from ..crypto import bls as _bls
    from ..crypto import curves as _curves

    indices = get_next_sync_committee_indices(state)
    pubkeys = [state.pubkeys[i] for i in indices]
    points = [_curves.g1_decompress(pk) for pk in pubkeys]
    agg = _bls.aggregate_pubkeys(points)
    return {
        "pubkeys": pubkeys,
        "aggregate_pubkey": _curves.g1_compress(agg),
    }


# -- committees (spec get_beacon_committee over the state) ------------------


def get_committee_count_per_slot(state, epoch: int) -> int:
    from .util import compute_committee_count_per_slot

    return compute_committee_count_per_slot(
        int(active_mask(state, epoch).sum())
    )


def get_beacon_committee(state, slot: int, index: int) -> np.ndarray:
    """Committee `index` at `slot` (one shuffle per epoch, sliced)."""
    epoch = compute_epoch_at_slot(slot)
    indices = get_active_validator_indices(state, epoch)
    seed = get_seed(state, epoch, params.DOMAIN_BEACON_ATTESTER)
    per_slot = get_committee_count_per_slot(state, epoch)
    assert 0 <= index < per_slot, "committee index out of range"
    committees_per_epoch = per_slot * P.SLOTS_PER_EPOCH
    committee_global = (slot % P.SLOTS_PER_EPOCH) * per_slot + index
    n = len(indices)
    # Memoize the whole-epoch shuffle on the state (one shuffle per epoch
    # serves every attestation in it — the EpochContext caching idea).
    cache = getattr(state, "_shuffle_cache", None)
    if cache is None:
        cache = {}
        state._shuffle_cache = cache
    key = (epoch, seed)
    shuffled = cache.get(key)
    if shuffled is None:
        shuffled = indices[shuffled_positions(n, seed)]
        cache[key] = shuffled
        if len(cache) > 4:
            cache.pop(next(iter(cache)))
    start = n * committee_global // committees_per_epoch
    end = n * (committee_global + 1) // committees_per_epoch
    return shuffled[start:end]


def get_attesting_indices(
    state, data: dict, aggregation_bits: Sequence[bool]
) -> List[int]:
    committee = get_beacon_committee(state, data["slot"], data["index"])
    assert len(aggregation_bits) == len(committee), (
        "aggregation bits length != committee size"
    )
    return sorted(
        int(v) for v, b in zip(committee, aggregation_bits) if b
    )
