"""Block processing — altair.

Reference: packages/state-transition/src/block/index.ts (processBlock
order), processBlockHeader.ts, processRandao.ts, processEth1Data.ts,
processOperations.ts, processAttestationsAltair.ts,
processProposerSlashing.ts, processAttesterSlashing.ts,
processDeposit.ts, processVoluntaryExit.ts, processSyncCommittee.ts,
slashValidator.ts, isValidIndexedAttestation.ts.

Signature verification is gated by `verify_signatures` exactly like the
reference's ProcessBlockOpts {verifySignatures} (block/types.ts): the
import pipeline verifies every signature up front in one TPU batch
(chain/block_processor.py + state_transition/signature_sets.py), then
runs the transition with verify_signatures=False — the reference's
"verified in bulk by the BLS worker pool" flow.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Sequence

import numpy as np

from .. import params
from ..ssz import hash_tree_root as _htr, is_valid_merkle_branch, uint64
from ..types import (
    AttestationData,
    BeaconBlockHeader,
    DepositDataType,
    Eth1Data,
    VoluntaryExit,
)
from .accessors import (
    get_attesting_indices,
    get_beacon_committee,
    get_beacon_proposer_index,
    get_block_root,
    get_block_root_at_slot,
    get_committee_count_per_slot,
    get_randao_mix,
    get_total_active_balance,
    integer_squareroot,
    is_slashable_validator_mask,
)
from .epoch import initiate_validator_exit
from .util import compute_epoch_at_slot

P = params.ACTIVE_PRESET
FAR_FUTURE = params.FAR_FUTURE_EPOCH


class BlockProcessError(AssertionError):
    """Raised when a block is invalid against the state."""


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise BlockProcessError(msg)


def _verify_sig(state, pubkey_index: int, signing_root: bytes, sig: bytes) -> bool:
    from ..crypto import bls as _bls

    return _bls.verify_bytes(
        state.pubkeys[pubkey_index], signing_root, sig
    )


# -- header -----------------------------------------------------------------


def process_block_header(state, block: Dict) -> None:
    _require(block["slot"] == state.slot, "block slot != state slot")
    _require(
        block["slot"] > state.latest_block_header["slot"],
        "block not newer than latest header",
    )
    proposer = get_beacon_proposer_index(state)
    _require(
        block["proposer_index"] == proposer, "wrong proposer index"
    )
    _require(
        block["parent_root"]
        == BeaconBlockHeader.hash_tree_root(state.latest_block_header),
        "parent root mismatch",
    )
    _require(not bool(state.slashed[proposer]), "proposer is slashed")
    body_type = _body_type(state, block["slot"], block["body"])
    state.latest_block_header = {
        "slot": block["slot"],
        "proposer_index": block["proposer_index"],
        "parent_root": block["parent_root"],
        "state_root": b"\x00" * 32,
        "body_root": body_type.hash_tree_root(block["body"]),
    }


def _body_type(state, slot: int, body: Dict = None):
    """Fork body container; the BLINDED variant when the body carries a
    payload header (builder flow — same hash_tree_root by design)."""
    if body is not None and "execution_payload_header" in body:
        return state.config.get_blinded_fork_types(slot)[2]
    return state.config.get_fork_types(slot)[2]


# -- randao -----------------------------------------------------------------


def process_randao(state, body: Dict, verify_signatures: bool) -> None:
    epoch = compute_epoch_at_slot(state.slot)
    reveal = body["randao_reveal"]
    if verify_signatures:
        proposer = get_beacon_proposer_index(state)
        domain = state.config.get_domain(state.slot, params.DOMAIN_RANDAO)
        root = state.config.compute_signing_root(
            uint64.hash_tree_root(epoch), domain
        )
        _require(
            _verify_sig(state, proposer, root, reveal),
            "invalid randao reveal",
        )
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(state, epoch), hashlib.sha256(reveal).digest()
        )
    )
    state.randao_mixes[epoch % P.EPOCHS_PER_HISTORICAL_VECTOR] = mix


# -- eth1 data --------------------------------------------------------------


def process_eth1_data(state, body: Dict) -> None:
    vote = body["eth1_data"]
    state.eth1_data_votes.append(dict(vote))
    period_slots = P.EPOCHS_PER_ETH1_VOTING_PERIOD * P.SLOTS_PER_EPOCH
    vote_root = Eth1Data.hash_tree_root(vote)
    votes = sum(
        1
        for v in state.eth1_data_votes
        if Eth1Data.hash_tree_root(v) == vote_root
    )
    if votes * 2 > period_slots:
        state.eth1_data = dict(vote)


# -- attestations (altair participation-flag path) --------------------------


def get_attestation_participation_flag_indices(
    state, data: Dict, inclusion_delay: int
) -> List[int]:
    """Spec get_attestation_participation_flag_indices."""
    current_epoch = compute_epoch_at_slot(state.slot)
    if data["target"]["epoch"] == current_epoch:
        justified_checkpoint = state.current_justified_checkpoint
    else:
        justified_checkpoint = state.previous_justified_checkpoint

    is_matching_source = (
        data["source"]["epoch"] == justified_checkpoint["epoch"]
        and data["source"]["root"] == justified_checkpoint["root"]
    )
    _require(is_matching_source, "attestation source does not match justified")
    is_matching_target = is_matching_source and data["target"][
        "root"
    ] == get_block_root(state, data["target"]["epoch"])
    is_matching_head = (
        is_matching_target
        and data["beacon_block_root"]
        == get_block_root_at_slot(state, data["slot"])
    )
    flags: List[int] = []
    if is_matching_source and inclusion_delay <= integer_squareroot(
        P.SLOTS_PER_EPOCH
    ):
        flags.append(params.TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= P.SLOTS_PER_EPOCH:
        flags.append(params.TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == P.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(params.TIMELY_HEAD_FLAG_INDEX)
    return flags


def _attestation_sanity_checks(state, attestation: Dict) -> None:
    """The fork-independent gossip/STF attestation preconditions (spec
    process_attestation head, shared by the phase0 and altair paths)."""
    data = attestation["data"]
    current_epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = max(current_epoch - 1, params.GENESIS_EPOCH)
    _require(
        data["target"]["epoch"] in (previous_epoch, current_epoch),
        "attestation target epoch out of range",
    )
    _require(
        data["target"]["epoch"] == compute_epoch_at_slot(data["slot"]),
        "target epoch != epoch of slot",
    )
    _require(
        data["slot"] + P.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot,
        "attestation too new",
    )
    _require(
        state.slot <= data["slot"] + P.SLOTS_PER_EPOCH,
        "attestation too old",
    )
    _require(
        data["index"]
        < get_committee_count_per_slot(state, data["target"]["epoch"]),
        "committee index out of range",
    )
    committee = get_beacon_committee(state, data["slot"], data["index"])
    _require(
        len(attestation["aggregation_bits"]) == len(committee),
        "aggregation bits length mismatch",
    )


def process_attestation_phase0(
    state, attestation: Dict, verify_signatures: bool
) -> None:
    """phase0: append a PendingAttestation record; FFG source must match
    the era's justified checkpoint (reference:
    state-transition/src/block/processAttestationPhase0.ts:1)."""
    data = attestation["data"]
    current_epoch = compute_epoch_at_slot(state.slot)
    _attestation_sanity_checks(state, attestation)
    if data["target"]["epoch"] == current_epoch:
        jcp = state.current_justified_checkpoint
        book = state.current_epoch_attestations
    else:
        jcp = state.previous_justified_checkpoint
        book = state.previous_epoch_attestations
    _require(
        data["source"]["epoch"] == jcp["epoch"]
        and bytes(data["source"]["root"]) == bytes(jcp["root"]),
        "attestation source does not match justified",
    )
    if verify_signatures:
        attesting = get_attesting_indices(
            state, data, attestation["aggregation_bits"]
        )
        _require(
            is_valid_indexed_attestation(
                state,
                {
                    "attesting_indices": attesting,
                    "data": data,
                    "signature": attestation["signature"],
                },
            ),
            "invalid attestation signature",
        )
    book.append(
        {
            "aggregation_bits": list(attestation["aggregation_bits"]),
            "data": {
                **dict(data),
                "source": dict(data["source"]),
                "target": dict(data["target"]),
            },
            "inclusion_delay": int(state.slot) - int(data["slot"]),
            "proposer_index": get_beacon_proposer_index(state),
        }
    )


def process_attestation(
    state, attestation: Dict, verify_signatures: bool
) -> None:
    if getattr(state, "previous_epoch_attestations", None) is not None:
        return process_attestation_phase0(
            state, attestation, verify_signatures
        )
    data = attestation["data"]
    current_epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = max(current_epoch - 1, params.GENESIS_EPOCH)
    _require(
        data["target"]["epoch"] in (previous_epoch, current_epoch),
        "attestation target epoch out of range",
    )
    _require(
        data["target"]["epoch"] == compute_epoch_at_slot(data["slot"]),
        "target epoch != epoch of slot",
    )
    _require(
        data["slot"] + P.MIN_ATTESTATION_INCLUSION_DELAY <= state.slot,
        "attestation too new",
    )
    _require(
        state.slot <= data["slot"] + P.SLOTS_PER_EPOCH,
        "attestation too old",
    )
    _require(
        data["index"]
        < get_committee_count_per_slot(state, data["target"]["epoch"]),
        "committee index out of range",
    )
    committee = get_beacon_committee(state, data["slot"], data["index"])
    _require(
        len(attestation["aggregation_bits"]) == len(committee),
        "aggregation bits length mismatch",
    )

    inclusion_delay = state.slot - data["slot"]
    flag_indices = get_attestation_participation_flag_indices(
        state, data, inclusion_delay
    )

    attesting = get_attesting_indices(
        state, data, attestation["aggregation_bits"]
    )
    if verify_signatures:
        _require(
            is_valid_indexed_attestation(
                state,
                {
                    "attesting_indices": attesting,
                    "data": data,
                    "signature": attestation["signature"],
                },
            ),
            "invalid attestation signature",
        )

    if data["target"]["epoch"] == current_epoch:
        participation = state.current_epoch_participation
    else:
        participation = state.previous_epoch_participation

    base_rewards = _base_rewards_vector(state)
    proposer_reward_numerator = 0
    idx = np.asarray(attesting, np.int64)
    for flag_index in flag_indices:
        weight = params.PARTICIPATION_FLAG_WEIGHTS[flag_index]
        bit = np.uint8(1 << flag_index)
        fresh = (participation[idx] & bit) == 0
        if fresh.any():
            new_idx = idx[fresh]
            proposer_reward_numerator += int(
                (base_rewards[new_idx] * weight).sum()
            )
            participation[new_idx] |= bit

    if proposer_reward_numerator:
        proposer_reward_denominator = (
            (params.WEIGHT_DENOMINATOR - params.PROPOSER_WEIGHT)
            * params.WEIGHT_DENOMINATOR
            // params.PROPOSER_WEIGHT
        )
        proposer_reward = (
            proposer_reward_numerator // proposer_reward_denominator
        )
        state.increase_balance(
            get_beacon_proposer_index(state), proposer_reward
        )


def _base_rewards_vector(state) -> np.ndarray:
    """Per-validator base rewards, memoized per epoch: effective
    balances and the active set only change at epoch processing, so one
    registry pass serves every attestation in the epoch (the reference
    caches baseRewardPerIncrement on the EpochCache)."""
    epoch = compute_epoch_at_slot(state.slot)
    cached = getattr(state, "_base_reward_cache", None)
    if cached is not None and cached[0] == (epoch, state.num_validators):
        return cached[1]
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    per_increment = (
        increment
        * P.BASE_REWARD_FACTOR
        // integer_squareroot(get_total_active_balance(state))
    )
    out = (
        state.effective_balance.astype(np.int64) // np.int64(increment)
    ) * np.int64(per_increment)
    state._base_reward_cache = ((epoch, state.num_validators), out)
    return out


def is_valid_indexed_attestation(state, indexed: Dict) -> bool:
    """Spec is_valid_indexed_attestation (with signature check)."""
    from ..crypto import bls as _bls
    from ..crypto import curves as _curves

    indices = list(indexed["attesting_indices"])
    if not indices or indices != sorted(set(indices)):
        return False
    if any(i >= state.num_validators for i in indices):
        return False
    domain = state.config.get_domain(
        state.slot,
        params.DOMAIN_BEACON_ATTESTER,
        indexed["data"]["slot"],
    )
    root = state.config.compute_signing_root(
        AttestationData.hash_tree_root(indexed["data"]), domain
    )
    try:
        pks = [_curves.g1_decompress(state.pubkeys[i]) for i in indices]
        sig = _curves.g2_decompress(indexed["signature"])
    except Exception:
        return False
    return _bls.fast_aggregate_verify(pks, root, sig)


# -- slashings --------------------------------------------------------------


def slash_validator(
    state, slashed_index: int, whistleblower_index: int = None
) -> None:
    """Spec slash_validator; penalty quotient and the whistleblower
    split are fork-scaled (phase0: quotient 128, proposer share
    whistleblower//PROPOSER_REWARD_QUOTIENT; altair: quotient 64,
    PROPOSER_WEIGHT/WEIGHT_DENOMINATOR)."""
    phase0 = getattr(state, "previous_epoch_attestations", None) is not None
    epoch = compute_epoch_at_slot(state.slot)
    initiate_validator_exit(state, slashed_index)
    state.slashed[slashed_index] = True
    state.withdrawable_epoch[slashed_index] = max(
        int(state.withdrawable_epoch[slashed_index]),
        epoch + P.EPOCHS_PER_SLASHINGS_VECTOR,
    )
    eff = int(state.effective_balance[slashed_index])
    state.slashings[epoch % P.EPOCHS_PER_SLASHINGS_VECTOR] += np.uint64(eff)
    min_quotient = (
        2 * P.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR  # phase0 = 128
        if phase0
        else P.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    )
    state.decrease_balance(slashed_index, eff // min_quotient)

    proposer_index = get_beacon_proposer_index(state)
    if whistleblower_index is None:
        whistleblower_index = proposer_index
    whistleblower_reward = eff // P.WHISTLEBLOWER_REWARD_QUOTIENT
    if phase0:
        proposer_reward = whistleblower_reward // P.PROPOSER_REWARD_QUOTIENT
    else:
        proposer_reward = (
            whistleblower_reward
            * params.PROPOSER_WEIGHT
            // params.WEIGHT_DENOMINATOR
        )
    state.increase_balance(proposer_index, proposer_reward)
    state.increase_balance(
        whistleblower_index, whistleblower_reward - proposer_reward
    )


def process_proposer_slashing(
    state, proposer_slashing: Dict, verify_signatures: bool
) -> None:
    h1 = proposer_slashing["signed_header_1"]["message"]
    h2 = proposer_slashing["signed_header_2"]["message"]
    _require(h1["slot"] == h2["slot"], "slashing headers differ in slot")
    _require(
        h1["proposer_index"] == h2["proposer_index"],
        "slashing headers differ in proposer",
    )
    _require(
        BeaconBlockHeader.hash_tree_root(h1)
        != BeaconBlockHeader.hash_tree_root(h2),
        "slashing headers identical",
    )
    proposer = h1["proposer_index"]
    _require(proposer < state.num_validators, "unknown proposer")
    epoch = compute_epoch_at_slot(state.slot)
    _require(
        bool(is_slashable_validator_mask(state, epoch)[proposer]),
        "proposer not slashable",
    )
    if verify_signatures:
        for signed in (
            proposer_slashing["signed_header_1"],
            proposer_slashing["signed_header_2"],
        ):
            domain = state.config.get_domain(
                state.slot,
                params.DOMAIN_BEACON_PROPOSER,
                signed["message"]["slot"],
            )
            root = state.config.compute_signing_root(
                BeaconBlockHeader.hash_tree_root(signed["message"]), domain
            )
            _require(
                _verify_sig(state, proposer, root, signed["signature"]),
                "invalid proposer slashing signature",
            )
    slash_validator(state, proposer)


def is_slashable_attestation_data(data_1: Dict, data_2: Dict) -> bool:
    """Double vote or surround vote (spec)."""
    double = (
        AttestationData.hash_tree_root(data_1)
        != AttestationData.hash_tree_root(data_2)
        and data_1["target"]["epoch"] == data_2["target"]["epoch"]
    )
    surround = (
        data_1["source"]["epoch"] < data_2["source"]["epoch"]
        and data_2["target"]["epoch"] < data_1["target"]["epoch"]
    )
    return double or surround


def process_attester_slashing(
    state, attester_slashing: Dict, verify_signatures: bool
) -> None:
    att_1 = attester_slashing["attestation_1"]
    att_2 = attester_slashing["attestation_2"]
    _require(
        is_slashable_attestation_data(att_1["data"], att_2["data"]),
        "attestations not slashable",
    )
    if verify_signatures:
        _require(
            is_valid_indexed_attestation(state, att_1),
            "attestation_1 invalid",
        )
        _require(
            is_valid_indexed_attestation(state, att_2),
            "attestation_2 invalid",
        )
    else:
        for att in (att_1, att_2):
            ind = list(att["attesting_indices"])
            _require(
                bool(ind) and ind == sorted(set(ind)),
                "attesting indices not sorted/unique",
            )
    epoch = compute_epoch_at_slot(state.slot)
    slashable = is_slashable_validator_mask(state, epoch)
    slashed_any = False
    for index in sorted(
        set(att_1["attesting_indices"]) & set(att_2["attesting_indices"])
    ):
        if index < state.num_validators and bool(slashable[index]):
            slash_validator(state, index)
            slashed_any = True
    _require(slashed_any, "no validator slashed")


# -- deposits ---------------------------------------------------------------


def get_deposit_signing_root(config, deposit_data: Dict) -> bytes:
    """Deposit message domain: genesis fork version, zero GVR (spec
    compute_domain default)."""
    from ..types import DepositMessage as deposit_message

    fork_version = config.fork_versions[params.ForkName.phase0]
    fork_data_root = config.fork_data_root(fork_version, b"\x00" * 32)
    domain = params.DOMAIN_DEPOSIT + fork_data_root[:28]
    return config.compute_signing_root(
        deposit_message.hash_tree_root(
            {
                "pubkey": deposit_data["pubkey"],
                "withdrawal_credentials": deposit_data[
                    "withdrawal_credentials"
                ],
                "amount": deposit_data["amount"],
            }
        ),
        domain,
    )


def process_deposit(state, deposit: Dict) -> None:
    """Spec process_deposit: merkle proof against eth1_data.deposit_root,
    then apply (deposit signatures are checked regardless of
    verify_signatures — they are self-certifying, reference
    processDeposit.ts)."""
    _require(
        is_valid_merkle_branch(
            DepositDataType.hash_tree_root(deposit["data"]),
            deposit["proof"],
            params.DEPOSIT_CONTRACT_TREE_DEPTH + 1,
            state.eth1_deposit_index,
            state.eth1_data["deposit_root"],
        ),
        "invalid deposit proof",
    )
    state.eth1_deposit_index += 1
    apply_deposit(state, deposit["data"])


def apply_deposit(state, data: Dict) -> None:
    from ..crypto import bls as _bls
    from ..crypto import curves as _curves

    pubkey = data["pubkey"]
    amount = data["amount"]
    index = state.pubkey_index(pubkey)
    if index is not None:
        state.increase_balance(index, amount)
        return
    # new validator: BLS proof-of-possession must verify
    root = get_deposit_signing_root(state.config, data)
    try:
        pk = _curves.g1_decompress(pubkey)
        sig = _curves.g2_decompress(data["signature"])
        ok = _bls.verify(pk, root, sig)
    except Exception:
        ok = False
    if not ok:
        return  # invalid deposit signature: ignored, not rejected
    state.add_validator(pubkey, data["withdrawal_credentials"], amount)


# -- voluntary exits --------------------------------------------------------


def process_voluntary_exit(
    state, signed_exit: Dict, verify_signatures: bool
) -> None:
    exit_msg = signed_exit["message"]
    index = exit_msg["validator_index"]
    _require(index < state.num_validators, "unknown validator")
    current_epoch = compute_epoch_at_slot(state.slot)
    _require(
        bool(
            (state.activation_epoch[index] <= current_epoch)
            & (current_epoch < state.exit_epoch[index])
        ),
        "validator not active",
    )
    _require(
        int(state.exit_epoch[index]) == FAR_FUTURE, "exit already initiated"
    )
    _require(
        current_epoch >= exit_msg["epoch"], "exit epoch in the future"
    )
    _require(
        current_epoch
        >= int(state.activation_epoch[index])
        + state.config.SHARD_COMMITTEE_PERIOD,
        "validator too young to exit",
    )
    if verify_signatures:
        from .signature_sets import voluntary_exit_signing_root

        root = voluntary_exit_signing_root(
            state.config,
            state.genesis_validators_root,
            state.fork_at_least(params.ForkName.deneb),
            state.slot,
            exit_msg,
        )
        _require(
            _verify_sig(state, index, root, signed_exit["signature"]),
            "invalid exit signature",
        )
    initiate_validator_exit(state, index)


# -- capella: withdrawals + BLS-to-execution changes ------------------------


def has_eth1_withdrawal_credential(cred: bytes) -> bool:
    return bytes(cred[:1]) == params.ETH1_ADDRESS_WITHDRAWAL_PREFIX


def _is_fully_withdrawable(state, index: int, epoch: int) -> bool:
    """spec is_fully_withdrawable_validator"""
    return (
        has_eth1_withdrawal_credential(state.withdrawal_credentials[index])
        and int(state.withdrawable_epoch[index]) <= epoch
        and int(state.balances[index]) > 0
    )


def _is_partially_withdrawable(state, index: int) -> bool:
    """spec is_partially_withdrawable_validator: effective balance pinned
    at max AND an excess balance above it."""
    return (
        has_eth1_withdrawal_credential(state.withdrawal_credentials[index])
        and int(state.effective_balance[index]) == P.MAX_EFFECTIVE_BALANCE
        and int(state.balances[index]) > P.MAX_EFFECTIVE_BALANCE
    )


def get_expected_withdrawals(state) -> List[Dict]:
    """spec get_expected_withdrawals (capella): sweep up to
    MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP validators from the rotating
    cursor, emitting full withdrawals for withdrawable validators and
    excess-balance skims for max-effective ones, capped at
    MAX_WITHDRAWALS_PER_PAYLOAD (reference:
    state-transition/src/block/processWithdrawals.ts)."""
    epoch = compute_epoch_at_slot(state.slot)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    n = state.num_validators
    withdrawals: List[Dict] = []
    for _ in range(min(P.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP, n)):
        if len(withdrawals) == P.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        balance = int(state.balances[validator_index])
        address = bytes(
            state.withdrawal_credentials[validator_index][12:]
        )
        if _is_fully_withdrawable(state, validator_index, epoch):
            withdrawals.append(
                {
                    "index": withdrawal_index,
                    "validator_index": validator_index,
                    "address": address,
                    "amount": balance,
                }
            )
            withdrawal_index += 1
        elif _is_partially_withdrawable(state, validator_index):
            withdrawals.append(
                {
                    "index": withdrawal_index,
                    "validator_index": validator_index,
                    "address": address,
                    "amount": balance - P.MAX_EFFECTIVE_BALANCE,
                }
            )
            withdrawal_index += 1
        validator_index = (validator_index + 1) % n
    return withdrawals


def process_withdrawals(state, payload: Dict) -> None:
    """spec process_withdrawals: the payload's withdrawal list must equal
    the protocol-computed expectation; balances are debited and both
    cursors advance."""
    from ..types import Withdrawal

    from ..ssz import List as SszList

    expected = get_expected_withdrawals(state)
    if "withdrawals" in payload:
        got = list(payload["withdrawals"])
        _require(
            len(got) == len(expected)
            and all(
                Withdrawal.hash_tree_root(a) == Withdrawal.hash_tree_root(e)
                for a, e in zip(got, expected)
            ),
            "payload withdrawals do not match protocol expectation",
        )
    else:
        # blinded body: the header commits to the list by root (spec
        # blinded process_withdrawals compares hash_tree_root)
        expected_root = SszList(
            Withdrawal, P.MAX_WITHDRAWALS_PER_PAYLOAD
        ).hash_tree_root(expected)
        _require(
            bytes(payload["withdrawals_root"]) == bytes(expected_root),
            "header withdrawals_root does not match protocol expectation",
        )
    for w in expected:
        state.decrease_balance(w["validator_index"], w["amount"])
    if expected:
        state.next_withdrawal_index = expected[-1]["index"] + 1
    n = state.num_validators
    if len(expected) == P.MAX_WITHDRAWALS_PER_PAYLOAD:
        # full payload: resume after the last withdrawn validator
        state.next_withdrawal_validator_index = (
            expected[-1]["validator_index"] + 1
        ) % n
    else:
        # partial sweep: jump the cursor by the UNCLAMPED sweep bound
        # before the modulo (spec get_expected_withdrawals epilogue —
        # clamping changes the post-state cursor when n < sweep bound)
        state.next_withdrawal_validator_index = (
            state.next_withdrawal_validator_index
            + P.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
        ) % n


def process_bls_to_execution_change(
    state, signed_change: Dict, verify_signatures: bool
) -> None:
    """spec process_bls_to_execution_change: rotate 0x00 BLS withdrawal
    credentials to a 0x01 execution address; signed against the GENESIS
    fork domain so pre-signed changes outlive forks."""
    change = signed_change["message"]
    index = change["validator_index"]
    _require(index < state.num_validators, "unknown validator")
    cred = bytes(state.withdrawal_credentials[index])
    _require(
        cred[:1] == params.BLS_WITHDRAWAL_PREFIX,
        "credentials already rotated",
    )
    pk_hash = hashlib.sha256(bytes(change["from_bls_pubkey"])).digest()
    _require(cred[1:] == pk_hash[1:], "from_bls_pubkey does not match credentials")
    if verify_signatures:
        from ..crypto import bls as _bls
        from ..crypto import curves as _curves
        from ..types import BLSToExecutionChange

        domain = state.config.compute_domain(
            params.DOMAIN_BLS_TO_EXECUTION_CHANGE,
            state.config.fork_versions[params.ForkName.phase0],
            state.genesis_validators_root,
        )
        root = state.config.compute_signing_root(
            BLSToExecutionChange.hash_tree_root(change), domain
        )
        try:
            pk = _curves.g1_decompress(bytes(change["from_bls_pubkey"]))
            sig = _curves.g2_decompress(bytes(signed_change["signature"]))
            ok = _bls.verify(pk, root, sig)
        except Exception:
            ok = False
        _require(ok, "invalid BLS-to-execution-change signature")
    state.withdrawal_credentials[index] = (
        params.ETH1_ADDRESS_WITHDRAWAL_PREFIX
        + b"\x00" * 11
        + bytes(change["to_execution_address"])
    )


# -- sync aggregate ---------------------------------------------------------


def process_sync_aggregate(
    state, sync_aggregate: Dict, verify_signatures: bool
) -> None:
    from ..crypto import bls as _bls
    from ..crypto import curves as _curves

    bits = sync_aggregate["sync_committee_bits"]
    committee_pubkeys = state.current_sync_committee["pubkeys"]
    _require(len(bits) == len(committee_pubkeys), "sync bits length")

    if verify_signatures:
        previous_slot = max(state.slot, 1) - 1
        domain = state.config.get_domain(
            state.slot, params.DOMAIN_SYNC_COMMITTEE, previous_slot
        )
        root = state.config.compute_signing_root(
            get_block_root_at_slot(state, previous_slot), domain
        )
        participant_pks = [
            pk for pk, bit in zip(committee_pubkeys, bits) if bit
        ]
        try:
            sig = _curves.g2_decompress(
                sync_aggregate["sync_committee_signature"]
            )
            pks = [_curves.g1_decompress(pk) for pk in participant_pks]
            ok = _eth_fast_aggregate_verify(_bls, pks, root, sig)
        except Exception:
            ok = False
        _require(ok, "invalid sync aggregate signature")

    # rewards
    total_active_increments = (
        get_total_active_balance(state) // P.EFFECTIVE_BALANCE_INCREMENT
    )
    base_reward_per_increment = (
        P.EFFECTIVE_BALANCE_INCREMENT
        * P.BASE_REWARD_FACTOR
        // integer_squareroot(get_total_active_balance(state))
    )
    total_base_rewards = base_reward_per_increment * total_active_increments
    max_participant_rewards = (
        total_base_rewards
        * params.SYNC_REWARD_WEIGHT
        // params.WEIGHT_DENOMINATOR
        // P.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // P.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward
        * params.PROPOSER_WEIGHT
        // (params.WEIGHT_DENOMINATOR - params.PROPOSER_WEIGHT)
    )
    proposer_index = get_beacon_proposer_index(state)
    committee_indices = _sync_committee_validator_indices(state)
    for i, bit in enumerate(bits):
        vindex = committee_indices[i]
        if bit:
            state.increase_balance(vindex, participant_reward)
            state.increase_balance(proposer_index, proposer_reward)
        else:
            state.decrease_balance(vindex, participant_reward)


def _sync_committee_validator_indices(state) -> List[int]:
    """Map current sync-committee pubkeys back to validator indices."""
    return [
        state.pubkey_index(pk)
        for pk in state.current_sync_committee["pubkeys"]
    ]


def _eth_fast_aggregate_verify(_bls, pks, root, sig) -> bool:
    """eth_fast_aggregate_verify: empty participation + infinity sig is
    valid (altair spec)."""
    if not pks and sig is None:
        return True
    if not pks:
        return False
    return _bls.fast_aggregate_verify(pks, root, sig)


# -- operations + entry -----------------------------------------------------


def process_operations(state, body: Dict, verify_signatures: bool) -> None:
    expected_deposits = min(
        P.MAX_DEPOSITS,
        state.eth1_data["deposit_count"] - state.eth1_deposit_index,
    )
    _require(
        len(body["deposits"]) == expected_deposits,
        "wrong deposit count in block",
    )
    for op in body["proposer_slashings"]:
        process_proposer_slashing(state, op, verify_signatures)
    for op in body["attester_slashings"]:
        process_attester_slashing(state, op, verify_signatures)
    for op in body["attestations"]:
        process_attestation(state, op, verify_signatures)
    for op in body["deposits"]:
        process_deposit(state, op)
    for op in body["voluntary_exits"]:
        process_voluntary_exit(state, op, verify_signatures)
    for op in body.get("bls_to_execution_changes", ()):
        _require(
            state.fork_at_least(params.ForkName.capella),
            "bls_to_execution_changes before capella",
        )
        process_bls_to_execution_change(state, op, verify_signatures)


def is_merge_transition_complete(state) -> bool:
    """The payload header differs from the default (spec
    is_merge_transition_complete)."""
    from ..types import ExecutionPayloadHeader

    header = state.latest_execution_payload_header
    return header is not None and ExecutionPayloadHeader.hash_tree_root(
        header
    ) != ExecutionPayloadHeader.hash_tree_root(ExecutionPayloadHeader.default())


def payload_to_header(payload: Dict) -> Dict:
    """ExecutionPayload -> ExecutionPayloadHeader (transactions list ->
    transactions_root; capella also roots the withdrawal list, deneb
    copies the blob gas fields)."""
    from ..types import Transaction, Withdrawal
    from ..ssz import List as SszList

    txs_root = SszList(Transaction, 1_048_576).hash_tree_root(
        payload["transactions"]
    )
    header = {
        k: payload[k]
        for k in (
            "parent_hash", "fee_recipient", "state_root", "receipts_root",
            "logs_bloom", "prev_randao", "block_number", "gas_limit",
            "gas_used", "timestamp", "extra_data", "base_fee_per_gas",
            "block_hash",
        )
    }
    header["transactions_root"] = txs_root
    if "withdrawals" in payload:
        header["withdrawals_root"] = SszList(
            Withdrawal, P.MAX_WITHDRAWALS_PER_PAYLOAD
        ).hash_tree_root(payload["withdrawals"])
    if "blob_gas_used" in payload:
        header["blob_gas_used"] = payload["blob_gas_used"]
        header["excess_blob_gas"] = payload["excess_blob_gas"]
    return header


def _is_nondefault_payload(payload: Dict) -> bool:
    """spec is_merge_transition_block's payload != ExecutionPayload()
    test (a default payload means execution is not yet enabled).
    Accepts either shape: a full payload or a blinded header (the
    bellatrix field subset decides default-ness in both cases)."""
    from ..types import ExecutionPayload, ExecutionPayloadHeader

    t = (
        ExecutionPayload
        if "transactions" in payload
        else ExecutionPayloadHeader
    )
    return t.hash_tree_root(payload) != t.hash_tree_root(t.default())


def process_execution_payload(state, payload: Dict) -> None:
    """Consensus-side payload checks + header update (reference:
    bellatrix block/processExecutionPayload.ts).  EL-side validity
    (engine_newPayload) runs at the chain layer as the parallel
    verification leg — NOT here."""
    from .accessors import get_randao_mix

    _require(
        state.latest_execution_payload_header is not None,
        "pre-bellatrix state cannot process an execution payload",
    )
    if is_merge_transition_complete(state):
        _require(
            bytes(payload["parent_hash"])
            == bytes(state.latest_execution_payload_header["block_hash"]),
            "payload parent hash does not extend the latest header",
        )
    epoch = compute_epoch_at_slot(state.slot)
    _require(
        bytes(payload["prev_randao"]) == bytes(get_randao_mix(state, epoch)),
        "payload prev_randao mismatch",
    )
    expected_time = (
        state.genesis_time + state.slot * params.SECONDS_PER_SLOT
    )
    _require(
        int(payload["timestamp"]) == expected_time,
        f"payload timestamp {payload['timestamp']} != slot time {expected_time}",
    )
    # a blinded body carries the HEADER (transactions_root instead of
    # the transactions list) — same consensus checks, stored verbatim
    # (spec: process_execution_payload on ExecutionPayloadHeader for
    # blinded blocks; reference state-transition handles both shapes)
    state.latest_execution_payload_header = (
        payload_to_header(payload)
        if "transactions" in payload
        else dict(payload)
    )


def process_block(state, block: Dict, verify_signatures: bool = False) -> None:
    """Full altair/bellatrix block processing (reference block/index.ts
    order; the payload step activates once the state carries a header)."""
    process_block_header(state, block)
    body = block["body"]
    if state.latest_execution_payload_header is not None:
        # blinded bodies carry the payload HEADER; the consensus checks
        # are identical (withdrawals verify against withdrawals_root)
        blinded = "execution_payload_header" in body
        _require(
            "execution_payload" in body or blinded,
            "bellatrix block must carry an execution payload",
        )
        payload = body["execution_payload_header" if blinded else "execution_payload"]
        if state.fork_at_least(params.ForkName.deneb):
            _require(
                len(body.get("blob_kzg_commitments", ()))
                <= P.MAX_BLOBS_PER_BLOCK,
                "too many blob commitments",
            )
        # spec is_execution_enabled: process the payload once the merge
        # transition is complete OR this block IS the transition block
        # (non-default payload); a pre-merge default payload is skipped.
        if is_merge_transition_complete(state) or _is_nondefault_payload(
            payload
        ):
            # capella order: withdrawals precede the payload header update
            # (spec capella process_block: process_withdrawals(payload)
            # then process_execution_payload)
            if state.next_withdrawal_index is not None:
                process_withdrawals(state, payload)
            # spec order: the payload step precedes randao — its
            # prev_randao check reads the PRE-block mix
            process_execution_payload(state, payload)
    process_randao(state, body, verify_signatures)
    process_eth1_data(state, body)
    process_operations(state, body, verify_signatures)
    if "sync_aggregate" in body:
        process_sync_aggregate(
            state, body["sync_aggregate"], verify_signatures
        )
