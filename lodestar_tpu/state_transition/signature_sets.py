"""Block/gossip signature-set extraction.

Mirror of the reference's extractor family (reference:
packages/state-transition/src/signatureSets/index.ts:26-73 and siblings;
block/processSyncCommittee.ts getSyncCommitteeSignatureSet): walk a
signed block (or gossip object) and emit every BLS statement it carries
as a wire-level set {validator indices, signing root, signature bytes}
ready for the TPU verifier's batched ingest.

Deposits are intentionally excluded — they may legally carry invalid
signatures (reference: signatureSets/index.ts:23-25).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import params
from ..bls.signature_set import WireSignatureSet
from ..config.chain_config import ChainConfig
from ..params import ForkName
from .. import types as T
from .epoch_cache import EpochCache
from .util import compute_epoch_at_slot, compute_start_slot_at_epoch


@dataclass
class BeaconStateView:
    """The slice of beacon state the extractors need: config + epoch
    cache + recent block roots (the reference passes the full
    CachedBeaconState; the TPU build's state surface is exactly this)."""

    config: ChainConfig
    slot: int
    epoch_cache: EpochCache
    # slot -> block root for sync-aggregate signing (reference:
    # getSyncCommitteeSignatureSet reads state.blockRoots)
    block_roots: Dict[int, bytes] = field(default_factory=dict)
    # previous-epoch committees (blocks carry prev-epoch attestations)
    prev_epoch_cache: Optional[EpochCache] = None
    # the STATE's genesis validators root — fork-agnostic domains
    # (deposits, BLS changes, EIP-7044 exits) must use the live chain's
    # value, not whatever the ChainConfig preset was built with
    _genesis_validators_root: Optional[bytes] = None

    @property
    def genesis_validators_root(self) -> bytes:
        if self._genesis_validators_root is not None:
            return self._genesis_validators_root
        return self.config.genesis_validators_root

    def get_block_root_at_slot(self, slot: int) -> bytes:
        return self.block_roots.get(slot, b"\x00" * 32)

    def get_indexed_attestation(self, attestation: dict) -> dict:
        """Dispatch to the committee cache of the attestation's epoch."""
        epoch = compute_epoch_at_slot(attestation["data"]["slot"])
        for cache in (self.epoch_cache, self.prev_epoch_cache):
            if cache is not None and cache.epoch == epoch:
                return cache.get_indexed_attestation(attestation)
        raise ValueError(f"no committee cache for epoch {epoch}")

    @classmethod
    def from_state(cls, state) -> "BeaconStateView":
        """Build the view from a full columnar BeaconState — the bridge
        from the state machine to the wire extractors (the reference
        passes CachedBeaconState straight through)."""
        from .accessors import get_active_validator_indices, get_seed

        epoch = compute_epoch_at_slot(state.slot)
        sync_indices = [
            state.pubkey_index(pk)
            for pk in state.current_sync_committee["pubkeys"]
        ]

        def _cache(ep: int) -> EpochCache:
            return EpochCache(
                state.pubkeys,
                ep,
                get_seed(state, ep, params.DOMAIN_BEACON_ATTESTER),
                active_indices=get_active_validator_indices(state, ep),
                sync_committee_indices=sync_indices,
            )

        window = {
            s: state.block_roots[s % params.SLOTS_PER_HISTORICAL_ROOT]
            for s in range(
                max(0, state.slot - params.SLOTS_PER_HISTORICAL_ROOT),
                state.slot,
            )
        }
        return cls(
            config=state.config,
            slot=state.slot,
            epoch_cache=_cache(epoch),
            block_roots=window,
            prev_epoch_cache=_cache(epoch - 1) if epoch > 0 else None,
            _genesis_validators_root=state.genesis_validators_root,
        )


def _block_types(config: ChainConfig, slot: int):
    block, _signed, body = config.get_fork_types(slot)
    return block, body


def _signing_root(config: ChainConfig, state_slot, domain_type, msg_slot, obj_root):
    domain = config.get_domain(state_slot, domain_type, msg_slot)
    return config.compute_signing_root(obj_root, domain)


# -- proposer (reference: signatureSets/proposer.ts) ------------------------


def get_proposer_signature_set(
    state: BeaconStateView, signed_block: dict
) -> WireSignatureSet:
    block = signed_block["message"]
    block_type, _ = _block_types(state.config, block["slot"])
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_BEACON_PROPOSER,
        block["slot"],
        block_type.hash_tree_root(block),
    )
    return WireSignatureSet.single(
        block["proposer_index"], root, signed_block["signature"]
    )


# -- randao (reference: signatureSets/randao.ts) ----------------------------


def get_randao_reveal_signature_set(
    state: BeaconStateView, block: dict
) -> WireSignatureSet:
    epoch = compute_epoch_at_slot(block["slot"])
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_RANDAO,
        block["slot"],
        T.Epoch.hash_tree_root(epoch),
    )
    return WireSignatureSet.single(
        block["proposer_index"], root, block["body"]["randao_reveal"]
    )


# -- attestations (reference: signatureSets/indexedAttestation.ts) ----------


def get_attestation_data_signing_root(state: BeaconStateView, data: dict) -> bytes:
    slot = compute_start_slot_at_epoch(data["target"]["epoch"])
    return _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_BEACON_ATTESTER,
        slot,
        T.AttestationData.hash_tree_root(data),
    )


def get_indexed_attestation_signature_set(
    state: BeaconStateView, indexed: dict
) -> WireSignatureSet:
    return WireSignatureSet.aggregate(
        indexed["attesting_indices"],
        get_attestation_data_signing_root(state, indexed["data"]),
        indexed["signature"],
    )


def get_attestation_signature_sets(
    state: BeaconStateView, signed_block: dict
) -> List[WireSignatureSet]:
    return [
        get_indexed_attestation_signature_set(
            state, state.get_indexed_attestation(att)
        )
        for att in signed_block["message"]["body"]["attestations"]
    ]


# -- slashings (reference: signatureSets/{proposer,attester}Slashings.ts) ---


def get_proposer_slashings_signature_sets(
    state: BeaconStateView, signed_block: dict
) -> List[WireSignatureSet]:
    out = []
    for slashing in signed_block["message"]["body"]["proposer_slashings"]:
        for key in ("signed_header_1", "signed_header_2"):
            signed_header = slashing[key]
            header = signed_header["message"]
            root = _signing_root(
                state.config,
                state.slot,
                params.DOMAIN_BEACON_PROPOSER,
                header["slot"],
                T.BeaconBlockHeader.hash_tree_root(header),
            )
            out.append(
                WireSignatureSet.single(
                    header["proposer_index"], root, signed_header["signature"]
                )
            )
    return out


def get_attester_slashings_signature_sets(
    state: BeaconStateView, signed_block: dict
) -> List[WireSignatureSet]:
    out = []
    for slashing in signed_block["message"]["body"]["attester_slashings"]:
        for key in ("attestation_1", "attestation_2"):
            out.append(
                get_indexed_attestation_signature_set(state, slashing[key])
            )
    return out


# -- exits (reference: signatureSets/voluntaryExits.ts) ---------------------


def voluntary_exit_signing_root(
    config: ChainConfig,
    genesis_validators_root: bytes,
    in_deneb: bool,
    state_slot: int,
    exit_msg: dict,
) -> bytes:
    """THE exit signing root — shared by the STF's per-op check
    (block.py process_voluntary_exit) and the wire extractor so the two
    verification paths cannot diverge.  EIP-7044 (deneb): exits verify
    against the CAPELLA fork domain permanently."""
    if in_deneb:
        domain = config.compute_domain(
            params.DOMAIN_VOLUNTARY_EXIT,
            config.fork_versions[ForkName.capella],
            genesis_validators_root,
        )
    else:
        domain = config.get_domain(
            state_slot,
            params.DOMAIN_VOLUNTARY_EXIT,
            compute_start_slot_at_epoch(exit_msg["epoch"]),
        )
    return config.compute_signing_root(
        T.VoluntaryExit.hash_tree_root(exit_msg), domain
    )


def get_voluntary_exits_signature_sets(
    state: BeaconStateView, signed_block: dict
) -> List[WireSignatureSet]:
    deneb = (
        state.config.get_fork_seq(state.slot)
        >= params.FORK_SEQ[ForkName.deneb]
    )
    out = []
    for signed_exit in signed_block["message"]["body"]["voluntary_exits"]:
        exit_msg = signed_exit["message"]
        root = voluntary_exit_signing_root(
            state.config,
            state.genesis_validators_root,
            deneb,
            state.slot,
            exit_msg,
        )
        out.append(
            WireSignatureSet.single(
                exit_msg["validator_index"], root, signed_exit["signature"]
            )
        )
    return out


# -- capella BLS-to-execution changes (reference: signatureSets/
# blsToExecutionChange.ts) — signed by the WITHDRAWAL key, which lives
# outside the validator signing-key registry, against the genesis fork
# domain so pre-signed changes survive forks ---------------------------------


def get_bls_to_execution_change_signature_sets(
    state: BeaconStateView, signed_block: dict
) -> List[WireSignatureSet]:
    out = []
    for signed_change in signed_block["message"]["body"].get(
        "bls_to_execution_changes", ()
    ):
        change = signed_change["message"]
        domain = state.config.compute_domain(
            params.DOMAIN_BLS_TO_EXECUTION_CHANGE,
            state.config.fork_versions[ForkName.phase0],
            state.genesis_validators_root,
        )
        root = state.config.compute_signing_root(
            T.BLSToExecutionChange.hash_tree_root(change), domain
        )
        out.append(
            WireSignatureSet.external(
                [bytes(change["from_bls_pubkey"])],
                root,
                signed_change["signature"],
            )
        )
    return out


# -- sync aggregate (reference: block/processSyncCommittee.ts) --------------


def get_sync_committee_signature_set(
    state: BeaconStateView, block: dict
) -> Optional[WireSignatureSet]:
    sync_aggregate = block["body"].get("sync_aggregate")
    if sync_aggregate is None:
        return None
    participants = state.epoch_cache.get_sync_committee_participant_indices(
        sync_aggregate["sync_committee_bits"]
    )
    # no participants -> nothing to verify (reference: index.ts:56-60)
    if not participants:
        return None
    # the aggregate signs the PREVIOUS slot's block root
    previous_slot = max(block["slot"], 1) - 1
    block_root = state.get_block_root_at_slot(previous_slot)
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_SYNC_COMMITTEE,
        previous_slot,
        T.Root.hash_tree_root(block_root),
    )
    return WireSignatureSet.aggregate(
        participants, root, sync_aggregate["sync_committee_signature"]
    )


# -- aggregate-and-proof (gossip; reference: chain/validation) --------------


def get_selection_proof_signature_set(
    state: BeaconStateView, slot: int, aggregator_index: int, selection_proof: bytes
) -> WireSignatureSet:
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_SELECTION_PROOF,
        slot,
        T.Slot.hash_tree_root(slot),
    )
    return WireSignatureSet.single(aggregator_index, root, selection_proof)


def get_aggregate_and_proof_signature_set(
    state: BeaconStateView, signed_agg: dict
) -> WireSignatureSet:
    msg = signed_agg["message"]
    slot = msg["aggregate"]["data"]["slot"]
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_AGGREGATE_AND_PROOF,
        slot,
        T.AggregateAndProof.hash_tree_root(msg),
    )
    return WireSignatureSet.single(
        msg["aggregator_index"], root, signed_agg["signature"]
    )


# -- sync-committee gossip objects (reference: chain/validation/
# syncCommittee.ts, syncCommitteeContributionAndProof.ts) -------------------


def get_sync_committee_message_signature_set(
    state: BeaconStateView, message: dict
) -> WireSignatureSet:
    """A SyncCommitteeMessage signs the beacon block root with
    DOMAIN_SYNC_COMMITTEE at the message slot."""
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_SYNC_COMMITTEE,
        message["slot"],
        T.Root.hash_tree_root(message["beacon_block_root"]),
    )
    return WireSignatureSet.single(
        message["validator_index"], root, message["signature"]
    )


def get_sync_committee_selection_proof_signature_set(
    state: BeaconStateView, contribution_and_proof: dict
) -> WireSignatureSet:
    """Selection proof over SyncAggregatorSelectionData{slot, subnet}."""
    contribution = contribution_and_proof["contribution"]
    data = {
        "slot": contribution["slot"],
        "subcommittee_index": contribution["subcommittee_index"],
    }
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
        contribution["slot"],
        T.SyncAggregatorSelectionData.hash_tree_root(data),
    )
    return WireSignatureSet.single(
        contribution_and_proof["aggregator_index"],
        root,
        contribution_and_proof["selection_proof"],
    )


def get_contribution_and_proof_signature_set(
    state: BeaconStateView, signed: dict
) -> WireSignatureSet:
    """The aggregator's signature over the ContributionAndProof."""
    msg = signed["message"]
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_CONTRIBUTION_AND_PROOF,
        msg["contribution"]["slot"],
        T.ContributionAndProof.hash_tree_root(msg),
    )
    return WireSignatureSet.single(
        msg["aggregator_index"], root, signed["signature"]
    )


def get_contribution_signature_set(
    state: BeaconStateView,
    contribution: dict,
    participant_indices,
) -> WireSignatureSet:
    """The contribution's aggregate over the subcommittee participants."""
    root = _signing_root(
        state.config,
        state.slot,
        params.DOMAIN_SYNC_COMMITTEE,
        contribution["slot"],
        T.Root.hash_tree_root(contribution["beacon_block_root"]),
    )
    return WireSignatureSet.aggregate(
        participant_indices, root, contribution["signature"]
    )


# -- the block-level aggregator (reference: signatureSets/index.ts:26-73) ---


def get_block_signature_sets(
    state: BeaconStateView,
    signed_block: dict,
    skip_proposer_signature: bool = False,
) -> List[WireSignatureSet]:
    """Every signature on the block except deposits."""
    block = signed_block["message"]
    sets: List[WireSignatureSet] = [
        get_randao_reveal_signature_set(state, block)
    ]
    sets.extend(get_proposer_slashings_signature_sets(state, signed_block))
    sets.extend(get_attester_slashings_signature_sets(state, signed_block))
    sets.extend(get_attestation_signature_sets(state, signed_block))
    sets.extend(get_voluntary_exits_signature_sets(state, signed_block))
    sets.extend(
        get_bls_to_execution_change_signature_sets(state, signed_block)
    )
    if not skip_proposer_signature:
        sets.append(get_proposer_signature_set(state, signed_block))
    if state.config.get_fork_seq(block["slot"]) >= params.FORK_SEQ[ForkName.altair]:
        sync_set = get_sync_committee_signature_set(state, block)
        if sync_set is not None:
            sets.append(sync_set)
    return sets
