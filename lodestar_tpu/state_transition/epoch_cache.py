"""EpochCache — committee assignments + pubkey index maps for one epoch.

The TPU-era analog of the reference's EpochContext/EpochCache
(reference: packages/state-transition/src/cache/epochContext.ts; pubkey
maps at cache/pubkeyCache.ts:29-47): the O(V) structures that scale with
validator count.  Differences by design:

  - index2pubkey IS the device-resident PubkeyTable (bls/pubkey_table.py)
    — the cache holds wire pubkeys + the index map, the curve points
    live in HBM,
  - committee shufflings are whole-registry numpy permutations
    (state_transition/util.py shuffle_list), sliced per (slot, index)
    — one vectorized shuffle per epoch instead of per-index loops,
  - seeds are injected (tests/replay synthesize them; a full state
    implementation derives them from randao mixes).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import params
from .util import (
    compute_committee_count_per_slot,
    compute_epoch_at_slot,
    shuffle_list,
)


class EpochCache:
    """Committees + pubkey maps for the epoch containing `epoch`."""

    def __init__(
        self,
        pubkeys: Sequence[bytes],
        epoch: int,
        seed: bytes,
        active_indices: Optional[np.ndarray] = None,
        sync_committee_indices: Optional[Sequence[int]] = None,
    ):
        self.epoch = epoch
        self.seed = seed
        self.pubkeys: List[bytes] = [bytes(pk) for pk in pubkeys]
        self.pubkey2index: Dict[bytes, int] = {
            pk: i for i, pk in enumerate(self.pubkeys)
        }
        n = len(self.pubkeys)
        self.active_indices = (
            np.arange(n, dtype=np.int64)
            if active_indices is None
            else np.asarray(active_indices, np.int64)
        )
        self.committees_per_slot = compute_committee_count_per_slot(
            len(self.active_indices)
        )
        # One whole-registry shuffle for the epoch; committees are slices.
        self._shuffling = shuffle_list(self.active_indices, seed)
        # Sync committee membership (reference: epochCtx.currentSyncCommitteeIndexed)
        self.sync_committee_indices = (
            list(sync_committee_indices)
            if sync_committee_indices is not None
            else list(
                np.resize(self.active_indices, params.SYNC_COMMITTEE_SIZE)
            )
        )

    # -- committees (reference: epochContext getBeaconCommittee) -----------

    def get_beacon_committee(self, slot: int, index: int) -> np.ndarray:
        """Validator indices of committee `index` at `slot`."""
        assert compute_epoch_at_slot(slot) == self.epoch, "slot outside epoch"
        assert 0 <= index < self.committees_per_slot, "committee index OOB"
        slots_per_epoch = params.SLOTS_PER_EPOCH
        committees_per_epoch = self.committees_per_slot * slots_per_epoch
        committee_global = (
            (slot % slots_per_epoch) * self.committees_per_slot + index
        )
        n = len(self._shuffling)
        start = n * committee_global // committees_per_epoch
        end = n * (committee_global + 1) // committees_per_epoch
        return self._shuffling[start:end]

    def get_attesting_indices(
        self, slot: int, index: int, aggregation_bits: Sequence[bool]
    ) -> List[int]:
        committee = self.get_beacon_committee(slot, index)
        if len(aggregation_bits) != len(committee):
            raise ValueError("aggregation bits length != committee size")
        return [int(v) for v, b in zip(committee, aggregation_bits) if b]

    def get_indexed_attestation(self, attestation: dict) -> dict:
        """phase0.Attestation value -> IndexedAttestation value (sorted
        indices, spec get_indexed_attestation)."""
        data = attestation["data"]
        indices = self.get_attesting_indices(
            data["slot"], data["index"], attestation["aggregation_bits"]
        )
        return {
            "attesting_indices": sorted(indices),
            "data": data,
            "signature": attestation["signature"],
        }

    def get_sync_committee_participant_indices(
        self, sync_committee_bits: Sequence[bool]
    ) -> List[int]:
        return [
            int(self.sync_committee_indices[i])
            for i, b in enumerate(sync_committee_bits)
            if b
        ]
