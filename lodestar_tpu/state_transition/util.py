"""Slot/epoch math + the swap-or-not shuffle, vectorized.

Reference: packages/state-transition/src/util/{epoch,shuffle}.ts.  The
reference shuffles the whole index list in one pass per round (the
"unshuffle list" optimization); here the same algorithm is expressed as
numpy array ops — one sha256 per 256-position block per round plus
vectorized bit selection, so a 1M-validator registry shuffles in
~SHUFFLE_ROUND_COUNT * (N/256) hashes instead of N * rounds of hashes.
"""

from __future__ import annotations

import hashlib

import numpy as np

from .. import params


def compute_epoch_at_slot(slot: int) -> int:
    return slot // params.SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(epoch: int) -> int:
    return epoch * params.SLOTS_PER_EPOCH


def compute_activation_exit_epoch(epoch: int) -> int:
    return epoch + 1 + params.ACTIVE_PRESET.MAX_SEED_LOOKAHEAD


def compute_committee_count_per_slot(active_validator_count: int) -> int:
    p = params.ACTIVE_PRESET
    return max(
        1,
        min(
            p.MAX_COMMITTEES_PER_SLOT,
            active_validator_count
            // p.SLOTS_PER_EPOCH
            // p.TARGET_COMMITTEE_SIZE,
        ),
    )


def compute_shuffled_index(index: int, index_count: int, seed: bytes) -> int:
    """Spec compute_shuffled_index — scalar reference used by tests; the
    list-at-once `shuffled_positions` below must agree with it."""
    assert 0 <= index < index_count
    for r in range(params.SHUFFLE_ROUND_COUNT):
        pivot = (
            int.from_bytes(hashlib.sha256(seed + bytes([r])).digest()[:8], "little")
            % index_count
        )
        flip = (pivot + index_count - index) % index_count
        position = max(index, flip)
        source = hashlib.sha256(
            seed + bytes([r]) + (position // 256).to_bytes(4, "little")
        ).digest()
        byte = source[(position % 256) // 8]
        if (byte >> (position % 8)) & 1:
            index = flip
    return index


def _round_hashes(seed: bytes, round_idx: int, n_blocks: int) -> np.ndarray:
    """Source bytes for every 256-position block of one shuffle round."""
    base = seed + bytes([round_idx])
    out = np.empty((n_blocks, 32), np.uint8)
    for b in range(n_blocks):
        out[b] = np.frombuffer(
            hashlib.sha256(base + b.to_bytes(4, "little")).digest(), np.uint8
        )
    return out


def shuffled_positions(n: int, seed: bytes) -> np.ndarray:
    """Vectorized compute_shuffled_index for every position 0..n-1."""
    pos = np.arange(n, dtype=np.int64)
    if n <= 1:
        return pos
    n_blocks = (n + 255) // 256 + 1
    for r in range(params.SHUFFLE_ROUND_COUNT):
        pivot = (
            int.from_bytes(hashlib.sha256(seed + bytes([r])).digest()[:8], "little")
            % n
        )
        flip = (pivot - pos) % n
        max_pos = np.maximum(pos, flip)
        hashes = _round_hashes(seed, r, n_blocks)
        byte = hashes[max_pos // 256, (max_pos % 256) // 8]
        bit = (byte >> (max_pos % 8).astype(np.uint8)) & 1
        pos = np.where(bit == 1, flip, pos)
    return pos


def shuffle_list(indices: np.ndarray, seed: bytes) -> np.ndarray:
    """The spec's shuffled committee order:
    out[j] == indices[compute_shuffled_index(j, n, seed)]."""
    idx = np.asarray(indices)
    if len(idx) <= 1:
        return idx.copy()
    return idx[shuffled_positions(len(idx), seed)]


def unshuffle_list(shuffled: np.ndarray, seed: bytes) -> np.ndarray:
    """Inverse of shuffle_list (scatter through the same permutation)."""
    s = np.asarray(shuffled)
    if len(s) <= 1:
        return s.copy()
    pos = shuffled_positions(len(s), seed)
    out = np.empty_like(s)
    out[pos] = s
    return out
