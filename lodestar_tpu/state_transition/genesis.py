"""Genesis state construction + the deposit merkle tree.

Reference: packages/state-transition/src/util/genesis.ts
(initializeBeaconStateFromEth1 / applyDeposits) and the interop helpers
in beacon-node/test/utils/state.ts.  `create_genesis_state` is the
interop-style fast path (validators injected directly, already active);
`DepositTree` reproduces the eth1 deposit contract's incremental merkle
tree so process_deposit's branch verification is exercised for real.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

from .. import params
from ..config.chain_config import ChainConfig
from ..ssz.core import _ZERO_HASHES
from ..types import BeaconBlockBodyAltair, DepositDataType, Validator
from ..ssz import List as SszList
from .accessors import get_next_sync_committee
from .state import BeaconState

P = params.ACTIVE_PRESET
FAR_FUTURE = params.FAR_FUTURE_EPOCH
DEPTH = params.DEPOSIT_CONTRACT_TREE_DEPTH


class DepositTree:
    """Incremental merkle tree of DepositData roots (eth1 contract shape).

    root() mixes in the leaf count (the +1 level process_deposit's
    branch check expects); proof(i) returns DEPTH siblings plus the
    count chunk as the final branch element.  push/root are O(DEPTH)
    via the deposit contract's partial-branch algorithm (the eth1
    tracker calls root() once per followed block); proof() rebuilds
    levels and is O(n) — it only runs per produced deposit op."""

    def __init__(self):
        self.leaves: List[bytes] = []
        self._branch: List[bytes] = [b"\x00" * 32] * DEPTH

    def push(self, deposit_data: Dict) -> None:
        node = DepositDataType.hash_tree_root(deposit_data)
        self.leaves.append(node)
        size = len(self.leaves)
        for h in range(DEPTH):
            if size & 1:
                self._branch[h] = node
                break
            node = hashlib.sha256(self._branch[h] + node).digest()
            size >>= 1

    def _levels(self) -> List[List[bytes]]:
        levels = [list(self.leaves)]
        for d in range(DEPTH):
            prev = levels[-1]
            nxt = []
            for i in range(0, len(prev), 2):
                left = prev[i]
                right = prev[i + 1] if i + 1 < len(prev) else _ZERO_HASHES[d]
                nxt.append(hashlib.sha256(left + right).digest())
            levels.append(nxt)
        return levels

    def _count_chunk(self) -> bytes:
        return len(self.leaves).to_bytes(32, "little")

    def root(self) -> bytes:
        """O(DEPTH) root from the partial branch (deposit contract
        get_deposit_root), count mixed in."""
        node = b"\x00" * 32
        size = len(self.leaves)
        for h in range(DEPTH):
            if size & 1:
                node = hashlib.sha256(self._branch[h] + node).digest()
            else:
                node = hashlib.sha256(node + _ZERO_HASHES[h]).digest()
            size >>= 1
        return hashlib.sha256(node + self._count_chunk()).digest()

    def proof(self, index: int) -> List[bytes]:
        assert 0 <= index < len(self.leaves)
        levels = self._levels()
        branch: List[bytes] = []
        pos = index
        for d in range(DEPTH):
            sibling = pos ^ 1
            level = levels[d]
            branch.append(
                level[sibling] if sibling < len(level) else _ZERO_HASHES[d]
            )
            pos //= 2
        branch.append(self._count_chunk())
        return branch


def create_genesis_state(
    config: ChainConfig,
    pubkeys: Sequence[bytes],
    genesis_time: int = 0,
    eth1_block_hash: bytes = b"\x42" * 32,
    balances: Optional[Sequence[int]] = None,
    deposit_count: Optional[int] = None,
) -> BeaconState:
    """Interop-style genesis: validators active at epoch 0."""
    state = BeaconState(config=config)
    state.genesis_time = genesis_time
    state.slot = params.GENESIS_SLOT

    fork_name = config.get_fork_name(params.GENESIS_SLOT)
    version = config.fork_versions[fork_name]
    state.fork = {
        "previous_version": version,
        "current_version": version,
        "epoch": params.GENESIS_EPOCH,
    }
    phase0_genesis = fork_name == params.ForkName.phase0
    if phase0_genesis:
        from ..types import BeaconBlockBody as _BodyPhase0

        body_root = _BodyPhase0.hash_tree_root(_BodyPhase0.default())
    else:
        body_root = BeaconBlockBodyAltair.hash_tree_root(
            BeaconBlockBodyAltair.default()
        )
    state.latest_block_header = {
        "slot": 0,
        "proposer_index": 0,
        "parent_root": b"\x00" * 32,
        "state_root": b"\x00" * 32,
        "body_root": body_root,
    }
    state.eth1_data = {
        "deposit_root": b"\x00" * 32,
        "deposit_count": (
            len(pubkeys) if deposit_count is None else deposit_count
        ),
        "block_hash": eth1_block_hash,
    }
    state.eth1_deposit_index = state.eth1_data["deposit_count"]
    state.randao_mixes = [eth1_block_hash] * P.EPOCHS_PER_HISTORICAL_VECTOR

    # columnar construction: no per-validator appends (1M-registry path)
    import numpy as np

    n = len(pubkeys)
    amounts = np.asarray(
        [P.MAX_EFFECTIVE_BALANCE] * n if balances is None else balances,
        np.uint64,
    )
    state.pubkeys = [bytes(pk) for pk in pubkeys]
    state.withdrawal_credentials = [
        b"\x00" + hashlib.sha256(pk).digest()[1:] for pk in pubkeys
    ]
    inc = np.uint64(P.EFFECTIVE_BALANCE_INCREMENT)
    state.effective_balance = np.minimum(
        amounts - amounts % inc, np.uint64(P.MAX_EFFECTIVE_BALANCE)
    )
    state.balances = amounts.copy()
    state.slashed = np.zeros(n, bool)
    state.activation_eligibility_epoch = np.full(
        n, params.GENESIS_EPOCH, np.uint64
    )
    state.activation_epoch = np.full(n, params.GENESIS_EPOCH, np.uint64)
    state.exit_epoch = np.full(n, FAR_FUTURE, np.uint64)
    state.withdrawable_epoch = np.full(n, FAR_FUTURE, np.uint64)
    state.previous_epoch_participation = np.zeros(n, np.uint8)
    state.current_epoch_participation = np.zeros(n, np.uint8)
    state.inactivity_scores = np.zeros(n, np.uint64)

    state.genesis_validators_root = SszList(
        Validator, P.VALIDATOR_REGISTRY_LIMIT
    ).hash_tree_root(state.validators_value())

    if phase0_genesis:
        # PendingAttestation era: record lists instead of participation
        # flags; sync committees do not exist yet (the altair upgrade
        # computes them)
        state.previous_epoch_attestations = []
        state.current_epoch_attestations = []
        return state
    committee = get_next_sync_committee(state)
    state.current_sync_committee = committee
    state.next_sync_committee = dict(committee)
    return state
