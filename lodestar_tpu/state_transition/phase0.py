"""phase0 state transition: PendingAttestation-era processing.

Mirror of the reference's phase0 paths (reference:
state-transition/src/block/processAttestationPhase0.ts,
epoch/getAttestationDeltas.ts, epoch/processPendingAttestations —
folded into cache/epochProcess.ts in the reference; and
slot/upgradeStateToAltair.ts): blocks append PendingAttestation records
instead of setting participation flags, and the epoch transition
derives justification/rewards from those records.

Representation: pending attestations are plain dicts
{aggregation_bits, data, inclusion_delay, proposer_index}; the epoch
transition resolves them to boolean attester masks over the registry
(vectorized where the data allows, committee resolution per
attestation like the reference's epochProcess loop).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from .. import params
from .accessors import (
    get_beacon_committee,
    get_block_root,
    get_block_root_at_slot,
    get_total_active_balance,
)
from .epoch import (
    EpochTransitionCache,
    process_effective_balance_updates,
    process_eth1_data_reset,
    process_historical_roots_update,
    process_randao_mixes_reset,
    process_registry_updates,
    process_slashings_reset,
    weigh_justification_and_finalization,
)
from .util import compute_epoch_at_slot, compute_start_slot_at_epoch

P = params.ACTIVE_PRESET
_I64 = np.int64
_U64 = np.uint64

# phase0 constants the later forks rescaled (consensus-specs phase0)
BASE_REWARDS_PER_EPOCH = 4
INACTIVITY_PENALTY_QUOTIENT_PHASE0 = 2**26
PROPORTIONAL_SLASHING_MULTIPLIER_PHASE0 = 1
MIN_EPOCHS_TO_INACTIVITY_PENALTY = 4


def is_phase0_state(state) -> bool:
    return getattr(state, "previous_epoch_attestations", None) is not None


# -- attester resolution ----------------------------------------------------


def attesting_mask(state, attestations: List[Dict]) -> np.ndarray:
    """Union of attesting validators over pending attestations
    (spec get_unslashed_attesting_indices without the slash filter)."""
    mask = np.zeros(state.num_validators, bool)
    for att in attestations:
        data = att["data"]
        committee = get_beacon_committee(
            state, int(data["slot"]), int(data["index"])
        )
        bits = att["aggregation_bits"]
        for pos, v in enumerate(committee):
            if bits[pos]:
                mask[int(v)] = True
    return mask


def _matching(state, epoch: int) -> Tuple[List[Dict], List[Dict], List[Dict]]:
    """(source, target, head) matching attestation lists for `epoch`
    (spec get_matching_*_attestations)."""
    current_epoch = compute_epoch_at_slot(state.slot)
    if epoch == current_epoch:
        source = list(state.current_epoch_attestations)
    else:
        source = list(state.previous_epoch_attestations)
    boundary = get_block_root(state, epoch)
    target = [
        a
        for a in source
        if bytes(a["data"]["target"]["root"]) == bytes(boundary)
    ]
    head = [
        a
        for a in target
        if bytes(a["data"]["beacon_block_root"])
        == bytes(get_block_root_at_slot(state, int(a["data"]["slot"])))
    ]
    return source, target, head


def _unslashed_mask(state, attestations: List[Dict]) -> np.ndarray:
    return attesting_mask(state, attestations) & ~state.slashed


def _attesting_balance(state, mask: np.ndarray) -> int:
    total = int(state.effective_balance[mask].sum())
    return max(P.EFFECTIVE_BALANCE_INCREMENT, total)


# -- justification ----------------------------------------------------------


def process_justification_and_finalization_phase0(state, cache=None) -> None:
    cache = cache or EpochTransitionCache(state)
    if cache.current_epoch <= params.GENESIS_EPOCH + 1:
        return
    _s, prev_target, _h = _matching(state, cache.previous_epoch)
    _s2, curr_target, _h2 = _matching(state, cache.current_epoch)
    weigh_justification_and_finalization(
        state,
        cache,
        cache.total_active_balance,
        _attesting_balance(state, _unslashed_mask(state, prev_target)),
        _attesting_balance(state, _unslashed_mask(state, curr_target)),
    )


# -- rewards & penalties (spec get_attestation_deltas) ----------------------


def get_base_rewards_phase0(state, total_balance: int) -> np.ndarray:
    from .accessors import integer_squareroot

    sqrt_total = integer_squareroot(total_balance)
    return (
        state.effective_balance.astype(object)
        * P.BASE_REWARD_FACTOR
        // sqrt_total
        // BASE_REWARDS_PER_EPOCH
    ).astype(_I64)


def get_attestation_deltas(state, cache=None) -> Tuple[np.ndarray, np.ndarray]:
    """(rewards, penalties) per validator for the PREVIOUS epoch."""
    n = state.num_validators
    rewards = np.zeros(n, _I64)
    penalties = np.zeros(n, _I64)
    cache = cache or EpochTransitionCache(state)
    prev_epoch = cache.previous_epoch
    total_balance = cache.total_active_balance
    base = get_base_rewards_phase0(state, total_balance)
    eligible = cache.eligible

    source_atts, target_atts, head_atts = _matching(state, prev_epoch)
    finality_delay = prev_epoch - int(state.finalized_checkpoint["epoch"])
    in_leak = finality_delay > MIN_EPOCHS_TO_INACTIVITY_PENALTY
    increment = P.EFFECTIVE_BALANCE_INCREMENT

    for atts in (source_atts, target_atts, head_atts):
        attester = _unslashed_mask(state, atts)
        attesting_balance = _attesting_balance(state, attester)
        hit = eligible & attester
        miss = eligible & ~attester
        if in_leak:
            # optimal participation is rewarded as if full to cancel the
            # base reward against the leak (spec get_attestation_
            # component_deltas "cancel" rule)
            rewards[hit] += base[hit]
        else:
            reward_num = base.astype(object) * (
                attesting_balance // increment
            )
            rewards[hit] += (
                reward_num[hit] // (total_balance // increment)
            ).astype(_I64)
        penalties[miss] += base[miss]

    # inclusion delay: earliest inclusion per attester; the proposer of
    # the including block earns base // PROPOSER_REWARD_QUOTIENT
    earliest: Dict[int, Dict] = {}
    for att in source_atts:
        committee = get_beacon_committee(
            state, int(att["data"]["slot"]), int(att["data"]["index"])
        )
        bits = att["aggregation_bits"]
        for pos, v in enumerate(committee):
            if not bits[pos] or bool(state.slashed[int(v)]):
                continue
            vi = int(v)
            if vi not in earliest or int(att["inclusion_delay"]) < int(
                earliest[vi]["inclusion_delay"]
            ):
                earliest[vi] = att
    for vi, att in earliest.items():
        proposer_reward = int(base[vi]) // P.PROPOSER_REWARD_QUOTIENT
        rewards[int(att["proposer_index"])] += proposer_reward
        max_attester = int(base[vi]) - proposer_reward
        rewards[vi] += max_attester // int(att["inclusion_delay"])

    if in_leak:
        target_attester = _unslashed_mask(state, target_atts)
        proposer_rewards = base // P.PROPOSER_REWARD_QUOTIENT
        penalties[eligible] += (
            BASE_REWARDS_PER_EPOCH * base[eligible]
            - proposer_rewards[eligible]
        )
        miss_t = eligible & ~target_attester
        penalties[miss_t] += (
            state.effective_balance[miss_t].astype(object)
            * finality_delay
            // INACTIVITY_PENALTY_QUOTIENT_PHASE0
        ).astype(_I64)
    return rewards, penalties


def process_rewards_and_penalties_phase0(state, cache=None) -> None:
    if compute_epoch_at_slot(state.slot) == params.GENESIS_EPOCH:
        return
    rewards, penalties = get_attestation_deltas(state, cache)
    balances = state.balances.astype(object)
    balances = balances + rewards.astype(object)
    balances = np.maximum(balances - penalties.astype(object), 0)
    state.balances = np.asarray(balances, _U64)


# -- slashings (multiplier 1) -----------------------------------------------


def process_slashings_phase0(state) -> None:
    epoch = compute_epoch_at_slot(state.slot)
    total_balance = get_total_active_balance(state)
    adjusted_total = min(
        int(state.slashings.sum()) * PROPORTIONAL_SLASHING_MULTIPLIER_PHASE0,
        total_balance,
    )
    increment = P.EFFECTIVE_BALANCE_INCREMENT
    target_withdrawable = epoch + P.EPOCHS_PER_SLASHINGS_VECTOR // 2
    mask = state.slashed & (
        state.withdrawable_epoch == _U64(target_withdrawable)
    )
    if not mask.any():
        return
    numerator = (
        state.effective_balance.astype(object) // increment
    ) * adjusted_total
    penalty = numerator // total_balance * increment
    balances = state.balances.astype(object)
    balances = np.where(mask, np.maximum(balances - penalty, 0), balances)
    state.balances = np.asarray(balances, _U64)


# -- participation record rotation ------------------------------------------


def process_participation_record_updates(state) -> None:
    state.previous_epoch_attestations = list(
        state.current_epoch_attestations
    )
    state.current_epoch_attestations = []


# -- the phase0 epoch transition --------------------------------------------


def process_epoch_phase0(state) -> Dict:
    """Spec phase0 process_epoch order.  ONE registry-scan cache
    serves justification, deltas, and the update steps (the same
    sharing the altair process_epoch does)."""
    cache = EpochTransitionCache(state)
    process_justification_and_finalization_phase0(state, cache)
    process_rewards_and_penalties_phase0(state, cache)
    process_registry_updates(state, cache)
    process_slashings_phase0(state)
    process_eth1_data_reset(state, cache)
    process_effective_balance_updates(state, cache)
    process_slashings_reset(state, cache)
    process_randao_mixes_reset(state, cache)
    process_historical_roots_update(state, cache)
    process_participation_record_updates(state)
    return {"cache": cache}


# -- the altair upgrade (reference: slot/upgradeStateToAltair.ts) -----------


def translate_participation(state, attestations: List[Dict]) -> None:
    """Pending attestations -> previous-epoch participation flags
    (spec upgrade translate_participation)."""
    from .block import get_attestation_participation_flag_indices

    for att in attestations:
        data = att["data"]
        flag_indices = get_attestation_participation_flag_indices(
            state, data, int(att["inclusion_delay"])
        )
        committee = get_beacon_committee(
            state, int(data["slot"]), int(data["index"])
        )
        bits = att["aggregation_bits"]
        flag_byte = np.uint8(0)
        for f in flag_indices:
            flag_byte |= np.uint8(1 << f)
        for pos, v in enumerate(committee):
            if bits[pos]:
                state.previous_epoch_participation[int(v)] |= flag_byte


def upgrade_to_altair(state) -> None:
    from .accessors import get_next_sync_committee

    n = state.num_validators
    state.previous_epoch_participation = np.zeros(n, np.uint8)
    state.current_epoch_participation = np.zeros(n, np.uint8)
    state.inactivity_scores = np.zeros(n, _U64)
    pending = list(state.previous_epoch_attestations)
    # fork record first: flag derivation reads justified checkpoints,
    # not the fork, but the spec upgrades the fork before translating
    state.fork = {
        "previous_version": state.fork["current_version"],
        "current_version": state.config.fork_versions[
            params.ForkName.altair
        ],
        "epoch": compute_epoch_at_slot(state.slot),
    }
    translate_participation(state, pending)
    state.previous_epoch_attestations = None
    state.current_epoch_attestations = None
    committee = get_next_sync_committee(state)
    state.current_sync_committee = committee
    state.next_sync_committee = dict(committee)
