"""Vectorized min-max span arrays — the slasher's dense math.

The min-max surround algorithm (Lighthouse slasher / "Detecting
slashing conditions" writeup) keeps, per validator and per epoch `e`
inside a sliding window:

  min_span[e] = min(target - e  :  recorded attestations with source > e)
  max_span[e] = max(target - e  :  recorded attestations with source < e)

A new attestation (s, t) then answers both surround questions with two
O(1) lookups at column `s`:

  min_span[s] < t - s   =>  the NEW attestation SURROUNDS a recorded one
                            (exists source > s with target < t)
  max_span[s] > t - s   =>  the new attestation IS SURROUNDED by one
                            (exists source < s with target > t)

Inserting (s, t) updates whole rows at once:

  min_span[e] = min(min_span[e], t - e)   for e in [window_start, s)
  max_span[e] = max(max_span[e], t - e)   for e in (s, t)

`span_update_rows` is the pure kernel: shape-stable over an
(n_validators, chunk) block, masks built from an iota instead of data-
dependent slices, and no captured array constants — the constraints the
Mosaic export path in `kernels/` already taught us — so a later PR can
jit/export it onto the TPU without restructuring.  Epochs are chunked
along the window axis (Lighthouse's chunked span arrays) and whole
gossip batches apply one distinct AttestationData at a time, vectorized
across every attesting validator and every epoch column.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

# "no recorded attestation" sentinels.  MIN sentinel is large (any real
# distance is smaller); MAX sentinel is 0 (real distances are >= 1, and
# the strict `> t - s` comparison can never fire on 0 since t >= s).
MIN_SPAN_SENTINEL = np.int32(1 << 30)
MAX_SPAN_SENTINEL = np.int32(0)

DEFAULT_HISTORY_LENGTH = 4096  # epochs of surround history retained
DEFAULT_CHUNK_SIZE = 16  # epoch columns per kernel invocation


def span_update_rows(
    min_rows: np.ndarray,
    max_rows: np.ndarray,
    source_col,
    target_col,
) -> Tuple[np.ndarray, np.ndarray]:
    """Pure span-update kernel over one (n_validators, width) block.

    `source_col`/`target_col` are window-relative scalars (may lie
    outside [0, width) — the masks handle chunk translation).  Shape-
    stable, branch-free, iota-masked: jnp-compatible as-is.
    """
    cols = np.arange(min_rows.shape[-1], dtype=np.int32)
    dist = np.int32(target_col) - cols  # t - e per column
    min_mask = cols < source_col
    max_mask = (cols > source_col) & (cols < target_col)
    new_min = np.where(min_mask, np.minimum(min_rows, dist), min_rows)
    new_max = np.where(max_mask, np.maximum(max_rows, dist), max_rows)
    return new_min, new_max


class SpanState:
    """The mutable (n_validators, history) span arrays + window base.

    Columns are absolute-epoch indexed: column j = epoch base_epoch + j.
    The window advances by whole chunks (prune on finalization, or when
    a target epoch outgrows the window); vacated columns reset to the
    sentinels.
    """

    def __init__(
        self,
        num_validators: int = 0,
        history_length: int = DEFAULT_HISTORY_LENGTH,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        base_epoch: int = 0,
    ):
        if history_length % chunk_size:
            history_length += chunk_size - history_length % chunk_size
        self.history_length = history_length
        self.chunk_size = chunk_size
        self.base_epoch = base_epoch
        self.min_spans = np.full(
            (num_validators, history_length), MIN_SPAN_SENTINEL, np.int32
        )
        self.max_spans = np.full(
            (num_validators, history_length), MAX_SPAN_SENTINEL, np.int32
        )

    @property
    def num_validators(self) -> int:
        return self.min_spans.shape[0]

    def ensure_validators(self, n: int) -> None:
        cur = self.num_validators
        if n <= cur:
            return
        # geometric over-allocation: registrations trickle in (a few new
        # indices per epoch), and exact-fit growth would re-copy the
        # full planes on every one of them
        n = max(n, cur + cur // 2 + 64)
        grow = n - cur
        self.min_spans = np.concatenate(
            [
                self.min_spans,
                np.full((grow, self.history_length), MIN_SPAN_SENTINEL, np.int32),
            ]
        )
        self.max_spans = np.concatenate(
            [
                self.max_spans,
                np.full((grow, self.history_length), MAX_SPAN_SENTINEL, np.int32),
            ]
        )

    def ensure_epoch(self, epoch: int) -> None:
        """Advance the window (chunk-aligned) so `epoch` has a column."""
        top = self.base_epoch + self.history_length
        if epoch < top:
            return
        shift = epoch - top + 1
        shift += (-shift) % self.chunk_size  # whole chunks only
        self.advance_base(self.base_epoch + shift)

    def advance_base(self, new_base: int) -> None:
        k = new_base - self.base_epoch
        if k <= 0:
            return
        h = self.history_length
        if k >= h:
            self.min_spans[:] = MIN_SPAN_SENTINEL
            self.max_spans[:] = MAX_SPAN_SENTINEL
        else:
            self.min_spans[:, : h - k] = self.min_spans[:, k:]
            self.min_spans[:, h - k :] = MIN_SPAN_SENTINEL
            self.max_spans[:, : h - k] = self.max_spans[:, k:]
            self.max_spans[:, h - k :] = MAX_SPAN_SENTINEL
        self.base_epoch = new_base

    # -- batch application -------------------------------------------------

    def lookup(self, rows: np.ndarray, source_epoch: int):
        """(min_span[s], max_span[s]) per row — the two O(1) surround
        probes.  Caller guarantees source_epoch is inside the window."""
        col = source_epoch - self.base_epoch
        return self.min_spans[rows, col], self.max_spans[rows, col]

    def apply(self, rows: np.ndarray, source_epoch: int, target_epoch: int) -> None:
        """Record one attestation data for `rows` validators: chunked,
        vectorized span update across the whole window."""
        if len(rows) == 0:
            return
        s_col = source_epoch - self.base_epoch
        t_col = target_epoch - self.base_epoch
        c = self.chunk_size
        # min updates touch cols < s_col, max updates cols < t_col;
        # s_col <= t_col, so chunks past t_col are untouched.
        last = min(self.history_length, max(t_col, 0))
        for off in range(0, last + (-last) % c, c):
            hi = off + c
            new_min, new_max = span_update_rows(
                self.min_spans[rows, off:hi],
                self.max_spans[rows, off:hi],
                s_col - off,
                t_col - off,
            )
            self.min_spans[rows, off:hi] = new_min
            self.max_spans[rows, off:hi] = new_max
