"""Attester slashing detection: per-target double-vote index + min-max
surround spans, with a naive O(n²) reference for cross-checking.

Detection semantics (spec is_slashable_attestation_data):
  - double vote: same target epoch, different AttestationData root;
  - surround:   att_1 surrounds att_2 iff s1 < s2 and t2 < t1 (strict
    on both sides — equal sources or equal targets are NOT surrounds;
    a source==target attestation can be surrounded but never surround).

The emitted AttesterSlashing always places the SURROUNDING attestation
first (process_attester_slashing checks s1 < s2 and t2 < t1 in that
order); double votes are order-insensitive.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..types import AttestationData
from .batch import DEFAULT_CHUNK_SIZE, DEFAULT_HISTORY_LENGTH, SpanState


def is_double_vote(data_1: dict, data_2: dict) -> bool:
    return (
        int(data_1["target"]["epoch"]) == int(data_2["target"]["epoch"])
        and AttestationData.hash_tree_root(data_1)
        != AttestationData.hash_tree_root(data_2)
    )


def is_surround_vote(data_1: dict, data_2: dict) -> bool:
    """True iff attestation 1 surrounds attestation 2."""
    return int(data_1["source"]["epoch"]) < int(
        data_2["source"]["epoch"]
    ) and int(data_2["target"]["epoch"]) < int(data_1["target"]["epoch"])


class AttesterSlasher:
    """Span-backed batch detector.

    `process_batch` takes verified IndexedAttestations (gossip singles
    and aggregates alike), groups them by AttestationData root, and for
    each distinct data runs the two vectorized span probes across every
    attesting validator before applying the (also vectorized, chunked)
    span update.  Groups are applied sequentially, so conflicting
    attestations arriving in the SAME batch still detect each other —
    whichever of the pair is processed second sees the first in the
    spans (both probe directions are covered either way).
    """

    def __init__(
        self,
        history_length: int = DEFAULT_HISTORY_LENGTH,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        num_validators: int = 0,
        span_backend: str = "numpy",
    ):
        if span_backend == "jax":
            # device-resident planes + jitted whole-window updates
            # (slasher/device.py); numpy stays the ground truth
            from .device import JaxSpanState

            self.spans = JaxSpanState(
                num_validators=num_validators,
                history_length=history_length,
                chunk_size=chunk_size,
            )
        else:
            self.spans = SpanState(
                num_validators=num_validators,
                history_length=history_length,
                chunk_size=chunk_size,
            )
        # validator -> {(source, target): (data_root, indexed_att)}
        self._records: Dict[int, Dict[Tuple[int, int], Tuple[bytes, dict]]] = {}
        # (validator, target) -> (data_root, indexed_att) — double votes
        self._by_target: Dict[Tuple[int, int], Tuple[bytes, dict]] = {}
        self.skipped_invalid = 0  # target < source: protocol-invalid
        self.evidence_missing = 0  # span hit whose record was pruned

    # -- record bookkeeping ------------------------------------------------

    def _record(self, v: int, s: int, t: int, root: bytes, att: dict) -> None:
        self._records.setdefault(v, {}).setdefault((s, t), (root, att))
        self._by_target.setdefault((v, t), (root, att))

    def _find_record(self, v: int, pred) -> Optional[dict]:
        for (s, t), (_root, att) in self._records.get(v, {}).items():
            if pred(s, t):
                return att
        return None

    def has_conflicting_target(self, v: int, target: int, root: bytes) -> bool:
        """True when `v` has a recorded attestation at `target` with a
        DIFFERENT data root — a double-vote candidate worth the cost of
        verifying a seen-cache-suppressed gossip duplicate."""
        prior = self._by_target.get((int(v), int(target)))
        return prior is not None and prior[0] != bytes(root)

    # -- batch processing --------------------------------------------------

    def process_batch(self, indexed_atts: List[dict]) -> List[Tuple[str, dict]]:
        """Returns [(kind, AttesterSlashing)] with kind in
        {"double_vote", "surround", "surrounded"}."""
        groups: Dict[bytes, Tuple[dict, List[dict]]] = {}
        for att in indexed_atts:
            root = bytes(AttestationData.hash_tree_root(att["data"]))
            groups.setdefault(root, (att["data"], []))[1].append(att)

        detections: List[Tuple[str, dict]] = []
        emitted: set = set()

        def emit(kind: str, att_1: dict, att_2: dict) -> None:
            # keyed on evidence OBJECT identity, not data roots: two
            # offenders sharing both evidence attestations collapse into
            # one slashing (its index intersection covers both), while
            # offenders with distinct evidence each get their own pair
            key = (kind, id(att_1), id(att_2))
            if key in emitted:
                return
            emitted.add(key)
            detections.append(
                (kind, {"attestation_1": att_1, "attestation_2": att_2})
            )

        for root, (data, atts) in groups.items():
            s = int(data["source"]["epoch"])
            t = int(data["target"]["epoch"])
            if t < s:
                self.skipped_invalid += len(atts)
                continue
            # validator -> a group attestation containing it (evidence)
            att_of: Dict[int, dict] = {}
            for att in atts:
                for v in att["attesting_indices"]:
                    att_of.setdefault(int(v), att)
            rows_all = sorted(att_of)
            # pure duplicates (same validator, same data) are no-ops
            rows = [
                v
                for v in rows_all
                if self._records.get(v, {}).get((s, t), (None,))[0] != root
            ]
            if not rows:
                continue

            # double votes via the per-target index
            for v in rows:
                prior = self._by_target.get((v, t))
                if prior is not None and prior[0] != root:
                    emit("double_vote", prior[1], att_of[v])

            # surround probes: two vectorized lookups at column s
            self.spans.ensure_epoch(t)
            self.spans.ensure_validators(max(rows) + 1)
            ra = np.asarray(rows, dtype=np.intp)
            if s >= self.spans.base_epoch:
                min_vals, max_vals = self.spans.lookup(ra, s)
                d = t - s
                for v, mn, mx in zip(rows, min_vals, max_vals):
                    if mx > d:  # an existing attestation surrounds (s, t)
                        prior = self._find_record(
                            v, lambda ps, pt: ps < s and pt > t
                        )
                        if prior is None:
                            self.evidence_missing += 1
                        else:
                            emit("surrounded", prior, att_of[v])
                    if mn < d:  # (s, t) surrounds an existing attestation
                        prior = self._find_record(
                            v, lambda ps, pt: ps > s and pt < t
                        )
                        if prior is None:
                            self.evidence_missing += 1
                        else:
                            emit("surround", att_of[v], prior)
            # apply UNCONDITIONALLY: a below-window source cannot be
            # probed, but its max-span updates over (s, t) still land
            # inside the window (the kernel clamps), so an INNER vote
            # arriving later is still caught — the classic old-source
            # surround attack must not slip through the window base
            self.spans.apply(ra, s, t)

            for v in rows:
                self._record(v, s, t, root, att_of[v])

        return detections

    # -- pruning -----------------------------------------------------------

    def prune(self, min_epoch: int) -> None:
        """Drop history with target epoch below `min_epoch` (finalized
        attestations can no longer pair into an includable slashing that
        matters) and advance the span window."""
        self.spans.advance_base(max(self.spans.base_epoch, min_epoch))
        for v in list(self._records):
            recs = self._records[v]
            for key in [k for k in recs if k[1] < min_epoch]:
                del recs[key]
            if not recs:
                del self._records[v]
        for key in [k for k in self._by_target if k[1] < min_epoch]:
            del self._by_target[key]

    def record_count(self) -> int:
        return sum(len(r) for r in self._records.values())


class NaiveAttesterSlasher:
    """O(n²) reference: scans every recorded attestation per validator.
    Same interface and detection semantics as AttesterSlasher — the
    randomized cross-check in tests/test_slasher.py holds them equal."""

    def __init__(self):
        self._history: Dict[int, List[Tuple[int, int, bytes, dict]]] = {}

    def process_batch(self, indexed_atts: List[dict]) -> List[Tuple[str, dict]]:
        detections: List[Tuple[str, dict]] = []
        emitted: set = set()

        def emit(kind, att_1, att_2):
            key = (kind, id(att_1), id(att_2))
            if key not in emitted:
                emitted.add(key)
                detections.append(
                    (kind, {"attestation_1": att_1, "attestation_2": att_2})
                )

        for att in indexed_atts:
            data = att["data"]
            s = int(data["source"]["epoch"])
            t = int(data["target"]["epoch"])
            if t < s:
                continue
            root = bytes(AttestationData.hash_tree_root(data))
            for v in (int(i) for i in att["attesting_indices"]):
                hist = self._history.setdefault(v, [])
                if any(ps == s and pt == t and pr == root for ps, pt, pr, _ in hist):
                    continue
                for ps, pt, pr, prior in hist:
                    if pt == t and pr != root:
                        emit("double_vote", prior, att)
                    if ps < s and t < pt:
                        emit("surrounded", prior, att)
                    if s < ps and pt < t:
                        emit("surround", att, prior)
                hist.append((s, t, root, att))
        return detections

    def prune(self, min_epoch: int) -> None:
        for v in list(self._history):
            self._history[v] = [
                r for r in self._history[v] if r[1] >= min_epoch
            ]
            if not self._history[v]:
                del self._history[v]
