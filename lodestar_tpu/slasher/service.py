"""SlasherService — lifecycle, batching, and the emission path.

Wiring (mirrors how the reference composes chain-side services):

  gossip handlers  --ingest_attestation/ingest_block-->  queues
  clock slot tick  --on_clock_slot-->  flush() (or earlier at max_batch)
  flush            --> AttesterSlasher.process_batch (vectorized spans)
  detection        --> STF dry-run (chain.validate_*_slashing, WITH
                       signatures: a forged equivocation must never
                       poison block production) --> op_pool insert +
                       fork-choice equivocator zeroing --> persisted
  finalization     --> chain calls on_finalized(epoch): window prune

Every verified gossip Attestation/aggregate is ingested post-validation;
block headers arrive from the chain's import pipeline (covering gossip,
range sync, and API publishes) plus the gossip duplicate-proposer branch
— the one place an equivocating second block surfaces without being
imported.
"""

from __future__ import annotations

import time
from typing import List, Optional

from .. import params
from ..utils.logger import get_logger
from .attester import AttesterSlasher
from .batch import DEFAULT_CHUNK_SIZE, DEFAULT_HISTORY_LENGTH
from .metrics import SlasherMetrics
from .proposer import ProposerSlasher
from .store import SlasherStore

DEFAULT_MAX_BATCH = 512  # attestations buffered before a forced flush

# Per-(slot, proposer) cap on REJECTED double-propose candidates: the
# duplicate-proposer gossip branch feeds unverified headers, so an
# attacker can manufacture candidates with garbage signatures; each one
# costs a head-state clone + BLS dry-run.  After this many failures the
# key is written off for UNTRUSTED sources (a real equivocating fork
# block still enters via the chain's verified import path).
MAX_PROPOSER_REJECTIONS = 5

# Bounds on the suppressed-double-vote probe bookkeeping (pruned on
# finalization): total remembered keys, and failed verifications per
# (validator, target, root) before that key is written off.  Keys are
# consumed on OUTCOME, never on the probe itself — a forged copy of a
# vote must not burn the key the real vote needs.
MAX_EQUIVOCATION_ATTEMPTS = 4096
MAX_EQUIVOCATION_PROBE_FAILURES = 3


class SlasherService:
    def __init__(
        self,
        chain=None,
        *,
        registry=None,
        db=None,
        history_length: int = DEFAULT_HISTORY_LENGTH,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        max_batch: int = DEFAULT_MAX_BATCH,
        span_backend: Optional[str] = None,
    ):
        self.chain = chain
        self.log = get_logger("slasher")
        self.metrics = SlasherMetrics(registry) if registry is not None else None
        self.store = SlasherStore(db)
        if span_backend is None:
            # env opt-in for the device-resident span planes
            # (slasher/device.py); numpy remains the default/ground truth
            import os

            span_backend = os.environ.get(
                "LODESTAR_TPU_SLASHER_BACKEND", "numpy"
            )
        self.span_backend = span_backend
        self.attester = AttesterSlasher(
            history_length=history_length,
            chunk_size=chunk_size,
            span_backend=span_backend,
        )
        self.proposer = ProposerSlasher()
        self._att_queue: List[dict] = []
        self.max_batch = max_batch
        self.running = False
        # offender pairs already emitted to the pool (per slot/proposer)
        self._proposer_emitted: set = set()
        # (slot, proposer) -> rejected-candidate count (DoS bound)
        self._proposer_rejections: dict = {}
        # (validator, target, root) probes: verified-and-ingested keys,
        # and per-key failed-verification counts
        self._equivocation_done: set = set()
        self._equivocation_failures: dict = {}
        self.detections = {"double_vote": 0, "surround": 0, "surrounded": 0,
                           "double_propose": 0}
        self.rejected = 0
        self.attestations_ingested = 0
        self.blocks_ingested = 0
        self.last_flush_seconds = 0.0
        self.min_epoch = 0  # pruned-below floor
        # wall-clock epoch (clock wiring); bounds ingestible targets so
        # a rogue far-future target cannot advance the span window past
        # the live epochs (gossip validation REJECTs these too — this
        # is the service-level backstop for other callers)
        self.clock_epoch = None
        self.skipped_future = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Restore persisted state and begin accepting work.

        Restore REPLAYS the persisted evidence through detection rather
        than trusting the span snapshot: spans are a pure function of
        the recorded (validator, source, target) set, so replay is
        always crash-consistent with the evidence — and any detection
        whose slashing had not yet landed in a block RE-EMITS into the
        op pool (a restart between detection and inclusion must not
        lose a provable offence)."""
        if self.running:
            return
        snapshot = self.store.load_spans()
        if snapshot is not None and (
            snapshot.history_length == self.attester.spans.history_length
            and snapshot.chunk_size == self.attester.spans.chunk_size
        ):
            # warm-start from the shutdown snapshot; the evidence replay
            # below re-applies on top (span updates are idempotent).
            # Planes are copied INTO the live SpanState so a jax-backed
            # window keeps its device apply path across restarts.
            spans = self.attester.spans
            spans.min_spans = snapshot.min_spans
            spans.max_spans = snapshot.max_spans
            spans.base_epoch = snapshot.base_epoch
        atts = list(self.store.iter_attestations())
        if atts:
            for kind, slashing in self.attester.process_batch(atts):
                self._emit_attester(kind, slashing)
        n_headers = 0
        for _slot, _proposer, signed in self.store.iter_headers():
            n_headers += 1
            slashing = self.proposer.process(signed)
            if slashing is not None:
                self._emit_proposer(slashing)
        if atts or n_headers:
            self.log.info(
                "slasher state restored",
                records=self.attester.record_count(),
                headers=self.proposer.record_count(),
            )
        self.running = True

    def stop(self) -> None:
        if not self.running:
            return
        self.flush()
        spans = self.attester.spans
        snapshot = getattr(spans, "snapshot", None)
        # device-resident planes persist through a numpy materialization
        self.store.save_spans(snapshot() if snapshot is not None else spans)
        self.running = False

    # -- ingestion (gossip pipeline + chain import) ------------------------

    def ingest_attestation(self, indexed: dict) -> None:
        """Queue one VERIFIED IndexedAttestation (gossip single or
        aggregate) for the next batch flush."""
        self._att_queue.append(indexed)
        self.attestations_ingested += 1
        if self.metrics is not None:
            self.metrics.attestations_ingested.inc()
            self.metrics.queue_length.set(len(self._att_queue))
        if len(self._att_queue) >= self.max_batch:
            self.flush()

    def should_check_equivocation(self, v: int, target: int, root: bytes) -> bool:
        """Gate for the gossip layer's suppressed-double-vote recovery:
        only a validator with a CONFLICTING root at `target` — recorded
        OR still sitting in the pending queue — is worth a signature
        verification.  The key is NOT consumed here: the handler
        reports the verification outcome via record_equivocation_probe,
        so a forged copy cannot burn the key the real vote needs, while
        per-key and global failure bounds still cap the cost."""
        key = (int(v), int(target), bytes(root))
        if key in self._equivocation_done:
            return False
        if (
            self._equivocation_failures.get(key, 0)
            >= MAX_EQUIVOCATION_PROBE_FAILURES
        ):
            return False
        if len(self._equivocation_failures) >= MAX_EQUIVOCATION_ATTEMPTS:
            return False  # fail closed until the window prunes
        if self.attester.has_conflicting_target(v, target, root):
            return True
        return self._queue_has_conflicting_target(
            int(v), int(target), bytes(root)
        )

    def _queue_has_conflicting_target(
        self, v: int, target: int, root: bytes
    ) -> bool:
        """Both halves of a double vote often arrive inside one flush
        window — the second must not be dropped just because the first
        has not been batch-processed yet."""
        from ..types import AttestationData

        for att in self._att_queue:
            data = att["data"]
            if int(data["target"]["epoch"]) != target:
                continue
            if all(int(i) != v for i in att["attesting_indices"]):
                continue
            if bytes(AttestationData.hash_tree_root(data)) != root:
                return True
        return False

    def record_equivocation_probe(
        self, indices, target: int, root: bytes, ok: bool
    ) -> None:
        """Outcome of a recovery probe's signature verification."""
        for v in indices:
            key = (int(v), int(target), bytes(root))
            if ok:
                self._equivocation_done.add(key)
                self._equivocation_failures.pop(key, None)
            else:
                self._equivocation_failures[key] = (
                    self._equivocation_failures.get(key, 0) + 1
                )

    def ingest_block(
        self,
        signed_block: dict,
        body_root: bytes = None,
        trusted: bool = False,
    ) -> None:
        """Index one verified signed block's header; double proposals
        emit immediately (no batching — the header index is O(1)).

        `body_root` lets the chain pass the root the STF already
        computed (post.latest_block_header) so the import hot path does
        not re-merkleize the body.  `trusted` marks headers whose
        proposer signature HAS been verified (the chain's import path):
        they bypass the rejection write-off, so a real equivocating
        fork block that imports is always processed even after forged
        gossip duplicates exhausted the key's cap.  Untrusted keys
        already emitted or written off return before ANY hashing — the
        bound on what a duplicate-proposer gossip flood can cost."""
        block = signed_block["message"]
        slot = int(block["slot"])
        proposer = int(block["proposer_index"])
        key = (slot, proposer)
        if key in self._proposer_emitted or (
            not trusted
            and self._proposer_rejections.get(key, 0) >= MAX_PROPOSER_REJECTIONS
        ):
            return
        signed_header = self._header_of(signed_block, body_root)
        self.blocks_ingested += 1
        if self.metrics is not None:
            self.metrics.blocks_ingested.inc()
        slashing = self.proposer.process(signed_header)
        if trusted:
            # ONLY signature-verified headers persist at ingest: a
            # forged gossip duplicate in the db would be replayed on
            # restart and could seat itself as the (slot, proposer)
            # index entry, masking the real equivocation forever.
            # Untrusted headers persist below, after their slashing
            # pair survives the full STF dry-run.
            self._persist_header(signed_header)
        if slashing is not None and self._emit_proposer(slashing):
            self._persist_header(slashing["signed_header_1"])
            self._persist_header(slashing["signed_header_2"])

    def _persist_header(self, signed_header: dict) -> None:
        from ..types import BeaconBlockHeader

        header = signed_header["message"]
        self.store.put_header(
            int(header["slot"]),
            int(header["proposer_index"]),
            bytes(BeaconBlockHeader.hash_tree_root(header)),
            signed_header,
        )

    def _header_of(self, signed_block: dict, body_root: bytes = None) -> dict:
        block = signed_block["message"]
        slot = int(block["slot"])
        if body_root is None:
            if self.chain is not None:
                body_type = self.chain.config.get_fork_types(slot)[2]
            else:
                from .. import types as T

                body_type = T.BeaconBlockBodyAltair
            body_root = body_type.hash_tree_root(block["body"])
        return {
            "message": {
                "slot": slot,
                "proposer_index": int(block["proposer_index"]),
                "parent_root": bytes(block["parent_root"]),
                "state_root": bytes(block["state_root"]),
                "body_root": bytes(body_root),
            },
            "signature": bytes(signed_block["signature"]),
        }

    # -- batch flush -------------------------------------------------------

    def on_clock_slot(self, slot: int) -> None:
        self.clock_epoch = int(slot) // params.SLOTS_PER_EPOCH
        self.flush()

    def flush(self) -> int:
        """Run the vectorized span batch over everything queued; emit
        validated detections.  Returns the number of detections."""
        if not self._att_queue:
            return 0
        batch, self._att_queue = self._att_queue, []
        if self.clock_epoch is not None:
            horizon = self.clock_epoch + 1
            sane = [
                a for a in batch
                if int(a["data"]["target"]["epoch"]) <= horizon
            ]
            self.skipped_future += len(batch) - len(sane)
            batch = sane
            if not batch:
                return 0
        # evidence persists BEFORE detection runs: if the span batch
        # throws, the verified attestations are already durable and the
        # restart replay re-derives everything ("the evidence records
        # are the durable truth" must hold across a mid-flush crash).
        # Span snapshots are NOT written here — that would be
        # O(validators x history) db churn per slot; stop() snapshots.
        if self.store.persistent:
            from ..types import IndexedAttestation

            for att in batch:
                s = int(att["data"]["source"]["epoch"])
                t = int(att["data"]["target"]["epoch"])
                if t < s:
                    continue  # protocol-invalid: never persisted/replayed
                self.store.put_attestation(
                    t, bytes(IndexedAttestation.hash_tree_root(att)), att
                )
        t0 = time.perf_counter()
        detections = self.attester.process_batch(batch)
        dt = time.perf_counter() - t0
        self.last_flush_seconds = dt
        if self.metrics is not None:
            self.metrics.queue_length.set(0)
            self.metrics.batch_time.observe(dt)
            self.metrics.batch_attestations.observe(len(batch))
            self.metrics.validators_tracked.set(
                self.attester.spans.num_validators
            )
        emitted = 0
        for kind, slashing in detections:
            if self._emit_attester(kind, slashing):
                emitted += 1
        return emitted

    # -- emission ----------------------------------------------------------

    def _emit_attester(self, kind: str, slashing: dict) -> bool:
        from ..chain.op_pools import attester_slashing_intersection

        offenders = attester_slashing_intersection(slashing)
        if self.chain is not None:
            # coverage first, dry-run second: evidence is already
            # signature-verified at ingestion, so a detection whose
            # offenders all have pooled slashings counts without paying
            # another head-state clone + BLS pass
            covered = self.chain.op_pool.covered_attester_offenders()
            if offenders and set(offenders) <= covered:
                self.detections[kind] += 1
                if self.metrics is not None:
                    self.metrics.detections.inc(kind, 1.0)
                return True
            try:
                # full STF dry-run INCLUDING signatures — candidates that
                # cannot land in a block must not enter the pool
                self.chain.validate_attester_slashing(slashing)
            except Exception as e:  # noqa: BLE001 — candidate refused
                self.rejected += 1
                if self.metrics is not None:
                    self.metrics.rejected_detections.inc()
                self.log.warn(
                    "detected attester slashing failed validation",
                    kind=kind, error=str(e),
                )
                return False
            self.chain.op_pool.insert_attester_slashing(slashing)
            self.chain.on_attester_slashing(slashing)
        self.detections[kind] += 1
        if self.metrics is not None:
            self.metrics.detections.inc(kind, 1.0)
        self.log.info(
            "attester slashing detected", kind=kind, offenders=offenders
        )
        return True

    def _emit_proposer(self, slashing: dict) -> bool:
        header = slashing["signed_header_1"]["message"]
        key = (int(header["slot"]), int(header["proposer_index"]))
        if key in self._proposer_emitted:
            return False
        if self.chain is not None:
            try:
                self.chain.validate_proposer_slashing(slashing)
            except Exception as e:  # noqa: BLE001
                self.rejected += 1
                self._proposer_rejections[key] = (
                    self._proposer_rejections.get(key, 0) + 1
                )
                if self.metrics is not None:
                    self.metrics.rejected_detections.inc()
                self.log.warn(
                    "detected proposer slashing failed validation",
                    error=str(e),
                )
                return False
            self.chain.op_pool.insert_proposer_slashing(slashing)
        self._proposer_emitted.add(key)
        self.detections["double_propose"] += 1
        if self.metrics is not None:
            self.metrics.detections.inc("double_propose", 1.0)
        self.log.info(
            "double proposal detected", slot=key[0], proposer=key[1]
        )
        return True

    # -- pruning (finalization) --------------------------------------------

    def on_finalized(self, finalized_epoch: int) -> None:
        """Epoch-windowed pruning: history at or below the finalized
        epoch can no longer matter (those validators are either already
        slashed in the finalized state or their old votes finalized)."""
        if finalized_epoch <= self.min_epoch:
            return
        self.min_epoch = finalized_epoch
        min_slot = finalized_epoch * params.SLOTS_PER_EPOCH
        self.attester.prune(finalized_epoch)
        self.proposer.prune(min_slot)
        self._proposer_emitted = {
            k for k in self._proposer_emitted if k[0] >= min_slot
        }
        self._proposer_rejections = {
            k: n for k, n in self._proposer_rejections.items()
            if k[0] >= min_slot
        }
        self._equivocation_done = {
            k for k in self._equivocation_done if k[1] >= finalized_epoch
        }
        self._equivocation_failures = {
            k: n
            for k, n in self._equivocation_failures.items()
            if k[1] >= finalized_epoch
        }
        self.store.prune(finalized_epoch, min_slot)
        # NOTE: no span snapshot here — rewriting O(validators x
        # history) bytes per finalized epoch is pure churn; the snapshot
        # is a clean-shutdown fast-restore artifact (stop()), and the
        # evidence records remain the durable truth

    # -- introspection (the API's slasher route) ---------------------------

    def status(self) -> dict:
        return {
            "running": self.running,
            "attestations_ingested": self.attestations_ingested,
            "blocks_ingested": self.blocks_ingested,
            "queue_length": len(self._att_queue),
            "detections": dict(self.detections),
            "rejected_detections": self.rejected,
            "attestation_records": self.attester.record_count(),
            "proposer_records": self.proposer.record_count(),
            "span_base_epoch": self.attester.spans.base_epoch,
            "span_history_length": self.attester.spans.history_length,
            "span_chunk_size": self.attester.spans.chunk_size,
            "validators_tracked": self.attester.spans.num_validators,
            "last_flush_seconds": self.last_flush_seconds,
            "skipped_invalid": self.attester.skipped_invalid,
        }
