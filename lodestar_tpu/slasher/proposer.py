"""Proposer slashing detection: slot → proposer → header-root index.

Reference: lighthouse/slasher block ingestion — every verified
SignedBeaconBlockHeader is recorded under (slot, proposer_index); a
second header for the same key with a DIFFERENT header root is a double
proposal, emitted as a ProposerSlashing (headers ordered by arrival:
signed_header_1 is the recorded one).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..types import BeaconBlockHeader


class ProposerSlasher:
    def __init__(self):
        # (slot, proposer) -> (header_root, signed_header)
        self._index: Dict[Tuple[int, int], Tuple[bytes, dict]] = {}

    def process(self, signed_header: dict) -> Optional[dict]:
        """Record one verified header; returns a ProposerSlashing when it
        equivocates with a recorded header, else None."""
        header = signed_header["message"]
        slot = int(header["slot"])
        proposer = int(header["proposer_index"])
        root = bytes(BeaconBlockHeader.hash_tree_root(header))
        key = (slot, proposer)
        existing = self._index.get(key)
        if existing is None:
            self._index[key] = (root, signed_header)
            return None
        if existing[0] == root:
            return None  # same block, re-observed
        return {
            "signed_header_1": existing[1],
            "signed_header_2": signed_header,
        }

    def prune(self, min_slot: int) -> None:
        for key in [k for k in self._index if k[0] < min_slot]:
            del self._index[key]

    def record_count(self) -> int:
        return len(self._index)
