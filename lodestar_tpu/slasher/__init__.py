"""Slasher — vectorized slashing detection (double votes, min-max
surround, double proposals).

Reference: lighthouse/slasher (chunked min-max span arrays, epoch
windowed, batched updates) and the reference node's opPool/gossip
wiring.  The span math lives in `batch.py` as a pure, shape-stable
array kernel so a later PR can move it onto the TPU path.
"""

from .attester import (
    AttesterSlasher,
    NaiveAttesterSlasher,
    is_double_vote,
    is_surround_vote,
)
from .batch import SpanState, span_update_rows
from .device import JaxSpanState, span_update_planes
from .metrics import SlasherMetrics
from .proposer import ProposerSlasher
from .service import SlasherService
from .store import SlasherStore

__all__ = [
    "AttesterSlasher",
    "JaxSpanState",
    "NaiveAttesterSlasher",
    "ProposerSlasher",
    "SlasherMetrics",
    "SlasherService",
    "SlasherStore",
    "SpanState",
    "is_double_vote",
    "is_surround_vote",
    "span_update_planes",
    "span_update_rows",
]
