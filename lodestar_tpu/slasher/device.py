"""Device-resident span planes — the slasher's TPU path.

`batch.py::span_update_rows` was written shape-stable / iota-masked /
constant-free exactly so it could move onto the accelerator without
restructuring (the Mosaic constraints the kernels/ package taught).
This module is that move: `JaxSpanState` keeps the (n_validators,
history) min/max span planes resident on the device and applies each
distinct AttestationData as ONE jitted whole-window masked update —
no per-chunk Python loop, no host round-trip per apply.

The kernel is registered with the AOT export cache
(kernels/export_cache.py, entry "slasher_span_update") so a TPU
process deserializes the traced artifact instead of re-tracing; on CPU
hosts it runs through plain jax.jit.  The numpy `SpanState` remains
the ground truth — `tests/test_slasher.py` cross-checks the two — and
is the default; opt in with `LODESTAR_TPU_SLASHER_BACKEND=jax` (or
`SlasherService(span_backend="jax")`).

Rare window operations (chunk-aligned advance on finalization,
geometric validator growth) round-trip through numpy: they happen per
finalized epoch / per registration trickle, not per attestation.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .batch import (
    DEFAULT_CHUNK_SIZE,
    DEFAULT_HISTORY_LENGTH,
    MAX_SPAN_SENTINEL,
    MIN_SPAN_SENTINEL,
    SpanState,
)


def span_update_planes(min_sp, max_sp, row_mask, s_col, t_col):
    """Whole-window span update: jnp mirror of span_update_rows with the
    chunk translation folded away (global column iota, row mask instead
    of fancy indexing — gathers break the Mosaic export path)."""
    import jax
    import jax.numpy as jnp

    cols = jax.lax.broadcasted_iota(jnp.int32, min_sp.shape, 1)
    dist = t_col - cols
    upd = row_mask[:, None]
    new_min = jnp.where(
        upd & (cols < s_col), jnp.minimum(min_sp, dist), min_sp
    )
    new_max = jnp.where(
        upd & (cols > s_col) & (cols < t_col),
        jnp.maximum(max_sp, dist),
        max_sp,
    )
    return new_min, new_max


_JITTED: Dict[Tuple[int, int, bool], object] = {}


def _update_fn(shape: Tuple[int, int], use_export: bool):
    """Per-plane-shape jitted (or AOT-exported) update callable.
    Scalars are traced arguments, so one trace serves every (s, t)."""
    import jax

    key = (shape[0], shape[1], use_export)
    fn = _JITTED.get(key)
    if fn is not None:
        return fn
    jitted = jax.jit(span_update_planes)
    if use_export:
        import jax.numpy as jnp

        from ..kernels import export_cache as EC

        specs = [
            jax.ShapeDtypeStruct(shape, jnp.int32),
            jax.ShapeDtypeStruct(shape, jnp.int32),
            jax.ShapeDtypeStruct((shape[0],), jnp.bool_),
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
        ]
        try:
            jitted = EC.load_or_export(
                "slasher_span_update", span_update_planes, specs
            )
        except Exception:  # noqa: BLE001 — export must never take the
            # slasher down; the plain jit path is always valid
            pass
    _JITTED[key] = jitted
    return jitted


def export_specs(
    num_validators: int = 4096, history_length: int = DEFAULT_HISTORY_LENGTH
):
    """(fn, specs) for the export pipeline's pre-trace registry."""
    import jax
    import jax.numpy as jnp

    shape = (num_validators, history_length)
    return span_update_planes, [
        jax.ShapeDtypeStruct(shape, jnp.int32),
        jax.ShapeDtypeStruct(shape, jnp.int32),
        jax.ShapeDtypeStruct((shape[0],), jnp.bool_),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
    ]


class JaxSpanState(SpanState):
    """SpanState with device-resident planes and a jitted apply.

    `min_spans`/`max_spans` hold jax arrays between applies; the numpy
    superclass paths (window advance, growth, persistence snapshots)
    see materialized copies on demand and push the result back.
    """

    def __init__(
        self,
        num_validators: int = 0,
        history_length: int = DEFAULT_HISTORY_LENGTH,
        chunk_size: int = DEFAULT_CHUNK_SIZE,
        base_epoch: int = 0,
        use_export: bool = None,
    ):
        super().__init__(
            num_validators=num_validators,
            history_length=history_length,
            chunk_size=chunk_size,
            base_epoch=base_epoch,
        )
        if use_export is None:
            import os

            env = os.environ.get("LODESTAR_TPU_SLASHER_EXPORT")
            if env is not None:
                use_export = env.strip().lower() in ("1", "true", "yes", "on")
            else:
                import jax

                use_export = jax.default_backend() == "tpu"
        self.use_export = bool(use_export)

    # -- host <-> device ---------------------------------------------------

    def _to_host(self) -> None:
        """Materialize the planes as (writable) numpy before a host-side
        structural operation (advance/growth/snapshot)."""
        if not isinstance(self.min_spans, np.ndarray):
            self.min_spans = np.asarray(self.min_spans).copy()
            self.max_spans = np.asarray(self.max_spans).copy()

    def _to_device(self) -> None:
        import jax.numpy as jnp

        if isinstance(self.min_spans, np.ndarray):
            self.min_spans = jnp.asarray(self.min_spans)
            self.max_spans = jnp.asarray(self.max_spans)

    # -- structural ops run on host (rare: finalization / registration) ----

    def ensure_validators(self, n: int) -> None:
        if n <= self.num_validators:
            return
        self._to_host()
        super().ensure_validators(n)

    def advance_base(self, new_base: int) -> None:
        if new_base <= self.base_epoch:
            return
        self._to_host()
        super().advance_base(new_base)

    # -- hot path ----------------------------------------------------------

    def lookup(self, rows: np.ndarray, source_epoch: int):
        col = source_epoch - self.base_epoch
        if isinstance(self.min_spans, np.ndarray):
            return super().lookup(rows, source_epoch)
        # one device gather per probe column, then a host-side row pick
        min_col = np.asarray(self.min_spans[:, col])
        max_col = np.asarray(self.max_spans[:, col])
        return min_col[rows], max_col[rows]

    def apply(self, rows: np.ndarray, source_epoch: int, target_epoch: int) -> None:
        if len(rows) == 0:
            return
        import jax.numpy as jnp

        self._to_device()
        mask = np.zeros(self.num_validators, bool)
        mask[rows] = True
        fn = _update_fn(tuple(self.min_spans.shape), self.use_export)
        self.min_spans, self.max_spans = fn(
            self.min_spans,
            self.max_spans,
            jnp.asarray(mask),
            jnp.int32(source_epoch - self.base_epoch),
            jnp.int32(target_epoch - self.base_epoch),
        )

    def snapshot(self) -> SpanState:
        """Numpy SpanState copy (persistence format compatibility)."""
        out = SpanState(
            num_validators=0,
            history_length=self.history_length,
            chunk_size=self.chunk_size,
            base_epoch=self.base_epoch,
        )
        out.min_spans = np.asarray(self.min_spans, np.int32).copy()
        out.max_spans = np.asarray(self.max_spans, np.int32).copy()
        return out


__all__ = [
    "JaxSpanState",
    "span_update_planes",
    "export_specs",
    "MIN_SPAN_SENTINEL",
    "MAX_SPAN_SENTINEL",
]
