"""Slasher persistence over the db's bucketed repositories.

Reference: lighthouse/slasher's database schema (indexed attestations,
min/max span chunks, proposer records) reduced to this framework's
repository layer (db/repository.py column families, wired as typed
repositories in db/beacon_db.py):

  - slasher_min_span / slasher_max_span: the span arrays, stored as one
    raw blob each plus a JSON metadata record (base epoch, shape);
  - slasher_attestation: SSZ IndexedAttestation keyed by
    target_epoch(8B)||hash_tree_root — evidence records, replayed into
    the detector on load; the epoch prefix makes pruning a key scan;
  - slasher_header: SSZ SignedBeaconBlockHeader keyed by
    slot(8B)||proposer(8B)||header_root — the double-propose index.
    The ROOT rides in the key so BOTH halves of an equivocation
    persist; a restart replays them and re-detects.

A SlasherStore with db=None is a no-op shell, so the service runs
memory-only in light compositions/tests.  The evidence records are the
durable source of truth — the service's restore path REPLAYS them
through detection — while the span blobs are a clean-shutdown snapshot
(written at stop(), loaded as a warm-start before the replay; span
updates are idempotent so re-applying evidence on top is safe).
"""

from __future__ import annotations

import json
from typing import Iterator, Optional, Tuple

import numpy as np

from .batch import SpanState

_META_KEY = b"meta"
_DATA_KEY = b"data"


def _header_key(slot: int, proposer: int, root: bytes) -> bytes:
    return slot.to_bytes(8, "big") + proposer.to_bytes(8, "big") + root


class SlasherStore:
    def __init__(self, db=None):
        """`db` is a BeaconDb exposing the slasher_* repositories (older
        test doubles without them degrade to memory-only)."""
        self._min = getattr(db, "slasher_min_span", None)
        self._max = getattr(db, "slasher_max_span", None)
        self._atts = getattr(db, "slasher_attestation", None)
        self._headers = getattr(db, "slasher_header", None)

    @property
    def persistent(self) -> bool:
        return self._min is not None

    # -- spans -------------------------------------------------------------

    def save_spans(self, spans: SpanState) -> None:
        if self._min is None:
            return
        meta = json.dumps(
            {
                "base_epoch": spans.base_epoch,
                "num_validators": spans.num_validators,
                "history_length": spans.history_length,
                "chunk_size": spans.chunk_size,
            }
        ).encode()
        self._min.put(_META_KEY, meta)
        self._min.put(_DATA_KEY, spans.min_spans.tobytes())
        self._max.put(_DATA_KEY, spans.max_spans.tobytes())

    def load_spans(self) -> Optional[SpanState]:
        if self._min is None:
            return None
        meta = self._min.get(_META_KEY)
        if meta is None:
            return None
        m = json.loads(meta.decode())
        spans = SpanState(
            num_validators=m["num_validators"],
            history_length=m["history_length"],
            chunk_size=m["chunk_size"],
            base_epoch=m["base_epoch"],
        )
        shape = (m["num_validators"], spans.history_length)
        spans.min_spans = np.frombuffer(
            self._min.get(_DATA_KEY), dtype=np.int32
        ).reshape(shape).copy()
        spans.max_spans = np.frombuffer(
            self._max.get(_DATA_KEY), dtype=np.int32
        ).reshape(shape).copy()
        return spans

    # -- evidence records --------------------------------------------------

    def put_attestation(self, target_epoch: int, root: bytes, indexed: dict) -> None:
        """Keyed target_epoch(8B big-endian)||root: epoch-ordered keys
        make pruning a key scan with NO value deserialization."""
        if self._atts is not None:
            key = target_epoch.to_bytes(8, "big") + root
            if not self._atts.has(key):
                self._atts.put(key, indexed)

    def iter_attestations(self) -> Iterator[dict]:
        if self._atts is not None:
            for _key, att in self._atts.entries():
                yield att

    def put_header(
        self, slot: int, proposer: int, root: bytes, signed_header: dict
    ) -> None:
        if self._headers is not None:
            key = _header_key(slot, proposer, root)
            if not self._headers.has(key):
                self._headers.put(key, signed_header)

    def iter_headers(self) -> Iterator[Tuple[int, int, dict]]:
        if self._headers is not None:
            for key, signed in self._headers.entries():
                yield (
                    int.from_bytes(key[:8], "big"),
                    int.from_bytes(key[8:16], "big"),
                    signed,
                )

    # -- pruning -----------------------------------------------------------

    def prune(self, min_epoch: int, min_slot: int) -> None:
        """Key-prefix scans only — both families encode their epoch/slot
        in the key, so pruning never deserializes a value."""
        if self._atts is not None:
            for key in [
                k
                for k in self._atts.keys()
                if int.from_bytes(k[:8], "big") < min_epoch
            ]:
                self._atts.delete(key)
        if self._headers is not None:
            for key in [
                k
                for k in self._headers.keys()
                if int.from_bytes(k[:8], "big") < min_slot
            ]:
                self._headers.delete(key)

