"""Slasher metrics — the lodestar_slasher_* family over the shared
registry (utils/metrics.py), alongside the bls_thread_pool and beacon
families the node already exposes."""

from __future__ import annotations

from typing import Optional

from ..utils.metrics import Registry

_BATCH_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0)


class SlasherMetrics:
    def __init__(self, registry: Optional[Registry] = None):
        r = registry or Registry()
        self.registry = r
        p = "lodestar_slasher_"
        self.attestations_ingested = r.counter(
            p + "attestations_ingested_total",
            "Verified indexed attestations fed to the slasher",
        )
        self.blocks_ingested = r.counter(
            p + "blocks_ingested_total",
            "Verified block headers fed to the slasher",
        )
        self.detections = r.labeled_counter(
            p + "detections_total",
            "Slashings detected, by kind",
            "kind",
        )
        self.rejected_detections = r.counter(
            p + "rejected_detections_total",
            "Detected slashings the STF dry-run refused (dropped)",
        )
        self.queue_length = r.gauge(
            p + "queue_length", "Attestations awaiting the next batch flush"
        )
        self.validators_tracked = r.gauge(
            p + "validators_tracked", "Validators with live span rows"
        )
        self.batch_time = r.histogram(
            p + "batch_seconds", "Span batch flush wall time", _BATCH_BUCKETS
        )
        self.batch_attestations = r.histogram(
            p + "batch_attestations_count",
            "Attestations per batch flush",
            (1, 8, 64, 256, 1024, 4096),
        )
