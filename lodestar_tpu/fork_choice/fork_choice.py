"""ForkChoice facade: latest messages + head computation.

Reference: packages/fork-choice/src/forkChoice/forkChoice.ts — tracks
per-validator latest messages (epoch, block root), queues attestations
from future slots, converts votes to proto-array score changes on
update_head, and exposes the IForkChoice surface the chain/processor
layers consume (hasBlock/getHead/onBlock/onAttestation).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from .compute_deltas import compute_deltas
from .proto_array import ProtoArray


@dataclass
class LatestMessage:
    epoch: int
    root: str


class ForkChoice:
    def __init__(
        self,
        proto_array: ProtoArray,
        justified_root: str,
        balances: Optional[np.ndarray] = None,
    ):
        self.proto = proto_array
        self.justified_root = justified_root
        self.balances = (
            balances if balances is not None else np.zeros(0, np.int64)
        )
        self._latest: Dict[int, LatestMessage] = {}
        # vote state at the last update_head (for delta computation)
        self._applied_votes: Dict[int, str] = {}
        self._applied_balances = np.zeros_like(self.balances)

    # -- block / attestation ingestion ------------------------------------

    def has_block(self, root: str) -> bool:
        return root in self.proto

    def on_block(
        self,
        slot: int,
        root: str,
        parent_root: Optional[str],
        justified_epoch: int = None,
        finalized_epoch: int = None,
    ) -> None:
        self.proto.on_block(
            slot,
            root,
            parent_root,
            self.proto.justified_epoch if justified_epoch is None else justified_epoch,
            self.proto.finalized_epoch if finalized_epoch is None else finalized_epoch,
        )

    def on_attestation(self, validator_index: int, epoch: int, root: str) -> None:
        """Track the validator's latest message (newest epoch wins)."""
        cur = self._latest.get(validator_index)
        if cur is None or epoch > cur.epoch:
            self._latest[validator_index] = LatestMessage(epoch, root)

    def set_balances(self, balances: np.ndarray) -> None:
        self.balances = np.asarray(balances, np.int64)

    # -- head (reference: forkChoice.updateHead) ---------------------------

    def update_head(self) -> str:
        n_val = max(
            len(self.balances),
            (max(self._latest) + 1) if self._latest else 0,
            len(self._applied_balances),
        )
        old_votes = np.full(n_val, -1, np.int64)
        new_votes = np.full(n_val, -1, np.int64)
        for v, root in self._applied_votes.items():
            idx = self.proto.indices.get(root)
            if idx is not None:
                old_votes[v] = idx
        for v, msg in self._latest.items():
            idx = self.proto.indices.get(msg.root)
            if idx is not None:
                new_votes[v] = idx
        old_bal = np.zeros(n_val, np.int64)
        old_bal[: len(self._applied_balances)] = self._applied_balances
        new_bal = np.zeros(n_val, np.int64)
        new_bal[: len(self.balances)] = self.balances

        deltas = compute_deltas(
            len(self.proto), old_votes, new_votes, old_bal, new_bal
        )
        self.proto.apply_score_changes(
            deltas, self.proto.justified_epoch, self.proto.finalized_epoch
        )
        self._applied_votes = {v: m.root for v, m in self._latest.items()}
        self._applied_balances = new_bal
        return self.proto.find_head(self.justified_root)
