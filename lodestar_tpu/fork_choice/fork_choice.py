"""ForkChoice facade: latest messages + head computation.

Reference: packages/fork-choice/src/forkChoice/forkChoice.ts — tracks
per-validator latest messages (epoch, block root), converts votes to
proto-array score changes on update_head, and exposes the IForkChoice
surface the chain/processor layers consume (hasBlock/getHead/onBlock/
onAttestation).

Hardening (round 4):
  - proposer boost: `on_timely_block` records the current slot's timely
    proposal; `update_head` applies the transient boost score =
    (total_active_balance / SLOTS_PER_EPOCH) * PROPOSER_SCORE_BOOST%
    (reference: forkChoice.ts:1188-1215 computeProposerBoostScore);
    `on_tick_slot` clears it at the slot boundary.
  - equivocation: `on_attester_slashing` zeroes the slashed validators'
    votes permanently (reference: forkChoice.ts onAttesterSlashing ->
    computeDeltas.ts:47-63).
  - prune: `prune(finalized_root)` forwards to ProtoArray.maybe_prune.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional

import numpy as np

from .. import params
from .compute_deltas import compute_deltas
from .proto_array import ExecutionStatus, ProtoArray, ProtoNode

SLOTS_PER_EPOCH = params.SLOTS_PER_EPOCH  # preset-aware
PROPOSER_SCORE_BOOST_PCT = 40  # config presets mainnet.ts:73


@dataclass
class LatestMessage:
    epoch: int
    root: str


class ForkChoice:
    def __init__(
        self,
        proto_array: ProtoArray,
        justified_root: str,
        balances: Optional[np.ndarray] = None,
        proposer_score_boost_pct: int = PROPOSER_SCORE_BOOST_PCT,
        slots_per_epoch: int = SLOTS_PER_EPOCH,
    ):
        self.proto = proto_array
        self.justified_root = justified_root
        self.balances = (
            balances if balances is not None else np.zeros(0, np.int64)
        )
        self.proposer_score_boost_pct = proposer_score_boost_pct
        self.slots_per_epoch = slots_per_epoch
        self._latest: Dict[int, LatestMessage] = {}
        # vote state at the last update_head (for delta computation)
        self._applied_votes: Dict[int, str] = {}
        self._applied_balances = np.zeros_like(self.balances)
        # all known equivocators: their future attestations are ignored;
        # removal from _latest backs their standing vote out on the next
        # update_head (no extra delta plumbing needed)
        self._equivocating: set[int] = set()
        # current slot's timely proposal (cleared every slot tick)
        self.proposer_boost_root: Optional[str] = None
        self._boost_slot: Optional[int] = None

    # -- block / attestation ingestion ------------------------------------

    def has_block(self, root: str) -> bool:
        return root in self.proto

    def on_block(
        self,
        slot: int,
        root: str,
        parent_root: Optional[str],
        justified_epoch: int = None,
        finalized_epoch: int = None,
        unrealized_justified_epoch: int = None,
        unrealized_finalized_epoch: int = None,
        execution_status: str = ExecutionStatus.PreMerge,
        execution_block_hash: Optional[str] = None,
    ) -> None:
        self.proto.on_block(
            slot,
            root,
            parent_root,
            self.proto.justified_epoch if justified_epoch is None else justified_epoch,
            self.proto.finalized_epoch if finalized_epoch is None else finalized_epoch,
            unrealized_justified_epoch=unrealized_justified_epoch,
            unrealized_finalized_epoch=unrealized_finalized_epoch,
            execution_status=execution_status,
            execution_block_hash=execution_block_hash,
        )

    def validate_latest_hash(
        self,
        execution_status: str,
        latest_valid_exec_hash: Optional[str],
        invalidate_from_block_root: Optional[str] = None,
    ) -> None:
        """Forward an EL latestValidHash verdict to the proto array
        (reference: forkChoice.ts validateLatestHash passthrough)."""
        self.proto.validate_latest_hash(
            execution_status,
            latest_valid_exec_hash,
            invalidate_from_block_root,
        )

    def get_execution_status(self, root: str) -> Optional[str]:
        idx = self.proto.indices.get(root)
        return self.proto.nodes[idx].execution_status if idx is not None else None

    def get_node(self, root: str) -> Optional[ProtoNode]:
        """Read-only node lookup (reference: forkChoice.getBlock)."""
        idx = self.proto.indices.get(root)
        return self.proto.nodes[idx] if idx is not None else None

    def propagate_valid_root(self, root: str) -> None:
        self.proto.propagate_valid_root(root)

    def set_finalized_root(self, root: str) -> None:
        """Arm the spec-form finalized viability filter (nodes must
        descend from this root, not merely match its epoch)."""
        self.proto.finalized_root = root

    def descends_from_finalized(self, root: str) -> bool:
        """Does `root`'s chain contain the tracked finalized root?
        True when no finalized root is tracked yet (bootstrap)."""
        fin = self.proto.finalized_root
        if fin is None:
            return True
        node = self.get_node(root)
        if node is None:
            return False
        fin_slot = self.proto.finalized_epoch * self.slots_per_epoch
        return self.proto._ancestor_root_at_slot(node, fin_slot) == fin

    def on_timely_block(self, root: str, slot: Optional[int] = None) -> None:
        """Arm the proposer boost for a block arriving before 1/3 slot
        (reference: forkChoice.ts onBlock's blockDelaySec gate).

        First block wins: the spec only boosts when no boost is armed
        (`if store.proposer_boost_root == Root()`), so an equivocating
        proposer cannot move the boost to its second block."""
        if self.proposer_boost_root is not None:
            return
        self.proposer_boost_root = root
        self._boost_slot = slot

    def on_tick_slot(self) -> None:
        """Slot boundary: the boost is strictly per-slot."""
        self.proposer_boost_root = None
        self._boost_slot = None

    def set_current_slot(self, slot: int) -> None:
        """Clock surrogate for clock-less compositions (BeaconChain):
        any evidence that time moved past the boosted slot clears the
        boost (reference: forkChoice.ts updateTime); the proto array's
        clock drives the prev-epoch unrealized-checkpoint filter."""
        self.proto.current_slot = max(self.proto.current_slot, slot)
        if self._boost_slot is not None and slot > self._boost_slot:
            self.on_tick_slot()

    def on_attestation(self, validator_index: int, epoch: int, root: str) -> None:
        """Track the validator's latest message (newest epoch wins).
        Equivocating validators' messages are dead on arrival."""
        if validator_index in self._equivocating:
            return
        cur = self._latest.get(validator_index)
        if cur is None or epoch > cur.epoch:
            self._latest[validator_index] = LatestMessage(epoch, root)

    def on_attester_slashing(self, indices: Iterable[int]) -> None:
        """Zero the slashed validators' fork-choice influence, once and
        permanently (reference: computeDeltas.ts:47-63).  Dropping the
        validator from the latest-message map makes the next
        compute_deltas back out its standing vote (new index -1), and
        the on_attestation guard keeps it out forever."""
        for v in indices:
            self._equivocating.add(v)
            self._latest.pop(v, None)

    def set_balances(self, balances: np.ndarray) -> None:
        self.balances = np.asarray(balances, np.int64)

    # -- head (reference: forkChoice.updateHead) ---------------------------

    def _proposer_boost_score(self) -> int:
        """Committee-weight approximation of one slot's attesters
        (reference: forkChoice.ts computeProposerBoostScore)."""
        total = int(self.balances.sum())
        return (total // self.slots_per_epoch) * self.proposer_score_boost_pct // 100

    def update_head(self) -> str:
        n_val = max(
            len(self.balances),
            (max(self._latest) + 1) if self._latest else 0,
            (max(self._applied_votes) + 1) if self._applied_votes else 0,
            len(self._applied_balances),
        )
        old_votes = np.full(n_val, -1, np.int64)
        new_votes = np.full(n_val, -1, np.int64)
        for v, root in self._applied_votes.items():
            idx = self.proto.indices.get(root)
            if idx is not None:
                old_votes[v] = idx
        for v, msg in self._latest.items():
            idx = self.proto.indices.get(msg.root)
            if idx is not None:
                new_votes[v] = idx
        old_bal = np.zeros(n_val, np.int64)
        old_bal[: len(self._applied_balances)] = self._applied_balances
        new_bal = np.zeros(n_val, np.int64)
        new_bal[: len(self.balances)] = self.balances

        deltas = compute_deltas(
            len(self.proto), old_votes, new_votes, old_bal, new_bal
        )
        boost = None
        if (
            self.proposer_boost_root is not None
            and self.proposer_boost_root in self.proto
        ):
            boost = (self.proposer_boost_root, self._proposer_boost_score())
        self.proto.apply_score_changes(
            deltas,
            self.proto.justified_epoch,
            self.proto.finalized_epoch,
            proposer_boost=boost,
        )
        self._applied_votes = {v: m.root for v, m in self._latest.items()}
        self._applied_balances = new_bal
        return self.proto.find_head(self.justified_root)

    # -- prune (reference: forkChoice.prune) -------------------------------

    def prune(self, finalized_root: str) -> List[ProtoNode]:
        removed = self.proto.maybe_prune(finalized_root)
        if removed:
            # standing votes for pruned roots resolve to "not in indices"
            # next update (outside the tree == pre-finalization, ignored)
            gone = {n.root for n in removed}
            self._applied_votes = {
                v: r for v, r in self._applied_votes.items() if r not in gone
            }
        return removed
