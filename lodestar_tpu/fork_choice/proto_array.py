"""ProtoArray — the append-only fork-choice DAG.

Reference: packages/fork-choice/src/protoArray/protoArray.ts.  Nodes are
stored in insertion order (parents before children), so score/weight
propagation is two linear passes: deltas apply backwards (child -> parent
accumulation), then best-child/best-descendant links update in a second
backward sweep over fully-coherent weights; head lookup is O(1) through
the cached best-descendant.

Hardening (reference parity, round 4):
  - proposer boost: a transient score added to the current slot's timely
    block and removed on the next score application
    (protoArray.ts:137-150 currentBoost/previousBoost accounting);
  - prune below finalized: drops pre-finalized nodes and remaps indices
    (protoArray.ts:525-600 maybePrune).

Optimistic sync (round 5, reference parity):
  - ExecutionStatus per node (Valid/Syncing/PreMerge/Invalid —
    protoArray/interface.ts:16-21) with the full LVH response handling:
    `validate_latest_hash` propagates Valid down to the ancestors or
    invalidates the [LVH-child .. invalid-payload] chain plus every
    descendant (protoArray.ts:245-388 validateLatestHash /
    propagateInValidExecutionStatusByIndex);
  - consensus-failure latching: Valid->Invalid or Invalid->Valid flips
    set `lvh_error` and every subsequent find_head raises
    (protoArray.ts:391-446, findHead:449-455);
  - unrealized justification/finalization: prev-epoch nodes are
    head-filtered on their unrealized (pulled-up) checkpoints, with the
    two-epoch pulled-up allowance (protoArray.ts:725-753
    nodeIsViableForHead).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import params

ZERO_HASH_HEX = "00" * 32


class ExecutionStatus:
    """Payload verdict for a proto node (reference: interface.ts:16-21).

    PreMerge = no execution payload; Syncing = imported optimistically
    (EL said SYNCING/ACCEPTED); Valid/Invalid = EL verdicts.
    """

    Valid = "Valid"
    Syncing = "Syncing"
    PreMerge = "PreMerge"
    Invalid = "Invalid"


@dataclass
class ProtoNode:
    slot: int
    root: str
    parent: Optional[int]  # index into the array
    justified_epoch: int
    finalized_epoch: int
    # pulled-up checkpoints: what justification WOULD be if the epoch
    # transition ran right after this block (reference: ProtoBlock
    # unrealizedJustifiedEpoch/unrealizedFinalizedEpoch)
    unrealized_justified_epoch: int = 0
    unrealized_finalized_epoch: int = 0
    execution_status: str = ExecutionStatus.PreMerge
    execution_block_hash: Optional[str] = None  # hex, None = pre-merge
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


class ProtoArrayError(Exception):
    pass


class LVHConsensusError(ProtoArrayError):
    """EL verdict contradicts an already-settled status (Valid->Invalid
    or Invalid->Valid): consensus failure, the array is perma-damaged
    (reference: protoArray.ts lvhError + LVHExecErrorCode)."""


# Pruning at small offsets costs more than it saves
# (reference: protoArray.ts DEFAULT_PRUNE_THRESHOLD = 256).
DEFAULT_PRUNE_THRESHOLD = 256


class ProtoArray:
    def __init__(
        self,
        finalized_root: str,
        finalized_slot: int = 0,
        justified_epoch: int = 0,
        finalized_epoch: int = 0,
        prune_threshold: int = DEFAULT_PRUNE_THRESHOLD,
    ):
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[str, int] = {}
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        # when set, correct-finalized viability uses the spec's ancestor
        # walk instead of the epoch-equality shortcut
        self.finalized_root: Optional[str] = None
        self.prune_threshold = prune_threshold
        # advances via apply_score_changes/set_current_slot; drives the
        # prev-epoch unrealized-checkpoint filter
        self.current_slot: int = finalized_slot
        # set on a consensus-failure status flip; poisons find_head
        self.lvh_error: Optional[str] = None
        # (root, score) applied last round, to be backed out next round
        # (reference: protoArray.ts previousProposerBoost)
        self.previous_proposer_boost: Optional[Tuple[str, int]] = None
        self.on_block(
            finalized_slot, finalized_root, None, justified_epoch, finalized_epoch
        )

    def __contains__(self, root: str) -> bool:
        return root in self.indices

    def __len__(self) -> int:
        return len(self.nodes)

    # -- insertion (reference: protoArray.ts onBlock) ----------------------

    def on_block(
        self,
        slot: int,
        root: str,
        parent_root: Optional[str],
        justified_epoch: int,
        finalized_epoch: int,
        unrealized_justified_epoch: Optional[int] = None,
        unrealized_finalized_epoch: Optional[int] = None,
        execution_status: str = ExecutionStatus.PreMerge,
        execution_block_hash: Optional[str] = None,
    ) -> None:
        if root in self.indices:
            return
        if execution_status == ExecutionStatus.Invalid:
            raise ProtoArrayError(f"cannot insert Invalid block {root}")
        parent = None
        if parent_root is not None:
            parent = self.indices.get(parent_root)
            if parent is None:
                raise ProtoArrayError(f"unknown parent {parent_root}")
        node = ProtoNode(
            slot,
            root,
            parent,
            justified_epoch,
            finalized_epoch,
            unrealized_justified_epoch=(
                justified_epoch
                if unrealized_justified_epoch is None
                else unrealized_justified_epoch
            ),
            unrealized_finalized_epoch=(
                finalized_epoch
                if unrealized_finalized_epoch is None
                else unrealized_finalized_epoch
            ),
            execution_status=execution_status,
            execution_block_hash=execution_block_hash,
        )
        idx = len(self.nodes)
        self.indices[root] = idx
        self.nodes.append(node)
        if parent is not None:
            # a Valid child proves its whole ancestry
            # (reference: protoArray.ts:227-229)
            if node.execution_status == ExecutionStatus.Valid:
                self._propagate_valid(parent)
            self._maybe_update_best_child(parent, idx)

    # -- execution-status transitions (optimistic sync) --------------------

    def validate_latest_hash(
        self,
        execution_status: str,
        latest_valid_exec_hash: Optional[str],
        invalidate_from_block_root: Optional[str] = None,
        current_slot: Optional[int] = None,
    ) -> None:
        """Apply an EL latestValidHash verdict to the DAG
        (reference: protoArray.ts:245-315 validateLatestHash).

        Valid: find the node carrying `latest_valid_exec_hash` and flip
        it plus all Syncing ancestors to Valid (forgiving: unknown hash
        is a no-op).

        Invalid: `invalidate_from_block_root` names the newest known
        block of the bad chain (the reference passes the invalid
        block's PARENT root, verifyBlocksExecutionPayloads.ts:307 —
        despite the field's "...BlockHash" name it is a beacon root).
        If the LVH is found among its ancestors, everything above the
        LVH is invalidated plus all descendants of invalid nodes; if
        not found, only the named node is invalidated (EL may be buggy
        or lazy — protoArray.ts:296-311).
        """
        if current_slot is not None:
            self.current_slot = max(self.current_slot, current_slot)
        if execution_status == ExecutionStatus.Valid:
            if latest_valid_exec_hash is None:
                return
            # reverse scan: the LVH is almost surely near the leaves
            for i in range(len(self.nodes) - 1, -1, -1):
                if self.nodes[i].execution_block_hash == latest_valid_exec_hash:
                    self._propagate_valid(i)
                    return
            return
        if execution_status != ExecutionStatus.Invalid:
            raise ProtoArrayError(
                f"validate_latest_hash: bad status {execution_status}"
            )
        if invalidate_from_block_root is None:
            raise ProtoArrayError("Invalid verdict without a from-root")
        from_idx = self.indices.get(invalidate_from_block_root)
        if from_idx is None:
            raise ProtoArrayError(
                f"unknown invalidate-from root {invalidate_from_block_root}"
            )
        lvh_idx = (
            self._node_index_from_lvh(latest_valid_exec_hash, from_idx)
            if latest_valid_exec_hash is not None
            else None
        )
        if lvh_idx is None:
            # LVH null/not-found: invalidate only the named payload and
            # let future responses resolve the rest
            self._invalidate_node(from_idx)
        else:
            # pass 1: up the ancestry until the LVH
            idx: Optional[int] = from_idx
            while idx is not None and idx > lvh_idx:
                idx = self._invalidate_node(idx).parent
            # pass 2: every child of an invalid node is invalid
            for i, node in enumerate(self.nodes):
                p = self.nodes[node.parent] if node.parent is not None else None
                if (
                    p is not None
                    and p.execution_status == ExecutionStatus.Invalid
                ):
                    self._invalidate_node(i)
        # refresh the DAG links under the new statuses (reference
        # re-runs applyScoreChanges with zero deltas; passing the
        # previous boost keeps its accounting net-zero)
        self.apply_score_changes(
            [0] * len(self.nodes),
            self.justified_epoch,
            self.finalized_epoch,
            proposer_boost=self.previous_proposer_boost,
        )

    def propagate_valid_root(self, root: str) -> None:
        """Flip `root` and its Syncing ancestry to Valid by known beacon
        root — O(branch depth), for callers that already know the node
        (the fcU-confirmed head) instead of the O(n) exec-hash scan."""
        idx = self.indices.get(root)
        if idx is not None:
            self._propagate_valid(idx)

    def _propagate_valid(self, idx: int) -> None:
        """Syncing -> Valid up the ancestry; stop at settled statuses
        (reference: propagateValidExecutionStatusByIndex:317-330)."""
        cur: Optional[int] = idx
        while cur is not None:
            node = self.nodes[cur]
            if node.execution_status in (
                ExecutionStatus.PreMerge,
                ExecutionStatus.Valid,
            ):
                break
            if node.execution_status == ExecutionStatus.Invalid:
                self.lvh_error = (
                    f"InvalidToValid at {node.root}"
                )
                raise LVHConsensusError(self.lvh_error)
            node.execution_status = ExecutionStatus.Valid
            cur = node.parent

    def _invalidate_node(self, idx: int) -> ProtoNode:
        """Flip one node to Invalid; a Valid/PreMerge victim is a
        consensus failure (reference: invalidateNodeByIndex:391-423)."""
        node = self.nodes[idx]
        if node.execution_status in (
            ExecutionStatus.Valid,
            ExecutionStatus.PreMerge,
        ):
            self.lvh_error = (
                f"{node.execution_status}ToInvalid at {node.root}"
            )
            raise LVHConsensusError(self.lvh_error)
        node.execution_status = ExecutionStatus.Invalid
        node.best_child = None
        node.best_descendant = None
        return node

    def _node_index_from_lvh(
        self, latest_valid_exec_hash: str, ancestor_of: int
    ) -> Optional[int]:
        """Walk the ancestry for the LVH node; a PreMerge ancestor
        matches the zero hash (reference: getNodeIndexFromLVH:374-389)."""
        idx = self.nodes[ancestor_of].parent
        while idx is not None:
            node = self.nodes[idx]
            if (
                node.execution_status == ExecutionStatus.PreMerge
                and latest_valid_exec_hash == ZERO_HASH_HEX
            ) or node.execution_block_hash == latest_valid_exec_hash:
                return idx
            idx = node.parent
        return None

    # -- scoring (reference: protoArray.ts applyScoreChanges) --------------

    def apply_score_changes(
        self,
        deltas: List[int],
        justified_epoch: int,
        finalized_epoch: int,
        proposer_boost: Optional[Tuple[str, int]] = None,
        current_slot: Optional[int] = None,
    ) -> None:
        """Apply per-node weight deltas and refresh all links.

        `deltas` is indexed like `nodes` (computeDeltas output).
        `proposer_boost` is (root, score) for the current slot's timely
        block; last round's boost is automatically backed out — the boost
        is transient, living exactly one score application.

        Two backward sweeps, like the reference: weights must be fully
        coherent before any best-child comparison, otherwise an
        equal-weight tiebreak can settle on a sibling whose delta had not
        landed yet (protoArray.ts:121-186).
        """
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("invalid deltas length")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        if current_slot is not None:
            self.current_slot = max(self.current_slot, current_slot)
        boost_root, boost_score = proposer_boost or (None, 0)
        prev_root, prev_score = self.previous_proposer_boost or (None, 0)
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.execution_status == ExecutionStatus.Invalid:
                # an invalidated node's standing weight is taken out of
                # consideration entirely — its delta becomes -weight and
                # back-propagates, so votes parked on the invalid
                # subtree stop counting toward ancestors
                # (reference: protoArray.ts:146-150)
                d = -node.weight
            else:
                d = deltas[i]
                if node.root == boost_root:
                    d += boost_score
                if node.root == prev_root:
                    d -= prev_score
            node.weight += d
            if node.weight < 0:
                raise ProtoArrayError(f"negative weight at {node.root}")
            if node.parent is not None:
                deltas[node.parent] += d
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child(node.parent, i)
        self.previous_proposer_boost = proposer_boost

    # -- head (reference: protoArray.ts findHead) --------------------------

    def find_head(self, justified_root: str) -> str:
        if self.lvh_error is not None:
            raise LVHConsensusError(self.lvh_error)
        idx = self.indices.get(justified_root)
        if idx is None:
            raise ProtoArrayError(f"unknown justified root {justified_root}")
        node = self.nodes[idx]
        best = node.best_descendant
        head = self.nodes[best] if best is not None else node
        if not self._node_is_viable_for_head(head):
            raise ProtoArrayError("head is not viable")
        return head.root

    # -- prune (reference: protoArray.ts maybePrune) -----------------------

    def maybe_prune(self, finalized_root: str) -> List[ProtoNode]:
        """Drop all nodes before the finalized one; remap indices.

        Returns the removed nodes (the archiver migrates their data).
        No-op below `prune_threshold` — pruning tiny prefixes costs more
        than it saves.
        """
        fin = self.indices.get(finalized_root)
        if fin is None:
            raise ProtoArrayError(f"unknown finalized root {finalized_root}")
        if fin < self.prune_threshold:
            return []
        removed = self.nodes[:fin]
        for node in removed:
            del self.indices[node.root]
        self.nodes = self.nodes[fin:]
        for root in self.indices:
            self.indices[root] -= fin
        for node in self.nodes:
            if node.parent is not None:
                node.parent = node.parent - fin if node.parent >= fin else None
            for attr in ("best_child", "best_descendant"):
                v = getattr(node, attr)
                if v is not None:
                    if v < fin:
                        raise ProtoArrayError(f"{attr} points below finalized")
                    setattr(node, attr, v - fin)
        return removed

    # -- internals ---------------------------------------------------------

    def _ancestor_root_at_slot(self, node: ProtoNode, slot: int) -> str:
        """Root of the node's chain at `slot` (reference: getAncestor)."""
        idx = self.indices[node.root]
        while True:
            n = self.nodes[idx]
            if n.slot <= slot or n.parent is None:
                return n.root
            idx = n.parent

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """filter_block_tree: FFG + execution viability
        (reference: nodeIsViableForHead, protoArray.ts:725-753)."""
        if node.execution_status == ExecutionStatus.Invalid:
            return False
        spe = params.SLOTS_PER_EPOCH
        current_epoch = self.current_slot // spe
        previous_epoch = current_epoch - 1
        # prev-epoch blocks are judged on unrealized (pulled-up)
        # justification; current-epoch blocks on their realized state
        is_from_prev = node.slot // spe < current_epoch
        voting_source = (
            node.unrealized_justified_epoch
            if is_from_prev
            else node.justified_epoch
        )
        correct_justified = (
            voting_source == self.justified_epoch or self.justified_epoch == 0
        )
        # pulled-up allowance: unrealized justification caught up and the
        # voting source is at most two epochs stale
        if (
            not correct_justified
            and current_epoch > 0
            and self.justified_epoch == previous_epoch
        ):
            correct_justified = (
                node.unrealized_justified_epoch >= previous_epoch
                and voting_source + 2 >= current_epoch
            )
        if self.finalized_epoch == 0:
            correct_finalized = True
        elif self.finalized_root is not None:
            # spec form: the node's chain must contain the finalized root
            fin_slot = self.finalized_epoch * spe
            correct_finalized = (
                self._ancestor_root_at_slot(node, fin_slot)
                == self.finalized_root
            )
        else:
            # epoch-equality shortcut for compositions that do not track
            # the finalized root
            correct_finalized = node.finalized_epoch == self.finalized_epoch
        return correct_justified and correct_finalized

    def _node_leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child(self, parent_idx: int, child_idx: int) -> None:
        """Re-evaluate parent's best child against `child_idx`
        (reference: maybeUpdateBestChildAndDescendant's three outcomes)."""
        parent = self.nodes[parent_idx]
        child = self.nodes[child_idx]
        child_viable = self._node_leads_to_viable_head(child)

        if parent.best_child == child_idx:
            if not child_viable:
                self._change_best_child(parent_idx, None)
            else:
                self._change_best_child(parent_idx, child_idx)  # refresh desc
            return
        if not child_viable:
            return
        if parent.best_child is None:
            self._change_best_child(parent_idx, child_idx)
            return
        best = self.nodes[parent.best_child]
        best_viable = self._node_leads_to_viable_head(best)
        if not best_viable:
            self._change_best_child(parent_idx, child_idx)
            return
        # Prefer the existing best unless strictly greater weight, with a
        # root-order tiebreak on exact equality (reference semantics).
        if child.weight > best.weight or (
            child.weight == best.weight and child.root > best.root
        ):
            self._change_best_child(parent_idx, child_idx)

    def _change_best_child(self, parent_idx: int, child_idx: Optional[int]):
        parent = self.nodes[parent_idx]
        parent.best_child = child_idx
        if child_idx is None:
            parent.best_descendant = None
        else:
            child = self.nodes[child_idx]
            parent.best_descendant = (
                child.best_descendant
                if child.best_descendant is not None
                else child_idx
            )
