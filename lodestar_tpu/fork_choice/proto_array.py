"""ProtoArray — the append-only fork-choice DAG.

Reference: packages/fork-choice/src/protoArray/protoArray.ts.  Nodes are
stored in insertion order (parents before children), so score/weight
propagation is two linear passes: deltas apply backwards (child -> parent
accumulation), then best-child/best-descendant links update in a second
backward sweep over fully-coherent weights; head lookup is O(1) through
the cached best-descendant.

Hardening (reference parity, round 4):
  - proposer boost: a transient score added to the current slot's timely
    block and removed on the next score application
    (protoArray.ts:137-150 currentBoost/previousBoost accounting);
  - prune below finalized: drops pre-finalized nodes and remaps indices
    (protoArray.ts:525-600 maybePrune).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class ProtoNode:
    slot: int
    root: str
    parent: Optional[int]  # index into the array
    justified_epoch: int
    finalized_epoch: int
    weight: int = 0
    best_child: Optional[int] = None
    best_descendant: Optional[int] = None


class ProtoArrayError(Exception):
    pass


# Pruning at small offsets costs more than it saves
# (reference: protoArray.ts DEFAULT_PRUNE_THRESHOLD = 256).
DEFAULT_PRUNE_THRESHOLD = 256


class ProtoArray:
    def __init__(
        self,
        finalized_root: str,
        finalized_slot: int = 0,
        justified_epoch: int = 0,
        finalized_epoch: int = 0,
        prune_threshold: int = DEFAULT_PRUNE_THRESHOLD,
    ):
        self.nodes: List[ProtoNode] = []
        self.indices: Dict[str, int] = {}
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        self.prune_threshold = prune_threshold
        # (root, score) applied last round, to be backed out next round
        # (reference: protoArray.ts previousProposerBoost)
        self.previous_proposer_boost: Optional[Tuple[str, int]] = None
        self.on_block(
            finalized_slot, finalized_root, None, justified_epoch, finalized_epoch
        )

    def __contains__(self, root: str) -> bool:
        return root in self.indices

    def __len__(self) -> int:
        return len(self.nodes)

    # -- insertion (reference: protoArray.ts onBlock) ----------------------

    def on_block(
        self,
        slot: int,
        root: str,
        parent_root: Optional[str],
        justified_epoch: int,
        finalized_epoch: int,
    ) -> None:
        if root in self.indices:
            return
        parent = None
        if parent_root is not None:
            parent = self.indices.get(parent_root)
            if parent is None:
                raise ProtoArrayError(f"unknown parent {parent_root}")
        node = ProtoNode(slot, root, parent, justified_epoch, finalized_epoch)
        idx = len(self.nodes)
        self.indices[root] = idx
        self.nodes.append(node)
        if parent is not None:
            self._maybe_update_best_child(parent, idx)

    # -- scoring (reference: protoArray.ts applyScoreChanges) --------------

    def apply_score_changes(
        self,
        deltas: List[int],
        justified_epoch: int,
        finalized_epoch: int,
        proposer_boost: Optional[Tuple[str, int]] = None,
    ) -> None:
        """Apply per-node weight deltas and refresh all links.

        `deltas` is indexed like `nodes` (computeDeltas output).
        `proposer_boost` is (root, score) for the current slot's timely
        block; last round's boost is automatically backed out — the boost
        is transient, living exactly one score application.

        Two backward sweeps, like the reference: weights must be fully
        coherent before any best-child comparison, otherwise an
        equal-weight tiebreak can settle on a sibling whose delta had not
        landed yet (protoArray.ts:121-186).
        """
        if len(deltas) != len(self.nodes):
            raise ProtoArrayError("invalid deltas length")
        self.justified_epoch = justified_epoch
        self.finalized_epoch = finalized_epoch
        boost_root, boost_score = proposer_boost or (None, 0)
        prev_root, prev_score = self.previous_proposer_boost or (None, 0)
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            d = deltas[i]
            if node.root == boost_root:
                d += boost_score
            if node.root == prev_root:
                d -= prev_score
            node.weight += d
            if node.weight < 0:
                raise ProtoArrayError(f"negative weight at {node.root}")
            if node.parent is not None:
                deltas[node.parent] += d
        for i in range(len(self.nodes) - 1, -1, -1):
            node = self.nodes[i]
            if node.parent is not None:
                self._maybe_update_best_child(node.parent, i)
        self.previous_proposer_boost = proposer_boost

    # -- head (reference: protoArray.ts findHead) --------------------------

    def find_head(self, justified_root: str) -> str:
        idx = self.indices.get(justified_root)
        if idx is None:
            raise ProtoArrayError(f"unknown justified root {justified_root}")
        node = self.nodes[idx]
        best = node.best_descendant
        head = self.nodes[best] if best is not None else node
        if not self._node_is_viable_for_head(head):
            raise ProtoArrayError("head is not viable")
        return head.root

    # -- prune (reference: protoArray.ts maybePrune) -----------------------

    def maybe_prune(self, finalized_root: str) -> List[ProtoNode]:
        """Drop all nodes before the finalized one; remap indices.

        Returns the removed nodes (the archiver migrates their data).
        No-op below `prune_threshold` — pruning tiny prefixes costs more
        than it saves.
        """
        fin = self.indices.get(finalized_root)
        if fin is None:
            raise ProtoArrayError(f"unknown finalized root {finalized_root}")
        if fin < self.prune_threshold:
            return []
        removed = self.nodes[:fin]
        for node in removed:
            del self.indices[node.root]
        self.nodes = self.nodes[fin:]
        for root in self.indices:
            self.indices[root] -= fin
        for node in self.nodes:
            if node.parent is not None:
                node.parent = node.parent - fin if node.parent >= fin else None
            for attr in ("best_child", "best_descendant"):
                v = getattr(node, attr)
                if v is not None:
                    if v < fin:
                        raise ProtoArrayError(f"{attr} points below finalized")
                    setattr(node, attr, v - fin)
        return removed

    # -- internals ---------------------------------------------------------

    def _node_is_viable_for_head(self, node: ProtoNode) -> bool:
        """FFG viability filter (reference: nodeIsViableForHead)."""
        return (
            node.justified_epoch == self.justified_epoch
            or self.justified_epoch == 0
        ) and (
            node.finalized_epoch == self.finalized_epoch
            or self.finalized_epoch == 0
        )

    def _node_leads_to_viable_head(self, node: ProtoNode) -> bool:
        if node.best_descendant is not None:
            return self._node_is_viable_for_head(self.nodes[node.best_descendant])
        return self._node_is_viable_for_head(node)

    def _maybe_update_best_child(self, parent_idx: int, child_idx: int) -> None:
        """Re-evaluate parent's best child against `child_idx`
        (reference: maybeUpdateBestChildAndDescendant's three outcomes)."""
        parent = self.nodes[parent_idx]
        child = self.nodes[child_idx]
        child_viable = self._node_leads_to_viable_head(child)

        if parent.best_child == child_idx:
            if not child_viable:
                self._change_best_child(parent_idx, None)
            else:
                self._change_best_child(parent_idx, child_idx)  # refresh desc
            return
        if not child_viable:
            return
        if parent.best_child is None:
            self._change_best_child(parent_idx, child_idx)
            return
        best = self.nodes[parent.best_child]
        best_viable = self._node_leads_to_viable_head(best)
        if not best_viable:
            self._change_best_child(parent_idx, child_idx)
            return
        # Prefer the existing best unless strictly greater weight, with a
        # root-order tiebreak on exact equality (reference semantics).
        if child.weight > best.weight or (
            child.weight == best.weight and child.root > best.root
        ):
            self._change_best_child(parent_idx, child_idx)

    def _change_best_child(self, parent_idx: int, child_idx: Optional[int]):
        parent = self.nodes[parent_idx]
        parent.best_child = child_idx
        if child_idx is None:
            parent.best_descendant = None
        else:
            child = self.nodes[child_idx]
            parent.best_descendant = (
                child.best_descendant
                if child.best_descendant is not None
                else child_idx
            )
