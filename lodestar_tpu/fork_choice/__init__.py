"""Fork choice: proto-array LMD-GHOST + Casper FFG filtering.

Mirror of the reference's `@lodestar/fork-choice` (reference:
packages/fork-choice/src/protoArray/protoArray.ts, computeDeltas.ts,
forkChoice/forkChoice.ts): an append-only node array with cached
best-child/best-descendant links, batched score changes from validator
latest-messages, and viability filtering by justified/finalized
checkpoints.
"""

from .proto_array import (  # noqa: F401
    ExecutionStatus,
    LVHConsensusError,
    ProtoArray,
    ProtoArrayError,
    ProtoNode,
)
from .fork_choice import ForkChoice, LatestMessage  # noqa: F401
from .compute_deltas import compute_deltas  # noqa: F401
