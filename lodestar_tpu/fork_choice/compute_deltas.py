"""compute_deltas — latest-message vote movement, vectorized.

Reference: packages/fork-choice/src/protoArray/computeDeltas.ts — for
each validator whose latest message or effective balance changed,
subtract the old balance at the old vote target and add the new balance
at the new target.  Here the per-validator loop is numpy-vectorized
(np.add.at scatter), matching the framework's batch-first shape.

Equivocating (slashed) validators need no special case at this layer:
ForkChoice removes them from the latest-message map, so their new vote
index is already -1 and the unconditional old-balance subtraction backs
their standing vote out exactly once (the reference's
computeDeltas.ts:47-63 semantics, achieved structurally).
"""

from __future__ import annotations

from typing import List

import numpy as np


def compute_deltas(
    num_nodes: int,
    vote_indices_old: np.ndarray,  # int64[V], -1 = no vote
    vote_indices_new: np.ndarray,  # int64[V], -1 = no vote
    old_balances: np.ndarray,  # int64[V] effective balances
    new_balances: np.ndarray,
) -> List[int]:
    deltas = np.zeros(num_nodes, np.int64)
    old_mask = vote_indices_old >= 0
    new_mask = vote_indices_new >= 0
    np.subtract.at(deltas, vote_indices_old[old_mask], old_balances[old_mask])
    np.add.at(deltas, vote_indices_new[new_mask], new_balances[new_mask])
    return deltas.tolist()
