"""Light client: sync-committee-based header tracking.

Mirror of the reference's `@lodestar/light-client` (reference:
packages/light-client/src/index.ts + validation.ts): bootstrap from a
trusted header + current sync committee, then advance optimistic and
finalized headers by verifying LightClientUpdates — sync-committee
BLS aggregate signatures over attested headers with a 2/3 participation
threshold, next-committee rotation at period boundaries.
"""

from .lightclient import Lightclient, LightClientUpdate, ValidationError  # noqa: F401
