"""Light-client transports: fetch bootstrap/updates over req/resp or REST.

Mirror of the reference's transport split (reference:
packages/light-client/src/transport/{rest,p2p}.ts): the Lightclient
consumes updates from ANY source; these adapters bind it to

  - the req/resp protocol layer (the p2p analog — LightClientBootstrap
    and LightClientUpdatesByRange over `network/reqresp`), and
  - the beacon REST API (`/eth/v1/beacon/light_client/*`).

Both return the repo's LightClientUpdate dataclass (wire containers
decode through network/reqresp_protocols' converters).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import List, Optional

from ..network.reqresp_protocols import (
    LightClientBootstrapType,
    LightClientUpdateType,
    light_client_update_from_value,
)
from .lightclient import LightClientUpdate


class ReqRespLightClientTransport:
    """Fetches over a connected ReqResp peer (reference: transport/p2p.ts)."""

    def __init__(self, reqresp, reqresp_node, peer_id: str):
        self.reqresp = reqresp
        self.protocols = reqresp_node.protocols
        self.peer_id = peer_id

    def get_bootstrap(self, block_root: bytes) -> dict:
        chunks = self.reqresp.send_request(
            self.peer_id, self.protocols["lc_bootstrap"], bytes(block_root)
        )
        return LightClientBootstrapType.deserialize(chunks[0][0])

    def get_updates(
        self, start_period: int, count: int
    ) -> List[LightClientUpdate]:
        chunks = self.reqresp.send_request(
            self.peer_id,
            self.protocols["lc_updates"],
            {"start_period": start_period, "count": count},
        )
        return [
            light_client_update_from_value(
                LightClientUpdateType.deserialize(data)
            )
            for data, _ctx in chunks
        ]


class RestLightClientTransport:
    """Fetches over the beacon REST API (reference: transport/rest.ts)."""

    def __init__(self, base_url: str, timeout: float = 30.0):
        self.base = base_url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str):
        with urllib.request.urlopen(
            self.base + path, timeout=self.timeout
        ) as resp:
            return json.loads(resp.read())

    def get_bootstrap(self, block_root: bytes) -> dict:
        from ..api.encoding import from_json

        out = self._get(
            "/eth/v1/beacon/light_client/bootstrap/0x"
            + bytes(block_root).hex()
        )
        return from_json(LightClientBootstrapType, out["data"])

    def get_updates(
        self, start_period: int, count: int
    ) -> List[LightClientUpdate]:
        from ..api.encoding import from_json

        out = self._get(
            "/eth/v1/beacon/light_client/updates"
            f"?start_period={start_period}&count={count}"
        )
        return [
            light_client_update_from_value(
                from_json(LightClientUpdateType, item["data"])
            )
            for item in out
        ]

    def get_optimistic_update(self) -> Optional[LightClientUpdate]:
        from ..api.encoding import from_json

        try:
            out = self._get("/eth/v1/beacon/light_client/optimistic_update")
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return None
            raise
        return light_client_update_from_value(
            from_json(LightClientUpdateType, out["data"])
        )


def bootstrap_lightclient(config, transport, trusted_root: bytes):
    """Trusted-root bootstrap through a transport (reference:
    Lightclient.initializeFromCheckpointRoot)."""
    from .lightclient import Lightclient

    boot = transport.get_bootstrap(trusted_root)
    return Lightclient(
        config,
        dict(boot["header"]),
        [bytes(pk) for pk in boot["current_sync_committee"]["pubkeys"]],
    )


def advance_lightclient(client, transport, head_period: int) -> int:
    """Pull + apply committee-period updates up to `head_period`;
    returns how many applied (reference: LightclientSync run loop)."""
    from .lightclient import sync_period

    applied = 0
    start = sync_period(client.finalized_header["slot"])
    count = max(0, head_period - start + 1)
    if count == 0:
        return 0
    for upd in transport.get_updates(start, count):
        client.process_update(upd)
        applied += 1
    return applied
