"""Lightclient — update validation + header tracking.

Reference: packages/light-client/src/index.ts (processOptimisticUpdate /
processFinalizedUpdate flow) and light-client/src/validation.ts
(assertValidLightClientUpdate: participation, signature, next-committee
handling).  Signature verification runs through the framework's BLS
stack (CPU oracle here; the same sets route to the TPU verifier when a
device is attached).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from .. import params
from ..config.chain_config import ChainConfig
from ..crypto import bls as B
from ..crypto import curves as C
from ..crypto import pairing as P
from ..crypto.hash_to_curve import hash_to_g2
from ..ssz import is_valid_merkle_branch
from ..types import BeaconBlockHeader, SyncCommittee


class ValidationError(Exception):
    pass


# Generalized index of next_sync_committee in the altair BeaconState:
# gindex 55 = 2**5 + 23 (spec NEXT_SYNC_COMMITTEE_INDEX)
NEXT_SYNC_COMMITTEE_DEPTH = 5
NEXT_SYNC_COMMITTEE_INDEX = 23
# Generalized index of finalized_checkpoint.root: gindex 105 = 2**6 + 41
FINALIZED_ROOT_DEPTH = 6
FINALIZED_ROOT_INDEX = 41


@dataclass
class LightClientUpdate:
    """The subset of the spec's LightClientUpdate the client consumes.

    `next_sync_committee` is a full SyncCommittee value ({pubkeys,
    aggregate_pubkey}) accompanied by its merkle branch against the
    attested header's state root — installing a committee requires the
    cryptographic binding, not just a signed header.
    """

    attested_header: dict  # BeaconBlockHeader value
    sync_committee_bits: List[bool]
    sync_committee_signature: bytes  # 96B compressed
    signature_slot: int
    finalized_header: Optional[dict] = None
    finality_branch: Optional[List[bytes]] = None
    next_sync_committee: Optional[dict] = None  # SyncCommittee value
    next_sync_committee_branch: Optional[List[bytes]] = None


def sync_period(slot: int) -> int:
    return slot // (
        params.SLOTS_PER_EPOCH * params.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
    )


class Lightclient:
    """Tracks optimistic + finalized headers from a trusted bootstrap."""

    MIN_PARTICIPATION = 2 / 3  # spec MIN_SYNC_COMMITTEE_PARTICIPANTS bound

    def __init__(
        self,
        config: ChainConfig,
        bootstrap_header: dict,
        current_sync_committee: Sequence[bytes],
    ):
        self.config = config
        self.optimistic_header = dict(bootstrap_header)
        self.finalized_header = dict(bootstrap_header)
        self.committees = {
            sync_period(bootstrap_header["slot"]): [
                C.g1_decompress(pk) for pk in current_sync_committee
            ]
        }

    # -- validation (reference: validation.ts assertValidLightClientUpdate)

    def validate_update(self, update: LightClientUpdate) -> None:
        bits = update.sync_committee_bits
        n_participants = sum(bits)
        if n_participants < len(bits) * self.MIN_PARTICIPATION:
            raise ValidationError(
                f"insufficient participation {n_participants}/{len(bits)}"
            )
        period = sync_period(update.signature_slot)
        committee = self.committees.get(period)
        if committee is None:
            raise ValidationError(f"no sync committee for period {period}")
        if len(bits) != len(committee):
            raise ValidationError("bits length != committee size")
        participants = [pk for pk, b in zip(committee, bits) if b]

        root = self.config.compute_signing_root(
            BeaconBlockHeader.hash_tree_root(update.attested_header),
            self.config.get_domain(
                update.signature_slot,
                params.DOMAIN_SYNC_COMMITTEE,
                max(update.signature_slot, 1) - 1,
            ),
        )
        try:
            sig = C.g2_decompress(update.sync_committee_signature)
        except ValueError:
            raise ValidationError("undecodable sync committee signature")
        if sig is None or not C.g2_subgroup_check(sig):
            raise ValidationError("invalid sync committee signature point")
        agg = B.aggregate_pubkeys(participants)
        if not P.multi_pairing_is_one(
            [(agg, hash_to_g2(root)), (B.NEG_G1_GEN, sig)]
        ):
            raise ValidationError("sync committee signature does not verify")

    # -- processing (reference: index.ts processOptimistic/FinalizedUpdate)

    def process_update(self, update: LightClientUpdate) -> None:
        self.validate_update(update)
        if update.next_sync_committee is not None:
            # a committee rotation MUST be merkle-bound to the signed
            # attested header's state root (reference:
            # validation.ts assertValidSyncCommitteeProof) — otherwise a
            # relayer could swap in an attacker committee
            if update.next_sync_committee_branch is None:
                raise ValidationError("next sync committee without branch")
            leaf = SyncCommittee.hash_tree_root(update.next_sync_committee)
            if not is_valid_merkle_branch(
                leaf,
                update.next_sync_committee_branch,
                NEXT_SYNC_COMMITTEE_DEPTH,
                NEXT_SYNC_COMMITTEE_INDEX,
                update.attested_header["state_root"],
            ):
                raise ValidationError("invalid next sync committee proof")
        if update.finalized_header is not None:
            # finality must be merkle-bound to the signed attested state
            # root too (reference: validation.ts finality_branch check)
            if update.finality_branch is None:
                raise ValidationError("finalized header without branch")
            leaf = BeaconBlockHeader.hash_tree_root(update.finalized_header)
            if not is_valid_merkle_branch(
                leaf,
                update.finality_branch,
                FINALIZED_ROOT_DEPTH,
                FINALIZED_ROOT_INDEX,
                update.attested_header["state_root"],
            ):
                raise ValidationError("invalid finality proof")
        if update.attested_header["slot"] > self.optimistic_header["slot"]:
            self.optimistic_header = dict(update.attested_header)
        if (
            update.finalized_header is not None
            and update.finalized_header["slot"] > self.finalized_header["slot"]
        ):
            self.finalized_header = dict(update.finalized_header)
        if update.next_sync_committee is not None:
            next_period = sync_period(update.attested_header["slot"]) + 1
            self.committees.setdefault(
                next_period,
                [
                    C.g1_decompress(pk)
                    for pk in update.next_sync_committee["pubkeys"]
                ],
            )
