"""lodestar-tpu CLI — beacon / validator / lightclient / bench entry.

Mirror of the reference's packages/cli (reference: cli/src/index.ts,
cli/src/cmds/{beacon,validator,lightclient}/): argument groups per
subcommand, preset/network selection via flags, composed over the same
library surface the tests drive.  Kept argparse-native (no yargs
analog needed) and import-light so `--help` is instant.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="lodestar-tpu",
        description="TPU-native beacon chain framework "
        "(capability mirror of ChainSafe Lodestar)",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    beacon = sub.add_parser("beacon", help="run a beacon node")
    beacon.add_argument("--preset", default=None, choices=["mainnet", "minimal"])
    beacon.add_argument("--db-path", default=None)
    beacon.add_argument("--api-port", type=int, default=9596)
    beacon.add_argument("--genesis-time", type=int, default=None)
    beacon.add_argument(
        "--validators", type=int, default=16,
        help="dev-mode interop validator count",
    )
    beacon.add_argument(
        "--slots", type=int, default=0,
        help="dev mode: self-propose this many slots then exit (0 = serve forever)",
    )
    beacon.add_argument(
        "--checkpoint-sync-url", default=None,
        help="bootstrap from a trusted node's debug state endpoint "
        "(weak-subjectivity checkpoint sync)",
    )
    beacon.add_argument(
        "--checkpoint-state", default=None,
        help="bootstrap from an SSZ state file",
    )
    beacon.add_argument(
        "--builder-url", default=None,
        help="MEV relay URL (builder-specs REST); enables the blinded "
        "production/publish endpoints",
    )
    beacon.add_argument(
        "--builder-enabled", action="store_true",
        help="enable the builder at boot after a successful status check",
    )

    validator = sub.add_parser("validator", help="run a validator client")
    validator.add_argument("--beacon-urls", nargs="+", required=True)
    validator.add_argument(
        "--interop-indices", type=int, nargs="*", default=(),
        help="interop validator indices to run (keys derived as in dev mode)",
    )
    validator.add_argument(
        "--keystores-dir", default=None,
        help="directory of EIP-2335 keystore *.json files to load; "
        "indices resolve from the beacon node's validator registry",
    )
    validator.add_argument(
        "--keystores-password-file", default=None,
        help="file holding the password for --keystores-dir keystores",
    )
    validator.add_argument("--slots", type=int, default=1)
    validator.add_argument(
        "--slashing-db-path", default=None,
        help="durable slashing-protection DB (survives restarts)",
    )
    validator.add_argument(
        "--doppelganger-protection", action="store_true",
        help="delay duties until keys prove silent on the network",
    )
    validator.add_argument(
        "--external-signer-url", default=None,
        help="Web3Signer-API remote signer; indices NOT in the local "
        "key set sign through it (their pubkeys come from the signer)",
    )
    validator.add_argument(
        "--remote-indices", type=int, nargs="*", default=(),
        help="validator indices whose keys live in the external signer",
    )
    validator.add_argument(
        "--proposer-settings-file", default=None,
        help="YAML/JSON per-key proposer settings (fee recipient, gas "
        "limit, builder flags); builder-enabled keys propose through "
        "the blinded flow",
    )

    bench = sub.add_parser("bench", help="run the headline TPU benchmark")
    bench.add_argument("--mode", default="wire", choices=["wire", "decoded"])

    lc = sub.add_parser("lightclient", help="run a light client (in-process demo)")
    lc.add_argument("--slots", type=int, default=2)

    flare = sub.add_parser(
        "flare", help="operator debug tool: self-slash test validators"
    )
    flare.add_argument(
        "action", choices=["self-slash-proposer", "self-slash-attester"]
    )
    flare.add_argument("--beacon-urls", nargs="+", required=True)
    flare.add_argument("--interop-indices", type=int, nargs="+", required=True)
    flare.add_argument("--slot", type=int, default=1)
    flare.add_argument("--epoch", type=int, default=0)

    return parser


def _interop_keys(n: int):
    from .crypto import bls as B
    from .crypto import curves as C

    sks = [B.keygen(b"lodestar-tpu-interop-%d" % i) for i in range(n)]
    pks = [C.g1_compress(B.sk_to_pk(sk)) for sk in sks]
    return sks, pks


def _dev_config(genesis_time=0):
    """The dev-mode chain config (altair at genesis) shared by the
    beacon, validator, lightclient, and flare subcommands."""
    from .config import MAINNET_CHAIN_CONFIG, create_chain_config
    from .params import ForkName

    return create_chain_config(
        MAINNET_CHAIN_CONFIG,
        genesis_time=genesis_time,
        fork_epochs={ForkName.altair: 0},
    )


def _dev_chain(args):
    from .chain.chain import BeaconChain
    from .chain.init_state import init_beacon_state
    from .db import BeaconDb
    from .state_transition import create_genesis_state

    cfg = _dev_config(
        args.genesis_time
        if getattr(args, "genesis_time", None) is not None
        else int(time.time())
    )
    sks, pks = _interop_keys(args.validators)
    db = BeaconDb(args.db_path, config=cfg)
    ckpt_bytes = None
    ckpt_file = getattr(args, "checkpoint_state", None)
    if ckpt_file:
        with open(ckpt_file, "rb") as f:
            ckpt_bytes = f.read()
    anchor, source = init_beacon_state(
        cfg,
        db=db if args.db_path else None,  # in-memory db has no archive
        checkpoint_state_bytes=ckpt_bytes,
        checkpoint_sync_url=getattr(args, "checkpoint_sync_url", None),
        genesis_fn=lambda: create_genesis_state(
            cfg, pks, genesis_time=cfg.genesis_time
        ),
    )
    if source != "genesis" and int(anchor.genesis_time) != cfg.genesis_time:
        # a resumed/checkpoint chain OWNS its genesis time — the wall
        # clock must not reinvent slot 0 (slot clock + doppelganger +
        # /beacon/genesis all derive from it)
        import dataclasses as _dc

        cfg = _dc.replace(cfg, genesis_time=int(anchor.genesis_time))
        anchor.config = cfg
    print(json.dumps({"anchor_source": source, "anchor_slot": anchor.slot}))
    chain = BeaconChain(cfg, anchor, db=db)
    return cfg, sks, pks, chain


def cmd_beacon(args) -> int:
    from .api.server import BeaconApiServer, DefaultHandlers
    from .chain.archiver import Archiver
    from .chain.light_client_server import LightClientServer

    cfg, sks, pks, chain = _dev_chain(args)
    Archiver(chain)
    LightClientServer(chain)
    if getattr(args, "builder_enabled", False) and not getattr(
        args, "builder_url", None
    ):
        print(json.dumps({"error": "--builder-enabled requires --builder-url"}))
        return 2
    if getattr(args, "builder_url", None):
        from .execution import ExecutionBuilderHttp

        builder = ExecutionBuilderHttp(args.builder_url, cfg)
        chain.execution_builder = builder
        if getattr(args, "builder_enabled", False):
            try:
                builder.check_status()
                builder.update_status(True)
            except Exception as e:  # noqa: BLE001 — relay down at boot:
                # stay dark; re-enable over the API later
                print(json.dumps({"builder_status_error": str(e)}))
    server = BeaconApiServer(
        DefaultHandlers(
            genesis_time=cfg.genesis_time,
            genesis_validators_root=cfg.genesis_validators_root,
            chain=chain,
        ),
        port=args.api_port,
    )
    server.listen()
    print(
        json.dumps(
            {
                "msg": "beacon node up",
                "api_port": server.port,
                "validators": len(pks),
                "genesis_time": cfg.genesis_time,
            }
        )
    )
    try:
        if args.slots:
            # dev mode: self-propose through the validator services
            from .api.client import ApiClient
            from .validator import BlockProposalService, ValidatorStore

            from . import params as _p

            client = ApiClient([f"http://127.0.0.1:{server.port}"], timeout=120)
            store = ValidatorStore(cfg, dict(enumerate(sks)))
            svc = BlockProposalService(store, client)
            for slot in range(1, args.slots + 1):
                epoch = slot // _p.SLOTS_PER_EPOCH
                if not svc.duties_at_slot(epoch, slot):
                    svc.poll_duties(epoch)
                n = svc.run_block_tasks(epoch, slot)
                print(
                    json.dumps(
                        {"slot": slot, "proposed": n, "head": chain.head_root_hex[:16]}
                    )
                )
            return 0
        while True:  # serve until interrupted
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0
    finally:
        server.close()
    return 0


def cmd_validator(args) -> int:
    from .api.client import ApiClient
    from .config import MAINNET_CHAIN_CONFIG
    from .validator import (
        AttestationService,
        BlockProposalService,
        ValidatorStore,
    )
    from . import params as _p

    remote = [
        i for i in getattr(args, "remote_indices", ()) or ()
        if i not in args.interop_indices
    ]
    if remote and not getattr(args, "external_signer_url", None):
        print(json.dumps({"error": "--remote-indices needs --external-signer-url"}))
        return 2
    # parse config files BEFORE touching the network: a typo in the
    # settings file must not hide behind a beacon connection error
    proposer_config = None
    if getattr(args, "proposer_settings_file", None):
        from .validator import ProposerConfig

        try:
            proposer_config = ProposerConfig.from_file(
                args.proposer_settings_file
            )
        except Exception as e:  # noqa: BLE001 — any parse fault
            # (YAML syntax, bad types) must exit cleanly, not traceback
            print(json.dumps({"error": f"proposer settings: {e}"}))
            return 2
    client = ApiClient(args.beacon_urls, timeout=120)
    genesis = client.get_genesis()
    # ONE derivation covering local + remote indices (keygen per index)
    n_keys = max([*args.interop_indices, *remote], default=-1) + 1
    sks, pks = _interop_keys(n_keys)
    local_sks = {i: sks[i] for i in args.interop_indices}

    if getattr(args, "keystores_dir", None):
        # EIP-2335 keystores from disk (reference: cli validator
        # keymanager importKeystoresFromDir): decrypt each *.json with
        # the password file, resolve indices from the node's registry
        import os as _os

        from .crypto import bls as _B
        from .crypto import curves as _C
        from .validator.keystore import KeystoreError, decrypt_keystore

        if not args.keystores_password_file:
            print(json.dumps(
                {"error": "--keystores-dir needs --keystores-password-file"}
            ))
            return 2
        try:
            with open(args.keystores_password_file) as f:
                password = f.read().strip()
            names = sorted(_os.listdir(args.keystores_dir))
        except OSError as e:
            print(json.dumps({"error": f"keystore config: {e}"}))
            return 2
        loaded = 0
        for name in names:
            if not name.endswith(".json"):
                continue
            path = _os.path.join(args.keystores_dir, name)
            try:
                with open(path) as f:
                    ks = json.load(f)
                sk = int.from_bytes(decrypt_keystore(ks, password), "big")
            except (KeystoreError, ValueError, OSError) as e:
                print(json.dumps({"keystore_error": f"{name}: {e}"}))
                continue
            pk = _C.g1_compress(_B.sk_to_pk(sk))
            try:
                rec = client.get_state_validator("0x" + pk.hex())
            except Exception as e:  # not (yet) in the registry
                print(json.dumps({"keystore_skipped": f"{name}: {e}"}))
                continue
            local_sks[int(rec["index"])] = sk
            loaded += 1
        print(json.dumps({"keystores_loaded": loaded}))
    if not local_sks and not remote:
        print(json.dumps({"error": "no validator keys (interop or keystores)"}))
        return 2
    doppelganger = None
    if args.doppelganger_protection:
        from .validator import DoppelgangerService

        genesis_time = int(genesis["genesis_time"])

        def _wall_epoch() -> int:
            return max(
                0,
                int(time.time() - genesis_time)
                // (_p.SECONDS_PER_SLOT * _p.SLOTS_PER_EPOCH),
            )

        def _liveness(epoch, indices):
            # a probe failure means "cannot verify yet" — the epoch must
            # not count toward the watch window (None = no data)
            try:
                return client.get_liveness(epoch, indices)
            except Exception as e:  # noqa: BLE001 - probe is best-effort
                print(json.dumps({"doppelganger_probe_error": str(e)}))
                return None

        doppelganger = DoppelgangerService(
            liveness_fn=_liveness,
            current_epoch_fn=_wall_epoch,
        )
    external_signer = None
    remote_keys = None
    if getattr(args, "external_signer_url", None):
        from .validator.external_signer import ExternalSignerClient

        external_signer = ExternalSignerClient(args.external_signer_url)
        if remote:
            # the interop key schedule also derives the REMOTE pubkeys
            # (a real deployment would match the signer's publicKeys)
            remote_keys = {i: pks[i] for i in remote}
    store = ValidatorStore(
        MAINNET_CHAIN_CONFIG,
        local_sks,
        slashing_db_path=args.slashing_db_path,
        doppelganger=doppelganger,
        external_signer=external_signer,
        remote_keys=remote_keys,
        proposer_config=proposer_config,
    )
    blocks = BlockProposalService(store, client)
    atts = AttestationService(store, client)
    last_wall_epoch = -1
    for slot in range(1, args.slots + 1):
        epoch = slot // _p.SLOTS_PER_EPOCH
        if doppelganger is not None:
            # the watch window lives in WALL-CLOCK epochs (the same
            # domain keys were registered in) — never the loop counter
            we = doppelganger.current_epoch_fn()
            if we != last_wall_epoch:
                doppelganger.on_epoch(we)
                last_wall_epoch = we
        blocks.poll_duties(epoch)
        atts.poll_duties(epoch)
        proposed = blocks.run_block_tasks(epoch, slot)
        attested = atts.run_attestation_tasks(epoch, slot)
        aggregated = atts.run_aggregation_tasks(epoch, slot)
        print(
            json.dumps(
                {
                    "slot": slot,
                    "proposed": proposed,
                    "attested": attested,
                    "aggregated": aggregated,
                }
            )
        )
    return 0


def cmd_bench(args) -> int:
    import os
    import runpy

    os.environ["BENCH_MODE"] = args.mode
    runpy.run_path(
        os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py"),
        run_name="__main__",
    )
    return 0


def cmd_lightclient(args) -> int:
    # in-process demo: a dev chain produces sync-aggregated blocks, the
    # LightClientServer emits updates, and a Lightclient follows them
    from types import SimpleNamespace

    from . import params as _p
    from .chain.light_client_server import LightClientServer
    from .chain.produce_block import produce_block
    from .crypto import bls as B
    from .crypto import curves as C
    from .light_client.lightclient import Lightclient
    from .ssz import uint64
    from .state_transition import process_slots
    from .state_transition.accessors import get_beacon_proposer_index
    from . import types as T

    ns = SimpleNamespace(
        preset=None, db_path=None, api_port=0, genesis_time=0,
        validators=16, slots=args.slots,
    )
    cfg, sks, pks, chain = _dev_chain(ns)
    server = LightClientServer(chain)
    anchor_header = dict(chain.head_state.latest_block_header)
    anchor_header["state_root"] = chain.head_state.hash_tree_root()
    client = Lightclient(
        cfg, anchor_header, chain.head_state.current_sync_committee["pubkeys"]
    )
    print(json.dumps({"msg": "lightclient bootstrapped", "slot": 0}))

    sk_of = {pks[i]: sks[i] for i in range(len(pks))}
    for slot in range(1, args.slots + 1):
        head = chain.head_state
        pre = head.clone()
        if pre.slot < slot:
            process_slots(pre, slot)
        proposer = get_beacon_proposer_index(pre)
        epoch = slot // _p.SLOTS_PER_EPOCH
        reveal = B.sign_bytes(
            sks[proposer],
            cfg.compute_signing_root(
                uint64.hash_tree_root(epoch),
                cfg.get_domain(slot, _p.DOMAIN_RANDAO),
            ),
        )
        sync_aggregate = None
        if slot > 1:  # the aggregate attests the parent block
            sroot = cfg.compute_signing_root(
                chain.get_head_root(),
                cfg.get_domain(slot, _p.DOMAIN_SYNC_COMMITTEE, slot - 1),
            )
            committee = head.current_sync_committee["pubkeys"]
            sig = B.aggregate_signatures(
                [B.sign(sk_of[pk], sroot) for pk in committee]
            )
            sync_aggregate = {
                "sync_committee_bits": [True] * _p.SYNC_COMMITTEE_SIZE,
                "sync_committee_signature": C.g2_compress(sig),
            }
        block, _post = produce_block(
            head, slot, reveal, sync_aggregate=sync_aggregate
        )
        broot = cfg.compute_signing_root(
            cfg.get_fork_types(slot)[0].hash_tree_root(block),
            cfg.get_domain(slot, _p.DOMAIN_BEACON_PROPOSER, slot),
        )
        chain.process_block(
            {"message": block, "signature": B.sign_bytes(sks[proposer], broot)}
        )
        update = server.get_optimistic_update()
        if update is not None:
            client.process_update(update)
        print(
            json.dumps(
                {
                    "slot": slot,
                    "lc_optimistic_slot": client.optimistic_header["slot"],
                    "updates_produced": server.produced,
                }
            )
        )
    return 0


def cmd_flare(args) -> int:
    from .api.client import ApiClient
    from .flare import self_slash_attester, self_slash_proposer

    client = ApiClient(args.beacon_urls, timeout=60)
    cfg = _dev_config()  # dev fork schedule; domains must match the node
    sks, _pks = _interop_keys(max(args.interop_indices) + 1)
    if args.action == "self-slash-proposer":
        for idx in args.interop_indices:
            self_slash_proposer(cfg, client, sks[idx], idx, args.slot)
            print(json.dumps({"self_slashed_proposer": idx}))
    else:
        keys = [sks[i] for i in args.interop_indices]
        self_slash_attester(
            cfg, client, keys, args.interop_indices, args.epoch
        )
        print(json.dumps({"self_slashed_attesters": args.interop_indices}))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    return {
        "beacon": cmd_beacon,
        "validator": cmd_validator,
        "bench": cmd_bench,
        "lightclient": cmd_lightclient,
        "flare": cmd_flare,
    }[args.command](args)


if __name__ == "__main__":
    sys.exit(main())
