"""Snappy codec bindings + the eth2 framed/raw compression layers.

Reference: @chainsafe/snappy-stream (reqresp ssz_snappy framing) and
snappyjs (gossip raw-block compression) — SURVEY.md §2.3.  The codec
itself is native (lodestar_tpu/native/snappy.cpp, ctypes ABI); this
module adds:

  - compress/decompress: raw snappy blocks (gossip messages),
  - frame_compress/frame_decompress: the snappy FRAMED format
    (stream identifier + compressed/uncompressed chunks with masked
    crc32c) used by reqresp ssz_snappy payloads,
  - encode_reqresp_chunk/decode_reqresp_chunk: <ssz-len varint> +
    framed body (reference: reqresp/encoders).
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Optional, Tuple

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "native",
    "libsnappy_tpu.so",
)

_lib: Optional[ctypes.CDLL] = None
if os.path.exists(_LIB_PATH):
    try:
        _lib = ctypes.CDLL(_LIB_PATH)
        _lib.snappy_compress.restype = ctypes.c_size_t
        _lib.snappy_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p
        ]
        _lib.snappy_decompress.restype = ctypes.c_size_t
        _lib.snappy_decompress.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_char_p, ctypes.c_size_t
        ]
        _lib.snappy_uncompressed_length.restype = ctypes.c_size_t
        _lib.snappy_uncompressed_length.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t
        ]
        _lib.snappy_max_compressed_length.restype = ctypes.c_size_t
        _lib.snappy_max_compressed_length.argtypes = [ctypes.c_size_t]
        _lib.snappy_crc32c.restype = ctypes.c_uint32
        _lib.snappy_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    except OSError:  # pragma: no cover
        _lib = None


def native_available() -> bool:
    return _lib is not None


class SnappyError(ValueError):
    pass


def compress(data: bytes) -> bytes:
    """Raw snappy block (the gossip message codec)."""
    if _lib is None:
        raise SnappyError("libsnappy_tpu.so not built")
    out = ctypes.create_string_buffer(
        _lib.snappy_max_compressed_length(len(data))
    )
    n = _lib.snappy_compress(data, len(data), out)
    return out.raw[:n]


def decompress(data: bytes, max_len: int = 1 << 27) -> bytes:
    if _lib is None:
        raise SnappyError("libsnappy_tpu.so not built")
    size = _lib.snappy_uncompressed_length(data, len(data))
    if size == ctypes.c_size_t(-1).value or size > max_len:
        raise SnappyError("malformed or oversized snappy block")
    out = ctypes.create_string_buffer(max(size, 1))
    n = _lib.snappy_decompress(data, len(data), out, size)
    if n == ctypes.c_size_t(-1).value:
        raise SnappyError("malformed snappy block")
    return out.raw[:n]


def crc32c(data: bytes) -> int:
    if _lib is None:
        raise SnappyError("libsnappy_tpu.so not built")
    return _lib.snappy_crc32c(data, len(data))


def _masked_crc(data: bytes) -> int:
    """Framing-format checksum mask: rotr15(crc) + 0xa282ead8."""
    c = crc32c(data)
    return ((((c >> 15) | (c << 17)) & 0xFFFFFFFF) + 0xA282EAD8) % (1 << 32)


# -- framed format (reqresp ssz_snappy payload body) ------------------------

_STREAM_ID = b"\xff\x06\x00\x00sNaPpY"
_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01
_MAX_CHUNK = 65536


def frame_compress(data: bytes) -> bytes:
    out = bytearray(_STREAM_ID)
    for i in range(0, max(len(data), 1), _MAX_CHUNK):
        chunk = data[i : i + _MAX_CHUNK]
        crc = _masked_crc(chunk)
        comp = compress(chunk)
        if len(comp) < len(chunk):
            body = struct.pack("<I", crc) + comp
            out += bytes([_CHUNK_COMPRESSED]) + struct.pack(
                "<I", len(body)
            )[:3] + body
        else:
            body = struct.pack("<I", crc) + chunk
            out += bytes([_CHUNK_UNCOMPRESSED]) + struct.pack(
                "<I", len(body)
            )[:3] + body
        if not data:
            break
    return bytes(out)


def frame_decompress(data: bytes) -> bytes:
    if not data.startswith(_STREAM_ID):
        raise SnappyError("missing snappy stream identifier")
    pos = len(_STREAM_ID)
    out = bytearray()
    while pos < len(data):
        if pos + 4 > len(data):
            raise SnappyError("truncated chunk header")
        ctype = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        if pos + length > len(data):
            raise SnappyError("truncated chunk body")
        body = data[pos : pos + length]
        pos += length
        if ctype in (_CHUNK_COMPRESSED, _CHUNK_UNCOMPRESSED):
            if length < 4:
                raise SnappyError("chunk too short for checksum")
            (crc,) = struct.unpack("<I", body[:4])
            payload = body[4:]
            chunk = (
                decompress(payload)
                if ctype == _CHUNK_COMPRESSED
                else payload
            )
            if _masked_crc(chunk) != crc:
                raise SnappyError("chunk checksum mismatch")
            out += chunk
        elif 0x80 <= ctype <= 0xFE:
            continue  # skippable padding chunks
        else:
            raise SnappyError(f"unknown chunk type {ctype:#x}")
    return bytes(out)


# -- reqresp ssz_snappy chunk (reference: reqresp/encoders/sszSnappy) -------


def _uvarint(v: int) -> bytes:
    out = bytearray()
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)
    return bytes(out)


def _read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while pos < len(data):
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not (b & 0x80):
            return v, pos
        shift += 7
    raise SnappyError("truncated varint")


def encode_reqresp_chunk(ssz_bytes: bytes) -> bytes:
    """<ssz length varint> + framed-snappy body."""
    return _uvarint(len(ssz_bytes)) + frame_compress(ssz_bytes)


def decode_reqresp_chunk(data: bytes, max_len: int = 1 << 27) -> bytes:
    """One chunk filling the whole buffer (delegates to the positional
    decoder so there is exactly ONE frame-parsing state machine)."""
    payload, pos = decode_reqresp_chunk_at(data, 0, max_len)
    if pos != len(data):
        raise SnappyError(f"{len(data) - pos} trailing bytes after chunk")
    return payload


def decode_reqresp_chunk_at(
    data: bytes, start: int, max_len: int = 1 << 27
) -> Tuple[bytes, int]:
    """Decode ONE ssz_snappy chunk out of a concatenated response stream
    (reference: response/responseDecode.ts reads chunk-by-chunk).

    Decompresses snappy frames until the declared ssz length is reached;
    returns (payload, next_position)."""
    declared, pos = _read_uvarint(data, start)
    if declared > max_len:
        raise SnappyError("declared length over limit")
    if data[pos : pos + len(_STREAM_ID)] != _STREAM_ID:
        raise SnappyError("missing snappy stream identifier")
    pos += len(_STREAM_ID)
    out = bytearray()
    data_frames = 0
    # declared == 0 still carries ONE (empty) DATA frame — consume it so
    # the stream position stays aligned for the next chunk (padding and
    # repeated stream-identifier frames do not count)
    while len(out) < declared or (declared == 0 and data_frames == 0):
        if pos + 4 > len(data):
            raise SnappyError("truncated chunk header")
        ctype = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        if pos + length > len(data):
            raise SnappyError("truncated chunk body")
        body = data[pos : pos + length]
        pos += length
        if ctype in (_CHUNK_COMPRESSED, _CHUNK_UNCOMPRESSED):
            data_frames += 1
            if length < 4:
                raise SnappyError("chunk too short for checksum")
            (crc,) = struct.unpack("<I", body[:4])
            payload = body[4:]
            chunk = (
                decompress(payload)
                if ctype == _CHUNK_COMPRESSED
                else payload
            )
            if _masked_crc(chunk) != crc:
                raise SnappyError("chunk checksum mismatch")
            out += chunk
        elif ctype == 0xFF:
            # repeated stream identifier: legal anywhere in a stream
            if body != _STREAM_ID[4:]:
                raise SnappyError("bad repeated stream identifier")
        elif 0x80 <= ctype <= 0xFE:
            continue
        else:
            raise SnappyError(f"unknown chunk type {ctype:#x}")
    if len(out) != declared:
        raise SnappyError(
            f"length mismatch: declared {declared}, got {len(out)}"
        )
    return bytes(out), pos
