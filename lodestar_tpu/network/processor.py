"""NetworkProcessor — gossip scheduling + backpressure, the hot loop that
feeds the BLS verifier.

Reproduces the reference's scheduling contract (reference:
packages/beacon-node/src/network/processor/index.ts):

  - per-topic GossipQueues (gossip_queues.py) buffer pending messages,
  - `execute_work()` drains them in a fixed priority order
    (executeGossipWorkOrderObj, index.ts:44-57), submitting at most
    MAX_JOBS_SUBMITTED_PER_TICK jobs per tick (index.ts:61),
  - before every job the processor re-checks downstream backpressure —
    the BLS service's `can_accept_work()` (the reference's
    blsThreadPoolCanAcceptWork, index.ts:357-371; the pipeline's
    high-water mark counts buffered + queued + in-flight SETS) and an
    optional regen gate — and stops pulling except for bypass topics
    (beacon_block),
  - when a stalled processor's queues overflow, the shed messages charge
    their publisher through the peer scorer's backpressure penalty
    (scoring.py on_backpressure_drop, gossipsub P7): peers flooding a
    saturated node pay for it while the drop lands on the per-topic
    `lodestar_gossip_queue_dropped_total` series (gossip_queues.py),
  - messages whose block root is unknown are parked for reprocessing and
    re-enqueued when the block arrives (capped at 16,384; index.ts:64-67),
    pruned per clock slot,
  - drops/priorities/queue lengths are observable for the replay harness.

The processor is host-side scheduling only; batching for the device
happens downstream in the BlsVerifierService's coalescing buffer.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from .gossip_queues import GossipQueue, GossipType, create_gossip_queues

# Priority order; bypass topics are processed even under backpressure
# (reference: index.ts:44-57).
EXECUTE_GOSSIP_WORK_ORDER: Tuple[Tuple[GossipType, bool], ...] = (
    (GossipType.beacon_block, True),
    (GossipType.beacon_aggregate_and_proof, False),
    (GossipType.voluntary_exit, False),
    (GossipType.bls_to_execution_change, False),
    (GossipType.beacon_attestation, False),
    (GossipType.proposer_slashing, False),
    (GossipType.attester_slashing, False),
    (GossipType.sync_committee_contribution_and_proof, False),
    (GossipType.sync_committee, False),
    (GossipType.light_client_finality_update, False),
    (GossipType.light_client_optimistic_update, False),
)

MAX_JOBS_SUBMITTED_PER_TICK = 128  # reference: index.ts:61
MAX_QUEUED_UNKNOWN_BLOCK_GOSSIP_OBJECTS = 16_384  # reference: index.ts:64
EARLIEST_PERMISSABLE_SLOT_DISTANCE = 32  # reference: index.ts:34


class PendingGossipMessage:
    """A received-but-unvalidated gossip message (the reference's
    PendingGossipsubMessage, processor/types.ts).  `peer_id` names the
    propagation source (reference: propagationSource) so overflow drops
    can be charged to the publisher."""

    __slots__ = ("topic", "data", "slot", "block_root", "seen_at", "peer_id")

    def __init__(
        self, topic, data, slot=None, block_root=None, seen_at=0.0,
        peer_id=None,
    ):
        self.topic = topic
        self.data = data
        self.slot = slot
        self.block_root = block_root
        self.seen_at = seen_at
        self.peer_id = peer_id


class ProcessorStats:
    __slots__ = (
        "submitted", "dropped", "past_slot", "reprocess_parked",
        "reprocess_rejected", "reprocess_expired", "cannot_accept_ticks",
    )

    def __init__(self):
        self.submitted = 0
        self.dropped = 0
        self.past_slot = 0
        self.reprocess_parked = 0
        self.reprocess_rejected = 0
        self.reprocess_expired = 0
        self.cannot_accept_ticks = 0


class NetworkProcessor:
    """Schedules gossip validation work against downstream backpressure.

    `worker(message)` performs the per-message validation (ultimately an
    async submit into the BlsVerifierService) and must not block on device
    results; `can_accept_work_fns` are polled before each job pull.
    """

    def __init__(
        self,
        worker: Callable[[PendingGossipMessage], None],
        can_accept_work_fns: List[Callable[[], bool]],
        has_block_root: Optional[Callable[[str], bool]] = None,
        max_jobs_per_tick: int = MAX_JOBS_SUBMITTED_PER_TICK,
        registry=None,
        scorer=None,
    ):
        # backpressure->scoring coupling (ISSUE 11): an object with
        # `on_backpressure_drop(peer_id, topic)` (GossipPeerScorer);
        # every SHED message charges its own publisher (a LIFO ratio
        # drop evicts the oldest backlog — the flooder's — so the peer
        # whose honest publish happened to overflow is not the one
        # penalized)
        self.scorer = scorer
        # registry: where queue latency/depth series land (node passes
        # its own; None = the process-global observability registry)
        self.queues: Dict[GossipType, GossipQueue] = create_gossip_queues(
            registry,
            on_drop=self._on_queue_drop if scorer is not None else None,
        )
        self.worker = worker
        self.can_accept_work_fns = can_accept_work_fns
        self.has_block_root = has_block_root
        self.max_jobs_per_tick = max_jobs_per_tick
        self.stats = ProcessorStats()
        self.current_slot = 0
        # anomaly hook (ISSUE 12): called ONCE per slot, the first time
        # a tick stalls on downstream backpressure — the flight
        # recorder's "backpressure trip" trigger.  Edge-triggered (the
        # per-tick stall count lives in stats.cannot_accept_ticks) and
        # re-armed by the slot clock, so a saturated slot costs one
        # callback, not one per stalled pull.
        self.on_backpressure_trip: Optional[Callable[[int], None]] = None
        self._backpressure_reported = False
        # slot -> root -> [messages awaiting that block]
        self._awaiting: Dict[int, Dict[str, List[PendingGossipMessage]]] = {}
        self._awaiting_count = 0
        # deferred forward verdicts (ISSUE 19, network/forwarding.py):
        # subnet attestation forward/score decisions awaiting their
        # pipeline verdict, bounded + expired per slot like _awaiting —
        # a verdict resolving after its slot's forward window drops
        # instead of forwarding a stale attestation, and a shed charges
        # the publisher (P7) exactly like a queue-overflow drop
        from .forwarding import DeferredForwardQueue

        self.deferred_forwards = DeferredForwardQueue(scorer=scorer)

    # -- ingress (reference: onPendingGossipsubMessage, index.ts:194-241) --

    def on_gossip_message(self, message: PendingGossipMessage) -> None:
        if message.slot is not None:
            if message.slot < self.current_slot - EARLIEST_PERMISSABLE_SLOT_DISTANCE:
                self.stats.past_slot += 1
                return
            root = message.block_root
            if (
                root is not None
                and self.has_block_root is not None
                and not self.has_block_root(root)
            ):
                if self._awaiting_count > MAX_QUEUED_UNKNOWN_BLOCK_GOSSIP_OBJECTS:
                    self.stats.reprocess_rejected += 1
                    return
                self._awaiting.setdefault(message.slot, {}).setdefault(
                    root, []
                ).append(message)
                self._awaiting_count += 1
                self.stats.reprocess_parked += 1
                return
        self._push(message)

    def _on_queue_drop(self, message: PendingGossipMessage) -> None:
        """Per-item overflow observer (gossip_queues.on_drop): the queue
        only overflows when downstream (the verification pipeline)
        cannot keep up — each shed message costs ITS publisher one
        behaviour-penalty unit (the drop count itself already landed on
        lodestar_gossip_queue_dropped_total)."""
        peer = getattr(message, "peer_id", None)
        if peer is not None:
            topic = getattr(message, "topic", None)
            self.scorer.on_backpressure_drop(
                peer, topic.value if topic is not None else None
            )

    def _push(self, message: PendingGossipMessage) -> None:
        dropped = self.queues[message.topic].add(message)
        self.stats.dropped += dropped
        self.execute_work()

    # -- block arrival / clock (reference: onBlockProcessed, onClockSlot) --

    def on_block_processed(self, slot: int, root: str) -> None:
        by_root = self._awaiting.get(slot)
        if not by_root:
            return
        waiting = by_root.pop(root, [])
        if not by_root:
            self._awaiting.pop(slot, None)
        self._awaiting_count -= len(waiting)
        for msg in waiting:
            self._push(msg)

    def on_clock_slot(self, slot: int) -> None:
        self.current_slot = slot
        self._backpressure_reported = False  # re-arm the trip hook
        # late deferred verdicts drop before anything else this slot
        self.deferred_forwards.on_clock_slot(slot)
        # awaiting messages are pruned every slot (reference: index.ts:281-299)
        for s in list(self._awaiting):
            if s < slot:
                for msgs in self._awaiting[s].values():
                    self.stats.reprocess_expired += len(msgs)
                    self._awaiting_count -= len(msgs)
                del self._awaiting[s]
        self.execute_work()

    # -- the scheduling loop (reference: executeWork, index.ts:306-352) ----

    def _check_accept_work(self) -> bool:
        return all(fn() for fn in self.can_accept_work_fns)

    def execute_work(self) -> int:
        submitted = 0
        while submitted < self.max_jobs_per_tick:
            accept = self._check_accept_work()
            pulled = False
            for topic, bypass in EXECUTE_GOSSIP_WORK_ORDER:
                if not accept and not bypass:
                    self.stats.cannot_accept_ticks += 1
                    self.stats.submitted += submitted
                    self._notify_backpressure_trip()
                    return submitted
                item = self.queues[topic].next()
                if item is not None:
                    self.worker(item)
                    submitted += 1
                    pulled = True
                    break  # restart priority scan + backpressure check
            if not pulled:
                break
        self.stats.submitted += submitted
        return submitted

    def _notify_backpressure_trip(self) -> None:
        if self._backpressure_reported or self.on_backpressure_trip is None:
            return
        self._backpressure_reported = True
        try:
            self.on_backpressure_trip(self.current_slot)
        except Exception:  # noqa: BLE001 — an observer fault must not
            pass  # break the scheduling loop

    # -- introspection (reference: dumpGossipQueue) ------------------------

    def queue_lengths(self) -> Dict[str, int]:
        return {t.value: len(q) for t, q in self.queues.items()}
