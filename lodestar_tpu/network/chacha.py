"""ChaCha20-Poly1305 AEAD (RFC 8439) — the noise-transport cipher.

Equivalent of the reference's `@chainsafe/as-chacha20poly1305` WASM
dependency (SURVEY.md §2.3; libp2p noise encryption).  Implemented from
RFC 8439: the ChaCha20 quarter-round block function, Poly1305 over the
AAD/ciphertext layout, constant structure matching the RFC test
vectors (exercised in tests/test_chacha.py).
"""

from __future__ import annotations

import struct
from typing import Optional

_MASK32 = 0xFFFFFFFF


def _rotl32(v: int, n: int) -> int:
    return ((v << n) | (v >> (32 - n))) & _MASK32


def _quarter(state, a, b, c, d):
    state[a] = (state[a] + state[b]) & _MASK32
    state[d] = _rotl32(state[d] ^ state[a], 16)
    state[c] = (state[c] + state[d]) & _MASK32
    state[b] = _rotl32(state[b] ^ state[c], 12)
    state[a] = (state[a] + state[b]) & _MASK32
    state[d] = _rotl32(state[d] ^ state[a], 8)
    state[c] = (state[c] + state[d]) & _MASK32
    state[b] = _rotl32(state[b] ^ state[c], 7)


def _chacha20_block(key: bytes, counter: int, nonce: bytes) -> bytes:
    state = [
        0x61707865, 0x3320646E, 0x79622D32, 0x6B206574,
        *struct.unpack("<8I", key),
        counter,
        *struct.unpack("<3I", nonce),
    ]
    working = list(state)
    for _ in range(10):
        _quarter(working, 0, 4, 8, 12)
        _quarter(working, 1, 5, 9, 13)
        _quarter(working, 2, 6, 10, 14)
        _quarter(working, 3, 7, 11, 15)
        _quarter(working, 0, 5, 10, 15)
        _quarter(working, 1, 6, 11, 12)
        _quarter(working, 2, 7, 8, 13)
        _quarter(working, 3, 4, 9, 14)
    out = [(w + s) & _MASK32 for w, s in zip(working, state)]
    return struct.pack("<16I", *out)


def chacha20_xor(key: bytes, counter: int, nonce: bytes, data: bytes) -> bytes:
    out = bytearray()
    for i in range(0, len(data), 64):
        block = _chacha20_block(key, counter + i // 64, nonce)
        chunk = data[i : i + 64]
        out += bytes(a ^ b for a, b in zip(chunk, block))
    return bytes(out)


def _poly1305(key32: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(key32[:16], "little")
    r &= 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        block = msg[i : i + 16]
        n = int.from_bytes(block + b"\x01", "little")
        acc = ((acc + n) * r) % p
    return ((acc + s) % (1 << 128)).to_bytes(16, "little")


def _pad16(data: bytes) -> bytes:
    return b"\x00" * ((-len(data)) % 16)


def _mac_data(aad: bytes, ciphertext: bytes) -> bytes:
    return (
        aad
        + _pad16(aad)
        + ciphertext
        + _pad16(ciphertext)
        + struct.pack("<QQ", len(aad), len(ciphertext))
    )


def seal(key: bytes, nonce: bytes, plaintext: bytes, aad: bytes = b"") -> bytes:
    """AEAD encrypt: ciphertext || 16-byte tag."""
    if len(key) != 32 or len(nonce) != 12:
        raise ValueError("key must be 32 bytes, nonce 12")
    otk = _chacha20_block(key, 0, nonce)[:32]
    ciphertext = chacha20_xor(key, 1, nonce, plaintext)
    tag = _poly1305(otk, _mac_data(aad, ciphertext))
    return ciphertext + tag


def open_(
    key: bytes, nonce: bytes, sealed: bytes, aad: bytes = b""
) -> Optional[bytes]:
    """AEAD decrypt; None on authentication failure."""
    if len(sealed) < 16:
        return None
    ciphertext, tag = sealed[:-16], sealed[-16:]
    otk = _chacha20_block(key, 0, nonce)[:32]
    expected = _poly1305(otk, _mac_data(aad, ciphertext))
    # constant-time compare
    diff = 0
    for a, b in zip(tag, expected):
        diff |= a ^ b
    if diff:
        return None
    return chacha20_xor(key, 1, nonce, ciphertext)
