"""Per-topic gossip queues with overload shedding.

Mirrors the reference's queue discipline (reference:
packages/beacon-node/src/network/processor/gossipQueues.ts):

  - each topic gets FIFO or LIFO ordering and a max length,
  - on overflow, drop either a fixed COUNT of items or an escalating
    RATIO of the queue (attestations: start 1%, +1% per overflow, cap
    95%, reset once the queue fully drains and stays drained for a full
    queue-length of processed items),
  - drops evict from the *stale* end (LIFO drops oldest, FIFO drops
    newest) so the work kept is the work most likely to still matter.

The queue is plain host code — it feeds fixed-shape device batches but
never touches the device itself.
"""

from __future__ import annotations

import enum
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

MAX_DROP_RATIO = 0.95


class QueueType(enum.Enum):
    FIFO = "FIFO"
    LIFO = "LIFO"


@dataclass(frozen=True)
class DropByCount:
    count: int = 1


@dataclass(frozen=True)
class DropByRatio:
    start: float = 0.01
    step: float = 0.01


@dataclass(frozen=True)
class GossipQueueOpts:
    type: QueueType
    max_length: int
    drop: object  # DropByCount | DropByRatio


class GossipType(enum.Enum):
    beacon_block = "beacon_block"
    beacon_aggregate_and_proof = "beacon_aggregate_and_proof"
    voluntary_exit = "voluntary_exit"
    bls_to_execution_change = "bls_to_execution_change"
    beacon_attestation = "beacon_attestation"
    proposer_slashing = "proposer_slashing"
    attester_slashing = "attester_slashing"
    sync_committee_contribution_and_proof = "sync_committee_contribution_and_proof"
    sync_committee = "sync_committee"
    light_client_finality_update = "light_client_finality_update"
    light_client_optimistic_update = "light_client_optimistic_update"


# Queue shapes per topic (reference: gossipQueues.ts gossipQueueOpts; the
# numbers trace to lighthouse's beacon_processor).
GOSSIP_QUEUE_OPTS: Dict[GossipType, GossipQueueOpts] = {
    GossipType.beacon_block: GossipQueueOpts(QueueType.FIFO, 1024, DropByCount(1)),
    GossipType.beacon_aggregate_and_proof: GossipQueueOpts(
        QueueType.LIFO, 5120, DropByCount(1)
    ),
    GossipType.beacon_attestation: GossipQueueOpts(
        QueueType.LIFO, 24576, DropByRatio(0.01, 0.01)
    ),
    GossipType.voluntary_exit: GossipQueueOpts(QueueType.FIFO, 4096, DropByCount(1)),
    GossipType.proposer_slashing: GossipQueueOpts(
        QueueType.FIFO, 4096, DropByCount(1)
    ),
    GossipType.attester_slashing: GossipQueueOpts(
        QueueType.FIFO, 4096, DropByCount(1)
    ),
    GossipType.sync_committee_contribution_and_proof: GossipQueueOpts(
        QueueType.LIFO, 4096, DropByCount(1)
    ),
    GossipType.sync_committee: GossipQueueOpts(QueueType.LIFO, 4096, DropByCount(1)),
    GossipType.light_client_finality_update: GossipQueueOpts(
        QueueType.FIFO, 1024, DropByCount(1)
    ),
    GossipType.light_client_optimistic_update: GossipQueueOpts(
        QueueType.FIFO, 1024, DropByCount(1)
    ),
    GossipType.bls_to_execution_change: GossipQueueOpts(
        QueueType.FIFO, 16384, DropByCount(1)
    ),
}


class GossipQueue(Generic[T]):
    """One topic's queue.  `add` returns the number of items dropped."""

    def __init__(self, opts: GossipQueueOpts):
        self.opts = opts
        self._q: Deque[T] = deque()
        self._drop_ratio = 0.0
        if isinstance(opts.drop, DropByRatio):
            if not (0.0 < opts.drop.start <= 1.0):
                raise ValueError(f"invalid drop ratio start {opts.drop.start}")
            self._drop_ratio = opts.drop.start
        # After a ratio-drop, the queue draining to empty is not by itself
        # evidence of good health (we may have just shed 90% of it); only
        # reset the ratio after a full max_length of items processed
        # without another overflow.
        self._recent_drop = False
        self._processed_since_drop = 0

    def __len__(self) -> int:
        return len(self._q)

    @property
    def drop_ratio(self) -> float:
        return self._drop_ratio

    def clear(self) -> None:
        self._q.clear()

    def add(self, item: T) -> int:
        drop = self.opts.drop
        if isinstance(drop, DropByRatio) and not self._recent_drop and not self._q:
            self._drop_ratio = drop.start  # node looks healthy: retest start
        self._q.append(item)
        if len(self._q) <= self.opts.max_length:
            return 0
        if isinstance(drop, DropByCount):
            return self._drop_by_count(drop.count)
        self._recent_drop = True
        dropped = self._drop_by_count(int(len(self._q) * self._drop_ratio))
        self._drop_ratio = min(MAX_DROP_RATIO, self._drop_ratio + drop.step)
        return dropped

    def next(self) -> Optional[T]:
        if not self._q:
            return None
        item = self._q.pop() if self.opts.type is QueueType.LIFO else self._q.popleft()
        if isinstance(self.opts.drop, DropByRatio) and self._recent_drop:
            self._processed_since_drop += 1
            if self._processed_since_drop >= self.opts.max_length:
                self._recent_drop = False
                self._processed_since_drop = 0
        return item

    def get_all(self) -> List[T]:
        return list(self._q)

    def _drop_by_count(self, count: int) -> int:
        if count <= 0:
            return 0
        if count >= len(self._q):
            n = len(self._q)
            self._q.clear()
            return n
        # LIFO keeps the newest (drop from the left/oldest); FIFO keeps
        # the oldest (drop from the right/newest).
        for _ in range(count):
            if self.opts.type is QueueType.LIFO:
                self._q.popleft()
            else:
                self._q.pop()
        return count


def create_gossip_queues() -> Dict[GossipType, GossipQueue]:
    return {t: GossipQueue(o) for t, o in GOSSIP_QUEUE_OPTS.items()}
