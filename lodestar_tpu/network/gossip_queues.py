"""Per-topic gossip queues with overload shedding.

Mirrors the reference's queue discipline (reference:
packages/beacon-node/src/network/processor/gossipQueues.ts):

  - each topic gets FIFO or LIFO ordering and a max length,
  - on overflow, drop either a fixed COUNT of items or an escalating
    RATIO of the queue (attestations: start 1%, +1% per overflow, cap
    95%, reset once the queue fully drains and stays drained for a full
    queue-length of processed items),
  - drops evict from the *stale* end (LIFO drops oldest, FIFO drops
    newest) so the work kept is the work most likely to still matter.

The queue is plain host code — it feeds fixed-shape device batches but
never touches the device itself.
"""

from __future__ import annotations

import enum
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

MAX_DROP_RATIO = 0.95

_LATENCY_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30)


class GossipQueueMetrics:
    """Per-topic queue observability: enqueue->dequeue latency, live
    depth, drop counts (ISSUE 8 — the series the async
    verification-pipeline ROADMAP item needs to size its flush
    deadlines).  One instance per Registry; shared across topics via
    the `topic` label."""

    def __init__(self, registry=None):
        if registry is None:
            from ..utils.metrics import global_registry

            registry = global_registry()
        self.latency = registry.labeled_histogram(
            "lodestar_gossip_queue_latency_seconds",
            "Enqueue-to-dequeue wait per gossip message",
            "topic",
            _LATENCY_BUCKETS,
        )
        self.depth = registry.labeled_gauge(
            "lodestar_gossip_queue_length",
            "Live gossip queue depth per topic",
            "topic",
        )
        self.dropped = registry.labeled_counter(
            "lodestar_gossip_queue_dropped_total",
            "Messages shed by overflow policy per topic",
            "topic",
        )


class QueueType(enum.Enum):
    FIFO = "FIFO"
    LIFO = "LIFO"


@dataclass(frozen=True)
class DropByCount:
    count: int = 1


@dataclass(frozen=True)
class DropByRatio:
    start: float = 0.01
    step: float = 0.01


@dataclass(frozen=True)
class GossipQueueOpts:
    type: QueueType
    max_length: int
    drop: object  # DropByCount | DropByRatio


class GossipType(enum.Enum):
    beacon_block = "beacon_block"
    beacon_aggregate_and_proof = "beacon_aggregate_and_proof"
    voluntary_exit = "voluntary_exit"
    bls_to_execution_change = "bls_to_execution_change"
    beacon_attestation = "beacon_attestation"
    proposer_slashing = "proposer_slashing"
    attester_slashing = "attester_slashing"
    sync_committee_contribution_and_proof = "sync_committee_contribution_and_proof"
    sync_committee = "sync_committee"
    light_client_finality_update = "light_client_finality_update"
    light_client_optimistic_update = "light_client_optimistic_update"


# Queue shapes per topic (reference: gossipQueues.ts gossipQueueOpts; the
# numbers trace to lighthouse's beacon_processor).
GOSSIP_QUEUE_OPTS: Dict[GossipType, GossipQueueOpts] = {
    GossipType.beacon_block: GossipQueueOpts(QueueType.FIFO, 1024, DropByCount(1)),
    GossipType.beacon_aggregate_and_proof: GossipQueueOpts(
        QueueType.LIFO, 5120, DropByCount(1)
    ),
    GossipType.beacon_attestation: GossipQueueOpts(
        QueueType.LIFO, 24576, DropByRatio(0.01, 0.01)
    ),
    GossipType.voluntary_exit: GossipQueueOpts(QueueType.FIFO, 4096, DropByCount(1)),
    GossipType.proposer_slashing: GossipQueueOpts(
        QueueType.FIFO, 4096, DropByCount(1)
    ),
    GossipType.attester_slashing: GossipQueueOpts(
        QueueType.FIFO, 4096, DropByCount(1)
    ),
    GossipType.sync_committee_contribution_and_proof: GossipQueueOpts(
        QueueType.LIFO, 4096, DropByCount(1)
    ),
    GossipType.sync_committee: GossipQueueOpts(QueueType.LIFO, 4096, DropByCount(1)),
    GossipType.light_client_finality_update: GossipQueueOpts(
        QueueType.FIFO, 1024, DropByCount(1)
    ),
    GossipType.light_client_optimistic_update: GossipQueueOpts(
        QueueType.FIFO, 1024, DropByCount(1)
    ),
    GossipType.bls_to_execution_change: GossipQueueOpts(
        QueueType.FIFO, 16384, DropByCount(1)
    ),
}


class GossipQueue(Generic[T]):
    """One topic's queue.  `add` returns the number of items dropped.

    When constructed with a `topic` + `metrics`, every add/next pair
    feeds the enqueue->dequeue latency histogram and the depth gauge —
    `_t` mirrors `_q`'s order exactly (same ends pushed/popped), so the
    timestamp popped with an item is always that item's."""

    def __init__(
        self,
        opts: GossipQueueOpts,
        topic: Optional[str] = None,
        metrics: Optional[GossipQueueMetrics] = None,
        on_drop=None,
    ):
        self.opts = opts
        self.topic = topic
        self.metrics = metrics if topic is not None else None
        # per-ITEM drop observer fn(item) — the backpressure->scoring
        # coupling charges each shed message's OWN publisher (a LIFO
        # ratio drop sheds the oldest backlog, which belongs to whoever
        # flooded it there, not to the peer whose publish overflowed)
        self.on_drop = on_drop
        self._q: Deque[T] = deque()
        self._t: Deque[float] = deque()  # per-item enqueue perf_counter
        self._drop_ratio = 0.0
        if isinstance(opts.drop, DropByRatio):
            if not (0.0 < opts.drop.start <= 1.0):
                raise ValueError(f"invalid drop ratio start {opts.drop.start}")
            self._drop_ratio = opts.drop.start
        # After a ratio-drop, the queue draining to empty is not by itself
        # evidence of good health (we may have just shed 90% of it); only
        # reset the ratio after a full max_length of items processed
        # without another overflow.
        self._recent_drop = False
        self._processed_since_drop = 0

    def __len__(self) -> int:
        return len(self._q)

    @property
    def drop_ratio(self) -> float:
        return self._drop_ratio

    def clear(self) -> None:
        self._q.clear()
        self._t.clear()
        self._set_depth()

    def _set_depth(self) -> None:
        if self.metrics is not None:
            self.metrics.depth.set(self.topic, float(len(self._q)))

    def add(self, item: T) -> int:
        drop = self.opts.drop
        if isinstance(drop, DropByRatio) and not self._recent_drop and not self._q:
            self._drop_ratio = drop.start  # node looks healthy: retest start
        self._q.append(item)
        self._t.append(time.perf_counter())
        if len(self._q) <= self.opts.max_length:
            self._set_depth()
            return 0
        if isinstance(drop, DropByCount):
            dropped = self._drop_by_count(drop.count)
        else:
            self._recent_drop = True
            dropped = self._drop_by_count(int(len(self._q) * self._drop_ratio))
            self._drop_ratio = min(MAX_DROP_RATIO, self._drop_ratio + drop.step)
        if dropped and self.metrics is not None:
            self.metrics.dropped.inc(self.topic, float(dropped))
        self._set_depth()
        return dropped

    def next(self) -> Optional[T]:
        if not self._q:
            return None
        if self.opts.type is QueueType.LIFO:
            item, t_in = self._q.pop(), self._t.pop()
        else:
            item, t_in = self._q.popleft(), self._t.popleft()
        if self.metrics is not None:
            self.metrics.latency.observe(
                self.topic, time.perf_counter() - t_in
            )
            self._set_depth()
        if isinstance(self.opts.drop, DropByRatio) and self._recent_drop:
            self._processed_since_drop += 1
            if self._processed_since_drop >= self.opts.max_length:
                self._recent_drop = False
                self._processed_since_drop = 0
        return item

    def get_all(self) -> List[T]:
        return list(self._q)

    def _drop_by_count(self, count: int) -> int:
        if count <= 0:
            return 0
        if count >= len(self._q):
            n = len(self._q)
            if self.on_drop is not None:
                for item in self._q:
                    self._observe_drop(item)
            self._q.clear()
            self._t.clear()
            return n
        # LIFO keeps the newest (drop from the left/oldest); FIFO keeps
        # the oldest (drop from the right/newest).
        for _ in range(count):
            if self.opts.type is QueueType.LIFO:
                item = self._q.popleft()
                self._t.popleft()
            else:
                item = self._q.pop()
                self._t.pop()
            self._observe_drop(item)
        return count

    def _observe_drop(self, item: T) -> None:
        if self.on_drop is None:
            return
        try:
            self.on_drop(item)
        except Exception:  # noqa: BLE001 — a scoring fault must never
            pass  # break the queue discipline


def create_gossip_queues(
    registry=None, on_drop=None
) -> Dict[GossipType, GossipQueue]:
    metrics = GossipQueueMetrics(registry)
    return {
        t: GossipQueue(o, topic=t.value, metrics=metrics, on_drop=on_drop)
        for t, o in GOSSIP_QUEUE_OPTS.items()
    }
