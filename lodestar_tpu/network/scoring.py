"""Gossipsub peer-scoring parameters — the policy layer.

Mirror of the reference's scoring parameter derivation (reference:
packages/beacon-node/src/network/gossip/scoringParameters.ts:1-333,
itself following Lighthouse's gossipsub_scoring_parameters.rs): per-topic
TopicScoreParams derived from the chain config and the active validator
count, plus the global PeerScoreParams and thresholds.  The wire mesh
(libp2p gossipsub) is off the TPU path (SURVEY §2.4 P9), so these
parameters drive the in-process PeerScoreBook: an invalid message on a
topic applies that topic's invalidMessageDeliveries penalty.

All formulas follow the gossipsub v1.1 scoring spec:
https://github.com/libp2p/specs/blob/master/pubsub/gossipsub/
gossipsub-v1.1.md#peer-scoring
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional

from .. import params
from .gossip import GossipTopicName, topic_string

GOSSIP_D = 8
GOSSIP_D_LOW = 6
GOSSIP_D_HIGH = 12

MAX_IN_MESH_SCORE = 10.0
MAX_FIRST_MESSAGE_DELIVERIES_SCORE = 40.0
BEACON_BLOCK_WEIGHT = 0.5
BEACON_AGGREGATE_PROOF_WEIGHT = 0.5
VOLUNTARY_EXIT_WEIGHT = 0.05
PROPOSER_SLASHING_WEIGHT = 0.05
ATTESTER_SLASHING_WEIGHT = 0.05
BLS_TO_EXECUTION_CHANGE_WEIGHT = 0.05

_ATT_SUBNET_WEIGHT = 1 / params.ATTESTATION_SUBNET_COUNT
MAX_POSITIVE_SCORE = (
    MAX_IN_MESH_SCORE + MAX_FIRST_MESSAGE_DELIVERIES_SCORE
) * (
    BEACON_BLOCK_WEIGHT
    + BEACON_AGGREGATE_PROOF_WEIGHT
    + _ATT_SUBNET_WEIGHT * params.ATTESTATION_SUBNET_COUNT
    + VOLUNTARY_EXIT_WEIGHT
    + PROPOSER_SLASHING_WEIGHT
    + ATTESTER_SLASHING_WEIGHT
    + BLS_TO_EXECUTION_CHANGE_WEIGHT
)


@dataclass(frozen=True)
class PeerScoreThresholds:
    """reference: scoringParameters.ts gossipScoreThresholds."""

    gossip_threshold: float = -4000.0
    publish_threshold: float = -8000.0
    graylist_threshold: float = -16000.0
    accept_px_threshold: float = 100.0
    opportunistic_graft_threshold: float = 5.0


GOSSIP_SCORE_THRESHOLDS = PeerScoreThresholds()
NEGATIVE_GOSSIP_SCORE_IGNORE_THRESHOLD = -1000.0


@dataclass
class TopicScoreParams:
    topic_weight: float = 0.0
    time_in_mesh_quantum_ms: float = 0.0
    time_in_mesh_cap: float = 0.0
    time_in_mesh_weight: float = 0.0
    first_message_deliveries_decay: float = 0.0
    first_message_deliveries_cap: float = 0.0
    first_message_deliveries_weight: float = 0.0
    mesh_message_deliveries_decay: float = 0.0
    mesh_message_deliveries_threshold: float = 0.0
    mesh_message_deliveries_cap: float = 0.0
    mesh_message_deliveries_activation_ms: float = 0.0
    mesh_message_deliveries_window_ms: float = 0.0
    mesh_message_deliveries_weight: float = 0.0
    mesh_failure_penalty_decay: float = 0.0
    mesh_failure_penalty_weight: float = 0.0
    invalid_message_deliveries_weight: float = 0.0
    invalid_message_deliveries_decay: float = 0.0


@dataclass
class PeerScoreParams:
    topics: Dict[str, TopicScoreParams] = field(default_factory=dict)
    decay_interval_ms: float = 12_000.0
    decay_to_zero: float = 0.01
    retain_score_ms: float = 0.0
    app_specific_weight: float = 1.0
    ip_colocation_factor_threshold: int = 3
    ip_colocation_factor_weight: float = 0.0
    behaviour_penalty_decay: float = 0.0
    behaviour_penalty_weight: float = 0.0
    behaviour_penalty_threshold: float = 6.0
    topic_score_cap: float = 0.0


# -- decay math (gossipsub v1.1 spec) ---------------------------------------


def score_parameter_decay_with_base(
    decay_time_ms: float, decay_interval_ms: float, decay_to_zero: float
) -> float:
    ticks = decay_time_ms / decay_interval_ms
    return decay_to_zero ** (1 / ticks)


def decay_convergence(decay: float, rate: float) -> float:
    return rate / (1 - decay)


def threshold(decay: float, rate: float) -> float:
    return decay_convergence(decay, rate) * decay


# -- validator-count-dependent rates (scoringParameters.ts:306-329) ---------


def expected_aggregator_count_per_slot(active_validator_count: int):
    """-> (aggregators_per_slot, committees_per_slot)."""
    spe = params.SLOTS_PER_EPOCH
    committees_per_slot = max(
        1,
        min(
            params.ACTIVE_PRESET.MAX_COMMITTEES_PER_SLOT,
            active_validator_count
            // spe
            // params.ACTIVE_PRESET.TARGET_COMMITTEE_SIZE,
        ),
    )
    committees_per_epoch = committees_per_slot * spe
    smaller = active_validator_count // committees_per_epoch
    larger = smaller + 1
    large_per_epoch = active_validator_count - smaller * committees_per_epoch
    small_per_epoch = committees_per_epoch - large_per_epoch
    mod_small = max(1, smaller // params.TARGET_AGGREGATORS_PER_COMMITTEE)
    mod_large = max(1, larger // params.TARGET_AGGREGATORS_PER_COMMITTEE)
    small_aggs = (smaller / mod_small) * small_per_epoch
    large_aggs = (larger / mod_large) * large_per_epoch
    return (
        max(1, int((small_aggs + large_aggs) // spe)),
        committees_per_slot,
    )


# -- the derivation (scoringParameters.ts computeGossipPeerScoreParams) -----


def _topic_params(
    pre: dict,
    topic_weight: float,
    expected_message_rate: float,
    first_message_decay_time_ms: float,
    mesh_info: Optional[dict] = None,
) -> TopicScoreParams:
    decay_fn = pre["decay_fn"]
    p = TopicScoreParams()
    p.topic_weight = topic_weight
    p.time_in_mesh_quantum_ms = pre["slot_ms"]
    p.time_in_mesh_cap = 3600 / (p.time_in_mesh_quantum_ms / 1000)
    p.time_in_mesh_weight = MAX_IN_MESH_SCORE / p.time_in_mesh_cap
    p.first_message_deliveries_decay = decay_fn(first_message_decay_time_ms)
    p.first_message_deliveries_cap = decay_convergence(
        p.first_message_deliveries_decay, 2 * expected_message_rate / GOSSIP_D
    )
    p.first_message_deliveries_weight = (
        MAX_FIRST_MESSAGE_DELIVERIES_SCORE / p.first_message_deliveries_cap
    )
    if mesh_info is not None:
        decay_time_ms = pre["slot_ms"] * mesh_info["decay_slots"]
        p.mesh_message_deliveries_decay = decay_fn(decay_time_ms)
        p.mesh_message_deliveries_threshold = threshold(
            p.mesh_message_deliveries_decay, expected_message_rate / 50
        )
        p.mesh_message_deliveries_cap = max(
            mesh_info["cap_factor"] * p.mesh_message_deliveries_threshold, 2
        )
        p.mesh_message_deliveries_activation_ms = mesh_info["activation_ms"]
        p.mesh_message_deliveries_window_ms = 12_000
        p.mesh_failure_penalty_decay = p.mesh_message_deliveries_decay
        p.mesh_message_deliveries_weight = (
            -MAX_POSITIVE_SCORE
            / (p.topic_weight * p.mesh_message_deliveries_threshold ** 2)
        )
        p.mesh_failure_penalty_weight = p.mesh_message_deliveries_weight
        if mesh_info["decay_slots"] >= mesh_info["current_slot"]:
            # young chain: do not punish mesh under-delivery yet
            p.mesh_message_deliveries_threshold = 0
            p.mesh_message_deliveries_weight = 0
    p.invalid_message_deliveries_weight = -MAX_POSITIVE_SCORE / p.topic_weight
    p.invalid_message_deliveries_decay = decay_fn(pre["epoch_ms"] * 50)
    return p


def compute_gossip_peer_score_params(
    config,
    active_validator_count: int,
    current_slot: int,
    fork_digest: Optional[bytes] = None,
) -> PeerScoreParams:
    """The full parameter set for one fork's topics (reference computes
    per active fork; compositions call once per fork digest)."""
    if active_validator_count <= 0:
        raise ValueError("active_validator_count must be positive")
    spe = params.SLOTS_PER_EPOCH
    slot_ms = (
        getattr(config, "SECONDS_PER_SLOT", params.SECONDS_PER_SLOT) * 1000
    )
    epoch_ms = slot_ms * spe
    decay_interval_ms = slot_ms
    decay_to_zero = 0.01

    def decay_fn(decay_time_ms: float) -> float:
        return score_parameter_decay_with_base(
            decay_time_ms, decay_interval_ms, decay_to_zero
        )

    pre = {"decay_fn": decay_fn, "slot_ms": slot_ms, "epoch_ms": epoch_ms}
    digest = fork_digest if fork_digest is not None else config.fork_digest(
        current_slot
    )

    def t(name, subnet=None):
        return topic_string(digest, name, subnet=subnet)

    topics: Dict[str, TopicScoreParams] = {}
    for name, weight, rate in (
        (GossipTopicName.voluntary_exit, VOLUNTARY_EXIT_WEIGHT, 4 / spe),
        (
            GossipTopicName.proposer_slashing,
            PROPOSER_SLASHING_WEIGHT,
            1 / 5 / spe,
        ),
        (
            GossipTopicName.attester_slashing,
            ATTESTER_SLASHING_WEIGHT,
            1 / 5 / spe,
        ),
    ):
        topics[t(name)] = _topic_params(
            pre, weight, rate, first_message_decay_time_ms=epoch_ms * 100
        )

    topics[t(GossipTopicName.beacon_block)] = _topic_params(
        pre,
        BEACON_BLOCK_WEIGHT,
        expected_message_rate=1,
        first_message_decay_time_ms=epoch_ms * 20,
        mesh_info={
            "decay_slots": spe * 5,
            "cap_factor": 3,
            "activation_ms": epoch_ms,
            "current_slot": current_slot,
        },
    )

    aggregators_per_slot, committees_per_slot = (
        expected_aggregator_count_per_slot(active_validator_count)
    )
    topics[t(GossipTopicName.beacon_aggregate_and_proof)] = _topic_params(
        pre,
        BEACON_AGGREGATE_PROOF_WEIGHT,
        expected_message_rate=aggregators_per_slot,
        first_message_decay_time_ms=epoch_ms,
        mesh_info={
            "decay_slots": spe * 2,
            "cap_factor": 4,
            "activation_ms": epoch_ms,
            "current_slot": current_slot,
        },
    )

    multiple_bursts = committees_per_slot >= (
        2 * params.ATTESTATION_SUBNET_COUNT
    ) / spe
    att_params = _topic_params(
        pre,
        _ATT_SUBNET_WEIGHT,
        expected_message_rate=(
            active_validator_count / params.ATTESTATION_SUBNET_COUNT / spe
        ),
        first_message_decay_time_ms=(
            epoch_ms if multiple_bursts else epoch_ms * 4
        ),
        mesh_info={
            "decay_slots": spe * 4 if multiple_bursts else spe * 16,
            "cap_factor": 16,
            "activation_ms": (
                slot_ms * (spe / 2 + 1) if multiple_bursts else epoch_ms
            ),
            "current_slot": current_slot,
        },
    )
    for subnet in range(params.ATTESTATION_SUBNET_COUNT):
        topics[t(GossipTopicName.beacon_attestation, subnet)] = att_params

    behaviour_penalty_decay = decay_fn(epoch_ms * 10)
    target_value = (
        decay_convergence(behaviour_penalty_decay, 10 / spe) - 6
    )
    topic_score_cap = MAX_POSITIVE_SCORE * 0.5
    return PeerScoreParams(
        topics=topics,
        decay_interval_ms=decay_interval_ms,
        decay_to_zero=decay_to_zero,
        retain_score_ms=epoch_ms * 100,
        app_specific_weight=1,
        ip_colocation_factor_threshold=3,
        ip_colocation_factor_weight=-topic_score_cap,
        behaviour_penalty_decay=behaviour_penalty_decay,
        behaviour_penalty_weight=(
            GOSSIP_SCORE_THRESHOLDS.gossip_threshold / target_value ** 2
        ),
        behaviour_penalty_threshold=6,
        topic_score_cap=topic_score_cap,
    )


class GossipPeerScorer:
    """The gossipsub score consumer: realizes the derived parameters as
    an actual per-peer GOSSIP score (the reference hands them to
    libp2p-gossipsub; this composition keeps the same two-tier split —
    the wide-scale gossip score with its own thresholds here, the
    +/-100 app-level PeerScoreBook observing a scaled summary).

    Per the gossipsub v1.1 spec, the invalid-message counter's
    contribution is QUADRATIC (P4: w4 * counter^2), so one corrupt
    relay costs ~one topic budget while graylisting (-16000) takes on
    the order of a dozen invalid messages."""

    def __init__(self, score_params: PeerScoreParams, score_book=None):
        self.params = score_params
        self.book = score_book  # optional app-level observer
        # (peer, topic) -> first-delivery counter (caps earned score)
        self._first_deliveries: Dict[tuple, float] = {}
        # (peer, topic) -> invalid-message counter (P4, squared)
        self._invalid_counts: Dict[tuple, float] = {}
        # peer -> positive deliveries score component
        self._positive: Dict[str, float] = {}
        # peer -> behaviour-penalty counter (P7, squared above the
        # threshold) — fed by the verification pipeline's backpressure
        # coupling: messages a peer keeps publishing into a saturated
        # node that the gossip queues then shed (ISSUE 11)
        self._behaviour_penalties: Dict[str, float] = {}

    def gossip_score(self, peer_id: str) -> float:
        """The peer's gossipsub score: capped positive deliveries plus
        the squared invalid-message penalties plus the squared
        above-threshold behaviour penalty (P7)."""
        score = min(
            self._positive.get(peer_id, 0.0), self.params.topic_score_cap
        )
        for (pid, topic), count in self._invalid_counts.items():
            if pid != peer_id:
                continue
            tp = self.params.topics.get(topic)
            if tp is None:
                continue
            score += (
                tp.topic_weight
                * tp.invalid_message_deliveries_weight
                * count
                * count
            )
        excess = (
            self._behaviour_penalties.get(peer_id, 0.0)
            - self.params.behaviour_penalty_threshold
        )
        if excess > 0:
            score += self.params.behaviour_penalty_weight * excess * excess
        return score

    def behaviour_penalty(self, peer_id: str) -> float:
        """The raw P7 counter (pre-threshold, pre-square) — test and
        dashboard introspection."""
        return self._behaviour_penalties.get(peer_id, 0.0)

    def on_backpressure_drop(
        self, peer_id: str, topic: Optional[str] = None, count: float = 1.0
    ) -> float:
        """Charge a peer whose publishing the overloaded node had to
        shed (gossip-queue overflow while the verification pipeline's
        high-water backpressure holds the processor).  Counted on the
        gossipsub BEHAVIOUR penalty (P7): unlike P4 the shed message was
        never validated, so it must not count as an invalid delivery —
        but a peer that keeps flooding a saturated node pays
        quadratically above the threshold, exactly like other protocol
        abuse.  Returns the peer's updated gossip score."""
        self._behaviour_penalties[peer_id] = (
            self._behaviour_penalties.get(peer_id, 0.0) + count
        )
        score = self.gossip_score(peer_id)
        if self.book is not None:
            # app-level observer: one unit per shed message (ratio
            # drops shed several per overflow; the book clamps totals)
            self.book.add(peer_id, -float(count))
        return score

    def decay(self) -> None:
        """One decay interval over the penalty counters (gossipsub spec:
        counters decay by their per-interval factor and zero out below
        decay_to_zero) — lets a peer that stopped flooding recover."""
        d = self.params.behaviour_penalty_decay
        floor = self.params.decay_to_zero
        for pid in list(self._behaviour_penalties):
            v = self._behaviour_penalties[pid] * d
            if v < floor:
                del self._behaviour_penalties[pid]
            else:
                self._behaviour_penalties[pid] = v
        for key in list(self._invalid_counts):
            topic = key[1]
            tp = self.params.topics.get(topic)
            decay_factor = (
                tp.invalid_message_deliveries_decay if tp is not None else d
            )
            v = self._invalid_counts[key] * decay_factor
            if v < floor:
                del self._invalid_counts[key]
            else:
                self._invalid_counts[key] = v
        # the POSITIVE components decay too (gossipsub P1/P2: delivery
        # counters decay by their per-interval factor and zero out
        # below decay_to_zero).  Before this, both maps grew one entry
        # per (peer, topic)/peer EVER seen — the block_state_roots bug
        # class under peer churn (cache-hygiene).
        for key in list(self._first_deliveries):
            tp = self.params.topics.get(key[1])
            decay_factor = (
                tp.first_message_deliveries_decay
                if tp is not None and tp.first_message_deliveries_decay
                else d
            )
            v = self._first_deliveries[key] * decay_factor
            if v < floor:
                del self._first_deliveries[key]
            else:
                self._first_deliveries[key] = v
        for pid in list(self._positive):
            v = self._positive[pid] * d
            if v < floor:
                del self._positive[pid]
            else:
                self._positive[pid] = v

    def on_invalid_message(self, peer_id: str, topic: str) -> float:
        key = (peer_id, topic)
        self._invalid_counts[key] = self._invalid_counts.get(key, 0.0) + 1
        score = self.gossip_score(peer_id)
        if self.book is not None:
            # app-level observer: one clamped unit per invalid message
            tp = self.params.topics.get(topic)
            self.book.add(
                peer_id,
                (
                    tp.invalid_message_deliveries_weight * tp.topic_weight
                    if tp is not None
                    else -MAX_POSITIVE_SCORE
                ),
            )
        return score

    def is_banned(self, peer_id: str) -> bool:
        """Graylist check at the mesh edge: the GOSSIP score against the
        derived graylist threshold (gossipsub drops messages from peers
        below it)."""
        return (
            self.gossip_score(peer_id)
            <= GOSSIP_SCORE_THRESHOLDS.graylist_threshold
        )

    def on_verdict(self, peer_id: str, topic: str, verdict) -> None:
        """Score one handler verdict (GossipHandlers.handle returns
        None on ACCEPT, else the GossipAction)."""
        from ..chain.validation import GossipAction

        if verdict is None:
            self.on_first_delivery(peer_id, topic)
        elif verdict == GossipAction.REJECT:
            self.on_invalid_message(peer_id, topic)
        # IGNORE: no score movement (gossipsub does not punish ignores)

    def on_first_delivery(self, peer_id: str, topic: str) -> float:
        """Credits one first-seen delivery, bounded by the topic's
        cumulative first_message_deliveries_cap (gossipsub spec: the
        counter, and therefore the earned score, saturates at the cap)."""
        tp = self.params.topics.get(topic)
        if tp is None:
            return self.gossip_score(peer_id)
        key = (peer_id, topic)
        count = self._first_deliveries.get(key, 0.0)
        if count >= tp.first_message_deliveries_cap:
            return self.gossip_score(peer_id)
        self._first_deliveries[key] = count + 1
        self._positive[peer_id] = self._positive.get(peer_id, 0.0) + (
            tp.first_message_deliveries_weight * tp.topic_weight
        )
        if self.book is not None:
            self.book.add(
                peer_id,
                min(tp.first_message_deliveries_weight * tp.topic_weight, 1.0),
            )
        return self.gossip_score(peer_id)
