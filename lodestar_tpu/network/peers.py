"""Peer score book + status tracking.

Reference: packages/beacon-node/src/network/peers/score/ (PeerRpcScore:
bounded score with exponential decay, ban thresholds, per-action
penalties) and peers/peerManager.ts (status handshake relevance:
fork digest match + finalized checkpoint sanity).  The wire transport
stays out of scope; the book is the reusable policy layer the sync and
gossip drivers consult.
"""

from __future__ import annotations

import enum
import math
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

# reference: score/constants.ts
GOODBYE_BAN_SCORE = -50.0
MIN_SCORE_BEFORE_DISCONNECT = -20.0
MAX_SCORE = 100.0
MIN_SCORE = -100.0
SCORE_HALFLIFE_S = 600.0


class PeerAction(str, enum.Enum):
    """score/index.ts PeerAction -> penalty."""

    fatal = "fatal"
    low_tolerance = "low_tolerance"
    mid_tolerance = "mid_tolerance"
    high_tolerance = "high_tolerance"


PEER_ACTION_SCORE = {
    PeerAction.fatal: MIN_SCORE,
    PeerAction.low_tolerance: -10.0,
    PeerAction.mid_tolerance: -5.0,
    PeerAction.high_tolerance: -1.0,
}


class ScoreState(str, enum.Enum):
    healthy = "Healthy"
    disconnected = "Disconnected"
    banned = "Banned"


@dataclass
class PeerStatus:
    """The status handshake (reference: reqresp Status payload)."""

    fork_digest: bytes
    finalized_root: bytes
    finalized_epoch: int
    head_root: bytes
    head_slot: int


@dataclass
class _PeerRecord:
    score: float = 0.0
    last_update: float = field(default_factory=time.time)
    status: Optional[PeerStatus] = None


class PeerScoreBook:
    def __init__(self, clock=time.time):
        self._peers: Dict[str, _PeerRecord] = {}
        self._clock = clock

    def _record(self, peer_id: str) -> _PeerRecord:
        rec = self._peers.get(peer_id)
        if rec is None:
            rec = _PeerRecord(last_update=self._clock())
            self._peers[peer_id] = rec
        return rec

    def _decay(self, rec: _PeerRecord) -> None:
        now = self._clock()
        dt = now - rec.last_update
        if dt > 0:
            rec.score *= math.exp(-math.log(2) * dt / SCORE_HALFLIFE_S)
            rec.last_update = now

    def apply_action(self, peer_id: str, action: PeerAction) -> float:
        rec = self._record(peer_id)
        self._decay(rec)
        rec.score = max(MIN_SCORE, min(MAX_SCORE, rec.score + PEER_ACTION_SCORE[action]))
        return rec.score

    def add(self, peer_id: str, delta: float) -> float:
        rec = self._record(peer_id)
        self._decay(rec)
        rec.score = max(MIN_SCORE, min(MAX_SCORE, rec.score + delta))
        return rec.score

    def score(self, peer_id: str) -> float:
        rec = self._record(peer_id)
        self._decay(rec)
        return rec.score

    def state(self, peer_id: str) -> ScoreState:
        s = self.score(peer_id)
        if s <= GOODBYE_BAN_SCORE:
            return ScoreState.banned
        if s <= MIN_SCORE_BEFORE_DISCONNECT:
            return ScoreState.disconnected
        return ScoreState.healthy

    def snapshot(self) -> dict:
        """peer_id -> decayed score, over a COPY of the book — the
        flight recorder's provider reads this while network callbacks
        insert peers, so it must neither iterate the live dict nor
        hand out pre-decay scores."""
        return {pid: self.score(pid) for pid in list(self._peers)}

    # forget() retains any score at or below this: a sub-ban offender
    # must keep accumulating toward the ban across reconnects (wiping
    # at disconnect would let a flooder reset by cycling connections);
    # near-zero records — the churn bulk — are dropped.
    FORGET_RETENTION_SCORE = -1.0

    def forget(self, peer_id: str) -> None:
        """Drop a departed peer's record (PeerManager.forget calls
        this) — without it the book grows one record per peer EVER
        seen, the block_state_roots bug class under peer churn.
        NEGATIVE records are retained: penalties must survive a
        disconnect/reconnect cycle or the ban threshold is unreachable
        (they still time-decay, and prune_stale drops the long tail)."""
        rec = self._peers.get(peer_id)
        if rec is not None and self.score(peer_id) > (
            self.FORGET_RETENTION_SCORE
        ):
            self._peers.pop(peer_id, None)

    def prune_stale(self, max_age_s: float = 6 * 3600.0) -> None:
        """Drop records untouched for `max_age_s` — decayed to ~zero
        and long past ban relevance (periodic heartbeat hygiene)."""
        now = self._clock()
        for pid in [
            p
            for p, rec in list(self._peers.items())
            if now - rec.last_update > max_age_s
        ]:
            self._peers.pop(pid, None)

    # -- status handshake (peerManager.ts assertPeerRelevance) -------------

    def on_status(self, peer_id: str, status: PeerStatus) -> None:
        self._record(peer_id).status = status

    def status_of(self, peer_id: str) -> Optional[PeerStatus]:
        return self._peers.get(peer_id, _PeerRecord()).status

    def is_relevant(
        self,
        status: PeerStatus,
        our_fork_digest: bytes,
        our_finalized_epoch: int,
        root_at_epoch=None,
    ) -> bool:
        """assertPeerRelevance: matching fork digest; if the peer's
        finalized epoch is at or behind ours, its finalized root must
        match OUR canonical root at that epoch (`root_at_epoch(epoch)
        -> Optional[bytes]`, e.g. a block_roots/archive lookup) — a
        peer finalized on a different history is irrelevant."""
        if status.fork_digest != our_fork_digest:
            return False
        if (
            status.finalized_epoch <= our_finalized_epoch
            and root_at_epoch is not None
        ):
            ours = root_at_epoch(status.finalized_epoch)
            if ours is not None and status.finalized_root != ours:
                return False
        return True

    def best_peers(self, min_state: ScoreState = ScoreState.healthy):
        """Healthy peers, best score first (range-sync peer selection)."""
        out = [
            (pid, self.score(pid))
            for pid in self._peers
            if self.state(pid) == min_state
        ]
        return [pid for pid, _ in sorted(out, key=lambda t: -t[1])]
