"""Beacon-node req/resp protocol bindings: wire types + handlers.

Mirror of the reference's protocol definitions and handler wiring
(reference: packages/beacon-node/src/network/reqresp/{types.ts,
protocols.ts:8-87, handlers/*.ts}): the SSZ request/response containers,
fork-digest context dispatch for v2 protocols, and handlers backed by
chain + db + light-client server.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from .. import params
from .. import types as T
from ..ssz import Bytes32, Container, List as SszList, uint64
from .reqresp import (
    ContextBytes,
    MAX_REQUEST_BLOB_SIDECARS,
    MAX_REQUEST_BLOCKS,
    MAX_REQUEST_LIGHT_CLIENT_UPDATES,
    Protocol,
    ReqResp,
    ReqRespError,
    ReqRespMethod,
    RespCode,
)

# -- wire containers (reference: network/reqresp/types.ts) ------------------

StatusType = Container(
    (
        ("fork_digest", T.Version),
        ("finalized_root", T.Root),
        ("finalized_epoch", T.Epoch),
        ("head_root", T.Root),
        ("head_slot", T.Slot),
    ),
    name="Status",
)

GoodbyeType = uint64
PingType = uint64

BeaconBlocksByRangeRequest = Container(
    (
        ("start_slot", T.Slot),
        ("count", uint64),
        ("step", uint64),
    ),
    name="BeaconBlocksByRangeRequest",
)

BlockRootsRequest = SszList(Bytes32, MAX_REQUEST_BLOCKS)

LightClientUpdatesByRangeRequest = Container(
    (
        ("start_period", uint64),
        ("count", uint64),
    ),
    name="LightClientUpdatesByRangeRequest",
)

# deneb blob transfer (p2p spec: blob_sidecars_by_range/v1, by_root/v1)
BlobSidecarsByRangeRequest = Container(
    (
        ("start_slot", T.Slot),
        ("count", uint64),
    ),
    name="BlobSidecarsByRangeRequest",
)

BlobIdentifierType = Container(
    (
        ("block_root", T.Root),
        ("index", uint64),
    ),
    name="BlobIdentifier",
)

BlobIdentifiersRequest = SszList(
    BlobIdentifierType, MAX_REQUEST_BLOB_SIDECARS
)

# altair light-client wire containers (reference: types/src/altair/
# sszTypes.ts LightClientUpdate/LightClientBootstrap); absent optional
# parts travel zero-filled, as in the spec containers
from ..light_client.lightclient import (  # noqa: E402
    FINALIZED_ROOT_DEPTH,
    NEXT_SYNC_COMMITTEE_DEPTH,
)
from ..ssz import Vector  # noqa: E402

LightClientUpdateType = Container(
    (
        ("attested_header", T.BeaconBlockHeader),
        ("next_sync_committee", T.SyncCommittee),
        (
            "next_sync_committee_branch",
            Vector(Bytes32, NEXT_SYNC_COMMITTEE_DEPTH),
        ),
        ("finalized_header", T.BeaconBlockHeader),
        ("finality_branch", Vector(Bytes32, FINALIZED_ROOT_DEPTH)),
        ("sync_aggregate", T.SyncAggregate),
        ("signature_slot", T.Slot),
    ),
    name="LightClientUpdate",
)

LightClientBootstrapType = Container(
    (
        ("header", T.BeaconBlockHeader),
        ("current_sync_committee", T.SyncCommittee),
        (
            "current_sync_committee_branch",
            Vector(Bytes32, NEXT_SYNC_COMMITTEE_DEPTH),
        ),
    ),
    name="LightClientBootstrap",
)

_ZERO_BRANCH5 = [b"\x00" * 32] * NEXT_SYNC_COMMITTEE_DEPTH
_ZERO_BRANCH6 = [b"\x00" * 32] * FINALIZED_ROOT_DEPTH


def light_client_update_to_value(upd) -> dict:
    """LightClientUpdate dataclass -> spec-shaped container value."""
    empty_committee = T.SyncCommittee.default()
    return {
        "attested_header": dict(upd.attested_header),
        "next_sync_committee": dict(
            upd.next_sync_committee or empty_committee
        ),
        "next_sync_committee_branch": list(
            upd.next_sync_committee_branch or _ZERO_BRANCH5
        ),
        "finalized_header": dict(
            upd.finalized_header or T.BeaconBlockHeader.default()
        ),
        "finality_branch": list(upd.finality_branch or _ZERO_BRANCH6),
        "sync_aggregate": {
            "sync_committee_bits": list(upd.sync_committee_bits),
            "sync_committee_signature": bytes(upd.sync_committee_signature),
        },
        "signature_slot": int(upd.signature_slot),
    }


def light_client_update_from_value(value: dict):
    """Container value -> LightClientUpdate dataclass (zero-filled parts
    become None)."""
    from ..light_client.lightclient import LightClientUpdate

    branch5 = [bytes(b) for b in value["next_sync_committee_branch"]]
    branch6 = [bytes(b) for b in value["finality_branch"]]
    has_committee = branch5 != _ZERO_BRANCH5
    has_finality = branch6 != _ZERO_BRANCH6
    agg = value["sync_aggregate"]
    return LightClientUpdate(
        attested_header=dict(value["attested_header"]),
        sync_committee_bits=list(agg["sync_committee_bits"]),
        sync_committee_signature=bytes(agg["sync_committee_signature"]),
        signature_slot=int(value["signature_slot"]),
        finalized_header=(
            dict(value["finalized_header"]) if has_finality else None
        ),
        finality_branch=branch6 if has_finality else None,
        next_sync_committee=(
            dict(value["next_sync_committee"]) if has_committee else None
        ),
        next_sync_committee_branch=branch5 if has_committee else None,
    )


def _metadata_type():
    """Metadata container built against the live bitvector types (the
    subnet services own the attnets/syncnets shapes)."""
    from ..ssz import Bitvector

    return Container(
        (
            ("seq_number", uint64),
            ("attnets", Bitvector(params.ATTESTATION_SUBNET_COUNT)),
            ("syncnets", Bitvector(params.SYNC_COMMITTEE_SUBNET_COUNT)),
        ),
        name="Metadata",
    )


METADATA_TYPE = _metadata_type()


# -- protocol constructors --------------------------------------------------


def _enc(t):
    return lambda body: t.serialize(body)


def _dec(t):
    return lambda data: t.deserialize(data)


def status_protocol() -> Protocol:
    return Protocol(
        method=ReqRespMethod.status,
        version=1,
        context_bytes=ContextBytes.empty,
        encode_request=_enc(StatusType),
        decode_request=_dec(StatusType),
        encode_response=_enc(StatusType),
        decode_response=_dec(StatusType),
    )


def goodbye_protocol() -> Protocol:
    return Protocol(
        method=ReqRespMethod.goodbye,
        version=1,
        context_bytes=ContextBytes.empty,
        encode_request=_enc(GoodbyeType),
        decode_request=_dec(GoodbyeType),
        encode_response=_enc(GoodbyeType),
        decode_response=_dec(GoodbyeType),
    )


def ping_protocol() -> Protocol:
    return Protocol(
        method=ReqRespMethod.ping,
        version=1,
        context_bytes=ContextBytes.empty,
        encode_request=_enc(PingType),
        decode_request=_dec(PingType),
        encode_response=_enc(PingType),
        decode_response=_dec(PingType),
    )


def metadata_protocol(version: int = 2) -> Protocol:
    return Protocol(
        method=ReqRespMethod.metadata,
        version=version,
        context_bytes=ContextBytes.empty,
        encode_request=None,  # metadata requests carry no body
        decode_request=None,
        encode_response=_enc(METADATA_TYPE),
        decode_response=_dec(METADATA_TYPE),
    )


def blocks_by_range_protocol(config, version: int = 2) -> Protocol:
    """v2 prefixes each block chunk with the block fork's digest."""
    return Protocol(
        method=ReqRespMethod.beacon_blocks_by_range,
        version=version,
        context_bytes=(
            ContextBytes.fork_digest if version >= 2 else ContextBytes.empty
        ),
        encode_request=_enc(BeaconBlocksByRangeRequest),
        decode_request=_dec(BeaconBlocksByRangeRequest),
        encode_response=None,  # handlers emit pre-encoded chunks
        decode_response=lambda data, ctx=None: _decode_signed_block(
            config, data, ctx
        ),
    )


def blocks_by_root_protocol(config, version: int = 2) -> Protocol:
    return Protocol(
        method=ReqRespMethod.beacon_blocks_by_root,
        version=version,
        context_bytes=(
            ContextBytes.fork_digest if version >= 2 else ContextBytes.empty
        ),
        encode_request=_enc(BlockRootsRequest),
        decode_request=_dec(BlockRootsRequest),
        encode_response=None,
        decode_response=lambda data, ctx=None: _decode_signed_block(
            config, data, ctx
        ),
    )


def _blob_sidecar_codec():
    """Per-sidecar wire codec: spec-shaped content with a
    length-prefixed blob (self-describing width), shared with the db
    layer.  The SSZ BlobSidecar container is preset-width; the p2p wire
    itself is off-scope (SURVEY P9), so the in-memory protocol carries
    the width-agnostic framing the rest of the framework uses."""
    from ..db.beacon_db import BlobSidecarListCodec

    codec = BlobSidecarListCodec()
    return (
        lambda sc: codec.serialize([sc]),
        lambda data: codec.deserialize(data)[0],
    )


def blob_sidecars_by_range_protocol(config) -> Protocol:
    enc, dec = _blob_sidecar_codec()
    return Protocol(
        method=ReqRespMethod.blob_sidecars_by_range,
        version=1,
        context_bytes=ContextBytes.fork_digest,
        encode_request=_enc(BlobSidecarsByRangeRequest),
        decode_request=_dec(BlobSidecarsByRangeRequest),
        encode_response=enc,
        decode_response=lambda data, ctx=None: dec(data),
    )


def blob_sidecars_by_root_protocol(config) -> Protocol:
    enc, dec = _blob_sidecar_codec()
    return Protocol(
        method=ReqRespMethod.blob_sidecars_by_root,
        version=1,
        context_bytes=ContextBytes.fork_digest,
        encode_request=_enc(BlobIdentifiersRequest),
        decode_request=_dec(BlobIdentifiersRequest),
        encode_response=enc,
        decode_response=lambda data, ctx=None: dec(data),
    )


def _decode_signed_block(config, data: bytes, ctx: Optional[bytes]):
    """Pick the signed-block container from the chunk's fork digest
    (v2 context bytes).  An unknown digest is a protocol violation —
    decoding it as some other fork would yield structurally-valid
    garbage that fails far from the cause."""
    if ctx is None:  # v1: no context bytes -> pre-bellatrix container
        return T.SignedBeaconBlockAltair.deserialize(data)
    for fork in config.fork_schedule():
        epoch = config.fork_epochs[fork]
        slot = epoch * params.SLOTS_PER_EPOCH
        if config.fork_digest(slot) == ctx:
            return config.get_fork_types(slot)[1].deserialize(data)
    raise ReqRespError(
        RespCode.INVALID_REQUEST, f"unknown fork digest {ctx.hex()}"
    )


def decode_block_chunks(config, chunks: List[Tuple[bytes, Optional[bytes]]]):
    return [_decode_signed_block(config, d, ctx) for d, ctx in chunks]


# -- node-side handlers (reference: network/reqresp/handlers/) --------------


class ReqRespBeaconNode:
    """Registers the full beacon protocol set on a ReqResp node and
    serves them from chain + db (reference: ReqRespBeaconNode.ts).

    `metadata_fn() -> {seq_number, attnets, syncnets}` comes from the
    subnet services; `on_goodbye(peer, reason)` feeds the peer manager.
    """

    def __init__(
        self,
        reqresp: ReqResp,
        config,
        chain=None,
        db=None,
        light_client_server=None,
        metadata_fn: Optional[Callable[[], dict]] = None,
        on_goodbye: Optional[Callable[[str, int], None]] = None,
        on_status: Optional[Callable[[str, dict], None]] = None,
    ):
        self.reqresp = reqresp
        self.config = config
        self.chain = chain
        self.db = db
        self.lc = light_client_server
        self.metadata_fn = metadata_fn
        self.on_goodbye = on_goodbye
        self.on_status = on_status
        self.protocols = {}
        self._register()

    def _register(self) -> None:
        r = self.reqresp
        p = self.protocols
        p["status"] = status_protocol()
        r.register_protocol(p["status"], self._handle_status)
        p["goodbye"] = goodbye_protocol()
        r.register_protocol(p["goodbye"], self._handle_goodbye)
        p["ping"] = ping_protocol()
        r.register_protocol(p["ping"], self._handle_ping)
        p["metadata"] = metadata_protocol()
        r.register_protocol(p["metadata"], self._handle_metadata)
        p["blocks_by_range"] = blocks_by_range_protocol(self.config)
        r.register_protocol(p["blocks_by_range"], self._handle_blocks_by_range)
        p["blocks_by_root"] = blocks_by_root_protocol(self.config)
        r.register_protocol(p["blocks_by_root"], self._handle_blocks_by_root)
        p["blob_sidecars_by_range"] = blob_sidecars_by_range_protocol(
            self.config
        )
        r.register_protocol(
            p["blob_sidecars_by_range"], self._handle_blob_sidecars_by_range
        )
        p["blob_sidecars_by_root"] = blob_sidecars_by_root_protocol(
            self.config
        )
        r.register_protocol(
            p["blob_sidecars_by_root"], self._handle_blob_sidecars_by_root
        )
        if self.lc is not None:
            self._register_light_client(r, p)

    def _register_light_client(self, r, p) -> None:
        p["lc_bootstrap"] = Protocol(
            method=ReqRespMethod.light_client_bootstrap,
            version=1,
            context_bytes=ContextBytes.fork_digest,
            encode_request=lambda root: bytes(root),
            decode_request=lambda data: bytes(data),
            encode_response=_enc(LightClientBootstrapType),
            decode_response=_dec(LightClientBootstrapType),
        )
        r.register_protocol(p["lc_bootstrap"], self._handle_lc_bootstrap)
        p["lc_updates"] = Protocol(
            method=ReqRespMethod.light_client_updates_by_range,
            version=1,
            context_bytes=ContextBytes.fork_digest,
            encode_request=_enc(LightClientUpdatesByRangeRequest),
            decode_request=_dec(LightClientUpdatesByRangeRequest),
            encode_response=_enc(LightClientUpdateType),
            decode_response=_dec(LightClientUpdateType),
        )
        r.register_protocol(p["lc_updates"], self._handle_lc_updates)

    # -- handlers ----------------------------------------------------------

    def _ctx(self, slot: int) -> bytes:
        return self.config.fork_digest(slot)

    def _handle_status(self, peer_id: str, req: dict):
        if self.on_status is not None:
            self.on_status(peer_id, req)
        st = self._local_status()
        return [(StatusType.serialize(st), None)]

    def _local_status(self) -> dict:
        chain = self.chain
        if chain is None:
            raise ReqRespError(RespCode.SERVER_ERROR, "no chain wired")
        head = chain.head_state
        fin = head.finalized_checkpoint
        return {
            "fork_digest": self.config.fork_digest(head.slot),
            "finalized_root": bytes(fin["root"]),
            "finalized_epoch": int(fin["epoch"]),
            "head_root": chain.get_head_root(),
            "head_slot": int(head.slot),
        }

    def _handle_goodbye(self, peer_id: str, reason: int):
        if self.on_goodbye is not None:
            self.on_goodbye(peer_id, int(reason))
        return [(GoodbyeType.serialize(0), None)]

    def _handle_ping(self, peer_id: str, seq: int):
        md = self.metadata_fn() if self.metadata_fn is not None else None
        seq_number = int(md["seq_number"]) if md else 0
        return [(PingType.serialize(seq_number), None)]

    def _handle_metadata(self, peer_id: str, _req):
        if self.metadata_fn is None:
            raise ReqRespError(RespCode.SERVER_ERROR, "no metadata source")
        return [(METADATA_TYPE.serialize(self.metadata_fn()), None)]

    def _handle_blocks_by_range(self, peer_id: str, req: dict):
        """Slot-ordered canonical blocks from the archive + hot chain
        (reference: handlers/beaconBlocksByRange.ts)."""
        start = int(req["start_slot"])
        count = min(int(req["count"]), MAX_REQUEST_BLOCKS)
        step = max(1, int(req.get("step", 1)))  # deprecated; 1 in practice
        if count < 1 or start < 0:
            raise ReqRespError(RespCode.INVALID_REQUEST, "bad range")
        out = []
        for slot in range(start, start + count * step, step):
            signed = self._canonical_block_at_slot(slot)
            if signed is None:
                continue
            slot_ = int(signed["message"]["slot"])
            signed_type = self.config.get_fork_types(slot_)[1]
            out.append((signed_type.serialize(signed), self._ctx(slot_)))
        return out

    def _canonical_block_at_slot(self, slot: int):
        if self.db is not None:
            key = slot.to_bytes(8, "big")
            data = self.db.block_archive.get(key)
            if data is not None:
                return data
        if self.chain is not None:
            root = self.chain.fork_choice.canonical_root_at_slot(slot) if (
                hasattr(self.chain, "fork_choice")
                and hasattr(self.chain.fork_choice, "canonical_root_at_slot")
            ) else None
            if root is not None:
                blk = self._block_by_root(root)
                if blk is not None:
                    return blk
            # fallback: scan hot blocks for an exact slot match on the
            # canonical chain
            getter = getattr(self.chain, "get_block_by_slot", None)
            if getter is not None:
                return getter(slot)
        return None

    def _block_by_root(self, root: bytes):
        if self.db is not None:
            blk = self.db.get_block_anywhere(bytes(root))
            if blk is not None:
                return blk
        if self.chain is not None:
            getter = getattr(self.chain, "get_block", None)
            if getter is not None:
                return getter(bytes(root))
        return None

    def _handle_blocks_by_root(self, peer_id: str, roots):
        out = []
        for root in roots[:MAX_REQUEST_BLOCKS]:
            signed = self._block_by_root(bytes(root))
            if signed is None:
                continue
            slot = int(signed["message"]["slot"])
            signed_type = self.config.get_fork_types(slot)[1]
            out.append((signed_type.serialize(signed), self._ctx(slot)))
        return out

    def _sidecars_for_root(self, root: bytes):
        """Validated sidecars for a block: db first (imported blocks),
        then the chain's in-memory availability bodies (gossip-window
        blocks not yet imported)."""
        if self.db is not None:
            getter = getattr(self.db, "get_blob_sidecars", None)
            if getter is not None:
                sidecars = getter(bytes(root))
                if sidecars is not None:
                    return sidecars
        if self.chain is not None:
            getter = getattr(self.chain, "get_blob_sidecars", None)
            if getter is not None:
                return getter(bytes(root))
        return None

    def _handle_blob_sidecars_by_range(self, peer_id: str, req: dict):
        """Slot-ordered sidecars of canonical blocks (p2p spec
        blob_sidecars_by_range/v1; reference:
        handlers/blobsSidecarsByRange.ts)."""
        from .reqresp import (
            MAX_REQUEST_BLOB_SIDECARS,
            MAX_REQUEST_BLOCKS_DENEB,
        )

        start = int(req["start_slot"])
        # deneb by-range requests are capped at 128 SLOTS (not the
        # 1024-block cap of blocks_by_range) — the scan itself is the
        # cost being bounded, not just the response size
        count = min(int(req["count"]), MAX_REQUEST_BLOCKS_DENEB)
        if count < 1 or start < 0:
            raise ReqRespError(RespCode.INVALID_REQUEST, "bad range")
        enc, _dec = _blob_sidecar_codec()
        out = []
        for slot in range(start, start + count):
            # archived slots serve straight off the slot key — no block
            # fetch or root recomputation
            sidecars = None
            if self.db is not None and hasattr(
                self.db, "blobs_sidecar_archive"
            ):
                sidecars = self.db.blobs_sidecar_archive.get(
                    slot.to_bytes(8, "big")
                )
            if sidecars is None:
                signed = self._canonical_block_at_slot(slot)
                if signed is None:
                    continue
                slot_ = int(signed["message"]["slot"])
                root = self.config.get_fork_types(slot_)[0].hash_tree_root(
                    signed["message"]
                )
                sidecars = self._sidecars_for_root(root) or []
            for sc in sidecars:
                if len(out) >= MAX_REQUEST_BLOB_SIDECARS:
                    return out
                sc_slot = int(sc["signed_block_header"]["message"]["slot"])
                out.append((enc(sc), self._ctx(sc_slot)))
        return out

    def _handle_blob_sidecars_by_root(self, peer_id: str, identifiers):
        from .reqresp import MAX_REQUEST_BLOB_SIDECARS

        enc, _dec = _blob_sidecar_codec()
        out = []
        for ident in identifiers[:MAX_REQUEST_BLOB_SIDECARS]:
            root = bytes(ident["block_root"])
            want = int(ident["index"])
            sidecars = self._sidecars_for_root(root) or []
            for sc in sidecars:
                if int(sc["index"]) == want:
                    slot = int(
                        sc["signed_block_header"]["message"]["slot"]
                    )
                    out.append((enc(sc), self._ctx(slot)))
                    break
        return out

    def _handle_lc_bootstrap(self, peer_id: str, root: bytes):
        boot = self.lc.get_bootstrap(bytes(root))
        if boot is None:
            raise ReqRespError(
                RespCode.RESOURCE_UNAVAILABLE, "no bootstrap for root"
            )
        slot = int(boot["header"]["slot"])
        return [(LightClientBootstrapType.serialize(boot), self._ctx(slot))]

    def _handle_lc_updates(self, peer_id: str, req: dict):
        start = int(req["start_period"])
        count = min(int(req["count"]), MAX_REQUEST_LIGHT_CLIENT_UPDATES)
        out = []
        for period in range(start, start + count):
            upd = self.lc.get_update(period)
            if upd is None:
                continue
            value = light_client_update_to_value(upd)
            slot = int(value["attested_header"]["slot"])
            out.append(
                (LightClientUpdateType.serialize(value), self._ctx(slot))
            )
        return out


class ReqRespBlockSource:
    """A sync BlockSource over one reqresp peer connection: blocks by
    range/root plus deneb blob sidecars, decoded to the repo-wide value
    shapes (reference: the sync layer's network.beaconBlocksMaybeBlobsByRange
    wrapper over ReqRespBeaconNode).

    Plugs straight into sync.SyncChain.add_peer — the batch state
    machine downloads through this adapter while a second peer's
    adapter can serve other batches.
    """

    def __init__(self, reqresp: ReqResp, peer_id: str, config):
        self.reqresp = reqresp
        self.peer_id = peer_id
        self.config = config
        self._range = blocks_by_range_protocol(config)
        self._roots = blocks_by_root_protocol(config)
        self._blob_range = blob_sidecars_by_range_protocol(config)
        self._blob_root = blob_sidecars_by_root_protocol(config)

    def get_blocks_by_range(self, start_slot: int, count: int):
        chunks = self.reqresp.send_request(
            self.peer_id,
            self._range,
            {"start_slot": start_slot, "count": count, "step": 1},
        )
        return decode_block_chunks(self.config, chunks)

    def get_blocks_by_root(self, roots):
        chunks = self.reqresp.send_request(
            self.peer_id, self._roots, [bytes(r) for r in roots]
        )
        return decode_block_chunks(self.config, chunks)

    def get_blob_sidecars_by_range(self, start_slot: int, count: int):
        chunks = self.reqresp.send_request(
            self.peer_id,
            self._blob_range,
            {"start_slot": start_slot, "count": count},
        )
        return [
            self._blob_range.decode_response(data, ctx)
            for data, ctx in chunks
        ]

    def get_blob_sidecars_by_root(self, identifiers):
        """identifiers: [(block_root, index), ...] or dicts."""
        body = [
            i
            if isinstance(i, dict)
            else {"block_root": bytes(i[0]), "index": int(i[1])}
            for i in identifiers
        ]
        chunks = self.reqresp.send_request(self.peer_id, self._blob_root, body)
        return [
            self._blob_root.decode_response(data, ctx)
            for data, ctx in chunks
        ]
