"""Attestation/sync-committee subnet services — subscription policy.

Mirror of the reference's subnet services (reference:
packages/beacon-node/src/network/subnets/{attnetsService,
syncnetsService}.ts): which gossip subnets a node subscribes to and
when.  The policy layer is transport-independent — the wire mesh is off
the TPU path (SURVEY §2.4 P9) — and is consumed by the gossip bus
subscriptions and the REST beacon_committee_subscriptions endpoint.

Long-lived attestation subnets follow the p2p spec's deterministic
node-id schedule (compute_subscribed_subnets): every node serves
SUBNETS_PER_NODE subnets derived from its node-id prefix, rotating
every EPOCHS_PER_SUBNET_SUBSCRIPTION epochs, so subnet backbones stay
populated without coordination.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Set, Tuple

from .. import params
from ..state_transition.util import compute_shuffled_index

# p2p spec constants (phase0/p2p-interface.md)
SUBNETS_PER_NODE = 2
ATTESTATION_SUBNET_PREFIX_BITS = 6
EPOCHS_PER_SUBNET_SUBSCRIPTION = 256
# short-lived duty subscriptions linger a few slots past the duty
SUBSCRIPTION_EXPIRY_SLOTS = 2


def compute_subscribed_subnet(node_id: int, epoch: int, index: int) -> int:
    """p2p spec compute_subscribed_subnet: the node-id prefix shuffled
    by the subscription period's seed, offset by the subnet index.

    The per-node offset (node_id % EPOCHS_PER_SUBNET_SUBSCRIPTION) enters
    the period so rotations are STAGGERED across nodes — without it every
    backbone would churn at the same epoch boundary."""
    node_id_prefix = node_id >> (256 - ATTESTATION_SUBNET_PREFIX_BITS)
    node_offset = node_id % EPOCHS_PER_SUBNET_SUBSCRIPTION
    period = (epoch + node_offset) // EPOCHS_PER_SUBNET_SUBSCRIPTION
    seed = hashlib.sha256(period.to_bytes(8, "little")).digest()
    permutated = compute_shuffled_index(
        node_id_prefix, 1 << ATTESTATION_SUBNET_PREFIX_BITS, seed
    )
    return (permutated + index) % params.ATTESTATION_SUBNET_COUNT


def compute_subscribed_subnets(node_id: int, epoch: int) -> List[int]:
    return [
        compute_subscribed_subnet(node_id, epoch, i)
        for i in range(SUBNETS_PER_NODE)
    ]


def compute_subnet_for_attestation(
    committees_per_slot: int, slot: int, committee_index: int
) -> int:
    """p2p spec compute_subnet_for_attestation (the publish side of the
    wrong-subnet REJECT check in chain/validation.py)."""
    slots_since_epoch_start = slot % params.SLOTS_PER_EPOCH
    committees_since = committees_per_slot * slots_since_epoch_start
    return (
        committees_since + committee_index
    ) % params.ATTESTATION_SUBNET_COUNT


class AttnetsService:
    """Long-lived node-id subnets + short-lived committee-duty
    subscriptions (reference: attnetsService.ts).

    `all_subnets` mirrors the reference's --subscribeAllSubnets: the
    service reports EVERY subnet as active and advertises all metadata
    bits, so gossip subscriptions, req/resp metadata, and peer
    selection stay consistent from one switch."""

    def __init__(self, node_id: int, all_subnets: bool = False):
        self.node_id = node_id
        self.all_subnets = all_subnets
        # (slot, subnet) -> expiry slot for duty subscriptions
        self._short_lived: Dict[int, int] = {}

    def long_lived_subnets(self, epoch: int) -> List[int]:
        if self.all_subnets:
            return list(range(params.ATTESTATION_SUBNET_COUNT))
        return compute_subscribed_subnets(self.node_id, epoch)

    def prepare_committee_subscription(
        self,
        committees_per_slot: int,
        slot: int,
        committee_index: int,
        is_aggregator: bool,
    ) -> int:
        """A validator duty announces itself (the REST
        beacon_committee_subscriptions flow); aggregators must join the
        subnet to collect attestations."""
        subnet = compute_subnet_for_attestation(
            committees_per_slot, slot, committee_index
        )
        if is_aggregator:
            expiry = slot + SUBSCRIPTION_EXPIRY_SLOTS
            self._short_lived[subnet] = max(
                self._short_lived.get(subnet, 0), expiry
            )
        return subnet

    def active_subnets(self, epoch: int, current_slot: int) -> Set[int]:
        self.prune(current_slot)
        return set(self.long_lived_subnets(epoch)) | set(self._short_lived)

    def prune(self, current_slot: int) -> None:
        for subnet in [
            s for s, exp in self._short_lived.items() if exp < current_slot
        ]:
            del self._short_lived[subnet]

    def metadata_attnets(self, epoch: int, current_slot: int) -> List[bool]:
        """The ENR/metadata attnets bitvector."""
        active = self.active_subnets(epoch, current_slot)
        return [
            s in active for s in range(params.ATTESTATION_SUBNET_COUNT)
        ]


class SyncnetsService:
    """Sync-committee subnets from duty windows (reference:
    syncnetsService.ts: subscribe while any local validator serves the
    committee period)."""

    def __init__(self, all_subnets: bool = False):
        self.all_subnets = all_subnets
        # subnet -> until_epoch
        self._subscriptions: Dict[int, int] = {}

    def subscribe_for_duty(self, subnet: int, until_epoch: int) -> None:
        if not 0 <= subnet < params.SYNC_COMMITTEE_SUBNET_COUNT:
            raise ValueError(f"invalid sync subnet {subnet}")
        self._subscriptions[subnet] = max(
            self._subscriptions.get(subnet, 0), until_epoch
        )

    def active_subnets(self, epoch: int) -> Set[int]:
        if self.all_subnets:
            return set(range(params.SYNC_COMMITTEE_SUBNET_COUNT))
        self.prune(epoch)
        return set(self._subscriptions)

    def prune(self, epoch: int) -> None:
        for subnet in [
            s for s, until in self._subscriptions.items() if until < epoch
        ]:
            del self._subscriptions[subnet]

    def metadata_syncnets(self, epoch: int) -> List[bool]:
        active = self.active_subnets(epoch)
        return [
            s in active for s in range(params.SYNC_COMMITTEE_SUBNET_COUNT)
        ]
