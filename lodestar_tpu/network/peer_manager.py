"""PeerManager — peer lifecycle: heartbeat, target count, ping/status.

Mirror of the reference's peer manager (reference:
packages/beacon-node/src/network/peers/peerManager.ts: the 30 s
heartbeat loop, ping/status timeouts, and utils/prioritizePeers.ts'
excess-peer pruning that protects subnet-duty peers and drops the
worst-scored first).  Discovery is an injected candidate source — the
discv5 UDP transport itself is off the TPU path (SURVEY §2.4 P6/P9);
anything that can produce (peer_id, connect_fn) pairs plugs in.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .peers import PeerAction, PeerScoreBook, PeerStatus, ScoreState
from .reqresp import ReqRespError

HEARTBEAT_INTERVAL_S = 30.0  # reference: peerManager.ts HEARTBEAT_INTERVAL_MS
PING_INTERVAL_INBOUND_S = 15.0  # reference: PING_INTERVAL_INBOUND_MS
PING_INTERVAL_OUTBOUND_S = 20.0
STATUS_INTERVAL_S = 300.0  # reference: STATUS_INTER_RELEVANT_PEERS_MS

# goodbye reason codes (p2p spec)
GOODBYE_CLIENT_SHUTDOWN = 1
GOODBYE_IRRELEVANT_NETWORK = 2
GOODBYE_ERROR = 3
GOODBYE_TOO_MANY_PEERS = 129
GOODBYE_BANNED = 251


@dataclass
class PeerData:
    """reference: peers/peersData.ts PeerData."""

    direction: str  # "inbound" | "outbound"
    connected_at: float
    last_ping: float = 0.0
    last_status: float = 0.0
    metadata: Optional[dict] = None  # {seq_number, attnets, syncnets}
    agent: str = ""


def prioritize_peers(
    connected: Sequence[Tuple[str, float, Sequence[int]]],
    active_subnets: Sequence[int],
    target_peers: int,
    max_peers: int,
) -> Tuple[int, List[str]]:
    """(peers_to_connect, peers_to_disconnect).

    Distills the reference's prioritizePeers.ts: below target -> how
    many to dial; above target -> drop the excess, worst score first,
    PROTECTING peers that serve subnets we actively need.
    `connected`: (peer_id, score, subnets_served)."""
    n = len(connected)
    if n < target_peers:
        return target_peers - n, []
    if n == target_peers:
        return 0, []
    needed = set(active_subnets)
    protected = set()
    # keep the best-scored provider per needed subnet
    for subnet in needed:
        best = None
        for pid, score, subnets in connected:
            if subnet in subnets and (best is None or score > best[1]):
                best = (pid, score)
        if best is not None:
            protected.add(best[0])
    excess = n - target_peers
    candidates = sorted(
        (p for p in connected if p[0] not in protected),
        key=lambda p: p[1],  # worst score first
    )
    drop = [pid for pid, _s, _n in candidates[:excess]]
    # the max_peers HARD cap overrides subnet protection: beyond it even
    # protected peers go, worst-scored first
    over_max = n - len(drop) - max_peers
    if over_max > 0:
        dropped = set(drop)
        rest = sorted(
            (p for p in connected if p[0] not in dropped),
            key=lambda p: p[1],
        )
        drop += [pid for pid, _s, _n in rest[:over_max]]
    return 0, drop


class PeerManager:
    """Owns the connected-peer set over a ReqRespBeaconNode.

    `discover(n) -> [(peer_id, connect_fn)]` supplies candidates;
    `connect_fn()` must wire the transport and return True on success
    (the in-memory bus pairs do this in tests; a real stack would dial).
    """

    def __init__(
        self,
        reqresp_node,
        score_book: Optional[PeerScoreBook] = None,
        target_peers: int = 55,  # reference: defaultNetworkOptions
        max_peers: int = 65,
        discover: Optional[Callable[[int], List]] = None,
        active_subnets_fn: Optional[Callable[[], Sequence[int]]] = None,
        clock=time.monotonic,
    ):
        self.node = reqresp_node
        self.reqresp = reqresp_node.reqresp
        self.score_book = score_book or PeerScoreBook()
        self.target_peers = target_peers
        self.max_peers = max_peers
        self.discover = discover
        self.active_subnets_fn = active_subnets_fn
        self.clock = clock
        self.peers: Dict[str, PeerData] = {}

    # -- connection lifecycle ----------------------------------------------

    def on_connect(
        self, peer_id: str, direction: str, send: Callable
    ) -> None:
        """Transport established: register + handshake (reference:
        onLibp2pPeerConnect -> requestStatus/Ping/Metadata)."""
        if direction == "inbound" and len(self.peers) >= self.max_peers:
            # hard inbound cap (reference: maxPeers gate on accept)
            self.reqresp.connect(peer_id, send)
            self.disconnect(peer_id, GOODBYE_TOO_MANY_PEERS)
            return
        self.reqresp.connect(peer_id, send)
        self.peers[peer_id] = PeerData(
            direction=direction, connected_at=self.clock()
        )
        try:
            self.request_status(peer_id)
            self.request_ping(peer_id)
        except Exception:  # noqa: BLE001 — ANY peer fault (malformed
            # SSZ included) ends the handshake, not just typed errors
            self.disconnect(peer_id, GOODBYE_ERROR)

    def disconnect(self, peer_id: str, reason: int) -> None:
        """Goodbye (best effort) + drop transport + forget."""
        try:
            self.reqresp.send_request(
                peer_id, self.node.protocols["goodbye"], reason
            )
        except Exception:  # noqa: BLE001 — goodbye is courtesy
            pass
        self.forget(peer_id)

    def forget(self, peer_id: str) -> None:
        """Drop transport + registry WITHOUT a goodbye — for remote-
        initiated goodbyes (the remote already left; sending one back
        would just error)."""
        self.reqresp.disconnect(peer_id)
        self.peers.pop(peer_id, None)
        # the score book forgets departed peers too (bans are retained
        # inside forget) — otherwise it grows one record per peer ever
        # seen under churn (cache-hygiene)
        self.score_book.forget(peer_id)

    @property
    def connected_peers(self) -> List[str]:
        return list(self.peers)

    # -- req/resp exchanges ------------------------------------------------

    @staticmethod
    def _one_chunk(chunks, what: str) -> bytes:
        """A single-response protocol MUST answer exactly one chunk; an
        empty stream is a peer fault, not an IndexError."""
        from .reqresp import RespCode

        if not chunks:
            raise ReqRespError(RespCode.SERVER_ERROR, f"empty {what} response")
        return chunks[0][0]

    def request_status(self, peer_id: str) -> None:
        chunks = self.reqresp.send_request(
            peer_id, self.node.protocols["status"], self.node._local_status()
        )
        from .reqresp_protocols import StatusType

        st = StatusType.deserialize(self._one_chunk(chunks, "status"))
        self.score_book.on_status(
            peer_id,
            PeerStatus(
                fork_digest=bytes(st["fork_digest"]),
                finalized_root=bytes(st["finalized_root"]),
                finalized_epoch=int(st["finalized_epoch"]),
                head_root=bytes(st["head_root"]),
                head_slot=int(st["head_slot"]),
            ),
        )
        if peer_id in self.peers:
            self.peers[peer_id].last_status = self.clock()

    def request_ping(self, peer_id: str) -> None:
        """Ping; a seq ahead of our cached metadata triggers a metadata
        re-fetch (reference: onPing -> requestMetadata on seq bump)."""
        md = self.node.metadata_fn() if self.node.metadata_fn else {"seq_number": 0}
        chunks = self.reqresp.send_request(
            peer_id, self.node.protocols["ping"], int(md["seq_number"])
        )
        seq = int.from_bytes(self._one_chunk(chunks, "ping"), "little")
        data = self.peers.get(peer_id)
        if data is not None:
            data.last_ping = self.clock()
            known = (
                int(data.metadata["seq_number"]) if data.metadata else -1
            )
            if seq > known:
                self.request_metadata(peer_id)

    def request_metadata(self, peer_id: str) -> None:
        from .reqresp_protocols import METADATA_TYPE

        chunks = self.reqresp.send_request(
            peer_id, self.node.protocols["metadata"]
        )
        if peer_id in self.peers:
            self.peers[peer_id].metadata = METADATA_TYPE.deserialize(
                self._one_chunk(chunks, "metadata")
            )

    # -- the heartbeat (reference: peerManager.ts heartbeat) ---------------

    def heartbeat(self) -> dict:
        """One maintenance pass; returns what it did (observability)."""
        actions = {"banned": [], "dialed": 0, "pruned": []}
        # score-book hygiene: records untouched for hours (incl. the
        # bans forget() retains) decay to irrelevance and drop here —
        # without this, one record per banned identity EVER seen
        # survives the process lifetime (cache-hygiene)
        self.score_book.prune_stale()
        # 1. drop banned/disconnect-scored peers
        for pid in list(self.peers):
            state = self.score_book.state(pid)
            if state is ScoreState.banned:
                self.disconnect(pid, GOODBYE_BANNED)
                actions["banned"].append(pid)
            elif state is ScoreState.disconnected:
                self.disconnect(pid, GOODBYE_ERROR)
                actions["banned"].append(pid)
        # 2. below target: dial discovered candidates
        subnets = (
            list(self.active_subnets_fn()) if self.active_subnets_fn else []
        )
        to_connect, to_disconnect = prioritize_peers(
            [
                (pid, self.score_book.score(pid), self._peer_subnets(pid))
                for pid in self.peers
            ],
            subnets,
            self.target_peers,
            self.max_peers,
        )
        if to_connect and self.discover is not None:
            for peer_id, connect_fn in self.discover(to_connect):
                if peer_id in self.peers:
                    continue
                # never dial a peer the score book still condemns
                if self.score_book.state(peer_id) is not ScoreState.healthy:
                    continue
                send = connect_fn()
                if send is not None:
                    self.on_connect(peer_id, "outbound", send)
                    # a failed handshake disconnects inside on_connect —
                    # only a peer that SURVIVED counts toward the target
                    if peer_id in self.peers:
                        actions["dialed"] += 1
                if actions["dialed"] >= to_connect:
                    break
        # 3. above target: prune the worst-scored unprotected peers
        for pid in to_disconnect:
            self.disconnect(pid, GOODBYE_TOO_MANY_PEERS)
            actions["pruned"].append(pid)
        return actions

    def _peer_subnets(self, peer_id: str) -> List[int]:
        md = self.peers[peer_id].metadata
        if not md:
            return []
        return [i for i, bit in enumerate(md.get("attnets", [])) if bit]

    def ping_and_status_timeouts(self) -> None:
        """Re-ping / re-status stale peers (reference:
        pingAndStatusTimeouts, CHECK_PING_STATUS_INTERVAL)."""
        now = self.clock()
        for pid, data in list(self.peers.items()):
            interval = (
                PING_INTERVAL_INBOUND_S
                if data.direction == "inbound"
                else PING_INTERVAL_OUTBOUND_S
            )
            try:
                if now - data.last_ping > interval:
                    self.request_ping(pid)
                if now - data.last_status > STATUS_INTERVAL_S:
                    self.request_status(pid)
            except Exception:  # noqa: BLE001 — a peer answering garbage
                # is a peer fault; isolate it and penalize
                self.score_book.apply_action(pid, PeerAction.low_tolerance)

    def close(self) -> None:
        for pid in list(self.peers):
            self.disconnect(pid, GOODBYE_CLIENT_SHUTDOWN)
