"""Per-topic gossip handlers: bytes -> SSZ -> validator -> side effects.

Mirror of the reference's gossipHandlers.ts (reference:
packages/beacon-node/src/network/processor/gossipHandlers.ts): each
topic maps to an SSZ type, a validator from chain/validation, and the
ACCEPT-side effects (which the validators already apply — pool inserts,
fork-choice updates).  Handlers return the GossipAction verdict so the
bus/peer layer can score the sender (gossipsub REJECT/IGNORE).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from .. import types as T
from ..chain.regen import RegenError
from ..chain.seen_cache import SeenBlockProposers
from ..chain.validation import (
    GossipAction,
    GossipValidationError,
    GossipValidators,
)
from ..utils.logger import get_logger
from .forwarding import PACKED_AGGREGATOR_INDEX, aggfwd_enabled
from .gossip import (
    GossipTopicName,
    InMemoryGossipBus,
    decode_message,
    parse_topic,
    topic_string,
)


class GossipHandlers:
    """Binds a chain's validators to the gossip bus.

    `results` counts verdicts per topic for tests/metrics; invalid
    payload bytes (bad snappy / bad SSZ) are REJECTs, like the
    reference's message deserialization errors.
    """

    def __init__(
        self,
        chain,
        verifier,
        current_slot_fn=None,
        kzg_setup=None,
        bls_service=None,
    ):
        self.chain = chain
        # `bls_service` (the node's BlsVerifierService/pipeline) routes
        # block-critical verifications onto the 25 ms critical lane
        # (validation.py `_verify(priority=True)`); without one, every
        # verification stays on the raw verifier exactly as before
        self.validators = GossipValidators(
            chain,
            verifier,
            current_slot_fn=current_slot_fn,
            bls_service=bls_service,
        )
        self.log = get_logger("network/gossip_handlers")
        self.seen_block_proposers = SeenBlockProposers()
        # optional SlasherService: every VERIFIED attestation/aggregate/
        # block is ingested post-validation (slasher/service.py)
        self.slasher = None
        self.results: Dict[str, Dict[str, int]] = {}  # tpulint: disable=cache-hygiene -- verdict tallies keyed (topic kind, verdict): both key spaces are enum-bounded, values are counters
        self._last_pruned_slot = 0
        # deneb blob verification needs a KZG trusted setup; without one
        # the blob topics are not served
        self.kzg_setup = kzg_setup
        # optional {verdict: LabeledCounter} incremented at the source
        # (utils/beacon_metrics.py observe_gossip)
        self.verdict_counters = None
        # aggregate-forward gossip (ISSUE 19): with the flag on AND a
        # bls service wired, subnet attestation verdicts defer through
        # the pipeline standard lane; LODESTAR_TPU_BLS_AGGFWD=0 keeps
        # the raw-sync path bit-for-bit
        self.aggfwd = aggfwd_enabled()
        # optional network/forwarding.DeferredForwardQueue (the node
        # wires the processor's): bounds in-flight deferrals with
        # per-slot expiry + shed charging
        self.deferred_forwards = None
        # live subnet-subscription state (set by subscribe_all, diffed
        # by sync_subnet_subscriptions on slot ticks)
        self._bus = None
        self._bus_node_id = None
        self._bus_digest = None
        self._bus_scorer = None
        self._subscribed_attnets: set = set()
        self._subscribed_syncnets: set = set()

    def _block_is_timely(self, slot: int) -> bool:
        """Measured arrival delay < 1/3 slot (reference: forkChoice.ts
        onBlock blockDelaySec) — never a static flag, or a withheld
        block could claim the proposer boost."""
        import time as _time

        from .. import params as _p

        genesis_time = getattr(self.chain.config, "genesis_time", None)
        if not genesis_time:
            return False
        delay = _time.time() - (genesis_time + slot * _p.SECONDS_PER_SLOT)
        return 0 <= delay < _p.SECONDS_PER_SLOT / 3

    # -- dispatch ----------------------------------------------------------

    def _signed_block_type_for_digest(self, digest: bytes):
        """Fork dispatch from the topic's fork digest (gossip topics are
        per-fork; reference: gossip/topic.ts sszType selection)."""
        from .. import params as _p

        cfg = self.chain.config
        for fork, epoch in cfg.fork_epochs.items():
            slot = epoch * _p.SLOTS_PER_EPOCH
            try:
                if cfg.fork_digest(slot) == digest:
                    return cfg.get_fork_types(slot)[1]
            except Exception:  # unscheduled fork (FAR_FUTURE overflow)
                continue
        return T.SignedBeaconBlockAltair

    def handle(self, topic: str, data: bytes, peer_id=None):
        """Returns None on ACCEPT, the failure GossipAction, or a
        DeferredVerdict when the verdict resolves asynchronously (the
        bus registers its scoring continuation on it).  `peer_id` names
        the propagation source so a shed deferral can charge its
        publisher."""
        from ..observability import trace_span

        digest, name = parse_topic(topic)
        # the ROOT of the gossip->verify->import span tree: everything
        # a message costs (decode, validation, BLS, a block's full
        # import) nests under this span in the Chrome trace
        with trace_span("gossip.handle", topic=name) as span:
            try:
                payload = decode_message(data)
                action = self._dispatch(name, payload, digest)
            except GossipValidationError as e:
                span.set(verdict=e.action.value)
                self._count(name, e.action.value)
                self.log.debug("gossip rejected", topic=name, reason=e.reason)
                return e.action
            except Exception as e:  # undecodable payload or import failure
                span.set(verdict="reject")
                self._count(name, "reject")
                self.log.debug("gossip undecodable", topic=name, error=str(e))
                return GossipAction.REJECT
            if action is not None and hasattr(action, "on_resolve"):
                # asynchronously verdict-gated (ISSUE 19): the span
                # closes now; counting fires on verdict resolution, and
                # the deferred-forward queue bounds/expires the wait
                span.set(verdict="deferred")
                if self.deferred_forwards is not None:
                    self.deferred_forwards.register(
                        action, peer_id=peer_id, topic=name
                    )
                action.on_resolve(
                    lambda verdict, name=name: self._count(
                        name,
                        "accept" if verdict is None else verdict.value,
                    )
                )
                return action
            span.set(verdict="accept")
            self._count(name, "accept")
            return action

    def _count(self, name: str, verdict: str) -> None:
        self.results.setdefault(name, {}).setdefault(verdict, 0)
        self.results[name][verdict] += 1
        if self.verdict_counters is not None:
            counter = self.verdict_counters.get(verdict)
            if counter is not None:
                counter.inc(name, 1.0)

    def _prune(self, slot: int) -> None:
        if slot > self._last_pruned_slot:
            self._last_pruned_slot = slot
            self.seen_block_proposers.prune(slot)
            self.validators.prune(slot)

    def on_clock_slot(self, slot: int) -> None:
        """Wire to the node Clock; also called opportunistically when an
        imported block advances the slot, so caches are bounded even in
        clock-less compositions."""
        self._prune(slot)

    def set_forwarder(self, forwarder) -> None:
        """Wire the AggregateForwarder (network/forwarding.py):
        attestation pre-checks then register (signing root ->
        committee) so verified layers can re-pack onto the aggregate
        topic."""
        self.validators.forwarder = forwarder

    def _slasher_ingest(self, fn, obj) -> None:
        """An internal slasher/db fault must never become a gossip
        verdict: the message already VALIDATED, and a raised exception
        here would REJECT-score the honest forwarding peer."""
        try:
            fn(obj)
        except Exception as e:  # noqa: BLE001
            self.log.warn("slasher ingestion failed", error=str(e))

    def _ingest_duplicate_proposer_block(self, signed: dict) -> None:
        """Verify a duplicate-proposer block's signature, then feed the
        slasher as TRUSTED (the only unverified field left is content
        the slashing dry-run re-checks anyway)."""
        from .. import params as _p
        from ..bls.signature_set import WireSignatureSet

        block = signed["message"]
        slot = int(block["slot"])
        proposer = int(block["proposer_index"])
        cfg = self.chain.config
        root = cfg.compute_signing_root(
            cfg.get_fork_types(slot)[0].hash_tree_root(block),
            cfg.get_domain(slot, _p.DOMAIN_BEACON_PROPOSER, slot),
        )
        # a proposer signature is a critical-lane verification whenever
        # the service is wired (same lane-routing seam as aggregates)
        ok = self.validators._verify_ok(
            [WireSignatureSet.single(proposer, root, bytes(signed["signature"]))],
            priority=True,
        )
        if ok:
            self.slasher.ingest_block(signed, trusted=True)

    def _recover_suppressed_double_vote(self, attestation: dict) -> None:
        """A gossip attestation the seen caches IGNORE can still be the
        second half of a DOUBLE VOTE (same target epoch => same seen-
        cache key), exactly like the duplicate-proposer block branch.
        Pay for a committee lookup, and — only when the slasher already
        holds a CONFLICTING root for the validator at that target
        (service gate, attempt-bounded) — one signature verification
        before ingesting.  Surround votes have distinct target epochs
        and are never suppressed, so this path is double-vote-only."""
        from ..bls.verifier import VerifyOptions
        from ..state_transition.signature_sets import (
            get_indexed_attestation_signature_set,
        )

        data = attestation["data"]
        target = int(data["target"]["epoch"])
        root = bytes(T.AttestationData.hash_tree_root(data))
        view = self.validators._view()
        indexed = view.get_indexed_attestation(attestation)
        if not any(
            self.slasher.should_check_equivocation(int(i), target, root)
            for i in indexed["attesting_indices"]
        ):
            return
        sset = get_indexed_attestation_signature_set(view, indexed)
        # a suppressed duplicate usually IS a message the pre-verify
        # aggregation stage already judged (same data root => same
        # bucket): serve the verdict from its seen-map — exact
        # (root, indices, signature) match only, so a forged duplicate
        # can never ride an honest verdict — and pay the standalone
        # verification only on a miss (ISSUE 13 satellite)
        ok = None
        service = getattr(self.validators, "service", None)
        lookup = getattr(service, "preagg_verdict", None)
        if lookup is not None:
            ok = lookup(sset)
        if ok is None:
            ok = self.validators.verifier.verify_signature_sets(
                [sset], VerifyOptions(batchable=True)
            )
        self.slasher.record_equivocation_probe(
            indexed["attesting_indices"], target, root, bool(ok)
        )
        if ok:
            self.slasher.ingest_attestation(indexed)

    def _dispatch(self, name: str, payload: bytes, digest: bytes) -> None:
        v = self.validators
        if name == "beacon_block":
            from ..execution import ExecutionEngineUnavailable

            signed = self._signed_block_type_for_digest(digest).deserialize(
                payload
            )
            slot = int(signed["message"]["slot"])
            proposer = int(signed["message"]["proposer_index"])
            # one block per proposer per slot at the gossip layer
            # (reference: validation/block.ts seenBlockProposers check)
            if self.seen_block_proposers.is_known(slot, proposer):
                # a SECOND block for the same (slot, proposer) is exactly
                # the equivocation a slasher exists for — ingest the
                # header before IGNORE-ing (lighthouse ingests on
                # RepeatProposal too).  The proposer signature is
                # verified FIRST (one BLS op against the known pubkey):
                # forged duplicates never reach the slasher, so they can
                # neither exhaust its rejection cap nor cost STF
                # dry-runs downstream.
                if self.slasher is not None:
                    self._slasher_ingest(
                        self._ingest_duplicate_proposer_block, signed
                    )
                raise GossipValidationError(
                    GossipAction.IGNORE, "proposer already seen this slot"
                )
            from ..chain.chain import BlobsUnavailableError

            try:
                self.chain.process_block(
                    signed, timely=self._block_is_timely(slot)
                )
            except (
                RegenError,
                ExecutionEngineUnavailable,
                BlobsUnavailableError,
            ) as e:
                # unknown parent / missing state / EL outage / blobs not
                # yet available: not the sender's fault — IGNORE (and
                # park for reprocess at the processor layer), never
                # penalize (p2p spec IGNORE conditions)
                raise GossipValidationError(
                    GossipAction.IGNORE, f"not verifiable now: {e}"
                )
            self.seen_block_proposers.add(slot, proposer)
            self._prune(slot)
            return None
        if name == "beacon_aggregate_and_proof":
            signed_agg = T.SignedAggregateAndProof.deserialize(payload)
            if (
                self.aggfwd
                and v.service is not None
                and int(signed_agg["message"]["aggregator_index"])
                == PACKED_AGGREGATOR_INDEX
            ):
                # a re-published packed layer (network/forwarding.py):
                # no real aggregator/selection proof to check — the
                # inner aggregated signature re-verifies through the
                # standard lane and the verdict defers.  With aggfwd
                # off, the sentinel falls through to the normal
                # validator and REJECTs (never in any committee).
                return v.validate_packed_aggregate(signed_agg)
            try:
                indexed = v.validate_aggregate_and_proof(signed_agg)
            except GossipValidationError as e:
                if e.action == GossipAction.IGNORE and self.slasher is not None:
                    self._slasher_ingest(
                        self._recover_suppressed_double_vote,
                        signed_agg["message"]["aggregate"],
                    )
                raise
            if self.slasher is not None:
                self._slasher_ingest(self.slasher.ingest_attestation, indexed)
            return None
        if name.startswith("beacon_attestation_"):
            subnet = int(name.rsplit("_", 1)[1])
            attestation = T.Attestation.deserialize(payload)
            if self.aggfwd and v.service is not None:
                # the ISSUE 19 tentpole: the signature rides the
                # pipeline standard lane (coalescing + pre-verify
                # aggregation) and the forward/score decision is a
                # continuation on the returned DeferredVerdict.
                # Slasher side effects keep their current ordering via
                # the accept/suppressed callbacks.
                on_accept = on_suppressed = None
                if self.slasher is not None:
                    on_accept = lambda indexed: self._slasher_ingest(  # noqa: E731
                        self.slasher.ingest_attestation, indexed
                    )
                    on_suppressed = lambda att: self._slasher_ingest(  # noqa: E731
                        self._recover_suppressed_double_vote, att
                    )
                try:
                    return v.validate_attestation_async(
                        attestation,
                        subnet=subnet,
                        on_accept=on_accept,
                        on_suppressed=on_suppressed,
                    )
                except GossipValidationError as e:
                    if (
                        e.action == GossipAction.IGNORE
                        and self.slasher is not None
                    ):
                        self._slasher_ingest(
                            self._recover_suppressed_double_vote, attestation
                        )
                    raise
            try:
                indexed = v.validate_attestation(attestation, subnet=subnet)
            except GossipValidationError as e:
                if e.action == GossipAction.IGNORE and self.slasher is not None:
                    self._slasher_ingest(
                        self._recover_suppressed_double_vote, attestation
                    )
                raise
            if self.slasher is not None:
                self._slasher_ingest(self.slasher.ingest_attestation, indexed)
            return None
        if name == "voluntary_exit":
            v.validate_voluntary_exit_gossip(
                T.SignedVoluntaryExit.deserialize(payload)
            )
            return None
        if name == "proposer_slashing":
            v.validate_proposer_slashing_gossip(
                T.ProposerSlashing.deserialize(payload)
            )
            return None
        if name == "attester_slashing":
            v.validate_attester_slashing_gossip(
                T.AttesterSlashing.deserialize(payload)
            )
            return None
        if name == "sync_committee_contribution_and_proof":
            v.validate_contribution_and_proof(
                T.SignedContributionAndProof.deserialize(payload)
            )
            return None
        if name == "bls_to_execution_change":
            v.validate_bls_to_execution_change_gossip(
                T.SignedBLSToExecutionChange.deserialize(payload)
            )
            return None
        if name.startswith("blob_sidecar_"):
            subnet = int(name.rsplit("_", 1)[1])
            self.handle_blob_sidecar(
                T.BlobSidecar.deserialize(payload), subnet
            )
            return None
        if name.startswith("sync_committee_"):
            subnet = int(name.rsplit("_", 1)[1])
            v.validate_sync_committee_message(
                T.SyncCommitteeMessage.deserialize(payload), subnet
            )
            return None
        raise GossipValidationError(
            GossipAction.REJECT, f"no handler for topic {name}"
        )

    def handle_blob_sidecar(self, sidecar: dict, subnet: int) -> None:
        """The blob_sidecar_{subnet} topic body (value level, so tests
        at non-preset blob widths can drive it without SSZ)."""
        if self.kzg_setup is None:
            raise GossipValidationError(
                GossipAction.IGNORE, "no KZG setup loaded"
            )
        if int(sidecar["index"]) != subnet:
            # sidecars ride the subnet of their own index (p2p spec)
            raise GossipValidationError(
                GossipAction.REJECT, "sidecar index != subnet"
            )
        self.validators.validate_blob_sidecar(sidecar, self.kzg_setup)

    # -- subscriptions (reference: network.ts subscribeGossipCoreTopics) ---

    def subscribe_all(
        self,
        bus: InMemoryGossipBus,
        node_id: str,
        fork_digest: bytes,
        attnets: Tuple[int, ...] = (0,),
        syncnets: Tuple[int, ...] = (0,),
        scorer=None,
    ) -> None:
        topics = [
            topic_string(fork_digest, GossipTopicName.beacon_block),
            topic_string(
                fork_digest, GossipTopicName.beacon_aggregate_and_proof
            ),
            topic_string(fork_digest, GossipTopicName.voluntary_exit),
            topic_string(fork_digest, GossipTopicName.proposer_slashing),
            topic_string(fork_digest, GossipTopicName.attester_slashing),
            topic_string(
                fork_digest,
                GossipTopicName.sync_committee_contribution_and_proof,
            ),
        ]
        topics += [
            topic_string(
                fork_digest, GossipTopicName.beacon_attestation, subnet=s
            )
            for s in attnets
        ]
        topics += [
            topic_string(fork_digest, GossipTopicName.sync_committee, subnet=s)
            for s in syncnets
        ]
        # capella-era topics (per-fork topic sets; reference: forks.ts
        # getCoreTopicsAtFork — harmless pre-fork on the bus transport)
        from .. import params as _p

        topics.append(
            topic_string(fork_digest, GossipTopicName.bls_to_execution_change)
        )
        if self.kzg_setup is not None:
            topics += [
                topic_string(
                    fork_digest, GossipTopicName.blob_sidecar, subnet=i
                )
                for i in range(_p.MAX_BLOBS_PER_BLOCK)
            ]
        for t in topics:
            bus.subscribe(
                node_id, t, self.handle, scorer=scorer, wants_peer=True
            )
        self._bus = bus
        self._bus_node_id = node_id
        self._bus_digest = fork_digest
        self._bus_scorer = scorer
        self._subscribed_attnets = set(attnets)
        self._subscribed_syncnets = set(syncnets)

    def sync_subnet_subscriptions(self, attnets, syncnets) -> None:
        """Diff the CURRENT policy-active subnets against what is live on
        the bus, subscribing/unsubscribing the delta.  This is the live
        leg the reference drives from attnetsService's subscription
        events (reference: attnetsService.ts onSlot -> gossip.subscribe
        TopicSubscription churn) — without it, duty subscriptions made
        after init (REST beacon_committee_subscriptions, sync-committee
        duty windows) never reach the transport and long-lived subnets
        never rotate."""
        if self._bus is None:
            return
        want_att, want_sync = set(attnets), set(syncnets)
        for want, have, topic_name in (
            (want_att, self._subscribed_attnets,
             GossipTopicName.beacon_attestation),
            (want_sync, self._subscribed_syncnets,
             GossipTopicName.sync_committee),
        ):
            for s in want - have:
                self._bus.subscribe(
                    self._bus_node_id,
                    topic_string(self._bus_digest, topic_name, subnet=s),
                    self.handle,
                    scorer=self._bus_scorer,
                    wants_peer=True,
                )
            for s in have - want:
                self._bus.unsubscribe(
                    self._bus_node_id,
                    topic_string(self._bus_digest, topic_name, subnet=s),
                )
        self._subscribed_attnets = want_att
        self._subscribed_syncnets = want_sync
