"""Network-side scheduling components: gossip queues + processor.

Only the scheduling layer is reproduced here — the libp2p/gossipsub
transport itself stays off the TPU path (SURVEY.md §2.4 P9).
"""

from .gossip_queues import (  # noqa: F401
    GossipQueue,
    GossipType,
    create_gossip_queues,
)
from .processor import (  # noqa: F401
    NetworkProcessor,
    PendingGossipMessage,
)
