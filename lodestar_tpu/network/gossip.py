"""Gossip topics, message encoding, and an in-memory pubsub bus.

Reference: packages/beacon-node/src/network/gossip/topic.ts (topic
strings `/eth2/{forkDigest}/{name}/ssz_snappy`), gossip/encoding.ts
(raw-snappy payloads; altair message-id =
sha256(MESSAGE_DOMAIN_VALID_SNAPPY + len(topic)_8le + topic +
decompressed)[:20]), and gossip/gossipsub.ts (publish/subscribe over
topic meshes).  The wire transport (libp2p) stays out of scope
(SURVEY.md §2.4 P9); `InMemoryGossipBus` provides the same
publish/subscribe/seen-dedup semantics in process so multi-node flows
are testable end to end.
"""

from __future__ import annotations

import enum
import hashlib
from collections import defaultdict
from typing import Callable, Dict, List, Optional, Tuple

from . import snappy as S
from ..utils.logger import get_logger

MESSAGE_DOMAIN_VALID_SNAPPY = bytes.fromhex("01000000")
MESSAGE_DOMAIN_INVALID_SNAPPY = bytes.fromhex("00000000")


class GossipTopicName(str, enum.Enum):
    beacon_block = "beacon_block"
    beacon_aggregate_and_proof = "beacon_aggregate_and_proof"
    beacon_attestation = "beacon_attestation_{subnet}"
    voluntary_exit = "voluntary_exit"
    proposer_slashing = "proposer_slashing"
    attester_slashing = "attester_slashing"
    sync_committee_contribution_and_proof = (
        "sync_committee_contribution_and_proof"
    )
    sync_committee = "sync_committee_{subnet}"
    light_client_finality_update = "light_client_finality_update"
    light_client_optimistic_update = "light_client_optimistic_update"
    # capella (reference: gossip/interface.ts GossipType additions)
    bls_to_execution_change = "bls_to_execution_change"
    # deneb: one subnet per blob index
    blob_sidecar = "blob_sidecar_{subnet}"


def topic_string(
    fork_digest: bytes, name: GossipTopicName, subnet: Optional[int] = None
) -> str:
    """`/eth2/{digest}/{name}/ssz_snappy` (reference topic.ts)."""
    base = name.value
    if "{subnet}" in base:
        if subnet is None:
            raise ValueError(f"{name} requires a subnet")
        base = base.format(subnet=subnet)
    return f"/eth2/{fork_digest.hex()}/{base}/ssz_snappy"


def parse_topic(topic: str) -> Tuple[bytes, str]:
    """-> (fork_digest, topic name with subnet suffix)."""
    parts = topic.split("/")
    if (
        len(parts) != 5
        or parts[1] != "eth2"
        or parts[4] != "ssz_snappy"
    ):
        raise ValueError(f"malformed gossip topic {topic}")
    return bytes.fromhex(parts[2]), parts[3]


# one gossip size cap shared by decode and message-id classification
GOSSIP_MAX_UNCOMPRESSED = 1 << 23


def encode_message(ssz_bytes: bytes) -> bytes:
    """Gossip payloads are RAW snappy blocks (encoding.ts)."""
    return S.compress(ssz_bytes)


def decode_message(data: bytes, max_len: int = GOSSIP_MAX_UNCOMPRESSED) -> bytes:
    return S.decompress(data, max_len)


def compute_message_id(
    topic: str, data: bytes, max_len: int = GOSSIP_MAX_UNCOMPRESSED
) -> bytes:
    """altair message-id (encoding.ts:51-58); falls back to the
    invalid-snappy domain over the raw data when decompression fails
    OR the declared size exceeds the gossip cap (so the id
    classification always agrees with what decode_message accepts)."""
    topic_bytes = topic.encode()
    try:
        payload = S.decompress(data, max_len)
        vec = (
            MESSAGE_DOMAIN_VALID_SNAPPY
            + len(topic_bytes).to_bytes(8, "little")
            + topic_bytes
            + payload
        )
    except S.SnappyError:
        vec = (
            MESSAGE_DOMAIN_INVALID_SNAPPY
            + len(topic_bytes).to_bytes(8, "little")
            + topic_bytes
            + data
        )
    return hashlib.sha256(vec).digest()[:20]


class InMemoryGossipBus:
    """Topic fanout with per-node handlers and seen-message dedup —
    the gossipsub mesh semantics without the libp2p wire.  Seen caches
    are FIFO-bounded per node (gossipsub's seenCache is TTL-bounded;
    a count bound gives the same no-unbounded-growth property here)."""

    SEEN_CAP = 8192

    def __init__(self, seen_cap: int = SEEN_CAP):
        from collections import deque

        self.seen_cap = seen_cap
        # topic -> [(node_id, handler, scorer-or-None, wants_peer)]
        self._subs: Dict[str, List[Tuple[str, Callable, object]]] = defaultdict(list)
        self._seen: Dict[str, set] = defaultdict(set)
        self._seen_order: Dict[str, "deque"] = defaultdict(deque)
        self.log = get_logger("network/gossip")
        self.published = 0
        self.delivered = 0
        self.duplicates = 0
        self.graylisted = 0
        # fault injection (ISSUE 14 chaos harness): an optional link
        # filter decides per (from, to, topic) whether delivery happens
        # — partitions, lossy links, and targeted blackholes all script
        # through it; `partitioned` counts what it suppressed
        self._link_filter: Optional[Callable[[str, str, str], bool]] = None
        self.partitioned = 0

    # -- fault injection (chaos harness) -----------------------------------

    def set_link_filter(
        self, fn: Optional[Callable[[str, str, str], bool]]
    ) -> None:
        """`fn(from_node, to_node, topic) -> deliver?`; None heals."""
        self._link_filter = fn

    def set_partitions(self, groups) -> None:
        """Partition the mesh: delivery only WITHIN a group.  A
        publisher alias of the form "<node>:<role>" (e.g.
        "node-1:val-3") partitions with its owning node; ids not
        resolvable to any group keep full connectivity."""
        membership: Dict[str, int] = {}
        for gi, group in enumerate(groups):
            for node in group:
                membership[node] = gi

        def _resolve(n: str):
            if n in membership:
                return membership[n]
            return membership.get(n.split(":", 1)[0])

        def _filter(src: str, dst: str, _topic: str) -> bool:
            a, b = _resolve(src), _resolve(dst)
            if a is None or b is None:
                return True
            return a == b

        self.set_link_filter(_filter)

    def heal(self) -> None:
        """Clear any partition/link fault (deliveries resume; seen
        caches are untouched, exactly like a real partition heal —
        missed messages stay missed until sync recovers them)."""
        self._link_filter = None

    def drop_node(self, node_id: str) -> None:
        """Simulate a node crash: remove every subscription and the
        seen cache (a restarted process remembers nothing)."""
        for topic in list(self._subs):
            self._subs[topic] = [
                e for e in self._subs[topic] if e[0] != node_id
            ]
        self._seen.pop(node_id, None)
        self._seen_order.pop(node_id, None)

    def _mark_seen(self, node_id: str, msg_id: bytes) -> None:
        seen = self._seen[node_id]
        if msg_id in seen:
            return
        seen.add(msg_id)
        order = self._seen_order[node_id]
        order.append(msg_id)
        while len(order) > self.seen_cap:
            seen.discard(order.popleft())

    @staticmethod
    def _accepts_peer(handler: Callable) -> bool:
        """Does the handler take a third REQUIRED positional arg (the
        publisher id)?  Decided ONCE at subscribe time — deferred-verdict
        sheds charge the publisher through such handlers; plain
        `(topic, data)` handlers keep working unchanged.  Defaulted
        params never count: a closure-bound capture (`lambda t, d, n=n`)
        must not have its binding clobbered by the publisher id —
        handlers whose peer slot carries a default (GossipHandlers.handle's
        `peer_id=None`) opt in with `subscribe(..., wants_peer=True)`."""
        import inspect

        try:
            sig = inspect.signature(handler)
        except (TypeError, ValueError):
            return False
        if any(
            p.kind == p.VAR_POSITIONAL for p in sig.parameters.values()
        ):
            return True
        required = [
            p
            for p in sig.parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
            and p.default is p.empty
        ]
        return len(required) >= 3

    def subscribe(
        self,
        node_id: str,
        topic: str,
        handler: Callable,
        scorer=None,
        wants_peer: Optional[bool] = None,
    ) -> None:
        if wants_peer is None:
            wants_peer = self._accepts_peer(handler)
        self._subs[topic].append((node_id, handler, scorer, wants_peer))

    def unsubscribe(self, node_id: str, topic: str) -> None:
        self._subs[topic] = [
            entry for entry in self._subs[topic] if entry[0] != node_id
        ]

    def publish(self, from_node: str, topic: str, data: bytes) -> int:
        """Deliver to every OTHER subscriber that has not seen the id.

        A subscriber registered with `scorer=` has the sender judged on
        every delivery: handler verdicts feed the gossipsub scoring
        policy, and messages from banned senders are dropped at the
        mesh edge (gossipsub graylisting)."""
        msg_id = compute_message_id(topic, data)
        self.published += 1
        # the publisher has seen its own message: a relayed copy must
        # not echo back (gossipsub inserts published ids into seenCache)
        self._mark_seen(from_node, msg_id)
        delivered = 0
        for node_id, handler, scorer, wants_peer in list(self._subs[topic]):
            if node_id == from_node:
                continue
            if scorer is not None and scorer.is_banned(from_node):
                self.graylisted += 1
                continue
            if self._link_filter is not None and not self._link_filter(
                from_node, node_id, topic
            ):
                self.partitioned += 1
                continue
            if msg_id in self._seen[node_id]:
                self.duplicates += 1
                continue
            self._mark_seen(node_id, msg_id)
            try:
                if wants_peer:
                    verdict = handler(topic, data, from_node)
                else:
                    verdict = handler(topic, data)
                delivered += 1
                self.delivered += 1
                if scorer is not None:
                    on_resolve = getattr(verdict, "on_resolve", None)
                    if on_resolve is not None:
                        # asynchronously verdict-gated (ISSUE 19): the
                        # sender is scored when the verdict lands; a
                        # dropped deferral (slot expiry, shed) never
                        # fires, so a late verdict neither forwards nor
                        # scores
                        on_resolve(
                            lambda v, fn=from_node, t=topic, s=scorer: (
                                s.on_verdict(fn, t, v)
                            )
                        )
                    else:
                        scorer.on_verdict(from_node, topic, verdict)
            except Exception as e:  # noqa: BLE001 - subscriber isolation
                self.log.warn(
                    "gossip handler failed", topic=topic, error=str(e)
                )
        return delivered
