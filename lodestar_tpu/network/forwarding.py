"""Aggregate-forward gossip: deferred forward verdicts + packed
re-publication (ISSUE 19 tentpole).

PR 13's PreVerifyAggregator spends the aggregated-signature-gossip
insight (arXiv:1911.04698) only on OUR verification cost — every
downstream peer still receives and verifies the full flood of
overlapping subnet attestations, and the committee-consensus
measurements (arXiv:2302.00418) locate per-message signature work as
exactly what caps node count.  This module moves the win into the
network plane, in two coupled pieces:

  - **Deferred forward verdicts.**  Subnet attestation handlers no
    longer block on the raw verifier for the gossip forward/score
    decision: validation returns a `DeferredVerdict` and the signature
    rides the pipeline's standard lane (coalescing + pre-verify
    aggregation), with the forward/score decision a continuation fired
    on verdict resolution.  `DeferredForwardQueue` (owned by the
    NetworkProcessor) bounds the number of in-flight deferrals with
    per-slot expiry — a verdict resolving after its slot's forward
    window DROPS instead of forwarding a stale attestation, and a
    backpressure shed releases its deferred slot while charging the
    publisher (gossipsub P7, like any other shed).
  - **Aggregate-forward.**  Every verified multi-member disjoint-index
    layer the PreVerifyAggregator produces is re-packed into a
    `SignedAggregateAndProof`-shaped message under the reserved
    `PACKED_AGGREGATOR_INDEX` sentinel and re-published on the
    aggregate topic: downstream peers receive — and verify — ONE
    aggregated set per (root, layer) instead of dozens of overlapping
    singles.  The bus marks the publisher as having seen its own
    message id, so a re-published pack never echoes back for
    re-verification and is never charged to a peer.

Soundness (README "Aggregate-forward gossip"): only layers the device
already VERIFIED are re-published, their index sets are pairwise
disjoint within a layer by construction (plan_disjoint_gathers), and
receivers re-verify the packed signature themselves — the pack is a
bandwidth/verification optimization, never a trust assertion.

Escape hatch: `LODESTAR_TPU_BLS_AGGFWD=0` restores the raw-sync subnet
handler behaviour bit-for-bit (no deferrals, no re-publication).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logger import get_logger

# Reserved aggregator-index sentinel for re-published packed layers: no
# real validator can hold uint64-max (VALIDATOR_REGISTRY_LIMIT is 2^40),
# so receivers can dispatch packed messages without ambiguity and nodes
# running with aggregate-forward disabled REJECT them naturally (the
# sentinel is never in any committee).
PACKED_AGGREGATOR_INDEX = (1 << 64) - 1

# DeferredForwardQueue bounds: in-flight deferrals (the standard lane
# resolves within its 250 ms window, so steady state is far below this)
# and how many slots a deferral may outlive its attestation's slot.
MAX_DEFERRED_FORWARDS = 4096
DEFERRED_EXPIRY_SLOTS = 1

# AggregateForwarder bounds: registered (signing root -> committee)
# entries and retained best packs, both pruned per clock slot.
MAX_REGISTERED_ROOTS = 8192
MAX_RETAINED_PACKS = 512
PACK_RETAIN_SLOTS = 2
# a 1-member "layer" carries no bandwidth win — never re-publish it
MIN_PACK_MEMBERS = 2


def aggfwd_enabled() -> bool:
    """`LODESTAR_TPU_BLS_AGGFWD` gate (default on) — same off-value
    grammar as the PIPELINE/PREAGG hatches."""
    env = os.environ.get("LODESTAR_TPU_BLS_AGGFWD", "1")
    return env.strip().lower() not in ("0", "false", "no", "off")


class DeferredVerdict:
    """A gossip verdict that resolves later (None = ACCEPT, else the
    GossipAction), with continuations fired on resolution.

    The bus duck-types on `on_resolve` (gossip.py): a handler returning
    one of these has its sender scored when the verdict lands instead
    of at delivery time.  `drop(reason)` — slot expiry, backpressure
    shed — wins over resolution: a dropped deferral NEVER fires its
    continuations, so a late verdict neither forwards a stale
    attestation nor scores its sender.  Callbacks always run OUTSIDE
    the internal lock, on whichever thread resolves/registers last.
    """

    __slots__ = ("slot", "_lock", "_callbacks", "_resolved", "verdict",
                 "dropped", "drop_reason")

    def __init__(self, slot: Optional[int] = None):
        self.slot = slot
        self._lock = threading.Lock()
        self._callbacks: List[Callable] = []
        self._resolved = False
        self.verdict = None
        self.dropped = False
        self.drop_reason: Optional[str] = None

    def on_resolve(self, fn: Callable) -> None:
        """Register `fn(verdict)`; fires immediately when the verdict
        already landed (and the deferral was not dropped first)."""
        with self._lock:
            if not self._resolved:
                self._callbacks.append(fn)
                return
            fire = not self.dropped
        if fire:
            fn(self.verdict)

    def resolve(self, verdict) -> None:
        """Idempotent; the first resolution wins.  Fires continuations
        unless the deferral was dropped first."""
        with self._lock:
            if self._resolved:
                return
            self._resolved = True
            self.verdict = verdict
            callbacks, self._callbacks = self._callbacks, []
            fire = not self.dropped
        if fire:
            for fn in callbacks:
                fn(verdict)

    def drop(self, reason: str) -> bool:
        """Mark dropped BEFORE resolution: continuations never fire.
        Returns False when the verdict already landed (too late)."""
        with self._lock:
            if self._resolved or self.dropped:
                return False
            self.dropped = True
            self.drop_reason = reason
            self._callbacks = []
            return True

    @property
    def resolved(self) -> bool:
        with self._lock:
            return self._resolved


class _DeferredEntry:
    __slots__ = ("deferred", "slot", "peer_id", "topic")

    def __init__(self, deferred, slot, peer_id, topic):
        self.deferred = deferred
        self.slot = slot
        self.peer_id = peer_id
        self.topic = topic


class DeferredForwardQueue:
    """Bounded registry of in-flight DeferredVerdicts with per-slot
    expiry (the NetworkProcessor owns one; reference analogue: the
    processor's awaiting-reprocess parking, index.ts:281-299).

      - normal resolution removes the entry (a cleanup continuation is
        registered FIRST, so it runs before any scoring continuation),
      - `on_clock_slot` drops entries older than DEFERRED_EXPIRY_SLOTS
        past their attestation slot — a late verdict then resolves into
        nothing instead of forwarding a stale attestation,
      - at capacity the OLDEST entry is shed: its deferral drops (slot
        released) and the shed charges the publisher through the
        scorer's backpressure penalty (gossipsub P7), exactly like a
        gossip-queue overflow drop.
    """

    def __init__(
        self,
        scorer=None,
        max_entries: int = MAX_DEFERRED_FORWARDS,
        expiry_slots: int = DEFERRED_EXPIRY_SLOTS,
    ):
        self.scorer = scorer
        self.max_entries = max_entries
        self.expiry_slots = expiry_slots
        self._lock = threading.Lock()
        self._entries: "OrderedDict[int, _DeferredEntry]" = OrderedDict()
        self._next_key = 0
        self.stats = {"registered": 0, "fired": 0, "expired": 0, "shed": 0}

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def register(
        self,
        deferred: DeferredVerdict,
        slot: Optional[int] = None,
        peer_id: Optional[str] = None,
        topic: Optional[str] = None,
    ) -> None:
        if slot is None:
            slot = deferred.slot
        shed: List[_DeferredEntry] = []
        with self._lock:
            key = self._next_key
            self._next_key += 1
            self._entries[key] = _DeferredEntry(deferred, slot, peer_id, topic)
            self.stats["registered"] += 1
            while len(self._entries) > self.max_entries:
                _k, entry = self._entries.popitem(last=False)
                self.stats["shed"] += 1
                shed.append(entry)

        def _cleanup(_verdict, key=key):
            with self._lock:
                if self._entries.pop(key, None) is not None:
                    self.stats["fired"] += 1

        deferred.on_resolve(_cleanup)
        for entry in shed:
            entry.deferred.drop("shed")
            self._charge_shed(entry)

    def _charge_shed(self, entry: _DeferredEntry) -> None:
        if self.scorer is None or entry.peer_id is None:
            return
        try:
            self.scorer.on_backpressure_drop(entry.peer_id, entry.topic)
        except Exception:  # noqa: BLE001 — scoring must never break
            pass  # verdict bookkeeping

    def on_clock_slot(self, slot: int) -> None:
        """Expire deferrals whose attestation slot fell out of the
        forward window (slot-less entries never expire — they are
        bounded by the shed cap)."""
        expired: List[_DeferredEntry] = []
        with self._lock:
            for key in list(self._entries):
                entry = self._entries[key]
                if entry.slot is not None and entry.slot + self.expiry_slots < slot:
                    del self._entries[key]
                    self.stats["expired"] += 1
                    expired.append(entry)
        for entry in expired:
            entry.deferred.drop("expired")

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


class _RootMeta:
    __slots__ = ("slot", "data", "data_root", "committee")

    def __init__(self, slot, data, data_root, committee):
        self.slot = slot
        self.data = data
        self.data_root = data_root
        self.committee = committee


class AggregateForwarder:
    """Re-packs verified disjoint-index layers into aggregate-topic
    publications and serves the best pack to the local aggregation duty.

    `register_root` is called from attestation validation pre-checks
    (the committee lookup already happened there); `on_layer_verified`
    is the PreVerifyAggregator's success hook (bls/aggregator.py,
    invoked OUTSIDE the pipeline lock) — it maps the layer's validator
    indices back onto the registered committee's aggregation bits,
    wraps the already-summed signature as a PACKED_AGGREGATOR_INDEX
    `SignedAggregateAndProof`, and publishes.  The bus marks the
    publisher seen for its own message id at publish time, so the pack
    never echoes back (the self-publish seen-cache rule).
    """

    def __init__(self, bus=None, node_id: Optional[str] = None,
                 fork_digest: Optional[bytes] = None):
        self.bus = bus
        self.node_id = node_id
        self.fork_digest = fork_digest
        self.log = get_logger("network/forwarding")
        self._lock = threading.Lock()
        self._roots: "OrderedDict[bytes, _RootMeta]" = OrderedDict()
        # (slot, data_root) -> (member count, attestation value) — the
        # largest verified pack per vote, the aggregation duty's source
        self._packs: "OrderedDict[Tuple[int, bytes], Tuple[int, dict]]" = (
            OrderedDict()
        )
        self.stats = {
            "published": 0,
            "bytes_published": 0,
            "members_forwarded": 0,
            "skipped": 0,
        }

    # -- registration (validation pre-checks) ------------------------------

    def register_root(
        self, signing_root: bytes, slot: int, data: dict, committee
    ) -> None:
        from ..types import AttestationData

        key = bytes(signing_root)
        with self._lock:
            if key in self._roots:
                self._roots.move_to_end(key)
                return
            data_root = bytes(AttestationData.hash_tree_root(data))
            self._roots[key] = _RootMeta(
                int(slot), data, data_root, tuple(int(v) for v in committee)
            )
            while len(self._roots) > MAX_REGISTERED_ROOTS:
                self._roots.popitem(last=False)

    # -- the publish hook (PreVerifyAggregator success path) ---------------

    def on_layer_verified(self, wire, n_members: int) -> None:
        """`wire` is the verified layer's aggregated WireSignatureSet
        (disjoint validator indices, summed signature)."""
        if n_members < MIN_PACK_MEMBERS:
            return
        with self._lock:
            meta = self._roots.get(bytes(wire.signing_root))
        if meta is None:
            # not an attestation root this node registered (e.g. a
            # foreign wire set routed through the stage) — nothing to
            # re-publish
            with self._lock:
                self.stats["skipped"] += 1
            return
        indices = set(int(i) for i in wire.indices)
        committee_set = set(meta.committee)
        if not indices <= committee_set:
            with self._lock:
                self.stats["skipped"] += 1
            return
        bits = [v in indices for v in meta.committee]
        attestation = {
            "aggregation_bits": bits,
            "data": meta.data,
            "signature": bytes(wire.signature),
        }
        with self._lock:
            key = (meta.slot, meta.data_root)
            best = self._packs.get(key)
            if best is None or best[0] < len(indices):
                self._packs[key] = (len(indices), attestation)
                self._packs.move_to_end(key)
            while len(self._packs) > MAX_RETAINED_PACKS:
                self._packs.popitem(last=False)
        self._publish(attestation, len(indices))

    def _publish(self, attestation: dict, n_members: int) -> None:
        if self.bus is None or self.node_id is None or self.fork_digest is None:
            return
        from ..types import SignedAggregateAndProof
        from .gossip import GossipTopicName, encode_message, topic_string

        signed = {
            "message": {
                "aggregator_index": PACKED_AGGREGATOR_INDEX,
                "aggregate": attestation,
                "selection_proof": b"\x00" * 96,
            },
            "signature": b"\x00" * 96,
        }
        try:
            payload = encode_message(
                SignedAggregateAndProof.serialize(signed)
            )
            topic = topic_string(
                self.fork_digest, GossipTopicName.beacon_aggregate_and_proof
            )
            # publish marks this node as having seen its own message id,
            # so the pack never comes back for re-verification and no
            # peer is ever charged for it
            self.bus.publish(self.node_id, topic, payload)
        except Exception as e:  # noqa: BLE001 — re-publication is an
            # optimization; a transport fault must never break verdict
            # delivery on the resolver thread
            self.log.warn("aggregate-forward publish failed", error=str(e))
            return
        with self._lock:
            self.stats["published"] += 1
            self.stats["bytes_published"] += len(payload)
            self.stats["members_forwarded"] += n_members

    # -- the consume side (validator aggregation duty) ---------------------

    def get_packed_aggregate(
        self, slot: int, data_root: bytes
    ) -> Optional[dict]:
        """Largest verified pack for (slot, data_root), or None — the
        aggregation duty consumes the already-summed layer instead of
        re-aggregating raw pool entries."""
        with self._lock:
            entry = self._packs.get((int(slot), bytes(data_root)))
            return entry[1] if entry is not None else None

    def on_clock_slot(self, slot: int) -> None:
        with self._lock:
            for key in [
                k for k, m in self._roots.items()
                if m.slot + PACK_RETAIN_SLOTS < slot
            ]:
                del self._roots[key]
            for key in [
                k for k in self._packs
                if k[0] + PACK_RETAIN_SLOTS < slot
            ]:
                del self._packs[key]

    def stats_snapshot(self) -> dict:
        with self._lock:
            return dict(self.stats)


__all__ = [
    "AggregateForwarder",
    "DeferredForwardQueue",
    "DeferredVerdict",
    "PACKED_AGGREGATOR_INDEX",
    "MAX_DEFERRED_FORWARDS",
    "DEFERRED_EXPIRY_SLOTS",
    "aggfwd_enabled",
]
