"""Req/resp protocol layer: protocol registry, chunked ssz_snappy
streams, GCRA rate limiting, node-side handlers.

Mirror of the reference's reqresp stack (reference:
packages/reqresp/src/ReqResp.ts, rate_limiter/rateLimiterGRCA.ts,
encodingStrategies/sszSnappy/, and the beacon-node bindings
packages/beacon-node/src/network/reqresp/{protocols,types,rateLimit,
handlers}.ts).  The ssz_snappy chunk codec lives in network/snappy.py;
this module adds everything above it:

  - protocol identifiers `/eth2/beacon_chain/req/<method>/<version>/ssz_snappy`
  - response chunk streams `<result:u8>[<context:4>]<ssz_snappy payload>`
    with fork-digest context bytes on v2 protocols
  - per-peer + total GCRA rate limiting with per-request token counts
  - a transport-agnostic `ReqResp` node: the libp2p wire itself is off
    the TPU path (SURVEY §2.4 P9); tests and the in-process stack
    connect two nodes with `connect_inmemory`.
"""

from __future__ import annotations

import enum
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .. import params
from . import snappy as SN

MAX_REQUEST_BLOCKS = 1024
MAX_REQUEST_LIGHT_CLIENT_UPDATES = 128
# p2p spec deneb: by-range requests span at most 128 slots, and the
# sidecar cap is MAX_REQUEST_BLOCKS_DENEB * MAX_BLOBS_PER_BLOCK(6)
MAX_REQUEST_BLOCKS_DENEB = 128
MAX_REQUEST_BLOB_SIDECARS = 768


class ReqRespMethod(str, enum.Enum):
    """reference: network/reqresp/types.ts ReqRespMethod."""

    status = "status"
    goodbye = "goodbye"
    ping = "ping"
    metadata = "metadata"
    beacon_blocks_by_range = "beacon_blocks_by_range"
    beacon_blocks_by_root = "beacon_blocks_by_root"
    blob_sidecars_by_range = "blob_sidecars_by_range"
    blob_sidecars_by_root = "blob_sidecars_by_root"
    light_client_bootstrap = "light_client_bootstrap"
    light_client_updates_by_range = "light_client_updates_by_range"
    light_client_finality_update = "light_client_finality_update"
    light_client_optimistic_update = "light_client_optimistic_update"


class RespCode(enum.IntEnum):
    """p2p spec response result byte."""

    SUCCESS = 0
    INVALID_REQUEST = 1
    SERVER_ERROR = 2
    RESOURCE_UNAVAILABLE = 3
    RATE_LIMITED = 139  # reference: RespStatus.RATE_LIMITED


class ContextBytes(str, enum.Enum):
    empty = "empty"
    fork_digest = "fork_digest"


class ReqRespError(Exception):
    def __init__(self, code: RespCode, message: str = ""):
        super().__init__(f"{code.name}: {message}")
        self.code = code
        self.message = message


class ReqRespTimeout(ReqRespError):
    """A request that never returned within its deadline — the peer is
    stalling, not erroring; retry logic demotes it and moves on."""


def call_with_timeout(fn: Callable[[], object], timeout_s: float,
                      desc: str = "request"):
    """Run `fn()` under the shared expendable-thread deadline runner
    (utils/misc.run_with_deadline); raise ReqRespTimeout when it does
    not return within `timeout_s`.  The stalled thread is abandoned —
    a peer that never answers must cost the caller one bounded wait,
    never a wedged sync loop (ISSUE 14 satellite)."""
    from ..utils.misc import DeadlineExceeded, run_with_deadline

    try:
        return run_with_deadline(fn, timeout_s, desc)
    except DeadlineExceeded:
        raise ReqRespTimeout(
            RespCode.SERVER_ERROR,
            f"{desc} timed out after {timeout_s:g}s",
        ) from None


@dataclass
class RetryPolicy:
    """Jittered exponential backoff between retry attempts."""

    attempts: int = 3
    backoff_initial_s: float = 0.05
    backoff_max_s: float = 2.0
    jitter: float = 0.25  # +/- fraction of the computed backoff

    def backoff(self, attempt: int, rng: random.Random) -> float:
        b = min(
            self.backoff_initial_s * (2.0 ** attempt), self.backoff_max_s
        )
        return b * (1.0 - self.jitter + 2.0 * self.jitter * rng.random())


class PeerDemotion:
    """Per-peer timeout demotion ledger: a peer that times out is
    deprioritized for a cooldown that doubles on every consecutive
    fault (capped) and fully resets on the first success.  `clock` is
    injectable so the chaos harness drives cooldowns deterministically."""

    def __init__(
        self,
        cooldown_initial_s: float = 5.0,
        cooldown_max_s: float = 120.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cooldown_initial_s = cooldown_initial_s
        self.cooldown_max_s = cooldown_max_s
        self._clock = clock
        self._lock = threading.Lock()
        # peer -> (demoted_until, consecutive_faults)
        self._entries: Dict[str, Tuple[float, int]] = {}

    def demote(self, peer_id: str) -> float:
        """Record one timeout fault; returns the cooldown applied."""
        with self._lock:
            _until, faults = self._entries.get(peer_id, (0.0, 0))
            cooldown = min(
                self.cooldown_initial_s * (2.0 ** faults),
                self.cooldown_max_s,
            )
            self._entries[peer_id] = (
                self._clock() + cooldown, faults + 1
            )
            return cooldown

    def restore(self, peer_id: str) -> None:
        with self._lock:
            self._entries.pop(peer_id, None)

    def is_demoted(self, peer_id: str) -> bool:
        with self._lock:
            entry = self._entries.get(peer_id)
            return entry is not None and self._clock() < entry[0]

    def order(self, peers: Sequence[str]) -> List[str]:
        """Healthy peers first (input order preserved), then demoted
        ones by soonest cooldown expiry — every peer stays reachable as
        a last resort."""
        now = self._clock()
        with self._lock:
            healthy, demoted = [], []
            for p in peers:
                entry = self._entries.get(p)
                if entry is not None and now < entry[0]:
                    demoted.append((entry[0], p))
                else:
                    healthy.append(p)
        return healthy + [p for _t, p in sorted(demoted)]

    def snapshot(self) -> Dict[str, dict]:
        now = self._clock()
        with self._lock:
            return {
                p: {
                    "cooldown_remaining_s": max(until - now, 0.0),
                    "consecutive_faults": faults,
                }
                for p, (until, faults) in self._entries.items()
            }


@dataclass(frozen=True)
class Protocol:
    """One protocol version (reference: protocols.ts toProtocol)."""

    method: ReqRespMethod
    version: int
    context_bytes: ContextBytes
    # ssz codecs as plain callables so dict-shaped bodies stay the
    # repo-wide currency: encode(body) -> bytes, decode(bytes) -> body.
    # None = no request body (metadata, light client head updates).
    encode_request: Optional[Callable] = None
    decode_request: Optional[Callable] = None
    # response codecs keyed by fork (context dispatch); for empty
    # context bytes only the `None` key is used
    encode_response: Callable = None
    decode_response: Callable = None

    @property
    def protocol_id(self) -> str:
        return (
            f"/eth2/beacon_chain/req/{self.method.value}/{self.version}/"
            "ssz_snappy"
        )


# -- GCRA rate limiter (reference: rate_limiter/rateLimiterGRCA.ts) ---------


@dataclass
class RateLimiterQuota:
    quota: float
    quota_time_ms: float


class RateLimiterGRCA:
    """Generic Cell Rate Algorithm: one stored value (the theoretical
    arrival time) per key; allows bursts up to `quota` while enforcing
    the long-run rate quota/quota_time_ms."""

    def __init__(self, quota: RateLimiterQuota, clock=time.monotonic):
        assert quota.quota > 0 and quota.quota_time_ms > 0
        self.ms_per_bucket = quota.quota_time_ms
        self.ms_per_token = quota.quota_time_ms / quota.quota
        self._tat: Dict[object, float] = {}
        self._clock = clock

    def peek(self, key, tokens: float = 1.0):
        """Admission decision WITHOUT committing: returns (ok, commit)
        where commit() applies the TAT update.  Lets callers coordinate
        several limiters — admit only if all admit, then commit all —
        so a request denied by one bucket never burns another's quota
        (ADVICE r4: per-peer tokens were consumed before the total
        limiter was consulted)."""
        now_ms = self._clock() * 1000.0
        tat = self._tat.get(key, now_ms)
        # earliest time the bucket could accept `tokens` more
        new_tat = max(now_ms, tat) + tokens * self.ms_per_token
        if new_tat - now_ms > self.ms_per_bucket:
            return False, (lambda: None)

        def commit(_key=key, _tat=new_tat):
            self._tat[_key] = _tat

        return True, commit

    def allows(self, key, tokens: float = 1.0) -> bool:
        ok, commit = self.peek(key, tokens)
        if ok:
            commit()
        return ok

    def prune(self, older_than_ms: float = 60_000.0) -> None:
        now_ms = self._clock() * 1000.0
        for k in [k for k, t in self._tat.items() if now_ms - t > older_than_ms]:
            del self._tat[k]


@dataclass
class InboundRateLimitQuota:
    """reference: network/reqresp/rateLimit.ts rateLimitQuotas."""

    by_peer: RateLimiterQuota
    total: Optional[RateLimiterQuota] = None
    # request bytes -> token count (blocks_by_range counts `count` etc.)
    get_request_count: Optional[Callable[[dict], float]] = None


def default_rate_limits() -> Dict[ReqRespMethod, InboundRateLimitQuota]:
    """The reference's per-peer quota table (rateLimit.ts:6-66), plus a
    node-wide `total` backstop on the expensive serving methods — the
    reference's table leaves totals unset, which lets N peers each pull
    a full per-peer quota with no aggregate cap on db reads."""
    M = ReqRespMethod
    return {
        M.status: InboundRateLimitQuota(RateLimiterQuota(5, 15_000)),
        M.goodbye: InboundRateLimitQuota(RateLimiterQuota(1, 10_000)),
        M.ping: InboundRateLimitQuota(RateLimiterQuota(2, 10_000)),
        M.metadata: InboundRateLimitQuota(RateLimiterQuota(2, 5_000)),
        M.beacon_blocks_by_range: InboundRateLimitQuota(
            RateLimiterQuota(MAX_REQUEST_BLOCKS, 10_000),
            total=RateLimiterQuota(4 * MAX_REQUEST_BLOCKS, 10_000),
            get_request_count=lambda req: max(1, int(req.get("count", 1))),
        ),
        M.beacon_blocks_by_root: InboundRateLimitQuota(
            RateLimiterQuota(128, 10_000),
            total=RateLimiterQuota(4 * 128, 10_000),
            get_request_count=lambda req: max(1, len(req)),
        ),
        M.blob_sidecars_by_range: InboundRateLimitQuota(
            RateLimiterQuota(MAX_REQUEST_BLOB_SIDECARS, 10_000),
            total=RateLimiterQuota(4 * MAX_REQUEST_BLOB_SIDECARS, 10_000),
            get_request_count=lambda req: max(1, int(req.get("count", 1))),
        ),
        M.blob_sidecars_by_root: InboundRateLimitQuota(
            RateLimiterQuota(128, 10_000),
            total=RateLimiterQuota(4 * 128, 10_000),
            get_request_count=lambda req: max(1, len(req)),
        ),
        M.light_client_bootstrap: InboundRateLimitQuota(
            RateLimiterQuota(5, 15_000)
        ),
        M.light_client_updates_by_range: InboundRateLimitQuota(
            RateLimiterQuota(MAX_REQUEST_LIGHT_CLIENT_UPDATES, 10_000),
            total=RateLimiterQuota(
                4 * MAX_REQUEST_LIGHT_CLIENT_UPDATES, 10_000
            ),
            get_request_count=lambda req: max(1, int(req.get("count", 1))),
        ),
        M.light_client_finality_update: InboundRateLimitQuota(
            RateLimiterQuota(2, 12_000)
        ),
        M.light_client_optimistic_update: InboundRateLimitQuota(
            RateLimiterQuota(2, 12_000)
        ),
    }


# -- chunk stream codec -----------------------------------------------------


def encode_response_chunks(
    chunks: List[Tuple[bytes, Optional[bytes]]]
) -> bytes:
    """[(ssz_bytes, context_bytes|None), ...] -> response stream."""
    out = bytearray()
    for ssz_bytes, ctx in chunks:
        out.append(RespCode.SUCCESS)
        if ctx is not None:
            assert len(ctx) == 4
            out += ctx
        out += SN.encode_reqresp_chunk(ssz_bytes)
    return bytes(out)


def encode_error_chunk(code: RespCode, message: str) -> bytes:
    payload = message.encode()[:256]
    return bytes([code]) + SN.encode_reqresp_chunk(payload)


def decode_response_chunks(
    data: bytes, context_bytes: ContextBytes
) -> List[Tuple[bytes, Optional[bytes]]]:
    """Response stream -> [(ssz_bytes, context|None)].  Raises
    ReqRespError on an error chunk (error terminates the stream)."""
    out = []
    pos = 0
    while pos < len(data):
        code = data[pos]
        pos += 1
        ctx = None
        if code == RespCode.SUCCESS and context_bytes is ContextBytes.fork_digest:
            ctx = bytes(data[pos : pos + 4])
            pos += 4
        ssz_bytes, pos = SN.decode_reqresp_chunk_at(data, pos)
        if code != RespCode.SUCCESS:
            try:
                msg = ssz_bytes.decode()
            except UnicodeDecodeError:
                msg = ssz_bytes.hex()
            # the p2p spec reserves EVERY nonzero result byte as an
            # error; map unknown codes to SERVER_ERROR instead of
            # crashing on the enum lookup
            try:
                rc = RespCode(code)
            except ValueError:
                rc = RespCode.SERVER_ERROR
                msg = f"error code {code}: {msg}"
            raise ReqRespError(rc, msg)
        out.append((ssz_bytes, ctx))
    return out


# -- the ReqResp node -------------------------------------------------------


Handler = Callable[[str, object], List[Tuple[bytes, Optional[bytes]]]]


class ReqResp:
    """Transport-agnostic req/resp node (reference: ReqResp.ts).

    Server side: `handle_request(peer, protocol_id, req_bytes)` returns
    the encoded response stream (rate-limited, error chunks on failure).
    Client side: `send_request(peer, protocol, body)` resolves the
    peer's transport (a callable set by `connect`), sends, and decodes.
    """

    def __init__(
        self,
        rate_limits: Optional[Dict[ReqRespMethod, InboundRateLimitQuota]] = None,
        clock=time.monotonic,
        on_rate_limit: Optional[Callable[[str, str], None]] = None,
    ):
        self._protocols: Dict[str, Protocol] = {}
        self._handlers: Dict[str, Handler] = {}
        self._transports: Dict[str, Callable[[str, bytes], bytes]] = {}
        self._rate_limits = (
            default_rate_limits() if rate_limits is None else rate_limits
        )
        self._by_peer: Dict[ReqRespMethod, RateLimiterGRCA] = {}
        self._total: Dict[ReqRespMethod, RateLimiterGRCA] = {}
        for m, q in self._rate_limits.items():
            self._by_peer[m] = RateLimiterGRCA(q.by_peer, clock)
            if q.total is not None:
                self._total[m] = RateLimiterGRCA(q.total, clock)
        self._on_rate_limit = on_rate_limit

    # -- registration ------------------------------------------------------

    def register_protocol(self, protocol: Protocol, handler: Handler) -> None:
        self._protocols[protocol.protocol_id] = protocol
        self._handlers[protocol.protocol_id] = handler

    def prune_limiters(self, older_than_ms: float = 60_000.0) -> None:
        """Drop stale per-peer limiter state (call on a slow tick —
        peers churn, their TAT entries must not accumulate forever)."""
        for limiter in self._by_peer.values():
            limiter.prune(older_than_ms)
        for limiter in self._total.values():
            limiter.prune(older_than_ms)

    def supported_protocols(self) -> List[str]:
        return list(self._protocols)

    # -- transport wiring --------------------------------------------------

    def connect(self, peer_id: str, send: Callable[[str, bytes], bytes]) -> None:
        """`send(protocol_id, request_bytes) -> response_bytes`."""
        self._transports[peer_id] = send

    def disconnect(self, peer_id: str) -> None:
        self._transports.pop(peer_id, None)

    # -- server side -------------------------------------------------------

    def handle_request(
        self, peer_id: str, protocol_id: str, req_bytes: bytes
    ) -> bytes:
        protocol = self._protocols.get(protocol_id)
        if protocol is None:
            return encode_error_chunk(
                RespCode.INVALID_REQUEST, f"unsupported protocol {protocol_id}"
            )
        try:
            body = None
            if protocol.decode_request is not None:
                body = protocol.decode_request(
                    SN.decode_reqresp_chunk(req_bytes)
                )
        except Exception as e:  # noqa: BLE001 — malformed wire input
            return encode_error_chunk(RespCode.INVALID_REQUEST, str(e))
        quota = self._rate_limits.get(protocol.method)
        if quota is not None:
            tokens = 1.0
            if quota.get_request_count is not None and body is not None:
                try:
                    tokens = float(quota.get_request_count(body))
                except Exception:  # noqa: BLE001
                    tokens = 1.0
            limiter = self._by_peer[protocol.method]
            total = self._total.get(protocol.method)
            # peek/commit split: both limiters decide before either
            # commits, so a denial by one never burns the other's quota
            peer_ok, peer_commit = limiter.peek(peer_id, tokens)
            total_ok, total_commit = (
                total.peek("total", tokens) if total is not None else (True, lambda: None)
            )
            if not (peer_ok and total_ok):
                if self._on_rate_limit is not None:
                    self._on_rate_limit(peer_id, protocol_id)
                return encode_error_chunk(
                    RespCode.RATE_LIMITED, "rate limited"
                )
            peer_commit()
            total_commit()
        try:
            chunks = self._handlers[protocol_id](peer_id, body)
            return encode_response_chunks(chunks)
        except ReqRespError as e:
            return encode_error_chunk(e.code, e.message)
        except Exception as e:  # noqa: BLE001 — handler crash = server error
            return encode_error_chunk(RespCode.SERVER_ERROR, str(e))

    # -- client side -------------------------------------------------------

    def send_request(
        self,
        peer_id: str,
        protocol: Protocol,
        body=None,
        timeout_s: Optional[float] = None,
    ) -> List[Tuple[bytes, Optional[bytes]]]:
        send = self._transports.get(peer_id)
        if send is None:
            raise ReqRespError(
                RespCode.SERVER_ERROR, f"no transport for peer {peer_id}"
            )
        req = b""
        if protocol.encode_request is not None:
            req = SN.encode_reqresp_chunk(protocol.encode_request(body))
        if timeout_s is not None:
            # a stalling peer costs one bounded wait (the transport
            # thread is abandoned), never a wedged caller
            resp = call_with_timeout(
                lambda: send(protocol.protocol_id, req),
                timeout_s,
                desc=f"{protocol.method.value}@{peer_id}",
            )
        else:
            resp = send(protocol.protocol_id, req)
        return decode_response_chunks(resp, protocol.context_bytes)


def request_with_retry(
    node: "ReqResp",
    peers: Sequence[str],
    protocol: Protocol,
    body=None,
    timeout_s: Optional[float] = None,
    policy: Optional[RetryPolicy] = None,
    demotion: Optional[PeerDemotion] = None,
    rng: Optional[random.Random] = None,
    sleep: Callable[[float], None] = time.sleep,
) -> Tuple[str, List[Tuple[bytes, Optional[bytes]]]]:
    """Send one request with jittered-exponential-backoff retries across
    `peers`: a peer that times out is demoted (doubling cooldown) and
    the next attempt goes to a DIFFERENT peer — never awaited forever
    (ISSUE 14 satellite).  Returns (serving_peer, chunks); raises the
    last ReqRespError when every attempt failed."""
    if not peers:
        raise ReqRespError(RespCode.SERVER_ERROR, "no peers to ask")
    policy = policy or RetryPolicy()
    rng = rng or random.Random()
    last: Optional[ReqRespError] = None
    just_failed: Optional[str] = None
    for attempt in range(policy.attempts):
        ordered = (
            demotion.order(peers) if demotion is not None else list(peers)
        )
        candidates = [p for p in ordered if p != just_failed] or ordered
        peer = candidates[0]
        try:
            out = node.send_request(
                peer, protocol, body, timeout_s=timeout_s
            )
            if demotion is not None:
                demotion.restore(peer)
            return peer, out
        except ReqRespError as e:
            last = e
            just_failed = peer
            if isinstance(e, ReqRespTimeout) and demotion is not None:
                demotion.demote(peer)
            if attempt + 1 < policy.attempts:
                sleep(policy.backoff(attempt, rng))
    assert last is not None
    raise last


def connect_inmemory(a: ReqResp, a_id: str, b: ReqResp, b_id: str) -> None:
    """Wire two nodes directly (the test/in-process transport)."""
    a.connect(b_id, lambda pid, req: b.handle_request(a_id, pid, req))
    b.connect(a_id, lambda pid, req: a.handle_request(b_id, pid, req))
