"""Host-side ingest bridge: wire bytes -> device-ready planes.

The reference pays deserialization + hashing per signature set inside
blst (worker.ts:30-50 uncompress; hashing inside verify).  Here:

  - `MessageCache` hashes signing roots to G2 in device batches
    (kernels/ingest.hash_to_g2_device) and memoizes the affine results —
    the TPU analog of SeenAttestationDatas' signing-root reuse
    (reference: chain/seenCache/seenAttestationData.ts), but keyed by
    root and shared across all set types,
  - `parse_signature_bytes` splits 96-byte compressed signatures into
    x-coordinate limb planes + (sign, infinity) flag bits, with the
    host-side wire checks (length, compression bit, padding, x < p);
    the y-recovery sqrt runs on device inside the verify pipeline
    (kernels/verify.verify_*_device_wire).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..crypto import fields as GT
from ..crypto import hash_to_curve as HC
from ..kernels import layout as LY

P = GT.P
BT = 128

_COMP = 0x80
_INF = 0x40
_SIGN = 0x20


class MessageCache:
    """signing_root -> affine G2 message point (ground-truth ints).

    Misses are hashed in one padded device batch per `get_many` call;
    an LRU bound keeps the cache sized to a few slots of distinct
    attestation/sync data.
    """

    def __init__(self, max_entries: int = 4096, use_device: bool = True):
        self.max_entries = max_entries
        self.use_device = use_device
        self._cache: "OrderedDict[bytes, Tuple]" = OrderedDict()
        # the service's dispatcher and resolver threads both reach the
        # cache (retry path); all OrderedDict mutation happens under here
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def get_many(self, roots: Sequence[bytes]) -> List[Tuple]:
        with self._lock:
            resolved = {}
            missing = []
            for r in roots:
                if r in self._cache:
                    # snapshot hits NOW: inserting a large miss set below
                    # may evict them before the final answer is built
                    resolved[r] = self._cache[r]
                    self._cache.move_to_end(r)
                    self.hits += 1
                elif r not in missing:
                    missing.append(r)
            if missing:
                self.misses += len(missing)
                if self.use_device:
                    fetched = self._hash_batch_device(missing)
                else:
                    fetched = {r: HC.hash_to_g2(r) for r in missing}
                for r, pt in fetched.items():
                    self._store(r)
                    self._cache[r] = pt
                resolved.update(fetched)
            return [resolved[r] for r in roots]

    def _store(self, root: bytes) -> None:
        while len(self._cache) >= self.max_entries:
            self._cache.popitem(last=False)

    def _hash_batch_device(self, roots: List[bytes]):
        import jax.numpy as jnp

        from ..kernels import ingest as IG

        n = len(roots)
        pad = (-n) % BT
        roots_p = list(roots) + [roots[-1]] * pad
        us = [HC.hash_to_field_fp2(r, 2, HC.DST_G2) for r in roots_p]
        sgn = np.zeros((2, len(roots_p)), np.int32)
        for i, (u0, u1) in enumerate(us):
            sgn[0, i] = HC._sgn0_fp2(u0)
            sgn[1, i] = HC._sgn0_fp2(u1)
        enc = lambda vals: jnp.asarray(LY.encode_plain_batch(vals))
        planes, ok = IG.hash_to_g2_device(
            enc([u[0][0] for u in us]),
            enc([u[0][1] for u in us]),
            enc([u[1][0] for u in us]),
            enc([u[1][1] for u in us]),
            jnp.asarray(sgn),
        )
        assert bool(np.asarray(ok).all()), "device hash_to_g2 flagged failure"
        X0, X1, Y0, Y1, Z0, Z1 = (LY.decode_batch(np.asarray(p)) for p in planes)
        fetched = {}
        for i, r in enumerate(roots):
            z = (Z0[i], Z1[i])
            zi = GT.fp2_inv(z)
            zi2 = GT.fp2_sqr(zi)
            x = GT.fp2_mul((X0[i], X1[i]), zi2)
            y = GT.fp2_mul((Y0[i], Y1[i]), GT.fp2_mul(zi2, zi))
            fetched[r] = (x, y)
        return fetched


def parse_signature_bytes(sig: bytes) -> Tuple[int, int, int, int, bool]:
    """96B compressed G2 -> (x0, x1, sign, inf, wire_ok).

    wire_ok=False marks malformed encodings (wrong length, missing
    compression bit, out-of-range x, bad infinity padding) — the set
    then fails without touching the device sqrt.  Mirrors the host
    oracle's checks (crypto/curves.py g2_decompress).
    """
    if len(sig) != 96:
        return 0, 0, 0, 0, False
    flags = sig[0]
    if not flags & _COMP:
        return 0, 0, 0, 0, False
    if flags & _INF:
        if flags & (_SIGN | 0x1F) or any(sig[1:]):
            return 0, 0, 0, 0, False
        return 0, 0, 0, 1, True
    x1 = int.from_bytes(bytes([flags & 0x1F]) + sig[1:48], "big")
    x0 = int.from_bytes(sig[48:], "big")
    if x0 >= P or x1 >= P:
        return 0, 0, 0, 0, False
    return x0, x1, 1 if flags & _SIGN else 0, 0, True


def parse_pubkey_bytes(pk: bytes) -> Tuple[int, int, int, bool]:
    """48B compressed G1 -> (x, sign, inf, wire_ok)."""
    if len(pk) != 48:
        return 0, 0, 0, False
    flags = pk[0]
    if not flags & _COMP:
        return 0, 0, 0, False
    if flags & _INF:
        if flags & (_SIGN | 0x1F) or any(pk[1:]):
            return 0, 0, 0, False
        return 0, 0, 1, True
    x = int.from_bytes(bytes([flags & 0x1F]) + pk[1:], "big")
    if x >= P:
        return 0, 0, 0, False
    return x, 1 if flags & _SIGN else 0, 0, True


def encode_pubkey_planes(
    keys: Sequence[bytes],
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pubkeys -> (x_planes, flag_bits[2, n], host_bad[n]) for the device
    KeyValidate kernel (kernels/ingest.g1_keyvalidate_device)."""
    n = len(keys)
    xs = []
    flags = np.zeros((2, n), np.int32)
    host_bad = np.zeros((n,), bool)
    for i, pk in enumerate(keys):
        x, sign, inf, ok = parse_pubkey_bytes(pk)
        xs.append(x)
        flags[0, i] = sign
        flags[1, i] = inf if ok else 1
        host_bad[i] = not ok
    return LY.encode_plain_batch(xs), flags, host_bad


def encode_wire_planes(
    sigs: Sequence[bytes], n: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Signatures -> (x0_planes, x1_planes, flag_bits[2, n], host_bad[n]).

    Malformed encodings get the infinity flag so the device marks the
    lane sig_bad; host_bad distinguishes them from honest infinity for
    accounting.
    """
    x0s, x1s = [], []
    flags = np.zeros((2, n), np.int32)
    host_bad = np.zeros((n,), bool)
    for i, sig in enumerate(sigs):
        x0, x1, sign, inf, ok = parse_signature_bytes(sig)
        x0s.append(x0)
        x1s.append(x1)
        flags[0, i] = sign
        flags[1, i] = inf if ok else 1
        host_bad[i] = not ok
    pad = n - len(sigs)
    x0s.extend([0] * pad)
    x1s.extend([0] * pad)
    flags[1, len(sigs):] = 1  # padding lanes: inert
    return (
        LY.encode_plain_batch(x0s),
        LY.encode_plain_batch(x1s),
        flags,
        host_bad,
    )
