"""The IBlsVerifier plugin boundary, TPU-native.

This package reproduces the semantics of the reference's `chain/bls`
subsystem (reference: packages/beacon-node/src/chain/bls/interface.ts:20-51,
multithread/index.ts, maybeBatch.ts) with the worker-thread pool replaced by
batched JAX kernels on a device:

  signature_set  — the ISignatureSet model (single | aggregate)
  pubkey_table   — device-resident validator pubkey table (Index2Pubkey)
  verifier       — TpuBlsVerifier: buckets, batch->retry, backpressure
  service        — BlsVerifierService: the flat coalescing job queue
  pipeline       — BlsVerificationPipeline: shape-bucketed accumulate-
                   and-flush feed with priority lanes (ISSUE 11)
  aggregator     — PreVerifyAggregator: same-root bucketing + dedupe +
                   G2 point-add ahead of the verify queue (ISSUE 13)
  supervisor     — DeviceSupervisor: the device circuit breaker +
                   degraded host-path routing + canary re-probe
                   (ISSUE 14; escape hatch LODESTAR_TPU_BLS_BREAKER=0)
  metrics        — lodestar_bls_thread_pool_* compatible counters
"""

from .signature_set import SignatureSet, SignatureSetType  # noqa: F401
from .pubkey_table import PubkeyTable, plan_disjoint_gathers  # noqa: F401
from .verifier import TpuBlsVerifier, VerifyOptions  # noqa: F401
from .pipeline import BlsVerificationPipeline, create_bls_service  # noqa: F401
from .aggregator import PreVerifyAggregator  # noqa: F401
from .supervisor import (  # noqa: F401
    BadDeviceOutput,
    DeviceSupervisor,
    DeviceTimeout,
    breaker_snapshot,
    classify_failure,
)
