"""Pre-verify attestation aggregation — verify fewer sets, not just
verify sets faster (ISSUE 13 tentpole).

PR 10/11 cut the cost of each verified set (one multi-pairing per RLC
job) and fed the device fuller batches; every duplicate-heavy subnet
attestation still costs a full signature set.  *Aggregated Signature
Gossip* (arXiv:1911.04698) shows the remaining multiplier: k messages
sharing one signing root aggregate into ONE verifiable statement

    e(sum_i pk_i, H(m)) == e(G1, sum_i sig_i)

cutting required verification throughput by up to k — multiplying
whatever the RLC path delivers — and the EdDSA/BLS committee-consensus
study (arXiv:2302.00418) locates exactly this aggregate-then-verify
step as where BLS wins at committee scale.  This module is that stage,
sitting AHEAD of the pipeline's accumulators:

  - **Bucketing.**  Batchable standard-lane WIRE sets are bucketed by
    `signing_root` — for attestations that root is derived from
    `AttestationData.hash_tree_root` plus the attester domain, so one
    bucket == one (slot, committee, vote) AttestationData.
  - **Dedupe.**  An exact duplicate (same root, indices, signature
    bytes — the shape of a gossip duplicate flood) never re-enters the
    math: while its twin is pending it becomes a follower sharing the
    verdict; after resolution it is served straight from the bucket
    seen-map with zero device work.
  - **Disjoint layers.**  Contributors with OVERLAPPING aggregation
    bits cannot merge into one sum (c-fold indices would need c*pk on
    the gather side — the same reason the eth2 spec refuses overlapping
    aggregates), so `pubkey_table.plan_disjoint_gathers` packs them
    into layers with pairwise-disjoint index sets: every pubkey row is
    gathered ONCE per layer (ISSUE 13 satellite).
  - **One set per layer.**  A layer's signatures are point-added in G2
    — on device through `verifier.aggregate_wire_signatures` (the
    `agg_g2_sum` export-cache entry wrapping kernels/verify.py's
    segmented jacobian sum) with a host ground-truth fallback — and the
    layer verifies as ONE `WireSignatureSet.aggregate` through the
    existing RLC batch path, K-bucketed and message-grouped like any
    other set.  Both legs sit under the device circuit breaker
    (ISSUE 14, bls/supervisor.py): the sum seam skips the device and a
    sum-stage fault classifies + trips inside
    `aggregate_wire_signatures`, and the layer's verify job degrades to
    host verdicts like any other job — the stage itself never needs a
    fault path of its own.
  - **Attribution.**  Every contributor's own future resolves from the
    layer verdict (gossip forwarding, peer scoring, slasher ingestion
    all key on per-message verdicts).  A FAILED layer bisects
    contributor-wise exactly like PR 10's batch bisection: halves
    re-aggregate and re-verify as smaller sets, recursing into failing
    halves, and single-contributor leaves verify the original wire set
    as submitted — one bad message in a k-contributor bucket costs
    O(log k) extra sets.  An isolated invalid contributor charges its
    publisher through the gossip peer scorer when the submission
    carried a `peer_id` (`VerifyOptions`).

Soundness (documented in README "Pre-verify aggregation"): within a
bucket the pairing check attests to the SUM of contributions, not each
one — per-contributor RLC randomizers would cost the per-set G2 scalar
mul this stage exists to remove.  A crafted pair (sig+D, sig'-D) can
therefore pass aggregated where both parts fail individually; the
blast radius is bounded (the attacker must beat the honest messages to
the seen caches, the corrupted votes still fail block-level
verification, and cross-bucket forgery stays blocked by the RLC
randomizers downstream), and `LODESTAR_TPU_BLS_PREAGG=0` restores
per-message verification wholesale.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..observability import trace_span as _trace_span
from .ingest import parse_signature_bytes
from .pubkey_table import plan_disjoint_gathers
from .service import _Job
from .signature_set import SignatureSetType, WireSignatureSet
from .verifier import VerifyOptions

# A layer never grows past one full K-bucket below the CPU-routing cap:
# the gather cost of the aggregated set stays device-bucketed, and the
# pairing win has long saturated by then.
MAX_LAYER_INDICES = 512
# Stage-wide flush caps: distinct output sets reaching one device lane
# tile, or raw contributions reaching a memory/latency bound.
MAX_STAGE_SETS = 128
MAX_STAGE_CONTRIBUTIONS = 4096
# Bounded verdict memory: (root, indices, signature) -> bool of recent
# resolutions, the seen-map gossip handlers consult for suppressed
# duplicates (network/gossip_handlers._recover_suppressed_double_vote).
SEEN_VERDICTS = 8192


class _Parent:
    """One submitted job awaiting its contributions' verdicts.

    Pending-sets accounting contract: the submitting path counted this
    job's sets into `_pending_sets`; each set's unit is RELEASED exactly
    once — at stage flush when its contribution hands off into a layer
    job (which carries its own accounting through the dispatch queue),
    or at credit time for sets judged without ever flushing (unparsable
    bytes, seen-map serves, close rejects).  The job unit itself stays
    in `_pending` until the future settles, mirroring the base service.
    """

    __slots__ = ("job", "remaining", "ok", "exc", "settled")

    def __init__(self, job: _Job):
        self.job = job
        self.remaining = len(job.sets)
        self.ok = True
        self.exc: Optional[BaseException] = None
        self.settled = False


class _Contribution:
    """One distinct (root, indices, signature) statement plus every
    submission awaiting its verdict (the original + exact duplicates)."""

    __slots__ = ("wire", "targets")

    def __init__(self, wire: WireSignatureSet, target) -> None:
        self.wire = wire
        self.targets: List[Tuple[_Parent, Optional[str], Optional[str]]] = [
            target
        ]


class _Bucket:
    __slots__ = ("contribs", "index")

    def __init__(self) -> None:
        self.contribs: List[_Contribution] = []
        # (indices, signature) -> position in contribs, the dedupe index
        self.index: Dict[Tuple, int] = {}


class PreVerifyAggregator:
    """The aggregation stage.  All `_locked` methods run under the
    owning pipeline's condition lock; future settlement is DEFERRED to
    `drain()` so no caller-visible callback ever fires under it."""

    def __init__(
        self,
        pipeline,
        lane_wait_s: float,
        sum_fn,
        scorer=None,
        max_layer_indices: int = MAX_LAYER_INDICES,
        max_stage_sets: int = MAX_STAGE_SETS,
        max_stage_contributions: int = MAX_STAGE_CONTRIBUTIONS,
    ):
        self._pipeline = pipeline
        self._lane_wait = lane_wait_s
        # aggregate-forward hook (ISSUE 19, network/forwarding.py):
        # `fn(wire, n_members)` fires OUTSIDE the pipeline lock for
        # every VERIFIED materialized multi-member layer — the network
        # plane re-packs it onto the aggregate topic
        self.on_layer_verified = None
        # List[List[bytes]] -> List[Optional[bytes]]: the G2 point-add of
        # each group's compressed signatures (TpuBlsVerifier's device/
        # host implementation, or a test stub's oracle)
        self._sum_fn = sum_fn
        self.scorer = scorer
        self._max_layer_indices = max_layer_indices
        self._max_stage_sets = max_stage_sets
        self._max_stage_contributions = max_stage_contributions
        self.metrics = pipeline.metrics
        self._buckets: "OrderedDict[bytes, _Bucket]" = OrderedDict()
        self._n_contribs = 0
        self._deadline: Optional[float] = None
        self._oldest_t: Optional[float] = None
        # settled-but-not-yet-delivered futures (see class docstring)
        self._deferred: List[Tuple] = []
        self._seen: "OrderedDict[Tuple, bool]" = OrderedDict()
        # cumulative stage stats (the bench probe's aggregation-factor
        # source): contributions = every submission routed through the
        # stage (followers and seen-serves included), sets = signature
        # sets handed to the verify path on its behalf (layers, bisect
        # re-aggregates, and leaves)
        self.stats = {
            "contributions": 0,
            "sets": 0,
            "dedup": 0,
            "seen_served": 0,
            "flushes": 0,
            "bisections": 0,
        }

    # -- eligibility -------------------------------------------------------

    def eligible(self, job: _Job) -> bool:
        """Standard-lane, registry-indexed wire sets only.  All-or-
        nothing per job: one ineligible set keeps the whole job on the
        plain accumulator path (the service's positional verdict
        slicing stays untouched)."""
        if not job.sets or getattr(job.opts, "priority", False):
            return False
        for s in job.sets:
            if not isinstance(s, WireSignatureSet):
                return False
            if s.pubkeys is not None or not s.indices:
                return False
            if len(s.indices) > self._max_layer_indices:
                return False
            if s.type not in (
                SignatureSetType.single,
                SignatureSetType.aggregate,
            ):
                return False
        return True

    # -- the accumulate side ----------------------------------------------

    def add_locked(self, job: _Job) -> None:
        parent = _Parent(job)
        peer = getattr(job.opts, "peer_id", None)
        topic = getattr(job.opts, "topic", None)
        for s in job.sets:
            target = (parent, peer, topic)
            self.stats["contributions"] += 1
            self.metrics.preagg_contributions.inc()
            _x0, _x1, _sgn, inf, wire_ok = parse_signature_bytes(s.signature)
            if not wire_ok or inf:
                # unparsable / infinity signatures can never verify and
                # must never poison a sum — verdict now
                self._credit_locked(target, False, release=True)
                continue
            key = s.dedupe_key()
            served = self._seen.get(key)
            if served is not None:
                self._seen.move_to_end(key)
                self.stats["seen_served"] += 1
                self.metrics.preagg_seen_served.inc()
                self._credit_locked(target, served, release=True)
                continue
            bucket = self._buckets.get(s.signing_root)
            if bucket is None:
                bucket = self._buckets[s.signing_root] = _Bucket()
            pos = bucket.index.get((s.indices, s.signature))
            if pos is not None:
                # in-flight exact duplicate: follow the twin's verdict
                bucket.contribs[pos].targets.append(target)
                self.stats["dedup"] += 1
                self.metrics.preagg_dedup.inc()
                continue
            bucket.index[(s.indices, s.signature)] = len(bucket.contribs)
            bucket.contribs.append(_Contribution(s, target))
            self._n_contribs += 1
            if self._deadline is None:
                # anchor on the OLDEST buffered contribution's enqueue
                # time (same rule as the accumulator deadlines)
                self._deadline = job.t_submit + self._lane_wait
                self._oldest_t = job.t_submit
        if self._n_contribs >= self._max_stage_contributions:
            self.flush_locked("cap")
        elif len(self._buckets) >= self._max_stage_sets:
            self.flush_locked("fill")

    def pending_contributions(self) -> int:
        return self._n_contribs

    # -- the flush side ----------------------------------------------------

    def poll_locked(self, now: float) -> Optional[float]:
        """Dispatcher hook: flush on the stage deadline; return seconds
        until it, or None when nothing is buffered."""
        if self._deadline is None:
            return None
        if now >= self._deadline:
            self.flush_locked("deadline")
            return None
        return max(self._deadline - now, 0.0)

    def flush_locked(self, reason: str) -> None:
        buckets = self._buckets
        if not buckets:
            self._deadline = self._oldest_t = None
            return
        self._buckets = OrderedDict()
        self._n_contribs = 0
        oldest_t, self._oldest_t = self._oldest_t, None
        self._deadline = None
        jobs: List[_Job] = []
        contributions = 0
        for _root, bucket in buckets.items():
            contributions += sum(len(c.targets) for c in bucket.contribs)
            layers = plan_disjoint_gathers(
                [c.wire.indices for c in bucket.contribs],
                self._max_layer_indices,
            )
            for layer in layers:
                jobs.append(
                    self._make_layer_job(
                        [bucket.contribs[p] for p in layer], oldest_t
                    )
                )
        # accounting handoff: the contributor sets leaving the stage are
        # now represented by the layer jobs' own pending counts (added
        # in _enqueue_locked, removed by the resolver) — release the
        # submission-side units so nothing is counted twice and the
        # high-water mark keeps meaning real in-flight sets
        self._release_sets_locked(contributions)
        factor = contributions / len(jobs)
        self.metrics.aggregation_factor.observe(factor)
        self.stats["flushes"] += 1
        oldest_wait = (
            time.perf_counter() - oldest_t if oldest_t is not None else 0.0
        )
        with _trace_span(
            "bls.preagg.flush",
            reason=reason,
            buckets=len(buckets),
            contributions=contributions,
            sets=len(jobs),
            factor=factor,
            oldest_wait_s=oldest_wait,
        ):
            self._enqueue_locked(jobs)

    def _make_layer_job(
        self, members: List[_Contribution], t_anchor: Optional[float]
    ) -> _Job:
        """One pending signature set for `members` (all sharing a root,
        pairwise-disjoint indices).  Multi-member layers carry their
        member wire sets until the dispatcher materializes the SUM
        (materialize_job, OUTSIDE the pipeline lock) so no submitter
        ever waits on point arithmetic."""
        job = _Job([c.wire for c in members], VerifyOptions(batchable=True))
        job.agg_members = members
        if t_anchor is not None:
            job.t_submit = t_anchor  # wait metrics span the full stage
        self.stats["sets"] += 1
        self.metrics.preagg_sets.inc()
        job.future.add_done_callback(
            lambda fut, job=job: self._on_layer_done(job, fut)
        )
        return job

    def _enqueue_locked(self, jobs: List[_Job]) -> None:
        """Queue layer jobs as ONE dispatch group (they merge into one
        RLC device job, splitting at the verifier cap) and take over
        their pending accounting."""
        if not jobs:
            return
        p = self._pipeline
        p._queue.append(jobs)
        p._pending += len(jobs)
        p._pending_sets += sum(len(j.sets) for j in jobs)
        p.metrics.pipeline_pending_sets.set(p._pending_sets)
        p.metrics.queue_length.set(p._pending)
        p._lock.notify_all()

    def materialize_job(self, job: _Job) -> None:
        """Dispatcher hook (called OUTSIDE the lock, before the device
        job begins): collapse a multi-member layer into its ONE
        aggregated wire set via the G2 sum.  If the sum is unavailable
        (an off-curve member the cheap host parse cannot see), the
        layer dispatches as its members' own sets instead — the merged
        verdict still bisects correctly on failure."""
        members = getattr(job, "agg_members", None)
        if members is None or len(job.sets) <= 1:
            return
        sig = None
        try:
            sig = self._sum_fn([[c.wire.signature for c in members]])[0]
        except Exception:  # noqa: BLE001 — aggregation is an optimization;
            sig = None  # verification must proceed without it
        if sig is None:
            return  # dispatch the members as their own sets
        root = members[0].wire.signing_root
        indices = tuple(i for c in members for i in c.wire.indices)
        before = len(job.sets)
        job.sets = [WireSignatureSet.aggregate(indices, root, sig)]
        # the group was accounted at the member count; reconcile to the
        # one aggregated set actually dispatching
        p = self._pipeline
        with p._lock:
            p._pending_sets -= before - 1
            p.metrics.pipeline_pending_sets.set(p._pending_sets)

    # -- verdict fan-out + contributor-wise bisection ----------------------

    def _on_layer_done(self, job: _Job, fut) -> None:
        """Future callback (resolver/closer thread, no pipeline lock
        held): credit members on success, bisect on failure."""
        members = getattr(job, "agg_members", None) or []
        exc = fut.exception() if fut.done() else None
        attribute: List[Tuple[Optional[str], Optional[str]]] = []
        forward: Optional[Tuple[WireSignatureSet, int]] = None
        with self._pipeline._lock:
            if exc is not None:
                for c in members:
                    for target in c.targets:
                        self._credit_locked(target, exc)
            elif fut.result():
                for c in members:
                    self._record_seen_locked(c, True)
                    for target in c.targets:
                        self._credit_locked(target, True)
                if len(members) > 1 and len(job.sets) == 1:
                    # a materialized multi-member layer VERIFIED: its
                    # union set is a re-publishable pack.  Mark the
                    # aggregated (root, indices, signature) in the
                    # seen-map too — an echoed copy of our own pack (or
                    # the same pack from a peer) serves with zero
                    # device work
                    union = job.sets[0]
                    self._seen[union.dedupe_key()] = True
                    self._seen.move_to_end(union.dedupe_key())
                    while len(self._seen) > SEEN_VERDICTS:
                        self._seen.popitem(last=False)
                    if self.on_layer_verified is not None:
                        forward = (union, len(members))
            elif len(members) <= 1:
                for c in members:
                    self._record_seen_locked(c, False)
                    for target in c.targets:
                        self._credit_locked(target, False)
                        if target[1] is not None:
                            attribute.append((target[1], target[2]))
            else:
                # contributor-wise bisection (the PR 10 shape): both
                # halves re-aggregate and dispatch as ONE group so they
                # pipeline on the device stream; failing halves recurse
                # through this same callback, leaves verify the
                # original wire set
                self.stats["bisections"] += 1
                self.metrics.preagg_bisections.inc()
                mid = (len(members) + 1) // 2
                halves = [members[:mid], members[mid:]]
                if not self._pipeline._closed:
                    self._enqueue_locked(
                        [self._make_layer_job(h, None) for h in halves]
                    )
                else:
                    err = RuntimeError("verifier closed")
                    for c in members:
                        for target in c.targets:
                            self._credit_locked(target, err)
        for peer, topic in attribute:
            # an isolated invalid contributor charges its publisher
            # (gossipsub P4 invalid-delivery, network/scoring.py) —
            # outside the pipeline lock, the scorer has its own state
            if self.scorer is not None:
                try:
                    self.scorer.on_invalid_message(peer, topic)
                except Exception:  # noqa: BLE001 — scoring must never
                    pass  # break verdict delivery
        if forward is not None:
            # re-publication is an optimization running on the resolver
            # thread: a forwarder fault must never break verdict fan-out
            try:
                self.on_layer_verified(forward[0], forward[1])
            except Exception:  # noqa: BLE001
                pass
        self.drain()

    def _record_seen_locked(self, c: _Contribution, verdict: bool) -> None:
        key = c.wire.dedupe_key()
        self._seen[key] = verdict
        self._seen.move_to_end(key)
        while len(self._seen) > SEEN_VERDICTS:
            self._seen.popitem(last=False)

    def _release_sets_locked(self, n: int) -> None:
        """Release `n` submission-side set units from the pipeline's
        pending accounting (see _Parent's contract: exactly once per
        set — at stage flush for sets handing off into layer jobs, or
        at credit time for sets judged without flushing)."""
        if not n:
            return
        p = self._pipeline
        p._pending_sets -= n
        p.metrics.pipeline_pending_sets.set(p._pending_sets)
        p._lock.notify_all()

    def _credit_locked(self, target, verdict, release: bool = False) -> None:
        parent, _peer, _topic = target
        if release:
            # this set never flushed into a layer job (unparsable,
            # seen-served, or rejected while buffered): its unit is
            # released here instead of at the flush handoff
            self._release_sets_locked(1)
        if isinstance(verdict, BaseException):
            parent.exc = verdict
        elif not verdict:
            parent.ok = False
        parent.remaining -= 1
        if parent.remaining > 0 or parent.settled:
            return
        parent.settled = True
        p = self._pipeline
        p._pending -= 1
        p.metrics.pipeline_pending_sets.set(p._pending_sets)
        p.metrics.queue_length.set(p._pending)
        p._lock.notify_all()
        self._deferred.append(
            (parent.job.future, parent.exc if parent.exc is not None else parent.ok)
        )

    def seen_verdict(self, wire: WireSignatureSet) -> Optional[bool]:
        """Resolved verdict for an EXACT (root, indices, signature)
        match, else None.  The gossip handlers' suppressed-duplicate
        recovery serves from here instead of paying a standalone
        verification (ISSUE 13 satellite); exact-match-only so a forged
        duplicate with a different signature can never ride an honest
        verdict."""
        with self._pipeline._lock:
            return self._seen.get(wire.dedupe_key())

    # -- settlement + shutdown --------------------------------------------

    def drain(self) -> None:
        """Deliver deferred verdicts (never called under the lock)."""
        with self._pipeline._lock:
            pending, self._deferred = self._deferred, []
        for fut, verdict in pending:
            if fut.done():
                continue
            if isinstance(verdict, BaseException):
                fut.set_exception(verdict)
            else:
                fut.set_result(verdict)

    def close_locked(self) -> None:
        """Reject every buffered contribution (the pipeline is closing;
        queued/in-flight layer jobs are rejected by the base path and
        credit their members through the future callbacks)."""
        buckets, self._buckets = self._buckets, OrderedDict()
        self._n_contribs = 0
        self._deadline = self._oldest_t = None
        err = RuntimeError("verifier closed")
        for bucket in buckets.values():
            for c in bucket.contribs:
                for target in c.targets:
                    # still buffered => never flushed => release here
                    self._credit_locked(target, err, release=True)

    def mean_aggregation_factor(self) -> Optional[float]:
        """contributions per verified set over the stage lifetime — the
        ISSUE 13 acceptance number (>= 3 under a duplicate-heavy
        flood)."""
        with self._pipeline._lock:
            if not self.stats["sets"]:
                return None
            return self.stats["contributions"] / self.stats["sets"]

    def stats_snapshot(self) -> dict:
        with self._pipeline._lock:
            return dict(self.stats)


__all__ = [
    "PreVerifyAggregator",
    "MAX_LAYER_INDICES",
    "MAX_STAGE_SETS",
    "MAX_STAGE_CONTRIBUTIONS",
]
