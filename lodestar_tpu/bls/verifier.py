"""TpuBlsVerifier — the IBlsVerifier implementation backed by the pallas
verification pipeline (kernels/verify.py).

Semantics reproduced from the reference (packages/beacon-node/src/chain/bls):

  - `verify_signature_sets(sets, batchable=...)` returns True iff EVERY set
    verifies (interface.ts:20-51).
  - Batchable jobs with >= 2 sets use random-linear-combination batch
    verification (maybeBatch.ts:16-27); on batch failure every set is
    re-verified individually so one bad signature cannot poison honest
    peers' messages (multithread/worker.ts:74-96), with
    `batch_retries`/`batch_sigs_success` accounted identically.
  - Jobs are chunked to <= MAX_JOB_SETS sets (the reference caps at 128,
    multithread/index.ts:39; the device path raises it to 512 so RLC
    batches amortize further and the bisection fallback is reachable).
  - `can_accept_work()` mirrors the 512-pending-job backpressure bound
    consumed by the gossip NetworkProcessor (multithread/index.ts:143-149,
    processor/index.ts:357-371).
  - `verify_on_main_thread` verifies synchronously on the host CPU — the
    latency fast path for block proposer signatures
    (reference: chain/validation/block.ts:146).

TPU-specific structure: sets are padded into fixed shape buckets
(N-bucket x K-bucket) so the pallas pipeline compiles once per bucket;
pubkeys are gathered from the device-resident table and aggregate sets
point-add on device; randomizers come from the OS CSPRNG.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import curves as C
from ..kernels import layout as LY
from ..kernels import verify as KV
from ..observability import enabled as _trace_enabled
from ..observability import trace_span as _trace_span
from ..ops import bls_kernels as BK
from ..utils.metrics import BlsPoolMetrics
from .ingest import MessageCache, encode_wire_planes
from .pubkey_table import PubkeyTable
from .signature_set import SignatureSet, WireSignatureSet
from .supervisor import (
    OUTCOME_BACKEND_INIT,
    OUTCOME_TIMEOUT,
    DeviceSupervisor,
    check_verdict_plane,
    classify_failure,
)

# Raised from the reference's 128 (chain/bls/multithread/index.ts:39):
# that cap keeps CPU worker-pool jobs small for scheduling fairness,
# which doesn't apply to one async device stream — and RLC batch
# verification WANTS jobs past one 128-lane tile, both for the final-exp
# amortization and because the bisection fallback only sheds work above
# the one-tile leaf.  Directly-submitted large batches (range sync,
# backfill) now ride 512-set RLC jobs; gossip latency is governed by the
# service coalescing window (bls/service.py), not this cap.
MAX_JOB_SETS = 512
MAX_PENDING_JOBS = 512      # reference: chain/bls/multithread/index.ts:64
# N buckets are multiples of the kernel lane tile (kernels/verify.py BT):
# a smaller job pads to one 128-lane tile, which costs the same wall time
# as a full tile (vector lanes are parallel hardware).
N_BUCKETS = (128, 256, 512, 1024, 2048)
K_BUCKETS = (1, 4, 16, 64, 512, 2048)
# Largest aggregate the device path handles (a full 2048-validator mainnet
# committee); beyond it the set is verified on the CPU ground-truth path.
MAX_AGG_INDICES = K_BUCKETS[-1]


class VerifyOptions:
    def __init__(
        self,
        batchable: bool = False,
        verify_on_main_thread: bool = False,
        priority: bool = False,
        peer_id: Optional[str] = None,
        topic: Optional[str] = None,
    ):
        self.batchable = batchable
        self.verify_on_main_thread = verify_on_main_thread
        # block-critical batchable sets (proposer signatures, aggregate-
        # and-proof): the accumulate-and-flush pipeline (bls/pipeline.py)
        # routes these onto its short-deadline lane so they are never
        # starved behind subnet-attestation bucket fill
        self.priority = priority
        # publish attribution (ISSUE 13): when the pre-verify
        # aggregation stage isolates THIS submission's signature as the
        # invalid one in a failed aggregate, the named peer is charged
        # through the gossip scorer (bls/aggregator.py)
        self.peer_id = peer_id
        self.topic = topic


class _DeviceJob:
    """An in-flight device job: lazy result handles + host-side context."""

    __slots__ = ("sets", "batchable", "ok_big", "args", "valid", "decodable",
                 "batch_ok", "per_set", "wire", "verdicts", "n_bucket",
                 "batch_retries", "batch_sigs_success", "unsort", "host_mode")

    def __init__(self, sets, batchable, ok_big, wire=False):
        self.sets = sets
        self.batchable = batchable
        self.ok_big = ok_big
        self.wire = wire
        # degraded-mode job (breaker open or dispatch failed): no device
        # handles; finish_job resolves it on the host ground-truth path
        self.host_mode = False
        self.n_bucket = 0  # padded N of the dispatched device job
        self.args = None
        self.valid = None
        self.decodable = None
        self.batch_ok = None  # lazy device scalar (RLC batch verdict)
        self.per_set = None  # lazy device vector (per-set verdicts)
        self.verdicts = None  # host per-set bools, set by finish_job retry
        # device planes may be SORTED by signing root (message grouping);
        # unsort[i] = plane lane of original set i (None = identity)
        self.unsort = None
        # per-job accounting (BlsWorkResult parity without racing the
        # process-global counters — the service reads these)
        self.batch_retries = 0
        self.batch_sigs_success = 0


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def _enc(vals) -> jnp.ndarray:
    # plain limbs — the device converts to Montgomery form (kernels/verify)
    return jnp.asarray(LY.encode_plain_batch(vals))


class TpuBlsVerifier:
    """The device-backed IBlsVerifier.

    One instance owns the pubkey table; the jitted pipeline is shared
    process-wide (jax.jit caches per bucket shape).  Concurrency control
    (job queue depth) models the reference's thread-pool backpressure
    contract.
    """

    def __init__(
        self,
        table: PubkeyTable,
        metrics: Optional[BlsPoolMetrics] = None,
        rng: Optional[np.random.Generator] = None,
        max_job_sets: int = MAX_JOB_SETS,
        bisect_leaf: Optional[int] = None,
        supervisor: Optional[DeviceSupervisor] = None,
    ):
        self.table = table
        self.metrics = metrics or BlsPoolMetrics()
        # None => OS CSPRNG randomizers (production); seeded rng for tests.
        self.rng = rng
        # Device job size: 128 mirrors the reference's per-worker cap; the
        # service raises it (512-2048) so each ~65 ms tunnel dispatch
        # carries more sets (dev/NOTES.md dispatch floor).
        # clamp to the largest device bucket: begin_job cannot exceed it
        self.max_job_sets = min(max_job_sets, N_BUCKETS[-1])
        # signing-root -> hashed G2 message, device-batched (wire path)
        self.messages = MessageCache()
        self._pending_jobs = 0
        # AOT export cache: on the TPU backend the top-level pipeline is
        # traced once per shape EVER (persisted to disk via jax.export)
        # instead of once per process — the ~10-minute per-process trace
        # cost on the 1-core driver host becomes a millisecond
        # deserialize (kernels/export_cache.py).  Off on CPU: the
        # monolithic graph is XLA:CPU-hostile (dev/NOTES.md).
        env = os.environ.get("LODESTAR_TPU_EXPORT")
        if env is not None:
            self._use_export = env.strip().lower() not in (
                "0", "false", "no", "off", "",
            )
        else:
            self._use_export = jax.default_backend() == "tpu"
        # RLC batch-verification escape hatch: LODESTAR_TPU_BLS_RLC=0
        # forces per-set device verdicts for every job (the pre-RLC
        # behavior) — soundness of the batch check rests on the 128-bit
        # randomizers, so operators get a kill switch.  Default on.
        rlc_env = os.environ.get("LODESTAR_TPU_BLS_RLC", "1")
        self._use_rlc = rlc_env.strip().lower() not in (
            "0", "false", "no", "off",
        )
        # Bisection stops splitting at one lane tile: below KV.BT every
        # sub-job pads to the same 128-lane bucket, so halving further
        # cannot shed device work and the leaf runs per-set verdicts.
        self.bisect_leaf = KV.BT if bisect_leaf is None else bisect_leaf
        # Fault-domain isolation (ISSUE 14): every device dispatch seam
        # runs under this circuit breaker — classified failures trip it
        # into a degraded mode that resolves jobs on the host
        # ground-truth path, and a canary re-probe restores the device
        # path.  LODESTAR_TPU_BLS_BREAKER=0 disables supervision.
        self.supervisor = supervisor or DeviceSupervisor(
            registry=self.metrics.registry
        )
        if self.supervisor.canary is None:
            self.supervisor.canary = self._device_canary

    def _device_call(self, name: str, fn, args):
        """Dispatch through the AOT export cache when enabled; plain
        call otherwise.  `name` keys the artifact with the arg shapes."""
        if not self._use_export:
            return fn(*args)
        try:
            from ..kernels import export_cache as EC

            # read shape/dtype WITHOUT materializing on device: numpy
            # and jax arrays both carry .dtype; jnp.asarray here would
            # pay a full H2D transfer per arg just to inspect it
            specs = [
                jax.ShapeDtypeStruct(
                    jnp.shape(a), getattr(a, "dtype", np.asarray(a).dtype)
                )
                for a in args
            ]
            call = EC.load_or_export(name, fn, specs)
            return call(*args)
        except Exception as e:  # noqa: BLE001 — the export layer must
            # never take down verification; fall back to the direct path
            import logging

            logging.getLogger("lodestar_tpu").warning(
                "export-cache dispatch failed (%s); direct call", e
            )
            # If the direct call ALSO fails, its exception propagates to
            # the calling seam, which records exactly ONE breaker
            # failure for the event (no double count).  If it succeeds,
            # the device demonstrably answered: surface a backend-init/
            # timeout export fault on the failure metric for visibility
            # WITHOUT advancing the trip streak.
            out = fn(*args)
            outcome = classify_failure(e)
            if outcome in (OUTCOME_BACKEND_INIT, OUTCOME_TIMEOUT):
                self.supervisor.note_nonfatal(
                    outcome, f"export:{name}", str(e)
                )
            return out

    # -- backpressure (reference: multithread/index.ts:143-149) -----------

    def can_accept_work(self) -> bool:
        return self._pending_jobs < MAX_PENDING_JOBS

    # -- the main entry (reference: bls/interface.ts verifySignatureSets) --

    def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: Optional[VerifyOptions] = None
    ) -> bool:
        if not sets:
            return True
        opts = opts or VerifyOptions()
        t_start = time.perf_counter()
        self._pending_jobs += 1
        try:
            with _trace_span(
                "bls.verify",
                batch_size=len(sets),
                batchable=opts.batchable,
                main_thread=opts.verify_on_main_thread,
            ):
                if opts.verify_on_main_thread:
                    verdicts = [
                        self._verify_set_cpu(
                            s.decode() if isinstance(s, WireSignatureSet) else s
                        )
                        for s in sets
                    ]
                    good = sum(verdicts)
                    self.metrics.success_jobs.inc(good)
                    self.metrics.invalid_sets.inc(len(sets) - good)
                    return all(verdicts)
                # Dispatch every chunk before syncing any: chunks
                # pipeline on the device stream instead of paying the
                # tunnel round-trip serially per chunk.
                jobs = [
                    self.begin_job(
                        list(sets[i : i + self.max_job_sets]), opts.batchable
                    )
                    for i in range(0, len(sets), self.max_job_sets)
                ]
                ok = True
                for job in jobs:
                    ok &= self.finish_job(job)
                return ok
        finally:
            self._pending_jobs -= 1
            dt = time.perf_counter() - t_start
            self.metrics.job_time.observe(dt)
            self.metrics.time_per_sig_set.observe(dt / len(sets))
            self.metrics.batch_size.observe(len(sets))
            self.metrics.verify_seconds.observe("total", dt)

    # -- job execution ----------------------------------------------------

    def _prepare(self, sets: List[SignatureSet]):
        """Pad sets into an (N, K) bucket and encode the device planes."""
        n = _bucket(len(sets), N_BUCKETS)
        kmax = _bucket(max(len(s.indices) for s in sets), K_BUCKETS)
        idx = np.zeros((n, kmax), np.int32)
        kmask = np.zeros((n, kmax), np.int32)
        valid = np.zeros((n,), np.int32)
        sig_inf = np.zeros((n,), np.int32)
        msgs, sigs = [], []
        g2 = C.G2_GEN
        for i, s in enumerate(sets):
            k = len(s.indices)
            idx[i, :k] = s.indices
            kmask[i, :k] = 1
            valid[i] = 1
            msgs.append(s.message)
            if s.signature is None:
                # undecodable/infinity: the kernel fails the set via sig_inf
                sig_inf[i] = 1
                sigs.append(g2)
            else:
                sigs.append(s.signature)
        for _ in range(n - len(sets)):
            msgs.append(g2)
            sigs.append(g2)
        tx, ty = self.table.device_planes()
        args = (
            tx, ty, jnp.asarray(idx), jnp.asarray(kmask),
            _enc([m[0][0] for m in msgs]), _enc([m[0][1] for m in msgs]),
            _enc([m[1][0] for m in msgs]), _enc([m[1][1] for m in msgs]),
            _enc([s[0][0] for s in sigs]), _enc([s[0][1] for s in sigs]),
            _enc([s[1][0] for s in sigs]), _enc([s[1][1] for s in sigs]),
            jnp.asarray(sig_inf),
        )
        return args, jnp.asarray(valid), n

    def _verify_set_cpu(self, s: SignatureSet) -> bool:
        """Ground-truth verification of one set on the host CPU.

        Used for `verify_on_main_thread` (latency fast path) and for
        aggregates too large for the device buckets.  Pubkeys were
        KeyValidated at table registration; messages are in-subgroup by
        construction (hash_to_g2)."""
        if s.signature is None:
            return False
        from ..crypto import bls as CB
        from ..crypto import pairing as CP

        if not C.is_on_curve(C.FP2_OPS, s.signature):
            return False
        if not C.g2_subgroup_check(s.signature):
            return False
        if s.external_pubkeys is not None:
            # keys outside the registry were never KeyValidated — do it here
            for pk in s.external_pubkeys:
                if (
                    pk is None
                    or not C.is_on_curve(C.FP_OPS, pk)
                    or not C.g1_subgroup_check(pk)
                ):
                    return False
            keys = list(s.external_pubkeys)
        else:
            keys = [self.table.host_affine(i) for i in s.indices]
        agg = C.multi_add(C.FP_OPS, keys)
        if agg is None:  # aggregate pubkey at infinity never verifies
            return False
        return CP.multi_pairing_is_one(
            [(agg, s.message), (CB.NEG_G1_GEN, s.signature)]
        )

    # -- pre-verify signature aggregation (ISSUE 13) ----------------------

    def aggregate_wire_signatures(
        self, groups: Sequence[Sequence[bytes]]
    ) -> List[Optional[bytes]]:
        """Point-add each group's compressed G2 signatures -> one
        compressed aggregate per group (None when a member is
        undecodable — the caller then dispatches the members
        unaggregated).  This is the aggregation stage's sum seam
        (bls/aggregator.py): on the TPU backend the adds run in one
        batched device dispatch (kernels/verify.aggregate_g2_sum_device
        via the `agg_g2_sum` export-cache entry); elsewhere — and as
        the fault fallback — the host ground-truth path decompresses
        and jacobian-adds per group."""
        groups = [list(g) for g in groups]
        if not groups:
            return []
        if self._use_agg_device() and self.supervisor.device_allowed():
            try:
                out = self.supervisor.run_guarded(
                    lambda: self._aggregate_wire_device(groups),
                    "agg_g2_sum",
                )
                self.supervisor.record_success()
                return out
            except Exception as e:  # noqa: BLE001 — aggregation must
                # never take down verification; host fallback
                import logging

                self.supervisor.record_failure(
                    classify_failure(e), "agg_g2_sum", str(e)
                )
                logging.getLogger("lodestar_tpu").warning(
                    "device signature aggregation failed (%s); host path", e
                )
        return [self._aggregate_wire_host(g) for g in groups]

    def _use_agg_device(self) -> bool:
        env = os.environ.get("LODESTAR_TPU_AGG_DEVICE")
        if env is not None:
            return env.strip().lower() not in ("0", "false", "no", "off", "")
        return jax.default_backend() == "tpu"

    @staticmethod
    def _aggregate_wire_host(sigs: List[bytes]) -> Optional[bytes]:
        from ..crypto.curves import g2_compress, g2_decompress

        pts = []
        for s in sigs:
            try:
                pts.append(g2_decompress(s))
            except ValueError:
                return None
        return g2_compress(C.multi_add(C.FP2_OPS, pts))

    def _aggregate_wire_device(
        self, groups: List[List[bytes]]
    ) -> List[Optional[bytes]]:
        """One `agg_g2_sum` dispatch per <= BT groups: segmented G2 sum
        of the decompressed signatures, group heads converted to affine
        on device, compressed back on the host (no sqrt — y is known)."""
        out: List[Optional[bytes]] = []
        start = 0
        while start < len(groups):
            chunk: List[List[bytes]] = []
            total = 0
            while (
                start + len(chunk) < len(groups)
                and len(chunk) < KV.BT
                and (
                    not chunk
                    or total + len(groups[start + len(chunk)])
                    <= N_BUCKETS[-1]
                )
            ):
                total += len(groups[start + len(chunk)])
                chunk.append(groups[start + len(chunk)])
            out.extend(self._aggregate_chunk_device(chunk, total))
            start += len(chunk)
        return out

    def _aggregate_chunk_device(
        self, chunk: List[List[bytes]], total: int
    ) -> List[Optional[bytes]]:
        from ..crypto.curves import g2_compress
        from .ingest import encode_wire_planes

        n = _bucket(total, N_BUCKETS)
        flat = [s for g in chunk for s in g]
        sig_x0, sig_x1, flags, host_bad = encode_wire_planes(flat, n)
        group = np.zeros(n, np.int32)
        head_lanes = np.zeros(KV.BT, np.int32)
        glive = np.zeros(KV.BT, np.int32)
        pos = 0
        for gi, g in enumerate(chunk):
            group[pos : pos + len(g)] = gi
            pos += len(g)
            head_lanes[gi] = pos - 1
            glive[gi] = 1
        # padding lanes: fresh ids so they can never merge into the
        # last real group (they are dead either way)
        if n > total:
            group[total:] = np.arange(
                len(chunk), len(chunk) + n - total, dtype=np.int32
            )
        ax0, ax1, ay0, ay1, g_inf, ok_row = self._device_call(
            "agg_g2_sum",
            KV.aggregate_g2_sum_device,
            (
                jnp.asarray(sig_x0), jnp.asarray(sig_x1), jnp.asarray(flags),
                jnp.asarray(group), jnp.asarray(head_lanes),
                jnp.asarray(glive),
            ),
        )
        ok = np.asarray(ok_row)[0, :total] != 0
        ok &= ~host_bad[:total]
        g_inf = np.asarray(g_inf)[0] != 0
        ax0, ax1, ay0, ay1 = (
            np.asarray(a) for a in (ax0, ax1, ay0, ay1)
        )
        out: List[Optional[bytes]] = []
        pos = 0
        rinv, p = LY.R_INV, LY.P
        for gi, g in enumerate(chunk):
            members_ok = bool(ok[pos : pos + len(g)].all())
            pos += len(g)
            if not members_ok:
                # an off-curve/undecodable member: the device excluded
                # it from the sum, so the total is NOT the aggregate —
                # the caller falls back to unaggregated dispatch
                out.append(None)
                continue
            if g_inf[gi]:
                out.append(g2_compress(None))
                continue
            x = (
                int(LY.from_limbs(ax0[:, gi])) * rinv % p,
                int(LY.from_limbs(ax1[:, gi])) * rinv % p,
            )
            y = (
                int(LY.from_limbs(ay0[:, gi])) * rinv % p,
                int(LY.from_limbs(ay1[:, gi])) * rinv % p,
            )
            out.append(g2_compress((x, y)))
        return out

    def begin_job(self, sets: List[SignatureSet], batchable: bool) -> "_DeviceJob":
        """Dispatch one job (<= max_job_sets sets) WITHOUT blocking.

        JAX dispatch is asynchronous: several begun jobs queue on the
        device stream and overlap the ~65 ms host<->device tunnel latency
        (dev/NOTES.md); `finish_job` syncs verdicts in order.

        Everything in here is HOST work (plane encoding, padding,
        dispatch) — it feeds the `lodestar_bls_verify_seconds{phase="host"}`
        series; `finish_job` owns the device-sync side.
        """
        t0 = time.perf_counter()
        with _trace_span(
            "bls.begin_job", sets=len(sets), batchable=batchable
        ) as span:
            sup = self.supervisor
            if not sup.device_allowed():
                # breaker open: degraded mode — no device dispatch at
                # all; the job resolves on the host ground-truth path at
                # finish time (resolver thread), so submitters never
                # block and no set is dropped
                job = self._begin_job_host(sets, batchable)
            else:
                try:
                    job = self._begin_job(sets, batchable, span)
                except Exception as e:  # noqa: BLE001 — a dispatch
                    # fault must not unwind through the service; trip
                    # the breaker and fall back to the host path
                    if not sup.active:
                        raise
                    sup.record_failure(
                        classify_failure(e), "begin_job", str(e)
                    )
                    job = self._begin_job_host(sets, batchable)
        self.metrics.verify_seconds.observe(
            "host", time.perf_counter() - t0
        )
        return job

    def _begin_job_host(
        self, sets: List[SignatureSet], batchable: bool
    ) -> "_DeviceJob":
        """A degraded-mode job: no device planes, no dispatch — the
        resolver-side finish_job computes every verdict through
        `_verify_set_host`.  NOTE: if the device dispatch failed partway
        through `_begin_job`, any CPU-routed ("big") sets it already
        verified are re-verified here — verdicts stay correct, only the
        success/invalid counters may double-count on that rare path."""
        wire = bool(sets) and isinstance(sets[0], WireSignatureSet)
        job = _DeviceJob(list(sets), batchable, True, wire)
        job.host_mode = True
        return job

    def _begin_job(
        self, sets: List[SignatureSet], batchable: bool, span=None
    ) -> "_DeviceJob":
        assert len(sets) <= self.max_job_sets
        wire = bool(sets) and isinstance(sets[0], WireSignatureSet)
        assert all(
            isinstance(s, WireSignatureSet) == wire for s in sets
        ), "begin_job requires a homogeneous wire/decoded job (service splits)"
        # CPU-path sets: aggregates beyond the largest device bucket
        # (> MAX_AGG_INDICES participants — an oversized but legitimate
        # aggregate still gets a verdict) and sets signed by keys outside
        # the validator registry.
        def _cpu_only(s):
            if len(s.indices) > MAX_AGG_INDICES:
                return True
            # getattr: a mixed-type group (service merge) must not crash
            return (
                getattr(s, "pubkeys", None) is not None
                or getattr(s, "external_pubkeys", None) is not None
            )

        big = [s for s in sets if _cpu_only(s)]
        if big:
            sets = [s for s in sets if not _cpu_only(s)]
            verdicts = [
                self._verify_set_cpu(s.decode() if wire else s) for s in big
            ]
            good = sum(verdicts)
            self.metrics.success_jobs.inc(good)
            self.metrics.invalid_sets.inc(len(big) - good)
            ok_big = all(verdicts)
        else:
            ok_big = True
        job = _DeviceJob(sets, batchable, ok_big, wire)
        if not sets:
            return job

        if wire:
            # SORT by signing root: lane-contiguous message groups let
            # the batch path run ONE Miller tile per distinct root
            # (kernels/verify.py grouping rationale) instead of one per
            # set.  Verdict order is restored through job.unsort.
            order = sorted(
                range(len(sets)), key=lambda i: sets[i].signing_root
            )
            if order != list(range(len(sets))):
                sets = [sets[i] for i in order]
                job.sets = sets
                job.unsort = np.empty(len(order), np.int64)
                job.unsort[np.asarray(order)] = np.arange(len(order))
            job.args, job.valid, n, host_bad = self._prepare_wire(sets)
            job.decodable = ~host_bad[: len(sets)]
        else:
            job.args, job.valid, n = self._prepare(sets)
            job.decodable = np.array([s.signature is not None for s in sets])
        job.n_bucket = n
        if span is not None and _trace_enabled():
            # the (N, K) shape bucket names which compiled pipeline this
            # job rides — the export-cache-bucketing ROADMAP item's unit
            span.set(
                wire=wire,
                n_bucket=n,
                k_bucket=_bucket(
                    max(len(s.indices) for s in sets), K_BUCKETS
                ),
            )
        batchable_job = batchable and len(sets) >= 2
        if batchable_job:
            # reference: maybeBatch.ts:16 (batch iff >= 2 sets)
            self.metrics.batchable_sigs.inc(len(sets))
        if batchable_job and self._use_rlc and job.decodable.all():
            job.batch_ok = self._dispatch_rlc_batch(
                sets, job.args, job.valid, n, wire
            )
        else:
            if batchable_job and self._use_rlc:
                # an undecodable signature voids the merged batch: count it
                # as a batch retry and go straight to per-set verdicts
                # (with RLC disabled nothing was batched, so no retry)
                self.metrics.batch_retries.inc()
                job.batch_retries += 1
            job.per_set = self._device_call(
                "each_wire" if job.wire else "each_decoded",
                self._each_fn(job),
                (*job.args, job.valid),
            )
        return job

    def _each_fn(self, job):
        return KV.verify_each_device_wire if job.wire else KV.verify_each_device

    def _grouping(self, sets, n):
        """Distinct-message group arrays for the grouped batch path
        (kernels/verify.py verify_batch_device_wire_grouped), or None
        when grouping does not apply: more distinct roots than one lane
        tile, or no duplicate roots at all (nothing to collapse).

        `sets` MUST be sorted by signing_root (begin_job does)."""
        roots = [s.signing_root for s in sets]
        group = np.zeros(n, np.int32)
        heads = []
        g = 0
        for i in range(1, len(sets)):
            if roots[i] != roots[i - 1]:
                heads.append(i - 1)
                g += 1
            group[i] = g
        heads.append(len(sets) - 1)
        n_groups = g + 1
        if n_groups > KV.BT or n_groups == len(sets):
            return None
        # padding lanes: fresh ids so they cannot merge into the last
        # real group (they are dead either way; this keeps it explicit)
        if n > len(sets):
            group[len(sets):] = np.arange(
                n_groups, n_groups + n - len(sets), dtype=np.int32
            )
        head_lanes = np.zeros(KV.BT, np.int32)
        head_lanes[:n_groups] = heads
        glive = np.zeros(KV.BT, np.int32)
        glive[:n_groups] = 1
        return (
            jnp.asarray(group),
            jnp.asarray(head_lanes),
            jnp.asarray(glive),
        )

    def _prepare_wire(self, sets: List[WireSignatureSet]):
        """Wire sets -> device planes: hashed messages from the device
        MessageCache, signatures as compressed-x limbs + flag bits."""
        n = _bucket(len(sets), N_BUCKETS)
        kmax = _bucket(max(len(s.indices) for s in sets), K_BUCKETS)
        idx = np.zeros((n, kmax), np.int32)
        kmask = np.zeros((n, kmax), np.int32)
        valid = np.zeros((n,), np.int32)
        for i, s in enumerate(sets):
            k = len(s.indices)
            idx[i, :k] = s.indices
            kmask[i, :k] = 1
            valid[i] = 1
        msgs = self.messages.get_many([s.signing_root for s in sets])
        g2 = C.G2_GEN
        msgs = msgs + [g2] * (n - len(sets))
        sig_x0, sig_x1, flags, host_bad = encode_wire_planes(
            [s.signature for s in sets], n
        )
        tx, ty = self.table.device_planes()
        args = (
            tx, ty, jnp.asarray(idx), jnp.asarray(kmask),
            _enc([m[0][0] for m in msgs]), _enc([m[0][1] for m in msgs]),
            _enc([m[1][0] for m in msgs]), _enc([m[1][1] for m in msgs]),
            jnp.asarray(sig_x0), jnp.asarray(sig_x1), jnp.asarray(flags),
        )
        return args, jnp.asarray(valid), n, host_bad

    def finish_job(self, job: "_DeviceJob") -> bool:
        """Sync a begun job's device results and produce the verdict.

        This is the device-sync leg (plus any per-set retry dispatch) —
        it feeds `lodestar_bls_verify_seconds{phase="device"}`."""
        t0 = time.perf_counter()
        with _trace_span("bls.finish_job", sets=len(job.sets)):
            sup = self.supervisor
            if getattr(job, "host_mode", False):
                ok = self._finish_job_host(job)
            elif not sup.active:
                ok = self._finish_job(job)
            else:
                # With a watchdog armed, the device sync runs against a
                # SHALLOW CLONE: a timeout abandons (not cancels) the
                # worker thread, and a late-returning orphan must
                # mutate only its clone — never the job object whose
                # verdicts the service is about to read (host fallback
                # wins).  Verifier-level counters may still double-
                # count on that rare orphan completion; per-job verdict
                # state cannot.  Without a deadline run_guarded is an
                # inline call — no orphan can exist, so no clone.
                if sup.job_deadline_s:
                    import copy as _copy

                    target = _copy.copy(job)
                else:
                    target = job
                try:
                    ok = sup.run_guarded(
                        lambda: self._finish_job(target), "finish_job"
                    )
                    if target is not job:
                        job.verdicts = target.verdicts
                        job.batch_retries = target.batch_retries
                        job.batch_sigs_success = target.batch_sigs_success
                    sup.record_success()
                except Exception as e:  # noqa: BLE001 — a device sync
                    # fault mid-job: classify, trip, and resolve THIS
                    # job's verdicts on the host path (zero lost sets)
                    sup.record_failure(
                        classify_failure(e), "finish_job", str(e)
                    )
                    ok = self._finish_job_host(job)
        self.metrics.verify_seconds.observe(
            "device", time.perf_counter() - t0
        )
        return ok

    def _verify_set_host(self, s) -> bool:
        """Ground-truth verdict for ONE set, wire or decoded — the
        degraded-mode seam every host-routed job resolves through.
        Bit-identical to the device path by the repo's standing
        equivalence invariant (tests/test_kernels_verify.py and the
        breaker property tests assert it)."""
        return self._verify_set_cpu(
            s.decode() if isinstance(s, WireSignatureSet) else s
        )

    def _finish_job_host(self, job: "_DeviceJob") -> bool:
        """Resolve one job entirely on the host ground-truth path.
        Handles both degraded-mode jobs (never dispatched) and jobs
        whose device sync failed mid-flight (planes may be sorted:
        verdict order is restored through job.unsort)."""
        sets = job.sets
        if not sets:
            return job.ok_big
        v = np.array([self._verify_set_host(s) for s in sets], bool)
        if job.unsort is not None:
            v = v[job.unsort]
        job.verdicts = v
        good = int(v.sum())
        self.metrics.success_jobs.inc(good)
        self.metrics.invalid_sets.inc(len(sets) - good)
        self.supervisor.note_host_fallback(len(sets))
        return job.ok_big and bool(v.all())

    def _finish_job(self, job: "_DeviceJob") -> bool:
        sets = job.sets
        if not sets:
            return job.ok_big
        if job.batch_ok is not None:
            per_set = self._resolve_rlc_batch(job)
            if per_set is None:
                return job.ok_big  # batch verdict accepted every set
        else:
            per_set = (
                check_verdict_plane(job.per_set, len(sets), "each")[
                    : len(sets)
                ]
                & job.decodable
            )
        if job.unsort is not None:
            # planes were sorted by signing root: restore the caller's
            # submission order (the service maps verdicts positionally)
            per_set = per_set[job.unsort]
        job.verdicts = per_set  # callers can slice per-set results
        good = int(per_set.sum())
        self.metrics.success_jobs.inc(good)
        self.metrics.invalid_sets.inc(len(sets) - good)
        return job.ok_big and bool(per_set.all())

    # -- RLC batch resolution + bisection fallback ------------------------

    def _resolve_rlc_batch(self, job: "_DeviceJob"):
        """Sync a dispatched RLC batch verdict.  Returns None when the
        batch accepted (all sets verified by the one multi-pairing
        check) or the per-set verdict array (job.sets order) after the
        fallback.  The `bls.rlc_batch` span brackets the device sync
        plus any fallback work; bisect_depth=0 means the plain per-set
        retry (job at or under the one-tile bisection leaf)."""
        sets = job.sets
        with _trace_span(
            "bls.rlc_batch", sets=len(sets), n_bucket=job.n_bucket
        ) as span:
            if bool(job.batch_ok):  # device sync point
                if _trace_enabled():
                    span.set(accepted=True, bisect_depth=0)
                self.metrics.batch_sigs_success.inc(len(sets))
                job.batch_sigs_success += len(sets)
                self.metrics.success_jobs.inc(len(sets))
                return None
            # batch failed (only fully-decodable jobs are dispatched as
            # batches — _begin_job routes undecodables straight to
            # per-set): find the bad sets without poisoning honest ones
            # (reference: multithread/worker.ts:74-96).  Above the one-tile
            # leaf the
            # job bisects — halves re-verify as smaller RLC batches
            # (reusing the smaller N-bucket artifacts) so one bad set in
            # a big job costs O(log N) batch checks instead of a full
            # per-set sweep; at or under the leaf it goes straight to
            # per-set verdicts.
            self.metrics.batch_retries.inc()
            job.batch_retries += 1
            self.metrics.rlc_fallback.inc()
            if len(sets) > self.bisect_leaf:
                per_set, depth = self._bisect(sets, job.wire, 1, job)
                self.metrics.rlc_bisect_depth.observe(depth)
                if _trace_enabled():
                    span.set(accepted=False, bisect_depth=depth)
            else:
                if _trace_enabled():
                    span.set(accepted=False, bisect_depth=0)
                job.per_set = self._device_call(
                    "each_wire" if job.wire else "each_decoded",
                    self._each_fn(job),
                    (*job.args, job.valid),
                )
                per_set = (
                    check_verdict_plane(job.per_set, len(sets), "each")[
                        : len(sets)
                    ]
                    & job.decodable
                )
        return per_set

    def _bisect(self, sets, wire: bool, depth: int, job=None):
        """Verdicts for a failed RLC batch by recursive halving.

        Both halves are DISPATCHED before either is synced so they
        pipeline on the device stream; a half that passes its batch
        check clears all its sets at once, a half that fails recurses,
        and leaves (<= bisect_leaf sets) fall back to per-set verdicts.
        Returns (bool ndarray in `sets` order, max recursion depth)."""
        if len(sets) <= self.bisect_leaf or len(sets) < 2:
            return self._per_set_verdicts(sets, wire), depth
        mid = (len(sets) + 1) // 2
        halves = [sets[:mid], sets[mid:]]
        handles = [self._dispatch_batch(h, wire) for h in halves]
        parts: List[np.ndarray] = []
        max_depth = depth
        for half, handle in zip(halves, handles):
            if self._batch_verdict(handle):
                if job is not None:
                    job.batch_sigs_success += len(half)
                self.metrics.batch_sigs_success.inc(len(half))
                parts.append(np.ones(len(half), bool))
            else:
                v, d = self._bisect(half, wire, depth + 1, job)
                parts.append(v)
                max_depth = max(max_depth, d)
        return np.concatenate(parts), max_depth

    def _dispatch_rlc_batch(self, sets, args, valid, n, wire: bool):
        """ONE RLC multi-pairing dispatch (no blocking): fresh
        randomizers + entry-name choice, shared by the primary job path
        (_begin_job) and the bisection recursion (_dispatch_batch) so
        the two can never diverge.  Wire sets MUST be sorted by signing
        root (bisection halves of a sorted job are sorted contiguous
        runs, so the grouped entry — one message-side Miller tile per
        distinct root — stays available on the adversarial path)."""
        rand = jnp.asarray(BK.make_rand_words(n, self.rng))
        grouping = self._grouping(sets, n) if wire else None
        if grouping is not None:
            group, head_lanes, glive = grouping
            batch_ok, _sub = self._device_call(
                "batch_wire_grouped",
                KV.verify_batch_device_wire_grouped,
                (*args, group, head_lanes, glive, rand, valid),
            )
            return batch_ok
        batch_fn = (
            KV.verify_batch_device_wire if wire else KV.verify_batch_device
        )
        batch_ok, _sub = self._device_call(
            "batch_wire" if wire else "batch_decoded",
            batch_fn,
            (*args, rand, valid),
        )
        return batch_ok

    def _dispatch_batch(self, sets, wire: bool):
        """Dispatch one RLC sub-batch WITHOUT blocking; returns the lazy
        device batch_ok scalar (the bisection recursion's handle)."""
        if wire:
            args, valid, n, _host_bad = self._prepare_wire(sets)
        else:
            args, valid, n = self._prepare(sets)
        return self._dispatch_rlc_batch(sets, args, valid, n, wire)

    def _batch_verdict(self, handle) -> bool:
        """Sync one sub-batch handle to a host bool (test seam)."""
        return bool(handle)

    def _per_set_verdicts(self, sets, wire: bool) -> np.ndarray:
        """Independent device verdicts for `sets` (the bisection leaf)."""
        if wire:
            args, valid, _n, host_bad = self._prepare_wire(sets)
            v = check_verdict_plane(
                self._device_call(
                    "each_wire", KV.verify_each_device_wire, (*args, valid)
                ),
                len(sets),
                "each_wire",
            )[: len(sets)]
            return v & ~host_bad[: len(sets)]
        args, valid, _n = self._prepare(sets)
        v = check_verdict_plane(
            self._device_call(
                "each_decoded", KV.verify_each_device, (*args, valid)
            ),
            len(sets),
            "each_decoded",
        )[: len(sets)]
        return v & np.array([s.signature is not None for s in sets])

    def verify_signature_sets_individually(
        self, sets: Sequence[SignatureSet]
    ) -> List[bool]:
        """Per-set verdicts (used by gossip validators that must tell WHICH
        aggregate in a job failed).  Breaker-supervised like the job
        paths: open -> host ground truth; a device fault mid-call trips
        and falls back, so the caller always gets verdicts."""
        sup = self.supervisor
        if not sup.device_allowed():
            sup.note_host_fallback(len(sets))
            return [self._verify_set_host(s) for s in sets]
        try:
            out = sup.run_guarded(
                lambda: self._verify_individually_device(sets),
                "individually",
            )
            sup.record_success()
            return out
        except Exception as e:  # noqa: BLE001 — verdicts must keep
            # flowing through the degraded path
            if not sup.active:
                raise
            sup.record_failure(classify_failure(e), "individually", str(e))
            sup.note_host_fallback(len(sets))
            return [self._verify_set_host(s) for s in sets]

    def _verify_individually_device(
        self, sets: Sequence[SignatureSet]
    ) -> List[bool]:
        verdicts: dict = {}
        device_sets: List[Tuple[int, SignatureSet]] = []
        wire_sets: List[Tuple[int, WireSignatureSet]] = []
        for pos, s in enumerate(sets):
            wire = isinstance(s, WireSignatureSet)
            ext = s.pubkeys if wire else s.external_pubkeys
            if len(s.indices) > MAX_AGG_INDICES or ext is not None:
                verdicts[pos] = self._verify_set_cpu(s.decode() if wire else s)
            elif wire:
                wire_sets.append((pos, s))
            else:
                device_sets.append((pos, s))
        for chunk_start in range(0, len(device_sets), MAX_JOB_SETS):
            chunk = device_sets[chunk_start : chunk_start + MAX_JOB_SETS]
            subset = [s for _, s in chunk]
            args, valid, _n = self._prepare(subset)
            per_set = np.asarray(KV.verify_each_device(*args, valid))[
                : len(subset)
            ]
            for (pos, s), v in zip(chunk, per_set):
                verdicts[pos] = bool(v) and s.signature is not None
        for chunk_start in range(0, len(wire_sets), MAX_JOB_SETS):
            chunk = wire_sets[chunk_start : chunk_start + MAX_JOB_SETS]
            subset = [s for _, s in chunk]
            args, valid, _n, host_bad = self._prepare_wire(subset)
            per_set = np.asarray(KV.verify_each_device_wire(*args, valid))[
                : len(subset)
            ]
            for j, ((pos, s), v) in enumerate(zip(chunk, per_set)):
                verdicts[pos] = bool(v) and not host_bad[j]
        return [verdicts[i] for i in range(len(sets))]

    # -- breaker canary (bls/supervisor.py half-open probe) ----------------

    def _device_canary(self) -> bool:
        """ONE minimal device job — the breaker's half-open probe.  A
        single junk set (signature at infinity) rides the smallest
        each_decoded bucket; the probe passes iff the dispatch completes
        under the watchdog deadline AND the verdict plane is well-formed
        with the expected False verdict.  A device that returns garbage
        fails the canary just like one that hangs."""
        def _probe() -> bool:
            s = SignatureSet.single(0, C.G2_GEN, None)
            args, valid, n = self._prepare([s])
            out = self._device_call(
                "each_decoded", KV.verify_each_device, (*args, valid)
            )
            arr = check_verdict_plane(out, n, "canary")
            return not bool(arr[0])

        return bool(self.supervisor.run_guarded(_probe, "canary"))

    def close(self) -> None:
        self.supervisor.close()
