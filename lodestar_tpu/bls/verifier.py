"""TpuBlsVerifier — the IBlsVerifier implementation backed by JAX kernels.

Semantics reproduced from the reference (packages/beacon-node/src/chain/bls):

  - `verify_signature_sets(sets, batchable=...)` returns True iff EVERY set
    verifies (interface.ts:20-51).
  - Batchable jobs with >= 2 sets use random-linear-combination batch
    verification (maybeBatch.ts:16-27); on batch failure every set is
    re-verified individually so one bad signature cannot poison honest
    peers' messages (multithread/worker.ts:74-96), with
    `batch_retries`/`batch_sigs_success` accounted identically.
  - Jobs are chunked to <= MAX_JOB_SETS sets (multithread/index.ts:39).
  - `can_accept_work()` mirrors the 512-pending-job backpressure bound
    consumed by the gossip NetworkProcessor (multithread/index.ts:143-149,
    processor/index.ts:357-371).

TPU-specific structure: sets are padded into fixed shape buckets
(N-bucket x K-bucket) so XLA compiles a handful of kernels once; pubkeys
are gathered from the device-resident table and aggregate sets point-add
on device; messages/signatures ship as plain limb planes and enter
Montgomery form on device.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import curves as C
from ..ops import bls_kernels as BK
from ..ops import curve as K
from ..ops import fp, fp2
from ..ops import limbs as L
from ..utils.metrics import BlsPoolMetrics
from .pubkey_table import PubkeyTable
from .signature_set import SignatureSet

MAX_JOB_SETS = 128          # reference: chain/bls/multithread/index.ts:39
MAX_PENDING_JOBS = 512      # reference: chain/bls/multithread/index.ts:64
N_BUCKETS = (4, 16, 64, 128, 256, 512)
K_BUCKETS = (1, 4, 16, 64, 512)


class VerifyOptions:
    def __init__(self, batchable: bool = False, verify_on_main_thread: bool = False):
        self.batchable = batchable
        # kept for interface parity; the CPU fallback path
        self.verify_on_main_thread = verify_on_main_thread


def _bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"size {n} exceeds largest bucket {buckets[-1]}")


def _ints_to_plain_limbs(vals: Sequence[int]) -> np.ndarray:
    """[v0, v1, ...] ints -> uint32[n, 32] plain (non-Montgomery) limbs."""
    out = np.zeros((len(vals), L.N_LIMBS), np.uint32)
    for i, v in enumerate(vals):
        out[i] = L.to_limbs(v)
    return out


def _encode_g2_plain(pts, pad_to: int) -> Tuple[np.ndarray, np.ndarray]:
    """Affine ground-truth G2 points -> plain-limb planes [pad, 2, 32]."""
    xs = np.zeros((pad_to, 2, L.N_LIMBS), np.uint32)
    ys = np.zeros((pad_to, 2, L.N_LIMBS), np.uint32)
    for i, pt in enumerate(pts):
        (x0, x1), (y0, y1) = pt
        xs[i, 0], xs[i, 1] = L.to_limbs(x0), L.to_limbs(x1)
        ys[i, 0], ys[i, 1] = L.to_limbs(y0), L.to_limbs(y1)
    return xs, ys


def _to_mont2(a):
    """Plain-limb packed array -> Montgomery form, on device."""
    return fp.mont_mul(a, jnp.asarray(fp.R2_LIMBS))


def _verify_batch_job(table_x, table_y, idx, mask, msg_x, msg_y, sig_x, sig_y,
                      rand_bits, valid):
    """Jitted: gather/aggregate pubkeys + RLC batch verification."""
    agg = BK.aggregate_pubkeys(table_x, table_y, idx, mask)
    pk_aff, pk_inf = K.to_affine(K.FP_OPS, agg)
    msg_aff = (_to_mont2(msg_x), _to_mont2(msg_y))
    sig_aff = (_to_mont2(sig_x), _to_mont2(sig_y))
    ok, sig_ok = BK.verify_batch(pk_aff, msg_aff, sig_aff, rand_bits, valid)
    ok = ok & ~jnp.any(pk_inf & valid)
    return ok, sig_ok


def _verify_each_job(table_x, table_y, idx, mask, msg_x, msg_y, sig_x, sig_y,
                     valid):
    """Jitted: independent per-set verdicts (the batch-failure retry path)."""
    agg = BK.aggregate_pubkeys(table_x, table_y, idx, mask)
    pk_aff, pk_inf = K.to_affine(K.FP_OPS, agg)
    msg_aff = (_to_mont2(msg_x), _to_mont2(msg_y))
    sig_aff = (_to_mont2(sig_x), _to_mont2(sig_y))
    ok = BK.verify_each(pk_aff, msg_aff, sig_aff, valid)
    return ok & ~(pk_inf & valid)


class TpuBlsVerifier:
    """The device-backed IBlsVerifier.

    One instance owns the jitted kernels and the pubkey table; concurrency
    control (job queue depth) models the reference's thread-pool
    backpressure contract.
    """

    def __init__(
        self,
        table: PubkeyTable,
        metrics: Optional[BlsPoolMetrics] = None,
        rng: Optional[np.random.Generator] = None,
    ):
        self.table = table
        self.metrics = metrics or BlsPoolMetrics()
        self.rng = rng or np.random.default_rng()
        self._pending_jobs = 0
        self._batch_fn = jax.jit(_verify_batch_job)
        self._each_fn = jax.jit(_verify_each_job)

    # -- backpressure (reference: multithread/index.ts:143-149) -----------

    def can_accept_work(self) -> bool:
        return self._pending_jobs < MAX_PENDING_JOBS

    # -- the main entry (reference: bls/interface.ts verifySignatureSets) --

    def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: Optional[VerifyOptions] = None
    ) -> bool:
        if not sets:
            return True
        opts = opts or VerifyOptions()
        t_start = time.perf_counter()
        self._pending_jobs += 1
        try:
            ok = True
            for chunk_start in range(0, len(sets), MAX_JOB_SETS):
                chunk = sets[chunk_start : chunk_start + MAX_JOB_SETS]
                ok &= self._verify_job(list(chunk), opts.batchable)
            return ok
        finally:
            self._pending_jobs -= 1
            dt = time.perf_counter() - t_start
            self.metrics.job_time.observe(dt)
            self.metrics.time_per_sig_set.observe(dt / len(sets))

    # -- job execution ----------------------------------------------------

    def _prepare(self, sets: List[SignatureSet]):
        n = _bucket(len(sets), N_BUCKETS)
        kmax = _bucket(max(len(s.indices) for s in sets), K_BUCKETS)
        idx = np.zeros((n, kmax), np.int32)
        mask = np.zeros((n, kmax), bool)
        valid = np.zeros((n,), bool)
        sig_pts = []
        msg_pts = []
        for i, s in enumerate(sets):
            k = len(s.indices)
            idx[i, :k] = s.indices
            mask[i, :k] = True
            # a set with an undecodable/infinity signature can never verify;
            # mark the slot invalid and fail the job up front (blst returns
            # false for such sets — reference: maybeBatch.ts per-set verify)
            valid[i] = s.signature is not None
            sig_pts.append(s.signature if s.signature is not None else C.G2_GEN)
            msg_pts.append(s.message)
        always_false = not all(valid[: len(sets)])
        # pad tail slots with the generator (kept off the verdict by `valid`)
        for _ in range(n - len(sets)):
            sig_pts.append(C.G2_GEN)
            msg_pts.append(C.G2_GEN)
        msg_x, msg_y = _encode_g2_plain(msg_pts, n)
        sig_x, sig_y = _encode_g2_plain(sig_pts, n)
        tx, ty = self.table.device_planes()
        args = (
            tx, ty, jnp.asarray(idx), jnp.asarray(mask),
            jnp.asarray(msg_x), jnp.asarray(msg_y),
            jnp.asarray(sig_x), jnp.asarray(sig_y),
        )
        return args, jnp.asarray(valid), always_false, n

    def _verify_job(self, sets: List[SignatureSet], batchable: bool) -> bool:
        args, valid, always_false, n = self._prepare(sets)
        if always_false:
            self.metrics.invalid_sets.inc(len(sets))
            return False
        if batchable and len(sets) >= 2:  # reference: maybeBatch.ts:16
            self.metrics.batchable_sigs.inc(len(sets))
            rand = jnp.asarray(BK.make_rand_bits(n, self.rng))
            ok, _sig_ok = self._batch_fn(*args, rand, valid)
            if bool(ok):
                self.metrics.batch_sigs_success.inc(len(sets))
                self.metrics.success_jobs.inc(len(sets))
                return True
            # batch failed: retry each set individually
            # (reference: multithread/worker.ts:74-96)
            self.metrics.batch_retries.inc()
        per_set = np.asarray(self._each_fn(*args, valid))[: len(sets)]
        good = int(per_set.sum())
        self.metrics.success_jobs.inc(good)
        self.metrics.invalid_sets.inc(len(sets) - good)
        return bool(per_set.all())

    def verify_signature_sets_individually(
        self, sets: Sequence[SignatureSet]
    ) -> List[bool]:
        """Per-set verdicts (used by gossip validators that must tell WHICH
        aggregate in a job failed)."""
        out: List[bool] = []
        for chunk_start in range(0, len(sets), MAX_JOB_SETS):
            chunk = list(sets[chunk_start : chunk_start + MAX_JOB_SETS])
            args, valid, _always_false, _n = self._prepare(chunk)
            per_set = np.asarray(self._each_fn(*args, valid))[: len(chunk)]
            decodable = np.array([s.signature is not None for s in chunk])
            out.extend((per_set & decodable).tolist())
        return out

    def close(self) -> None:
        pass
