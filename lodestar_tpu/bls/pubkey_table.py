"""Device-resident validator pubkey table — the TPU Index2PubkeyCache.

The reference deserializes every validator pubkey once into a blst
PublicKey object held in a JS array (reference:
packages/state-transition/src/cache/pubkeyCache.ts:29-47; ~30 s for 350k
keys noted at packages/beacon-node/src/chain/chain.ts:218-220).  Here the
equivalent is two int32[33, V] transposed limb planes in HBM (Montgomery
form, affine, kernels/layout.py), indexable by validator index along the
lane axis, so `single` sets ship only (index, root, sig) across the
host->device boundary and `aggregate` sets gather+point-add entirely on
device (reference main-thread aggregation:
packages/beacon-node/src/chain/bls/utils.ts:5-16).

1M validators = 2 planes x 33 x 1M x 4 B = 264 MB — fits v5e HBM (16 GB).
Capacity grows by doubling; a growth step changes the device shape and
recompiles the gather (pre-size `capacity` for the expected validator
count to avoid it).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..crypto import curves as C
from ..kernels import layout as LY


def plan_disjoint_gathers(
    index_tuples: Sequence[Sequence[int]], max_indices: int
) -> List[List[int]]:
    """Pack contributor index tuples into layers with UNIQUE indices.

    The pre-verify aggregation stage (bls/aggregator.py) merges wire
    attestations sharing one signing root into one signature set whose
    pubkey side is a device gather over the combined validator indices.
    Naively concatenating the tuples fetches the same pubkey row once
    per message when aggregation bits overlap — and, worse, repeated
    rows change the aggregate sum (c*pk per c-fold index), which is why
    the eth2 spec refuses to merge overlapping aggregates at all.  This
    planner keeps both properties: contributors are packed greedily
    (first fit, submission order) into layers whose index sets are
    pairwise DISJOINT and whose combined size stays <= `max_indices`,
    so within a layer every pubkey row is gathered exactly once and the
    plain G1 tree-add is the exact aggregate pubkey.  Overlapping
    contributors land in separate layers (one extra verified set per
    overlap depth — rare outside adversarial floods, since the seen
    caches already dedupe per-validator gossip).

    Returns layers as lists of POSITIONS into `index_tuples`.  A
    contributor whose own tuple repeats an index or exceeds
    `max_indices` gets a singleton layer (verified as submitted, never
    merged).
    """
    layers: List[List[int]] = []
    layer_sets: List[set] = []
    for pos, idxs in enumerate(index_tuples):
        own = set(idxs)
        if len(own) != len(idxs) or len(idxs) > max_indices:
            layers.append([pos])
            layer_sets.append(set())  # poisoned: nothing else joins
            continue
        for li, seen in enumerate(layer_sets):
            if seen and not (seen & own) and len(seen) + len(own) <= max_indices:
                layers[li].append(pos)
                seen |= own
                break
        else:
            layers.append([pos])
            layer_sets.append(set(own))
    return layers


class PubkeyTable:
    """Append-only affine G1 table with device mirror."""

    def __init__(self, capacity: int = 1024):
        self._cap = max(capacity, 1)
        self._n = 0
        self._host_x = np.zeros((LY.NL, self._cap), np.int32)
        self._host_y = np.zeros((LY.NL, self._cap), np.int32)
        self._device: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None

    def __len__(self) -> int:
        return self._n

    def register(self, pubkeys: Sequence) -> List[int]:
        """Validate + append ground-truth affine pubkeys; returns indices.

        Raises ValueError on an invalid key (infinity, off-curve, or out of
        subgroup — blst KeyValidate semantics).  Every downstream path
        (device kernels and the CPU fallback) relies on registered keys
        having passed KeyValidate, so there is no opt-out.
        """
        idxs = []
        for pk in pubkeys:
            if pk is None:
                raise ValueError("pubkey is the point at infinity")
            if not C.is_on_curve(C.FP_OPS, pk):
                raise ValueError("pubkey not on curve")
            if not C.g1_subgroup_check(pk):
                raise ValueError("pubkey not in G1 subgroup")
            if self._n == self._cap:
                self._grow()
            self._host_x[:, self._n] = LY.to_limbs(pk[0] * LY.R_MOD_P % LY.P)
            self._host_y[:, self._n] = LY.to_limbs(pk[1] * LY.R_MOD_P % LY.P)
            idxs.append(self._n)
            self._n += 1
        self._device = None  # invalidate mirror
        return idxs

    def register_compressed(
        self, keys: Sequence[bytes], device_batch: int = 65536
    ) -> List[int]:
        """Bulk-register 48B compressed pubkeys with DEVICE KeyValidate.

        The 1M-validator ingest path: decompression (Fp sqrt) and the
        [r]P subgroup test run lane-parallel on TPU
        (kernels/ingest.g1_keyvalidate_device); the reference pays ~30 s
        of host blst deserialization for 350k keys
        (packages/beacon-node/src/chain/chain.ts:218-220).  Raises on
        the first invalid key, naming its position.
        """
        from ..kernels import ingest as IG
        from .ingest import encode_pubkey_planes

        import jax.numpy as jnp

        # two-phase: validate EVERY chunk before committing anything, so a
        # late invalid key cannot leave partially-registered rows behind a
        # stale device mirror
        validated = []
        for start in range(0, len(keys), device_batch):
            chunk = list(keys[start : start + device_batch])
            n = len(chunk)
            pad = (-n) % 128
            planes, flags, host_bad = encode_pubkey_planes(
                chunk + [chunk[-1]] * pad
            )
            (mx, my), ok = IG.g1_keyvalidate_device(
                jnp.asarray(planes), jnp.asarray(flags)
            )
            ok = np.asarray(ok)[:n] & ~host_bad[:n]
            if not ok.all():
                bad = int(np.argmin(ok))
                raise ValueError(
                    f"pubkey {start + bad} failed KeyValidate "
                    "(malformed, off-curve, infinity, or out of subgroup)"
                )
            validated.append((np.asarray(mx)[:, :n], np.asarray(my)[:, :n]))
        idxs: List[int] = []
        for mx, my in validated:
            n = mx.shape[1]
            while self._n + n > self._cap:
                self._grow()
            self._host_x[:, self._n : self._n + n] = mx
            self._host_y[:, self._n : self._n + n] = my
            idxs.extend(range(self._n, self._n + n))
            self._n += n
        self._device = None
        return idxs

    def register_points_unchecked(
        self, pubkeys: Sequence, tile_to: Optional[int] = None
    ) -> List[int]:
        """Bulk-append affine points KNOWN to satisfy KeyValidate.

        For harnesses and states whose keys were validated elsewhere
        (e.g. replay synthesis from known secret keys, or a batch device
        KeyValidate).  With `tile_to`, the given keys are tiled cyclically
        up to that many rows — the replay trick that makes a full-size
        1M-row device table from a few distinct keypairs.
        """
        n_in = len(pubkeys)
        if n_in == 0:
            raise ValueError("register_points_unchecked needs >= 1 pubkey")
        total = tile_to if tile_to is not None else n_in
        if total < n_in:
            raise ValueError(f"tile_to {total} < {n_in} input keys")
        if self._n != 0:
            raise ValueError("bulk load only into an empty table")
        if self._cap < total:
            self._cap = total
            self._host_x = np.zeros((LY.NL, self._cap), np.int32)
            self._host_y = np.zeros((LY.NL, self._cap), np.int32)
        base_x = np.stack(
            [LY.to_limbs(pk[0] * LY.R_MOD_P % LY.P) for pk in pubkeys], axis=-1
        )
        base_y = np.stack(
            [LY.to_limbs(pk[1] * LY.R_MOD_P % LY.P) for pk in pubkeys], axis=-1
        )
        reps = (total + n_in - 1) // n_in
        self._host_x[:, :total] = np.tile(base_x, (1, reps))[:, :total]
        self._host_y[:, :total] = np.tile(base_y, (1, reps))[:, :total]
        self._n = total
        self._device = None
        return list(range(total))

    def _grow(self) -> None:
        self._cap *= 2
        for name in ("_host_x", "_host_y"):
            old = getattr(self, name)
            new = np.zeros((LY.NL, self._cap), np.int32)
            new[:, : self._n] = old[:, : self._n]
            setattr(self, name, new)

    def device_planes(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """The (x, y) planes on device, padded to capacity (stable shape).

        Padding rows are zeros; kernels must only gather registered rows.
        """
        if self._device is None:
            self._device = (
                jnp.asarray(self._host_x),
                jnp.asarray(self._host_y),
            )
        return self._device

    def host_affine(self, index: int):
        """Ground-truth affine point for the CPU fallback paths/tests."""
        assert 0 <= index < self._n
        rinv = LY.R_INV
        return (
            LY.from_limbs(self._host_x[:, index]) * rinv % LY.P,
            LY.from_limbs(self._host_y[:, index]) * rinv % LY.P,
        )
