"""Device-resident validator pubkey table — the TPU Index2PubkeyCache.

The reference deserializes every validator pubkey once into a blst
PublicKey object held in a JS array (reference:
packages/state-transition/src/cache/pubkeyCache.ts:29-47; ~30 s for 350k
keys noted at packages/beacon-node/src/chain/chain.ts:218-220).  Here the
equivalent is two uint32[V, 32] coordinate planes in HBM (Montgomery form,
affine), indexable by validator index, so `single` sets ship only
(index, root, sig) across the host->device boundary and `aggregate` sets
gather+point-add entirely on device (reference main-thread aggregation:
packages/beacon-node/src/chain/bls/utils.ts:5-16).

1M validators = 2 planes x 1M x 32 x 4 B = 256 MB — fits v5e HBM (16 GB).
Registration validates each key (on-curve + subgroup, blst KeyValidate
semantics) through the CPU ground truth; amortized once per validator per
process lifetime, exactly like the reference's cache build.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto import curves as C
from ..ops import fp


class PubkeyTable:
    """Append-only affine G1 table with device mirror."""

    def __init__(self, capacity: int = 1024):
        self._cap = max(capacity, 1)
        self._n = 0
        self._host_x = np.zeros((self._cap, fp.L.N_LIMBS), np.uint32)
        self._host_y = np.zeros((self._cap, fp.L.N_LIMBS), np.uint32)
        self._device: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None

    def __len__(self) -> int:
        return self._n

    def register(self, pubkeys: Sequence) -> List[int]:
        """Validate + append ground-truth affine pubkeys; returns indices.

        Raises ValueError on an invalid key (infinity, off-curve, or out of
        subgroup — blst KeyValidate semantics).
        """
        idxs = []
        for pk in pubkeys:
            if pk is None:
                raise ValueError("pubkey is the point at infinity")
            if not C.is_on_curve(C.FP_OPS, pk):
                raise ValueError("pubkey not on curve")
            if not C.g1_subgroup_check(pk):
                raise ValueError("pubkey not in G1 subgroup")
            if self._n == self._cap:
                self._grow()
            self._host_x[self._n] = fp.const(pk[0])
            self._host_y[self._n] = fp.const(pk[1])
            idxs.append(self._n)
            self._n += 1
        self._device = None  # invalidate mirror
        return idxs

    def _grow(self) -> None:
        self._cap *= 2
        for name in ("_host_x", "_host_y"):
            old = getattr(self, name)
            new = np.zeros((self._cap, fp.L.N_LIMBS), np.uint32)
            new[: self._n] = old[: self._n]
            setattr(self, name, new)

    def device_planes(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """The (x, y) planes on device, padded to capacity (stable shape).

        Padding rows are zeros; kernels must only gather registered rows.
        """
        if self._device is None:
            self._device = (
                jnp.asarray(self._host_x),
                jnp.asarray(self._host_y),
            )
        return self._device

    def host_affine(self, index: int):
        """Ground-truth affine point for tests/debugging."""
        assert 0 <= index < self._n
        return (
            fp.decode(self._host_x[index]),
            fp.decode(self._host_y[index]),
        )
