"""The signature-set model — mirror of the reference's ISignatureSet.

Reference: packages/state-transition/src/util/signatureSets.ts:5-22 defines

    SignatureSetType = single | aggregate
    ISignatureSet   = { type, pubkey | pubkeys, signingRoot, signature }

Here a set carries validator *indices* into the device-resident pubkey
table instead of deserialized pubkey objects (the reference parses blst
PublicKey objects once into Index2PubkeyCache — reference:
packages/state-transition/src/cache/pubkeyCache.ts:29-47; on TPU the
table itself lives in HBM and only indices cross the boundary).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

Affine = Optional[Tuple]  # ground-truth affine point or None (infinity)


class SignatureSetType(enum.Enum):
    single = "single"
    aggregate = "aggregate"


@dataclass(frozen=True)
class SignatureSet:
    """One verifiable (pubkey(s), message, signature) statement.

    type=single:    one validator index, one signing root.
    type=aggregate: several validator indices whose keys are point-added on
                    device before the pairing (sync committees, aggregates).

    `signature` is the decompressed affine G2 point; `message` is the
    hashed-to-curve affine G2 point of the signing root.  Decompression
    and hashing happen at ingest (see verifier.prepare_sets) so the hot
    loop works on fixed-shape arrays only.
    """

    type: SignatureSetType
    indices: Tuple[int, ...]
    message: Tuple  # affine G2 (ground-truth ints) — hash_to_g2(signing_root)
    signature: Affine  # affine G2 or None (invalid/infinity -> always False)

    @staticmethod
    def single(index: int, message, signature) -> "SignatureSet":
        return SignatureSet(SignatureSetType.single, (index,), message, signature)

    @staticmethod
    def aggregate(indices: Sequence[int], message, signature) -> "SignatureSet":
        return SignatureSet(
            SignatureSetType.aggregate, tuple(indices), message, signature
        )
