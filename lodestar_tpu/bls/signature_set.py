"""The signature-set model — mirror of the reference's ISignatureSet.

Reference: packages/state-transition/src/util/signatureSets.ts:5-22 defines

    SignatureSetType = single | aggregate
    ISignatureSet   = { type, pubkey | pubkeys, signingRoot, signature }

Here a set carries validator *indices* into the device-resident pubkey
table instead of deserialized pubkey objects (the reference parses blst
PublicKey objects once into Index2PubkeyCache — reference:
packages/state-transition/src/cache/pubkeyCache.ts:29-47; on TPU the
table itself lives in HBM and only indices cross the boundary).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

Affine = Optional[Tuple]  # ground-truth affine point or None (infinity)


class SignatureSetType(enum.Enum):
    single = "single"
    aggregate = "aggregate"


@dataclass(frozen=True)
class SignatureSet:
    """One verifiable (pubkey(s), message, signature) statement.

    type=single:    one validator index, one signing root.
    type=aggregate: several validator indices whose keys are point-added on
                    device before the pairing (sync committees, aggregates).

    `signature` is the decompressed affine G2 point; `message` is the
    hashed-to-curve affine G2 point of the signing root.  Decompression
    and hashing happen at ingest (see verifier.prepare_sets) so the hot
    loop works on fixed-shape arrays only.

    `external_pubkeys` carries decompressed affine G1 points for signers
    OUTSIDE the validator registry (BLSToExecutionChange withdrawal
    keys); such sets verify on the CPU path, which KeyValidates them.
    """

    type: SignatureSetType
    indices: Tuple[int, ...]
    message: Tuple  # affine G2 (ground-truth ints) — hash_to_g2(signing_root)
    signature: Affine  # affine G2 or None (invalid/infinity -> always False)
    external_pubkeys: Optional[Tuple] = None  # affine G1 points

    @staticmethod
    def single(index: int, message, signature) -> "SignatureSet":
        return SignatureSet(SignatureSetType.single, (index,), message, signature)

    @staticmethod
    def aggregate(indices: Sequence[int], message, signature) -> "SignatureSet":
        return SignatureSet(
            SignatureSetType.aggregate, tuple(indices), message, signature
        )


@dataclass(frozen=True)
class WireSignatureSet:
    """A signature set at the wire level — what actually crosses the
    host boundary: {validator indices | raw pubkeys, 32B signing root,
    96B compressed signature} (reference: the serialized job layout in
    packages/beacon-node/src/chain/bls/multithread/index.ts:177 and
    types.ts:14-38).

    Hashing the root to G2 and decompressing the signature happen at
    ingest — batched on device in the production path, or on the host
    via `decode()` (the CPU-oracle/fallback path).

    `pubkeys` (48B compressed each) is only set for signers outside the
    validator registry (e.g. BLSToExecutionChange withdrawal keys); such
    sets verify on the CPU path.
    """

    type: SignatureSetType
    indices: Tuple[int, ...]
    signing_root: bytes  # 32 bytes
    signature: bytes  # 96 bytes, compressed G2
    pubkeys: Optional[Tuple[bytes, ...]] = None

    @staticmethod
    def single(index: int, signing_root: bytes, signature: bytes):
        return WireSignatureSet(
            SignatureSetType.single, (index,), bytes(signing_root), bytes(signature)
        )

    @staticmethod
    def aggregate(indices: Sequence[int], signing_root: bytes, signature: bytes):
        return WireSignatureSet(
            SignatureSetType.aggregate,
            tuple(indices),
            bytes(signing_root),
            bytes(signature),
        )

    def dedupe_key(self) -> Tuple[bytes, Tuple[int, ...], bytes]:
        """The exact-identity key of this statement: (signing root,
        indices, signature bytes).  Two wire sets with equal keys are
        the SAME message (BLS signing is deterministic), so one verdict
        serves both — the pre-verify aggregation stage's dedupe index
        and seen-map key on this (bls/aggregator.py); anything looser
        would let a forged duplicate ride an honest verdict."""
        return (self.signing_root, self.indices, self.signature)

    @staticmethod
    def external(pubkeys: Sequence[bytes], signing_root: bytes, signature: bytes):
        """A set whose keys are not validator-registry members."""
        return WireSignatureSet(
            SignatureSetType.aggregate,
            (),
            bytes(signing_root),
            bytes(signature),
            tuple(bytes(p) for p in pubkeys),
        )

    def decode(self) -> SignatureSet:
        """Host-side ingest: hash-to-curve + signature (and, for external
        sets, pubkey) decompression.  Undecodable bytes decode to a set
        that always verifies False (signature=None)."""
        from ..crypto.curves import g1_decompress, g2_decompress
        from ..crypto.hash_to_curve import hash_to_g2

        try:
            sig = g2_decompress(self.signature)
        except ValueError:
            sig = None
        ext = None
        if self.pubkeys is not None:
            try:
                ext = tuple(g1_decompress(p) for p in self.pubkeys)
                if any(p is None for p in ext):  # infinity pubkey
                    ext, sig = None, None
            except ValueError:
                ext, sig = None, None
        return SignatureSet(
            self.type, self.indices, hash_to_g2(self.signing_root), sig, ext
        )
