"""Accumulate-and-flush verification pipeline — shape-bucketed,
deadline-driven batching from gossip to pairing (ISSUE 11 tentpole).

PR 10 made the device side cheap (one final exponentiation per N-set RLC
job); this module builds the FEED.  The flat 100 ms window of
`BlsVerifierService` coalesces whatever arrives, so at mainnet rates
(~1.8k atts/s spread over 64 subnets) the 128/512 N-buckets dispatch
mostly padding.  *Aggregated Signature Gossip* (arXiv:1911.04698) and
the EdDSA/BLS committee-consensus study (arXiv:2302.00418) both locate
the batch-verification win at the ACCUMULATION layer, not the pairing —
so the pipeline accumulates:

  - **Shape buckets.**  Batchable submissions coalesce ACROSS gossip
    topics/subnets into per-(kind, K-bucket, lane) accumulators — the
    exact shape classes the export cache holds artifacts for
    (`kernels/export_cache.py`, `kernels/rlc_entries.py`).  A bucket
    that exactly fills an N-bucket (verifier.N_BUCKETS) flushes
    IMMEDIATELY: waiting longer can only burn deadline latency or spill
    into the next, twice-as-large bucket.
  - **Priority lanes.**  Block-critical sets (proposer signatures,
    aggregate-and-proof — `VerifyOptions(priority=True)`) ride a SHORT
    deadline lane so they are never starved behind subnet-attestation
    fill; plain subnet attestations ride a longer window to maximize
    bucket occupancy.  Non-batchable jobs (block import) bypass
    buffering entirely, exactly as in the base service.  A critical
    job submitted into an otherwise-IDLE pipeline (no queued groups,
    no in-flight device work, no other accumulating bucket) flushes
    immediately (`reason=idle`): the window only buys occupancy when
    something could coalesce with it, and synchronous submitters —
    the full-node gossip loop verifying aggregates one at a time —
    must not serialize a pure lane-window wait per message.
  - **Deadlines anchor on the oldest set.**  Each accumulator's flush
    timer is `oldest_job.t_submit + lane_wait` (stamped before lock
    acquisition), so p99 submit->flush latency is bounded by the lane
    window regardless of contention (ISSUE 11 satellite).
  - **End-to-end backpressure.**  `can_accept_work()` goes False when
    buffered + queued + in-flight SETS cross the high-water mark — the
    signal `network/processor.py` throttles on; queue overflow drops
    then charge the flooding peer through
    `network/scoring.py::GossipPeerScorer.on_backpressure_drop` and
    surface on the existing `gossip_queues.py` drop/depth metrics.

Observability: every flush emits a `bls.pipeline.flush` span
(reason/lane/kind/sets/n_bucket) and feeds
`lodestar_bls_bucket_fill_ratio` +
`lodestar_bls_flush_reason_total{reason=fill|spill|deadline|close}`
(utils/metrics.py); `flush_stats()` exposes the same records to tests
and the `bench.py bls_pipeline_verified_atts_per_s` probe.

Ahead of the accumulators sits the PRE-VERIFY AGGREGATION stage
(ISSUE 13, bls/aggregator.py): batchable standard-lane wire sets are
bucketed by signing root, exact duplicates deduped (in-flight followers
+ a resolved-verdict seen-map), and each bucket's disjoint-index layers
point-add their signatures in G2 to verify as ONE set through the same
RLC batch path — per-message verdicts fan back out, and a failed layer
bisects contributor-wise.  The stage engages only when the verifier can
aggregate (`aggregate_wire_signatures`) and `LODESTAR_TPU_BLS_PREAGG`
is not 0; off, every message verifies as its own set exactly as in
PR 11.

Fault tolerance (ISSUE 14): the pipeline needs no fault path of its
own — the verifier's device circuit breaker (bls/supervisor.py) sits
BELOW the flush boundary, so a tripped breaker resolves every flushed
job through the host ground-truth seam with identical verdicts while
accumulation, lane deadlines, and the high-water backpressure keep
operating unchanged.  `breaker_status()` (inherited from the base
service) is the health surface's read path.

Escape hatch: `LODESTAR_TPU_BLS_PIPELINE=0` makes `create_bls_service`
return the PR 10 flat-buffer `BlsVerifierService` instead.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..observability import trace_span as _trace_span
from .service import BlsVerifierService, _Job
from .signature_set import WireSignatureSet
from .verifier import K_BUCKETS, N_BUCKETS

# Lane windows.  The critical lane undercuts the reference's flat 100 ms
# window (multithread/index.ts:57) — a proposer/aggregate set must reach
# the device before attestation fill, not after it; the standard lane
# stretches past it because subnet attestations are latency-tolerant
# (ATTESTATION_PROPAGATION_SLOT_RANGE is measured in slots) and bucket
# occupancy is what the RLC final-exp amortization pays for.
CRITICAL_WAIT_MS = 25.0
STANDARD_WAIT_MS = 250.0
# Backpressure high-water: buffered + queued + in-flight signature sets.
# Sized at 8 full 512-set device jobs — past this the node is saturated
# and the gossip processor must stop pulling (and start charging peers).
HIGH_WATER_SETS = 4096

LANE_CRITICAL = "critical"
LANE_STANDARD = "standard"


def _pad_bucket(n: int) -> int:
    """The padded device N-bucket one job of `n` <= max-bucket sets
    dispatches into (verifier._prepare pads up to N_BUCKETS)."""
    for b in N_BUCKETS:
        if n <= b:
            return b
    return N_BUCKETS[-1]


def _padded_lanes(n: int, cap: int) -> int:
    """Total device lanes a flush of `n` sets occupies after the
    dispatcher splits it into <= `cap`-set runs: full cap-sized jobs
    plus the padded bucket of the remainder.  This is the occupancy
    denominator — a single _pad_bucket would overstate fill for
    oversized flushes."""
    full, rem = divmod(n, cap)
    return full * cap + (_pad_bucket(rem) if rem else 0)


class _Accumulator:
    """One shape bucket's pending jobs + its oldest-set-anchored
    deadline."""

    __slots__ = ("jobs", "sets", "deadline")

    def __init__(self):
        self.jobs: List[_Job] = []
        self.sets = 0
        self.deadline: Optional[float] = None


class BlsVerificationPipeline(BlsVerifierService):
    """The per-shape-bucket accumulate-and-flush front of the verifier.

    Drop-in for `BlsVerifierService` (same submission/backpressure/
    shutdown contract); only the buffering-policy seams are replaced.
    """

    def __init__(
        self,
        verifier,
        critical_wait_ms: float = CRITICAL_WAIT_MS,
        standard_wait_ms: float = STANDARD_WAIT_MS,
        high_water_sets: int = HIGH_WATER_SETS,
        preagg: Optional[bool] = None,
        scorer=None,
        **kwargs,
    ):
        # attrs the dispatcher thread reads must exist before
        # super().__init__ starts it
        self._buckets: Dict[Tuple[bool, int, str], _Accumulator] = {}
        self._lane_wait = {
            LANE_CRITICAL: critical_wait_ms / 1000.0,
            LANE_STANDARD: standard_wait_ms / 1000.0,
        }
        self._high_water_sets = high_water_sets
        self._flush_records: deque = deque(maxlen=512)
        # monotone per-flush sequence: incremental consumers (the SLO
        # engine's per-slot critical-lane p99) remember the last seq
        # they saw instead of re-counting the ring
        self._flush_seq = 0
        # pre-verify aggregation stage (ISSUE 13): requires a verifier
        # that can point-add wire signatures; LODESTAR_TPU_BLS_PREAGG=0
        # restores per-message verification
        self._agg = None
        sum_fn = getattr(verifier, "aggregate_wire_signatures", None)
        if preagg is None:
            env = os.environ.get("LODESTAR_TPU_BLS_PREAGG", "1")
            preagg = env.strip().lower() not in ("0", "false", "no", "off")
        kwargs.setdefault("max_buffered_sigs", N_BUCKETS[-1])
        kwargs.setdefault("buffer_wait_ms", standard_wait_ms)
        # backpressure is counted in SETS here: the inherited job cap
        # must not bind first (512 one-set gossip jobs are 1/8 of the
        # high-water work, not a full queue) — one job holds >= 1 set,
        # so a job cap equal to the set mark keeps _pending_sets the
        # binding constraint while still bounding bookkeeping
        kwargs.setdefault("max_pending_jobs", high_water_sets)
        super().__init__(verifier, **kwargs)
        if preagg and sum_fn is not None:
            from .aggregator import PreVerifyAggregator

            self._agg = PreVerifyAggregator(
                self,
                self._lane_wait[LANE_STANDARD],
                sum_fn,
                scorer=scorer,
            )
        # full-window cap per bucket: the largest exact fill the device
        # accepts — past it the flush can only split into capped runs
        self._max_fill = (
            max(self._bucket_fills) if self._bucket_fills else self._max_buffered
        )

    # -- backpressure -----------------------------------------------------

    def can_accept_work(self) -> bool:
        with self._lock:
            return (
                not self._closed
                and self._pending < self._max_pending
                and self._pending_sets < self._high_water_sets
            )

    def pending_sets(self) -> int:
        """Buffered + queued + in-flight signature sets (the high-water
        unit); exported as `lodestar_bls_pipeline_pending_sets`."""
        with self._lock:
            return self._pending_sets

    # -- pre-verify aggregation seams (ISSUE 13) ---------------------------

    def set_scorer(self, scorer) -> None:
        """Late-bind the gossip peer scorer (the node builds it after
        the service): isolated invalid contributors then charge their
        publisher (bls/aggregator.py attribution)."""
        if self._agg is not None:
            self._agg.scorer = scorer

    def set_layer_forward(self, fn) -> None:
        """Late-bind the aggregate-forward hook (ISSUE 19):
        `fn(wire, n_members)` fires for every VERIFIED materialized
        multi-member layer — the network plane's AggregateForwarder
        re-packs it onto the aggregate topic.  No-op when the
        aggregation stage is off."""
        if self._agg is not None:
            self._agg.on_layer_verified = fn

    def verify_signature_sets_async(self, sets, opts=None):
        fut = super().verify_signature_sets_async(sets, opts)
        if self._agg is not None and self._agg._deferred:
            # deliver verdicts the aggregation stage settled under the
            # submission lock (seen-map serves) outside it.  The
            # lock-free emptiness read keeps the common no-settlement
            # submit at one lock acquisition; a racy stale read is
            # harmless — every settling path drains its own deferrals
            # (_on_layer_done) or is followed by a drain (close)
            self._agg.drain()
        return fut

    def preagg_verdict(self, wire_set) -> Optional[bool]:
        """Resolved verdict for an exact (root, indices, signature)
        match in the aggregation stage's seen-map, else None (the
        gossip handlers' suppressed-duplicate fast path)."""
        if self._agg is None:
            return None
        return self._agg.seen_verdict(wire_set)

    def agg_stats(self) -> Optional[dict]:
        if self._agg is None:
            return None
        return self._agg.stats_snapshot()

    def mean_aggregation_factor(self) -> Optional[float]:
        """Contributions per verified set through the aggregation stage
        (None when the stage is off or idle) — the ISSUE 13 acceptance
        number."""
        if self._agg is None:
            return None
        return self._agg.mean_aggregation_factor()

    def _dispatch(self, group) -> None:
        if self._agg is not None:
            for job in group:
                # collapse pending layers into their aggregated set
                # OUTSIDE the lock, in the dispatcher thread (the G2
                # point-add is host/device work no submitter should
                # serialize behind)
                self._agg.materialize_job(job)
        super()._dispatch(group)

    # -- the accumulate side ----------------------------------------------

    @staticmethod
    def _k_bucket(job: _Job) -> int:
        kmax = max((len(s.indices) for s in job.sets), default=1)
        for b in K_BUCKETS:
            if kmax <= b:
                return b
        return K_BUCKETS[-1]  # oversized aggregates CPU-route anyway

    def _bucket_key(self, job: _Job) -> Tuple[bool, int, str]:
        wire = bool(job.sets) and isinstance(job.sets[0], WireSignatureSet)
        lane = (
            LANE_CRITICAL
            if getattr(job.opts, "priority", False)
            else LANE_STANDARD
        )
        return (wire, self._k_bucket(job), lane)

    def _submit_buffered_locked(self, job: _Job) -> None:
        if self._agg is not None and self._agg.eligible(job):
            # standard-lane wire sets route through the aggregation
            # stage: bucketed by signing root, deduped, layered, and
            # verified as aggregated sets (bls/aggregator.py)
            self._agg.add_locked(job)
            return
        key = self._bucket_key(job)
        acc = self._buckets.get(key)
        if acc is None:
            acc = self._buckets[key] = _Accumulator()
        new_total = acc.sets + len(job.sets)
        if new_total in self._bucket_fills or new_total >= self._max_fill:
            # exact fill (or past the largest device job): padding-free
            # dispatch, flush everything now
            acc.jobs.append(job)
            acc.sets = new_total
            self._flush_bucket_locked(key, "fill")
            return
        if acc.sets and any(
            acc.sets < b <= new_total for b in self._bucket_fills
        ):
            # a multi-set job OVERSHOOTS a bucket boundary: appending it
            # would strand ~a full bucket of sets waiting on the
            # deadline at half occupancy — SPILL the near-boundary jobs
            # as-is and start a fresh accumulation with this job
            self._flush_bucket_locked(key, "spill")
            acc = self._buckets[key] = _Accumulator()
        acc.jobs.append(job)
        acc.sets += len(job.sets)
        if acc.sets in self._bucket_fills or acc.sets >= self._max_fill:
            # the job alone exactly fills a bucket (reachable right
            # after a spill): same padding-free dispatch, no deadline
            self._flush_bucket_locked(key, "fill")
            return
        if key[2] == LANE_CRITICAL and self._pipeline_idle_locked(key):
            # adaptive batching (ISSUE 12 review fix): waiting out the
            # critical window only buys occupancy when OTHER work could
            # join or the device is busy anyway.  A lone critical job
            # submitted into an otherwise-idle pipeline — the full-node
            # gossip loop verifying aggregates SYNCHRONOUSLY, one at a
            # time — would serialize a pure 25 ms idle wait per
            # message; flush it now instead.  Under load (queued
            # groups, in-flight device work, or other accumulating
            # buckets) criticals still coalesce toward the deadline.
            self._flush_bucket_locked(key, "idle")
            return
        if acc.deadline is None:
            # anchor on the oldest buffered set's enqueue time (stamped
            # in _Job.__init__, before lock acquisition)
            acc.deadline = job.t_submit + self._lane_wait[key[2]]

    def _pipeline_idle_locked(self, key: Tuple[bool, int, str]) -> bool:
        """Nothing for a critical job to overlap with: no dispatch-
        queued groups, no in-flight device work, and no OTHER
        accumulator holding sets that will flush soon."""
        if self._queue or self._inflight_groups:
            return False
        if self._agg is not None and self._agg.pending_contributions():
            return False  # buffered aggregation work will flush soon
        return not any(
            acc.sets for k, acc in self._buckets.items() if k != key
        )

    # -- the flush side ---------------------------------------------------

    def _flush_bucket_locked(self, key: Tuple[bool, int, str], reason: str) -> None:
        acc = self._buckets.pop(key, None)
        if acc is None or not acc.jobs:
            return
        self._queue.append(acc.jobs)
        pad = _padded_lanes(acc.sets, self._max_fill)
        ratio = min(acc.sets / pad, 1.0)
        wire, k_bucket, lane = key
        # submit->flush wait of the OLDEST buffered job — the quantity
        # the lane deadline bounds, and the series the SLO engine's
        # pipeline_critical_p99 objective evaluates per slot (jobs
        # append in arrival order, so jobs[0] is the anchor)
        oldest_wait = time.perf_counter() - acc.jobs[0].t_submit
        self.metrics.bucket_fill_ratio.observe(ratio)
        self.metrics.flush_reason.inc(reason, 1.0)
        self._flush_seq += 1
        with _trace_span(
            "bls.pipeline.flush",
            reason=reason,
            lane=lane,
            wire=wire,
            k_bucket=k_bucket,
            sets=acc.sets,
            n_bucket=pad,
            oldest_wait_s=oldest_wait,
        ):
            self._flush_records.append(
                {
                    "seq": self._flush_seq,
                    "reason": reason,
                    "lane": lane,
                    "wire": wire,
                    "k_bucket": k_bucket,
                    "sets": acc.sets,
                    "n_bucket": pad,
                    "fill_ratio": ratio,
                    "oldest_wait_s": oldest_wait,
                }
            )

    def _poll_buffers_locked(self, now: float) -> Optional[float]:
        next_deadline: Optional[float] = None
        for key in list(self._buckets):
            acc = self._buckets.get(key)
            if acc is None or not acc.jobs:
                self._buckets.pop(key, None)
                continue
            if acc.deadline is not None and now >= acc.deadline:
                self._flush_bucket_locked(key, "deadline")
                continue
            if acc.deadline is not None and (
                next_deadline is None or acc.deadline < next_deadline
            ):
                next_deadline = acc.deadline
        if self._agg is not None:
            agg_wait = self._agg.poll_locked(now)
            if agg_wait is not None and (
                next_deadline is None or now + agg_wait < next_deadline
            ):
                next_deadline = now + agg_wait
        if next_deadline is None:
            return None
        return max(next_deadline - now, 0.0)

    def _close_flush_locked(self) -> None:
        if self._agg is not None:
            # buffered contributions reject like queued jobs; layer
            # jobs already queued/in-flight credit their members
            # through the standard rejection/resolution callbacks
            self._agg.close_locked()
        for key in list(self._buckets):
            self._flush_bucket_locked(key, "close")

    def close(self) -> None:
        super().close()
        if self._agg is not None:
            self._agg.drain()

    # -- introspection ----------------------------------------------------

    def flush_stats(self) -> List[dict]:
        """Recent flush records (reason/lane/sets/n_bucket/fill_ratio) —
        the bench probe's and tests' occupancy source."""
        with self._lock:
            return list(self._flush_records)

    def reset_flush_stats(self) -> None:
        """Drop the recorded flushes (bench probes reset after warmup so
        occupancy reflects only the measured flood)."""
        with self._lock:
            self._flush_records.clear()

    def mean_fill_ratio(self) -> Optional[float]:
        """Set-weighted mean bucket occupancy over the recent flushes:
        sum(sets) / sum(padded bucket) — the acceptance number ISSUE 11
        compares against the flat coalescer."""
        with self._lock:
            recs = list(self._flush_records)
        total = sum(r["sets"] for r in recs)
        padded = sum(r["n_bucket"] for r in recs)
        if padded == 0:
            return None
        return total / padded


def create_bls_service(verifier, **kwargs) -> BlsVerifierService:
    """The node's service factory: the accumulate-and-flush pipeline by
    default; `LODESTAR_TPU_BLS_PIPELINE=0` falls back to the PR 10 flat
    coalescing buffer (same submission contract, 100 ms single window)."""
    env = os.environ.get("LODESTAR_TPU_BLS_PIPELINE", "1")
    if env.strip().lower() in ("0", "false", "no", "off"):
        return BlsVerifierService(verifier, **kwargs)
    return BlsVerificationPipeline(verifier, **kwargs)


__all__ = [
    "BlsVerificationPipeline",
    "create_bls_service",
    "CRITICAL_WAIT_MS",
    "STANDARD_WAIT_MS",
    "HIGH_WATER_SETS",
    "LANE_CRITICAL",
    "LANE_STANDARD",
]
