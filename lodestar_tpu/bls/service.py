"""BlsVerifierService — the async job-queue front of the TPU verifier.

The service reproduces the reference `BlsMultiThreadWorkerPool` contract
(packages/beacon-node/src/chain/bls/multithread/index.ts):

  - callers submit jobs and receive futures; a single dispatcher thread
    owns the device (one TPU stream replaces the N worker threads),
  - small batchable jobs are COALESCED: a job buffer flushes when it
    reaches MAX_BUFFERED_SIGS sets or after MAX_BUFFER_WAIT_MS
    (index.ts:48-57 — the 100 ms / 32-sig window),
  - backpressure: `can_accept_work()` is False once MAX_PENDING_JOBS jobs
    are queued or buffered (index.ts:143-149), the signal the gossip
    NetworkProcessor throttles on (processor/index.ts:357-371),
  - the buffering POLICY is a seam: `_submit_buffered_locked`,
    `_poll_buffers_locked`, and `_close_flush_locked` are the three
    hooks the accumulate-and-flush pipeline (bls/pipeline.py) overrides
    to replace this flat window with per-shape-bucket accumulators,
  - a failed merged batch re-verifies per job so one bad signature cannot
    poison other jobs' verdicts (worker.ts:74-96),
  - `verify_on_main_thread` bypasses the queue and verifies synchronously
    on the host CPU (the proposer-signature latency fast path,
    validation/block.ts:146),
  - `close()` rejects queued jobs and stops the dispatcher
    (index.ts:193-214),
  - metrics: queue_length, job_wait_time, workers_busy populated here;
    verification counters inside the verifier.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from queue import SimpleQueue
from typing import List, Optional, Sequence

from ..observability import current_id as _trace_current_id
from ..observability import trace_span as _trace_span
from .signature_set import SignatureSet, WireSignatureSet
from .verifier import (
    MAX_PENDING_JOBS,
    N_BUCKETS,
    TpuBlsVerifier,
    VerifyOptions,
)

# Raised from the reference's 32 to one full kernel lane tile: RLC batch
# verification amortizes ONE final exponentiation over the whole device
# job, so a coalescing window that stops at 32 sets leaves 3/4 of the
# smallest (128-lane) N-bucket as padding.  Latency stays bounded by
# MAX_BUFFER_WAIT_MS, and an exact bucket fill flushes immediately
# (_maybe_flush_buffer_locked).
MAX_BUFFERED_SIGS = 128
MAX_BUFFER_WAIT_MS = 100    # reference: multithread/index.ts:57
# Device jobs dispatched but not yet resolved.  JAX dispatch is async, so
# in-flight jobs overlap the ~65 ms host<->device tunnel latency
# (dev/NOTES.md); the bound keeps retry latency and memory in check and
# is the backpressure coupling between the resolver and the dispatcher.
MAX_INFLIGHT_JOBS = 4


class _Job:
    # `agg_members` is set only on the pre-verify aggregation stage's
    # internal layer jobs (bls/aggregator.py): the contributions whose
    # verdicts the job's future fans out to
    __slots__ = ("sets", "opts", "future", "t_submit", "t_submit_ns",
                 "trace_parent", "agg_members")

    def __init__(self, sets, opts):
        self.sets = sets
        self.opts = opts
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.t_submit_ns = time.time_ns()
        # submitting context's span id: the dispatcher/resolver threads
        # do NOT inherit contextvars, so the device-side spans link back
        # to the gossip/import span that queued the work explicitly
        self.trace_parent = _trace_current_id()


class BlsVerifierService:
    def __init__(
        self,
        verifier: TpuBlsVerifier,
        max_pending_jobs: int = MAX_PENDING_JOBS,
        max_buffered_sigs: int = MAX_BUFFERED_SIGS,
        buffer_wait_ms: float = MAX_BUFFER_WAIT_MS,
        max_inflight_jobs: int = MAX_INFLIGHT_JOBS,
    ):
        self.verifier = verifier
        self.metrics = verifier.metrics
        if hasattr(verifier, "observe_single_thread"):
            # pooled worker, not single-thread mode (see CpuBlsVerifier)
            verifier.observe_single_thread = False
        self._max_pending = max_pending_jobs
        self._max_buffered = max_buffered_sigs
        self._buffer_wait = buffer_wait_ms / 1000.0
        self._lock = threading.Condition()
        self._queue: List[List[_Job]] = []
        self._buffer: List[_Job] = []
        self._buffer_deadline: Optional[float] = None
        # exact N-bucket fills flush immediately (no padding to gain by
        # waiting); stubs without a device job cap use every bucket
        self._bucket_fills = frozenset(
            b
            for b in N_BUCKETS
            if b <= getattr(verifier, "max_job_sets", N_BUCKETS[-1])
        )
        # trailing dispatch-run tracker for the exact-fill trigger (the
        # buffer is append-only between flushes, so O(new sets) updates
        # in _buffer_append_locked replace an O(buffer) rescan per
        # submission under the lock)
        self._buffered_sets = 0
        self._tail_run_len = 0
        self._tail_run_wire: Optional[bool] = None
        self._pending = 0  # queued + buffered + in-flight jobs
        # queued + buffered + in-flight SETS — the unit the pipeline's
        # high-water backpressure is measured in (a 1-set gossip job and
        # a 512-set range-sync job are very different work)
        self._pending_sets = 0
        self._closed = False
        # dispatcher begins device jobs; resolver syncs them in order.
        # The bounded in-flight queue pipelines dispatch latency.
        self._inflight: "SimpleQueue" = SimpleQueue()
        self._inflight_slots = threading.Semaphore(max_inflight_jobs)
        # groups begun but not yet resolved — the pipeline's critical-
        # lane idle test reads this (under the lock): batching is only
        # worth waiting for while the device has work to overlap with
        self._inflight_groups = 0
        # BlsWorkResult-parity records of recent device jobs (reference:
        # multithread/types.ts:26-38 — workerId, batchRetries,
        # batchSigsSuccess, workerStartNs, workerEndNs)
        from collections import deque

        self.recent_job_timings: "deque" = deque(maxlen=64)
        self._timings_lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._run, name="bls-verifier-dispatch", daemon=True
        )
        self._resolver = threading.Thread(
            target=self._resolve_loop, name="bls-verifier-resolve", daemon=True
        )
        self._thread.start()
        self._resolver.start()

    def job_timings(self) -> List[dict]:
        """Thread-safe snapshot of the BlsWorkResult-parity records."""
        with self._timings_lock:
            return list(self.recent_job_timings)

    def breaker_status(self) -> Optional[dict]:
        """The verifier's device-circuit-breaker status (ISSUE 14), or
        None for verifiers without a supervisor (CPU fallback/stubs) —
        the health endpoint's and bench's read path."""
        sup = getattr(self.verifier, "supervisor", None)
        return sup.status() if sup is not None else None

    # -- submission -------------------------------------------------------

    def can_accept_work(self) -> bool:
        with self._lock:
            return not self._closed and self._pending < self._max_pending

    def verify_signature_sets_async(
        self, sets: Sequence[SignatureSet], opts: Optional[VerifyOptions] = None
    ) -> "Future[bool]":
        opts = opts or VerifyOptions()
        if opts.verify_on_main_thread:
            fut: Future = Future()
            t0 = time.perf_counter()
            try:
                fut.set_result(
                    self.verifier.verify_signature_sets(list(sets), opts)
                )
            except Exception as e:  # pragma: no cover
                fut.set_exception(e)
            self.metrics.main_thread_time.observe(time.perf_counter() - t0)
            return fut
        job = _Job(list(sets), opts)
        with self._lock:
            closed = self._closed
            if not closed:
                self._pending += 1
                self._pending_sets += len(job.sets)
                self.metrics.pipeline_pending_sets.set(self._pending_sets)
                if opts.batchable and len(job.sets) < self._max_buffered:
                    self._submit_buffered_locked(job)
                else:
                    self._queue.append([job])
                self.metrics.queue_length.set(self._pending)
                self._lock.notify_all()
        if closed:
            # settle AFTER the lock releases: set_exception runs
            # done-callbacks synchronously on this thread, and a
            # continuation must never run inside the service Condition
            job.future.set_exception(RuntimeError("verifier closed"))
        return job.future

    def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: Optional[VerifyOptions] = None
    ) -> bool:
        """Synchronous wrapper (blocks on the service future)."""
        return self.verify_signature_sets_async(sets, opts).result()

    def _submit_buffered_locked(self, job: _Job) -> None:
        """Buffering-policy hook: route one batchable job into the
        coalescing buffer.  The flush timer anchors on the OLDEST
        buffered set's enqueue time (`job.t_submit` is stamped before
        lock acquisition), so p99 submit->flush latency stays bounded by
        the window even when lock contention or a busy dispatcher delays
        the append (ISSUE 11 satellite)."""
        self._buffer_append_locked(job)
        if self._buffer_deadline is None:
            self._buffer_deadline = job.t_submit + self._buffer_wait
        self._maybe_flush_buffer_locked()

    def _buffer_append_locked(self, job: _Job) -> None:
        """Append to the buffer, advancing the trailing-run tracker with
        the same rules as _dispatch's run split (contiguous same-kind
        runs, wire vs decoded, capped at max_job_sets)."""
        self._buffer.append(job)
        self._buffered_sets += len(job.sets)
        cap = getattr(self.verifier, "max_job_sets", N_BUCKETS[-1])
        for s in job.sets:
            is_wire = isinstance(s, WireSignatureSet)
            if is_wire == self._tail_run_wire and self._tail_run_len < cap:
                self._tail_run_len += 1
            else:
                self._tail_run_len, self._tail_run_wire = 1, is_wire

    def _maybe_flush_buffer_locked(self) -> None:
        """Flush on a full window OR an exact N-bucket fill.

        The RLC device job pads its sets up to a fixed N-bucket
        (verifier.N_BUCKETS); when the bucket is exactly filled, more
        waiting can only (a) burn the remaining `_buffer_deadline`
        latency and (b) spill the job into the next, twice-as-large
        bucket — so flush immediately.  The fill test keys on the
        TRAILING dispatch run: only it can still grow — earlier runs'
        padding is locked in however long we wait.  For the common
        homogeneous buffer this is just "total sets == a bucket".
        """
        if (
            self._buffered_sets >= self._max_buffered
            or self._tail_run_len in self._bucket_fills
        ):
            self._flush_buffer_locked()

    def _flush_buffer_locked(self) -> None:
        if self._buffer:
            self._queue.append(self._buffer)
            self._buffer = []
        self._buffered_sets = 0
        self._tail_run_len, self._tail_run_wire = 0, None
        self._buffer_deadline = None

    # -- dispatcher -------------------------------------------------------

    def _poll_buffers_locked(self, now: float) -> Optional[float]:
        """Buffering-policy hook: flush any deadline-due buffers into
        the dispatch queue; return seconds until the next deadline (the
        dispatcher's wait timeout), or None when nothing is buffered."""
        if self._buffer and (
            self._buffer_deadline is not None
            and now >= self._buffer_deadline
        ):
            self._flush_buffer_locked()
        if self._buffer_deadline is None:
            return None
        return max(self._buffer_deadline - now, 0.0)

    def _run(self) -> None:
        """Dispatcher: pull groups, begin device jobs, hand to resolver."""
        while True:
            with self._lock:
                while True:
                    if self._closed:
                        self._inflight.put(None)  # wake + stop resolver
                        return
                    now = time.perf_counter()
                    timeout = self._poll_buffers_locked(now)
                    if self._queue:
                        group = self._queue.pop(0)
                        break
                    self._lock.wait(timeout=timeout)
                self.metrics.queue_length.set(self._pending)
            self._dispatch(group)

    def _dispatch(self, group: List[_Job]) -> None:
        t0 = time.perf_counter()
        dispatch_start_ns = time.time_ns()
        for j in group:
            self.metrics.job_wait_time.observe(t0 - j.t_submit)
            # submit -> device dispatch (reference latencyToWorker)
            self.metrics.latency_to_worker.observe(
                max(dispatch_start_ns - j.t_submit_ns, 0) / 1e9
            )
        self.metrics.total_job_groups_started.inc()
        self.metrics.total_jobs_started.inc(len(group))
        self.metrics.total_sig_sets_started.inc(
            sum(len(j.sets) for j in group)
        )
        try:
            if len(group) == 1 and not group[0].opts.batchable:
                batchable = False
            else:
                batchable = True
            merged = [s for j in group for s in j.sets]
            begin = getattr(self.verifier, "begin_job", None)
            if begin is None:
                # verifier without async dispatch (CPU fallback/stubs):
                # the whole job runs at resolve time
                handles = (merged, batchable)
            else:
                # device jobs must be homogeneous (wire vs decoded sets);
                # a buffer window can legally mix submitters of both kinds
                cap = self.verifier.max_job_sets
                runs: List[List] = []
                for s in merged:
                    is_wire = isinstance(s, WireSignatureSet)
                    if (
                        runs
                        and isinstance(runs[-1][0], WireSignatureSet) == is_wire
                        and len(runs[-1]) < cap
                    ):
                        runs[-1].append(s)
                    else:
                        runs.append([s])
                handles = [begin(run, batchable) for run in runs]
        except Exception as e:
            for j in group:
                if not j.future.done():
                    j.future.set_exception(e)
            self.metrics.error_jobs.inc(len(group))
            with self._lock:
                self._pending -= len(group)
                self._pending_sets -= sum(len(j.sets) for j in group)
                self.metrics.pipeline_pending_sets.set(self._pending_sets)
                self.metrics.queue_length.set(self._pending)
                self._lock.notify_all()
            return
        self._inflight_slots.acquire()  # backpressure: bounded in-flight
        with self._lock:
            self._inflight_groups += 1
        self._inflight.put((group, handles, t0, dispatch_start_ns))

    def _resolve_loop(self) -> None:
        """Resolver: sync begun jobs in dispatch order, settle futures."""
        while True:
            item = self._inflight.get()
            if item is None:
                return
            group, handles, t0, worker_start_ns = item
            self._inflight_slots.release()
            self.metrics.workers_busy.set(1)
            worker_end_ns = None
            # explicit enter/exit (not `with`): the span must close at
            # the TOP of the finally so it brackets only the device
            # resolution, parented to the submitting context's span
            span = _trace_span(
                "bls.job",
                parent_id=group[0].trace_parent if group else None,
                jobs=len(group),
                sets=sum(len(j.sets) for j in group),
            )
            span.__enter__()
            try:
                if isinstance(handles, tuple):
                    merged, batchable = handles
                    ok = self.verifier.verify_signature_sets(
                        merged, VerifyOptions(batchable=batchable)
                    )
                else:
                    ok = True
                    for h in handles:
                        ok &= self.verifier.finish_job(h)
                worker_end_ns = time.time_ns()
                if ok:
                    for j in group:
                        j.future.set_result(True)
                elif len(group) == 1:
                    group[0].future.set_result(False)
                elif isinstance(handles, tuple):
                    # no-begin_job fallback: re-verify per job so one bad
                    # signature cannot poison other jobs' verdicts
                    # (reference: worker.ts:74-96)
                    for j in group:
                        j.future.set_result(
                            self.verifier.verify_signature_sets(j.sets, j.opts)
                        )
                else:
                    # a failed merged batch: finish_job already produced
                    # per-set verdicts for failed handles (the device
                    # retry pass) — slice them back to the submitting
                    # jobs by position instead of re-verifying
                    # (reference accounting: worker.ts:74-96)
                    per_set = []
                    aligned = True
                    for h in handles:
                        if not bool(h.ok_big):
                            aligned = False  # a CPU-routed set failed in
                            break  # this handle; positions ambiguous
                        if getattr(h, "verdicts", None) is not None:
                            per_set.extend(bool(v) for v in h.verdicts)
                        else:
                            per_set.extend([True] * len(h.sets))
                    total = sum(len(j.sets) for j in group)
                    if aligned and len(per_set) == total:
                        pos = 0
                        for j in group:
                            nj = len(j.sets)
                            j.future.set_result(all(per_set[pos : pos + nj]))
                            pos += nj
                    else:
                        # CPU-routed sets (oversized aggregates, external
                        # keys) broke positional alignment: re-verify per
                        # job to attribute failures correctly
                        for j in group:
                            j.future.set_result(
                                self.verifier.verify_signature_sets(
                                    j.sets, j.opts
                                )
                            )
            except Exception as e:
                for j in group:
                    if not j.future.done():
                        j.future.set_exception(e)
                self.metrics.error_jobs.inc(len(group))
            finally:
                span.__exit__(None, None, None)
                self.metrics.workers_busy.set(0)
                settled_ns = time.time_ns()
                if worker_end_ns is not None:
                    # device result ready -> futures settled (reference
                    # latencyFromWorker), device-bracket ns timestamps
                    # (reference workerStartNs/workerEndNs)
                    self.metrics.latency_from_worker.observe(
                        max(settled_ns - worker_end_ns, 0) / 1e9
                    )
                    self.metrics.jobs_worker_time.inc(
                        "0", (worker_end_ns - worker_start_ns) / 1e9
                    )
                    with self._timings_lock:
                        self.recent_job_timings.append(
                            {
                                "worker_id": 0,
                                # per-job fields carried on the device
                                # handles themselves (no racy global
                                # counter diffs).  KNOWN GAP: the
                                # no-begin_job tuple path and the
                                # misaligned re-verify fallback create
                                # internal jobs whose counters are not
                                # attributed here (global counters stay
                                # correct; only the per-job record
                                # underreports on those rare paths)
                                "batch_retries": sum(
                                    getattr(h, "batch_retries", 0)
                                    for h in (
                                        handles
                                        if not isinstance(handles, tuple)
                                        else ()
                                    )
                                ),
                                "batch_sigs_success": sum(
                                    getattr(h, "batch_sigs_success", 0)
                                    for h in (
                                        handles
                                        if not isinstance(handles, tuple)
                                        else ()
                                    )
                                ),
                                "worker_start_ns": worker_start_ns,
                                "worker_end_ns": worker_end_ns,
                                "sig_sets": sum(len(j.sets) for j in group),
                            }
                        )
                # verify_signature_sets observes job_time itself; only the
                # begin/finish handle path accounts here (no double count)
                if not isinstance(handles, tuple):
                    dt = time.perf_counter() - t0
                    nsets = sum(len(j.sets) for j in group)
                    self.metrics.job_time.observe(dt)
                    if nsets:
                        self.metrics.time_per_sig_set.observe(dt / nsets)
                with self._lock:
                    self._pending -= len(group)
                    self._pending_sets -= sum(len(j.sets) for j in group)
                    self._inflight_groups -= 1
                    self.metrics.pipeline_pending_sets.set(self._pending_sets)
                    self.metrics.queue_length.set(self._pending)
                    self._lock.notify_all()

    # -- shutdown (reference: multithread/index.ts:193-214) ---------------

    def _close_flush_locked(self) -> None:
        """Buffering-policy hook: drain every buffer into the dispatch
        queue at shutdown (the queued jobs are then rejected)."""
        self._flush_buffer_locked()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._close_flush_locked()
            rejected = [j for g in self._queue for j in g]
            self._queue = []
            self._pending -= len(rejected)
            self._pending_sets -= sum(len(j.sets) for j in rejected)
            self.metrics.pipeline_pending_sets.set(self._pending_sets)
            self._lock.notify_all()
        for j in rejected:
            j.future.set_exception(RuntimeError("verifier closed"))
        self._thread.join(timeout=5)
        self._resolver.join(timeout=30)  # drains in-flight device jobs
        self.verifier.close()
