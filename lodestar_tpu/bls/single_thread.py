"""CpuBlsVerifier — the single-thread CPU fallback verifier.

Mirror of the reference's BlsSingleThreadVerifier (reference:
packages/beacon-node/src/chain/bls/singleThread.ts): verifies every set
synchronously on the host with the ground-truth crypto oracle — the
latency fast path for proposer signatures and the fallback when no TPU
is attached (the reference's herumi/main-thread role).  Implements the
same IBlsVerifier surface as TpuBlsVerifier so chain/node compositions
swap freely (reference: chain.ts:196-198 verifier selection).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..utils.metrics import BlsPoolMetrics, BlsSingleThreadMetrics
from .signature_set import SignatureSet, WireSignatureSet


class CpuBlsVerifier:
    """Host-CPU IBlsVerifier over a pubkey registry.

    `pubkeys` maps validator index -> affine G1 point (the same
    ground-truth points a PubkeyTable holds); `table` may be passed
    instead to share the node's registry.
    """

    def __init__(
        self,
        pubkeys: Optional[Sequence] = None,
        table=None,
        metrics: Optional[BlsPoolMetrics] = None,
    ):
        self._pubkeys = pubkeys
        self._table = table
        self.metrics = metrics or BlsPoolMetrics()
        self.single_thread_metrics = BlsSingleThreadMetrics(
            self.metrics.registry
        )
        # True when this verifier IS the single-thread mode (the
        # reference's blsSingleThread family measures the pool-BYPASS
        # path only); BlsVerifierService clears it when pooling this
        # verifier as its worker so pool jobs don't double-count
        self.observe_single_thread = True
        self.max_job_sets = 128

    def _pubkey(self, index: int):
        if self._pubkeys is not None:
            return self._pubkeys[index]
        return self._table.host_affine(index)

    def can_accept_work(self) -> bool:
        return True

    def verify_signature_sets(self, sets, opts=None) -> bool:
        import time as _time

        from ..observability import trace_span

        t0 = _time.perf_counter()
        with trace_span("bls.verify", batch_size=len(sets), backend="cpu"):
            verdicts = [self._verify_one(s) for s in sets]
        dt = _time.perf_counter() - t0
        self.metrics.batch_size.observe(len(sets))
        self.metrics.verify_seconds.observe("total", dt)
        if self.observe_single_thread:
            self.single_thread_metrics.duration.observe(dt)
            if sets:
                self.single_thread_metrics.time_per_sig_set.observe(
                    dt / len(sets)
                )
        good = sum(verdicts)
        self.metrics.success_jobs.inc(good)
        self.metrics.invalid_sets.inc(len(sets) - good)
        return all(verdicts)

    def verify_signature_sets_individually(self, sets) -> List[bool]:
        return [self._verify_one(s) for s in sets]

    def _verify_one(self, s) -> bool:
        from ..crypto import bls as CB
        from ..crypto import curves as C
        from ..crypto import pairing as CP

        dec: SignatureSet = s.decode() if isinstance(s, WireSignatureSet) else s
        if dec.signature is None:
            return False
        if not C.is_on_curve(C.FP2_OPS, dec.signature):
            return False
        if not C.g2_subgroup_check(dec.signature):
            return False
        if dec.external_pubkeys is not None:
            keys = []
            for pk in dec.external_pubkeys:
                if (
                    pk is None
                    or not C.is_on_curve(C.FP_OPS, pk)
                    or not C.g1_subgroup_check(pk)
                ):
                    return False
                keys.append(pk)
        else:
            try:
                keys = [self._pubkey(i) for i in dec.indices]
            except (IndexError, KeyError):
                return False
        agg = C.multi_add(C.FP_OPS, keys)
        if agg is None:  # aggregate pubkey at infinity never verifies
            return False
        return CP.multi_pairing_is_one(
            [(agg, dec.message), (CB.NEG_G1_GEN, dec.signature)]
        )

    def close(self) -> None:
        pass
