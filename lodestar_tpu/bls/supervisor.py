"""Device circuit breaker — fault-domain isolation for the BLS data plane.

The verification data plane is liveness-critical (EdDSA/BLS committee
study, arXiv:2302.00418): a TPU stream that hangs mid-slot must not
take the gossip loop down with it.  Bench rounds r03–r05 showed the
failure mode concretely — 180 s backend-init probes with nothing
supervising them.  This module puts every device dispatch seam of
`TpuBlsVerifier` behind one breaker:

  - **CLOSED** (healthy): jobs dispatch to the device as before.  Every
    supervised failure is CLASSIFIED — ``timeout`` (the optional
    per-job watchdog deadline fired), ``backend_init`` (tunnel/backend
    initialization errors), ``bad_output`` (malformed device results),
    ``error`` (anything else) — and ``failure_threshold`` consecutive
    failures trip the breaker.
  - **OPEN** (degraded): no device dispatch happens at all.  The
    verifier routes every flushed job through the host ground-truth
    path (`_verify_set_host`), so verdicts keep flowing — zero dropped
    sets, pipeline/aggregator/backpressure semantics unchanged.  A
    background task re-probes on a jittered exponential backoff.
  - **HALF_OPEN**: the re-probe window arrived; ONE canary job runs on
    the device.  Success closes the breaker (device path restored);
    failure re-opens it and doubles the backoff (capped).

Metrics (`lodestar_bls_breaker_*`): state gauge (0 closed / 1 half-open
/ 2 open), trip counter, per-outcome failure counter, probe counter,
cumulative degraded seconds, host-fallback set counter.

Hooks: ``on_trip(info)`` / ``on_recover(info)`` — node.py wires these
into the SLO engine (anomaly + flight-record capture) and registers
``is_open`` as a health ``degraded`` source.

Escape hatch: ``LODESTAR_TPU_BLS_BREAKER=0`` disables supervision
entirely (calls pass through, failures propagate as before).  The
watchdog deadline defaults ON only on the TPU backend
(``LODESTAR_TPU_BLS_JOB_DEADLINE_S`` overrides; ``0`` disables) — on
the CPU test backend a first-dispatch kernel compile legitimately
takes longer than any sane device deadline.
"""

from __future__ import annotations

import concurrent.futures
import os
import re
import threading
import time
import weakref
from typing import Callable, Optional

from ..utils.metrics import Registry
from ..utils.misc import DeadlineExceeded, run_with_deadline

STATE_CLOSED = 0
STATE_HALF_OPEN = 1
STATE_OPEN = 2
_STATE_NAMES = {STATE_CLOSED: "closed", STATE_HALF_OPEN: "half_open",
                STATE_OPEN: "open"}

OUTCOME_TIMEOUT = "timeout"
OUTCOME_BACKEND_INIT = "backend_init"
OUTCOME_BAD_OUTPUT = "bad_output"
OUTCOME_ERROR = "error"

DEFAULT_BACKOFF_INITIAL_S = 1.0
DEFAULT_BACKOFF_MAX_S = 60.0
DEFAULT_FAILURE_THRESHOLD = 1
# watchdog default on the TPU backend: a device job is ~65 ms of tunnel
# latency; a minute without a verdict is the r03-style hang, not a slow
# batch
DEFAULT_TPU_JOB_DEADLINE_S = 60.0


class BreakerOpen(RuntimeError):
    """The device path is unavailable (breaker open/half-open)."""


class DeviceTimeout(RuntimeError):
    """A supervised device call exceeded its watchdog deadline."""


class BadDeviceOutput(RuntimeError):
    """A device call returned a malformed result (wrong shape/dtype)."""


# error text that indicates the BACKEND (tunnel, TPU runtime) is sick,
# as opposed to a bug in one job's inputs — the r03–r05 probe deaths
# all match
_BACKEND_INIT_PAT = re.compile(
    r"backend|initializ|UNAVAILABLE|DEADLINE_EXCEEDED|failed to connect"
    r"|tunnel|socket|libtpu|DataLoss|ABORTED|device.*(lost|reset)",
    re.IGNORECASE,
)


def classify_failure(exc: BaseException) -> str:
    """Map one device-path exception to a breaker outcome label."""
    if isinstance(exc, DeviceTimeout):
        return OUTCOME_TIMEOUT
    if isinstance(exc, BadDeviceOutput):
        return OUTCOME_BAD_OUTPUT
    if isinstance(
        exc,
        (concurrent.futures.TimeoutError, TimeoutError, DeadlineExceeded),
    ):
        return OUTCOME_TIMEOUT
    if _BACKEND_INIT_PAT.search(f"{type(exc).__name__}: {exc}"):
        return OUTCOME_BACKEND_INIT
    return OUTCOME_ERROR


def check_verdict_plane(arr, n_expected: int, name: str = "device"):
    """Validate one per-set verdict plane: the bad-output classifier's
    entry point.  Returns the array; raises BadDeviceOutput on a
    malformed shape (a truncated or empty result must trip the breaker,
    not silently zero-fill verdicts)."""
    import numpy as np

    a = np.asarray(arr)
    if a.ndim < 1 or a.shape[0] < n_expected:
        raise BadDeviceOutput(
            f"{name}: verdict plane shape {a.shape} < {n_expected} sets"
        )
    return a


# live supervisors, for bench.py's per-record "breaker" snapshot (the
# bench world builds its verifier in-process; mirroring slo.breach_snapshot)
_ACTIVE: "weakref.WeakSet[DeviceSupervisor]" = weakref.WeakSet()


def breaker_snapshot() -> dict:
    """Aggregate state of every live supervisor in this process —
    zeros/closed when none exist.  Attached to every bench record."""
    sups = list(_ACTIVE)
    if not sups:
        return {
            "state": "closed",
            "trips": 0,
            "time_in_degraded_s": 0.0,
            "supervisors": 0,
        }
    worst = max(s.state for s in sups)
    return {
        "state": _STATE_NAMES[worst],
        "trips": sum(s.trip_count for s in sups),
        "time_in_degraded_s": round(
            sum(s.time_in_degraded_s() for s in sups), 3
        ),
        "supervisors": len(sups),
    }


def breaker_enabled_env() -> bool:
    env = os.environ.get("LODESTAR_TPU_BLS_BREAKER", "1")
    return env.strip().lower() not in ("0", "false", "no", "off")


class DeviceSupervisor:
    """The breaker state machine + watchdog + re-probe task.

    `canary` is a zero-arg callable returning truthy when one minimal
    device job succeeded (the verifier binds `_device_canary`).  `clock`
    is injectable (chaos tests drive backoff deterministically with a
    fake clock); `rng` seeds the backoff jitter.  With
    `auto_probe=True` (production) a daemon thread wakes at each
    re-probe deadline; tests pass False and call `poll()` themselves.
    """

    def __init__(
        self,
        registry: Optional[Registry] = None,
        canary: Optional[Callable[[], bool]] = None,
        clock: Callable[[], float] = time.monotonic,
        failure_threshold: int = DEFAULT_FAILURE_THRESHOLD,
        job_deadline_s: Optional[float] = None,
        backoff_initial_s: float = DEFAULT_BACKOFF_INITIAL_S,
        backoff_max_s: float = DEFAULT_BACKOFF_MAX_S,
        auto_probe: bool = True,
        enabled: Optional[bool] = None,
        rng=None,
    ):
        self.enabled = breaker_enabled_env() if enabled is None else enabled
        self.canary = canary
        self.clock = clock
        self.failure_threshold = max(1, int(failure_threshold))
        self.backoff_initial_s = backoff_initial_s
        self.backoff_max_s = backoff_max_s
        self.auto_probe = auto_probe
        if rng is None:
            import random

            rng = random.Random()
        self._rng = rng
        if job_deadline_s is None:
            env = os.environ.get("LODESTAR_TPU_BLS_JOB_DEADLINE_S")
            env_valid = False
            if env is not None:
                try:
                    job_deadline_s = float(env) or None
                    env_valid = True
                except ValueError:
                    # a malformed override must NOT silently disable
                    # the hang watchdog — warn and fall through to the
                    # backend default below
                    from ..utils.logger import get_logger

                    get_logger("bls/supervisor").warn(
                        "ignoring malformed "
                        f"LODESTAR_TPU_BLS_JOB_DEADLINE_S={env!r} "
                        "(expected seconds as a float; 0 disables)"
                    )
            if not env_valid:
                # watchdog only where the 65 ms-dispatch assumption
                # holds; XLA:CPU first-dispatch compiles legitimately
                # run minutes on the 1-core test host
                try:
                    import jax

                    if jax.default_backend() == "tpu":
                        job_deadline_s = DEFAULT_TPU_JOB_DEADLINE_S
                except Exception:  # noqa: BLE001 — no jax, no watchdog
                    job_deadline_s = None
        self.job_deadline_s = job_deadline_s

        # hooks the node composition wires (exception-isolated at call)
        self.on_trip: Optional[Callable[[dict], None]] = None
        self.on_recover: Optional[Callable[[dict], None]] = None

        self._lock = threading.Lock()
        self.state = STATE_CLOSED
        self.trip_count = 0
        self._consecutive = 0
        self._t_opened: Optional[float] = None
        self._degraded_total_s = 0.0
        self._backoff_s = backoff_initial_s
        self._next_probe_t: Optional[float] = None
        self._last_failure: Optional[dict] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._probe_wake = threading.Event()
        self._closed = False

        r = registry or Registry()
        self.m_state = r.gauge(
            "lodestar_bls_breaker_state",
            "BLS device breaker state (0 closed, 1 half-open, 2 open)",
        )
        self.m_trips = r.counter(
            "lodestar_bls_breaker_trips_total",
            "BLS device breaker trips (device path -> degraded host path)",
        )
        self.m_failures = r.labeled_counter(
            "lodestar_bls_breaker_failures_total",
            "Supervised device-path failures by classified outcome",
            "outcome",
        )
        self.m_probes = r.labeled_counter(
            "lodestar_bls_breaker_probes_total",
            "Canary re-probe attempts by result",
            "result",
        )
        self.m_degraded_seconds = r.counter(
            "lodestar_bls_breaker_degraded_seconds_total",
            "Cumulative seconds spent with the breaker open",
        )
        self.m_host_fallback_sets = r.counter(
            "lodestar_bls_breaker_host_fallback_sets_total",
            "Signature sets resolved through the degraded host path",
        )
        self.m_state.set(0.0)
        if self.enabled:
            _ACTIVE.add(self)

    # -- gating (read on every job) ----------------------------------------

    @property
    def active(self) -> bool:
        return self.enabled

    def device_allowed(self) -> bool:
        """True when jobs may dispatch to the device (breaker closed, or
        supervision disabled)."""
        if not self.enabled:
            return True
        # tpulint: disable=guarded-by -- benign race: per-job hot-path advisory read; a stale breaker state costs one extra probe/shed, and transitions settle under the lock
        return self.state == STATE_CLOSED

    def is_open(self) -> bool:
        """True while degraded (open or half-open) — the health
        endpoint's `degraded` source."""
        # tpulint: disable=guarded-by -- benign race: health-endpoint advisory read; staleness is bounded by one watchdog tick and the value is monotonic within a probe window
        return self.enabled and self.state != STATE_CLOSED

    # -- the watchdog ------------------------------------------------------

    def run_guarded(self, fn: Callable[[], object], name: str = "device"):
        """Run one device-path call under the per-job deadline.  With no
        deadline configured (or supervision disabled) this is `fn()`;
        otherwise the call runs on its OWN expendable thread
        (utils/misc.run_with_deadline) and a hang past the deadline
        raises DeviceTimeout — the thread is abandoned so the
        dispatcher/resolver can never be wedged by a dead device
        stream.  Thread-per-call, not a shared worker: concurrent seams
        (the resolver's finish_job vs the dispatcher's agg_g2_sum) must
        never have queue wait behind each other counted against their
        own deadline."""
        if not self.enabled or not self.job_deadline_s:
            return fn()
        try:
            return run_with_deadline(fn, self.job_deadline_s, name)
        except DeadlineExceeded:
            raise DeviceTimeout(
                f"{name} exceeded the {self.job_deadline_s:.1f}s job deadline"
            ) from None

    # -- failure/success accounting ----------------------------------------

    def record_success(self) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._consecutive = 0

    def record_failure(
        self, outcome: str, seam: str, detail: str = ""
    ) -> None:
        """One classified device-path failure at `seam` (begin_job /
        finish_job / agg_g2_sum / export:<entry>).  Trips the breaker at
        the consecutive-failure threshold."""
        if not self.enabled:
            return
        self.m_failures.inc(outcome, 1.0)
        info = None
        with self._lock:
            self._last_failure = {
                "outcome": outcome,
                "seam": seam,
                "detail": detail[:500],
            }
            self._consecutive += 1
            if (
                self.state == STATE_CLOSED
                and self._consecutive >= self.failure_threshold
            ):
                info = self._trip_locked()
        if info is not None:
            self._fire(self.on_trip, info)
            if self.auto_probe:
                self._ensure_probe_thread()

    def note_host_fallback(self, n_sets: int) -> None:
        self.m_host_fallback_sets.inc(n_sets)

    def note_nonfatal(self, outcome: str, seam: str, detail: str = "") -> None:
        """Surface a device-adjacent fault on the failure metric WITHOUT
        advancing the trip streak — for faults whose fallback already
        proved the device alive (an export-stage error followed by a
        successful direct dispatch)."""
        if not self.enabled:
            return
        self.m_failures.inc(outcome, 1.0)
        with self._lock:
            self._last_failure = {
                "outcome": outcome,
                "seam": seam,
                "detail": detail[:500],
            }

    def _trip_locked(self) -> dict:
        # a trip AFTER close() re-arms the supervisor: services that
        # share one verifier across lifecycles (bench probes, test
        # worlds) keep supervision for as long as the verifier is used
        _ACTIVE.add(self)
        self.state = STATE_OPEN
        self.trip_count += 1
        self.m_trips.inc()
        self.m_state.set(float(STATE_OPEN))
        self._t_opened = self.clock()
        self._backoff_s = self.backoff_initial_s
        self._next_probe_t = self._t_opened + self._jittered(self._backoff_s)
        self._probe_wake.set()
        info = dict(self._last_failure or {})
        info["trip_count"] = self.trip_count
        return info

    def _jittered(self, backoff: float) -> float:
        # +/- 25% jitter decorrelates re-probes across a fleet sharing
        # one sick tunnel
        return backoff * (0.75 + 0.5 * self._rng.random())

    def _fire(self, hook, info: dict) -> None:
        if hook is None:
            return
        try:
            hook(info)
        except Exception:  # noqa: BLE001 — observers must never break
            pass  # the breaker itself

    # -- re-probe ----------------------------------------------------------

    def poll(self) -> None:
        """Run the canary if the re-probe window arrived.  Idempotent
        and cheap when closed or not yet due; chaos tests call this
        directly with a fake clock, production rides the probe thread."""
        with self._lock:
            if (
                not self.enabled
                or self.state != STATE_OPEN
                or self._next_probe_t is None
                or self.clock() < self._next_probe_t
            ):
                return
            self.state = STATE_HALF_OPEN
            self.m_state.set(float(STATE_HALF_OPEN))
        ok = False
        try:
            ok = bool(self.canary()) if self.canary is not None else True
        except Exception:  # noqa: BLE001 — a failing canary is a failed
            ok = False  # probe, never an escape
        info = None
        with self._lock:
            self.m_probes.inc("success" if ok else "failure", 1.0)
            if ok:
                info = self._close_locked()
            else:
                self.state = STATE_OPEN
                self.m_state.set(float(STATE_OPEN))
                self._backoff_s = min(
                    self._backoff_s * 2.0, self.backoff_max_s
                )
                self._next_probe_t = self.clock() + self._jittered(
                    self._backoff_s
                )
        if info is not None:
            self._fire(self.on_recover, info)

    def _close_locked(self) -> dict:
        self.state = STATE_CLOSED
        self.m_state.set(float(STATE_CLOSED))
        self._consecutive = 0
        degraded = 0.0
        if self._t_opened is not None:
            degraded = max(self.clock() - self._t_opened, 0.0)
            self._degraded_total_s += degraded
            self.m_degraded_seconds.inc(degraded)
        self._t_opened = None
        self._next_probe_t = None
        return {
            "trip_count": self.trip_count,
            "degraded_s": round(degraded, 3),
        }

    def _ensure_probe_thread(self) -> None:
        with self._lock:
            self._closed = False  # a new trip re-arms a closed supervisor
            if (
                self._probe_thread is not None
                and self._probe_thread.is_alive()
            ):
                return
            self._probe_thread = threading.Thread(
                target=self._probe_loop,
                name="bls-breaker-probe",
                daemon=True,
            )
            self._probe_thread.start()

    def _probe_loop(self) -> None:
        while True:
            with self._lock:
                if self._closed or self.state == STATE_CLOSED:
                    self._probe_thread = None
                    return
                wait = (
                    max(self._next_probe_t - self.clock(), 0.0)
                    if self._next_probe_t is not None
                    else 0.5
                )
            self._probe_wake.clear()
            if self.clock is time.monotonic:
                # real clock: the computed wait IS wall time, and every
                # schedule change sets the wake event — sleep the full
                # window instead of polling
                self._probe_wake.wait(timeout=max(wait, 0.01))
            else:
                # injectable clock (chaos tests): wall sleeps say
                # nothing about fake time — poll at a short cadence so
                # an advanced clock is observed promptly
                self._probe_wake.wait(timeout=min(max(wait, 0.01), 0.05))
            self.poll()

    # -- lifecycle / introspection -----------------------------------------

    def time_in_degraded_s(self) -> float:
        with self._lock:
            total = self._degraded_total_s
            if self._t_opened is not None:
                total += max(self.clock() - self._t_opened, 0.0)
        return total

    def status(self) -> dict:
        with self._lock:
            next_probe = (
                max(self._next_probe_t - self.clock(), 0.0)
                if self._next_probe_t is not None
                and self.state != STATE_CLOSED
                else None
            )
            return {
                "enabled": self.enabled,
                "state": _STATE_NAMES[self.state],
                "trips": self.trip_count,
                "consecutive_failures": self._consecutive,
                "time_in_degraded_s": round(
                    self._degraded_total_s
                    + (
                        max(self.clock() - self._t_opened, 0.0)
                        if self._t_opened is not None
                        else 0.0
                    ),
                    3,
                ),
                "last_failure": self._last_failure,
                "next_probe_in_s": (
                    round(next_probe, 3) if next_probe is not None else None
                ),
                "job_deadline_s": self.job_deadline_s,
                "failure_threshold": self.failure_threshold,
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
        self._probe_wake.set()
        _ACTIVE.discard(self)


__all__ = [
    "DeviceSupervisor",
    "BreakerOpen",
    "DeviceTimeout",
    "BadDeviceOutput",
    "classify_failure",
    "check_verdict_plane",
    "breaker_snapshot",
    "breaker_enabled_env",
    "STATE_CLOSED",
    "STATE_HALF_OPEN",
    "STATE_OPEN",
    "OUTCOME_TIMEOUT",
    "OUTCOME_BACKEND_INIT",
    "OUTCOME_BAD_OUTPUT",
    "OUTCOME_ERROR",
]
