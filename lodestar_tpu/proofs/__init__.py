"""Proof-serving data plane — the read surface over the state-root engine.

The state-root engine (state_transition/state_root.py) keeps every
internal Merkle plane of the hot state resident in `ChunkTree`s.  This
package turns those warm planes from a cost center into a product
surface:

  - `plane_reader`: single- and multi-leaf Merkle proofs as O(log n)
    plane READS with zero re-hashing, returning None when planes are
    not resident (callers fall through to the `container_branch` host
    path — a cold or evicted plane can never produce a wrong or
    missing proof);
  - `bundle_cache`: a bounded LRU of per-checkpoint proof bundles,
    byte-accounted into the memory governor (under squeeze it drains
    BEFORE live states demote);
  - `service`: the `ProofService` serving `/eth/v1/beacon/light_client/*`
    and `/eth/v0/beacon/proof/state/*` bundle-first, plane-second,
    host-last, with per-source accounting.
"""

from .bundle_cache import ProofBundleCache, estimate_bytes
from .plane_reader import (
    pack_multiproof,
    state_multiproof,
    state_proof,
    verify_multiproof,
)
from .service import ProofService

__all__ = [
    "ProofBundleCache",
    "ProofService",
    "estimate_bytes",
    "pack_multiproof",
    "state_multiproof",
    "state_proof",
    "verify_multiproof",
]
