"""ProofService — the bundle-first, plane-second, host-last proof server.

Reference: packages/beacon-node/src/api/impl/lightclient/index.ts and
api/impl/proof/index.ts, which answer every light-client request by
re-walking the persistent merkle tree.  Here the answers are layered by
cost instead:

  1. **bundle** — the fully rendered JSON payload from the
     `ProofBundleCache` (a dict lookup; a light-client horde asks the
     SAME few questions thousands of times per head),
  2. **plane** — O(log n) sibling reads off the warm state-root engine
     (`proofs.plane_reader`), zero re-hashing,
  3. **host** — the `container_branch`/`container_branches` fallback,
     which ALWAYS completes, so a cold cache and an evicted plane can
     only cost latency, never correctness.

Every answer increments exactly one source counter; the bench and the
chaos harness assert on that accounting.  The cache registers with the
memory governor as a drainable auxiliary: under squeeze the bundles go
first, live states last.
"""

from __future__ import annotations

from contextlib import nullcontext
from typing import List, Optional, Sequence

from ..light_client.lightclient import sync_period
from ..ssz.core import container_branches
from ..utils.logger import get_logger
from .bundle_cache import ProofBundleCache
from .plane_reader import pack_multiproof, state_multiproof

# period-rollover warmer: how many trailing periods to pre-render
WARM_PERIODS = 2


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


class ProofService:
    """Serves light-client payloads and state-field Merkle proofs.

    Wired between the API handlers and the `LightClientServer`: the
    handlers delegate here first and keep their own paths as the
    no-service fallback.  Subscribes to chain events for cache
    invalidation (head movement stales finality/optimistic/state
    proofs; a better update stales its period's bundle)."""

    def __init__(
        self,
        chain,
        light_client_server=None,
        governor=None,
        cache: Optional[ProofBundleCache] = None,
        max_bundle_entries: int = 512,
        max_bundle_bytes: int = 16 << 20,
    ):
        self.chain = chain
        self.lc = light_client_server
        self.governor = governor
        self.log = get_logger("proofs/service")
        self.cache = (
            cache
            if cache is not None
            else ProofBundleCache(
                max_entries=max_bundle_entries, max_bytes=max_bundle_bytes
            )
        )
        # per-source answer accounting (fixed key set, counters only)
        self.sources = {"bundle": 0, "plane": 0, "host": 0}
        self.batch_generated = 0  # period-rollover pre-renders
        self._last_period: Optional[int] = None
        if governor is not None and hasattr(governor, "register_aux"):
            governor.register_aux("proof_bundles", self.cache)
        emitter = getattr(chain, "emitter", None) if chain is not None else None
        if emitter is not None:
            # deferred import: this module is reachable from chain/
            # submodules via the package __init__
            from ..chain.emitter import ChainEvent

            emitter.on(ChainEvent.head, self._on_head)
            emitter.on(ChainEvent.light_client_update, self._on_lc_update)

    # -- invalidation ------------------------------------------------------

    def _on_head(self, root: bytes, slot: int) -> None:
        # head-anchored payloads are stale the moment the head moves
        self.cache.invalidate("finality")
        self.cache.invalidate("optimistic")
        self.cache.invalidate("state_proof")

    def _on_lc_update(self, update) -> None:
        # a better update may have replaced this period's best; the
        # latest finality/optimistic payloads certainly changed
        period = sync_period(int(update.attested_header["slot"]))
        self.cache.invalidate("lc_update", period)
        self.cache.invalidate("finality")
        self.cache.invalidate("optimistic")

    # -- rendering helpers (the api/server.py response shapes) -------------

    def _version(self, slot: int) -> str:
        config = getattr(self.chain, "config", None)
        if config is None:
            return "altair"
        return config.get_fork_name(int(slot)).value

    def _render_update(self, upd) -> dict:
        from ..api.encoding import to_json
        from ..network.reqresp_protocols import (
            LightClientUpdateType,
            light_client_update_to_value,
        )

        return to_json(
            LightClientUpdateType, light_client_update_to_value(upd)
        )

    def _update_item(self, upd) -> dict:
        slot = int(upd.attested_header["slot"])
        return {
            "version": self._version(slot),
            "data": self._render_update(upd),
        }

    # -- light-client serving ----------------------------------------------

    def light_client_updates(self, start: int, count: int) -> List[dict]:
        """Rendered {version, data} items for [start, start+count) —
        periods without a best update are skipped (API contract)."""
        out: List[dict] = []
        for period in range(int(start), int(start) + int(count)):
            item = self.cache.get("lc_update", period)
            if item is not None:
                self.sources["bundle"] += 1
                out.append(item)
                continue
            upd = self.lc.get_update(period) if self.lc is not None else None
            if upd is None:
                continue
            item = self._update_item(upd)
            # attribution: the expensive branch extraction happened at
            # production time (LightClientServer counts plane vs host);
            # a fresh render here is a host-side pass
            self.sources["host"] += 1
            self.cache.put("lc_update", period, item)
            out.append(item)
        return out

    def finality_update(self) -> Optional[dict]:
        return self._latest("finality", "get_finality_update")

    def optimistic_update(self) -> Optional[dict]:
        return self._latest("optimistic", "get_optimistic_update")

    def _latest(self, kind: str, getter: str) -> Optional[dict]:
        item = self.cache.get(kind, "latest")
        if item is not None:
            self.sources["bundle"] += 1
            return item
        if self.lc is None:
            return None
        upd = getattr(self.lc, getter)()
        if upd is None:
            return None
        item = self._render_update(upd)
        self.sources["host"] += 1
        self.cache.put(kind, "latest", item)
        return item

    def bootstrap(self, block_root: bytes) -> Optional[dict]:
        """Rendered LightClientBootstrap for a trusted block root."""
        key = bytes(block_root)
        item = self.cache.get("bootstrap", key)
        if item is not None:
            self.sources["bundle"] += 1
            return item
        if self.lc is None:
            return None
        planes_before = getattr(self.lc, "plane_proofs", 0)
        boot = self.lc.get_bootstrap(key)
        if boot is None:
            return None
        from ..api.encoding import to_json
        from ..network.reqresp_protocols import LightClientBootstrapType

        item = to_json(LightClientBootstrapType, boot)
        if getattr(self.lc, "plane_proofs", 0) > planes_before:
            self.sources["plane"] += 1
        else:
            self.sources["host"] += 1
        self.cache.put("bootstrap", key, item)
        return item

    # -- state-field proofs -------------------------------------------------

    def state_proof_data(self, state, paths: Sequence[Sequence[str]]) -> dict:
        """Response payload for /eth/v0/beacon/proof/state.

        One path keeps the original single-proof shape ({leaf, branch,
        depth, index, state_root}); several paths add a proofs list and
        the deduped descending multiproof.  Raises KeyError/ValueError/
        TypeError on a bad path (the handler's 400)."""
        paths = [list(p) for p in paths]
        with self._lease(getattr(self.chain, "head_root_hex", "")):
            # plane residency BEFORE touching the root: hash_tree_root
            # on an engineless (spilled/evicted) state rebuilds its
            # engine as a side effect, and the evicted -> host
            # degradation contract must not be masked by that rebuild
            engine = getattr(state, "_root_engine", None)
            planes_warm = (
                engine is not None and getattr(engine, "top", None) is not None
            )
            # key the bundle on the PROVED state's own root, never the
            # head root read at call time: if the head advances between
            # the handler resolving its state and this call, a head key
            # would file the old state's proofs under the NEW head —
            # right after _on_head invalidated that key — and serve
            # them stale until the next head event
            state_root = state.hash_tree_root()
            key = (
                _hex(state_root),
                tuple(".".join(str(s) for s in p) for p in paths),
            )
            item = self.cache.get("state_proof", key)
            if item is not None:
                self.sources["bundle"] += 1
                return item
            proofs = (
                state_multiproof(state, paths, expected_root=state_root)
                if planes_warm
                else None
            )
        if proofs is not None:
            self.sources["plane"] += 1
        else:
            # host path raises on a bad path — the plane reader returns
            # None for those, so errors surface exactly once, here
            proofs = container_branches(
                state._container(), state.to_value(), paths
            )
            self.sources["host"] += 1
        item = self._render_proofs(paths, proofs, state_root)
        self.cache.put("state_proof", key, item)
        return item

    @staticmethod
    def _render_proofs(paths, proofs, state_root: bytes) -> dict:
        rendered = [
            {
                "path": ".".join(str(s) for s in path),
                "leaf": _hex(leaf),
                "branch": [_hex(b) for b in branch],
                "depth": depth,
                "index": index,
            }
            for path, (leaf, branch, depth, index) in zip(paths, proofs)
        ]
        if len(proofs) == 1:
            one = dict(rendered[0])
            del one["path"]
            one["state_root"] = _hex(state_root)
            return one
        packed = pack_multiproof(proofs)
        return {
            "state_root": _hex(state_root),
            "proofs": rendered,
            "multiproof": {
                "leaves": [
                    {"gindex": str(g), "node": _hex(n)}
                    for g, n in packed["leaves"].items()
                ],
                "helpers": [
                    {"gindex": str(g), "node": _hex(n)}
                    for g, n in packed["helpers"]
                ],
            },
        }

    def _lease(self, root_hex: str):
        gov = self.governor
        if gov is None:
            gov = getattr(self.chain, "memory_governor", None)
        if gov is None or not hasattr(gov, "lease") or not root_hex:
            return nullcontext()
        return gov.lease(("state", root_hex))

    # -- period rollover batch generation ----------------------------------

    def on_slot(self, slot: int) -> None:
        """At a sync-period rollover, pre-render the trailing periods'
        best updates into the bundle cache so the first horde request
        after the boundary is a bundle hit, not a render stampede."""
        period = sync_period(int(slot))
        if period == self._last_period:
            return
        first_tick = self._last_period is None
        self._last_period = period
        if first_tick or self.lc is None:
            return
        warmed = 0
        for p in range(max(0, period - WARM_PERIODS), period):
            if self.cache.peek("lc_update", p) is not None:
                continue
            upd = self.lc.get_update(p)
            if upd is None:
                continue
            self.cache.put("lc_update", p, self._update_item(upd))
            warmed += 1
        if warmed:
            self.batch_generated += warmed
            self.log.info(
                "light-client bundles pre-rendered",
                period=period,
                warmed=warmed,
            )

    # -- observability -----------------------------------------------------

    def status(self) -> dict:
        total = sum(self.sources.values())
        return {
            "requests": total,
            "sources": dict(self.sources),
            "batch_generated": self.batch_generated,
            "cache": self.cache.stats(),
        }
