"""O(log n) Merkle proofs read straight off the warm hash forest.

The host proof producer (`ssz/core.py::container_branch`) re-merkleizes
every top-level field root per request — O(state size), dominated by
the validator registry.  But after PR 16 the state-root engine already
holds every internal node of the hot state: the top-level field tree
(`StateRootEngine.top`), one `ChunkTree` per big packed field, and the
per-validator root plane.  A proof is then a pure READ: one sibling per
level, zero hashing.

Seam convention (the established None-falls-through contract): every
entry point returns None whenever the planes cannot serve the request —
engine absent (spilled/evicted state), planes released, engine stale
(`LODESTAR_TPU_HTR=full` bypasses it), or a path shape the planes do
not cover.  Callers MUST fall through to `container_branch` /
`container_branches`; the host path always completes the request, so a
cold plane can never produce a wrong or missing proof.

What the planes cover:
  - any top-level field leaf (one `top.branch()` read),
  - a trailing numeric chunk index inside a ChunkTree-backed field
    (balances, validators, block_roots, ... — `cell.tree.branch()`
    plus the mix-in length chunk for lists),
  - nested paths below memo-backed container fields
    (finalized_checkpoint.root, latest_block_header.state_root, ...)
    via host recursion over the SMALL sub-container only — O(sub
    fields), never O(state).

The descending-multiproof packer dedupes branch nodes shared across
leaves (sibling overlap grows with path locality), and
`verify_multiproof` folds the packed form back to the root.
"""

from __future__ import annotations

from typing import Dict, List as PyList, Optional, Sequence, Tuple

from ..ssz.core import (
    Container,
    List as SszList,
    Vector,
    _is_leaf_index,
    container_branch,
    leaf_chunk_branch,
)
from ..ssz.hasher import digest

# (leaf, branch, depth, index) — container_branch's shape, verbatim
Proof = Tuple[bytes, PyList[bytes], int, int]


def _warm_engine(state):
    """(engine, state_root) when the resident engine can serve plane
    reads for `state`, else None.

    The one hash_tree_root() call here is the warm incremental sync —
    O(dirty chunks), which is what makes the subsequent branch reads
    current.  It is only issued when the engine AND its top tree are
    already resident: an engineless (spilled/evicted) state returns
    None immediately rather than paying a full cold rebuild that would
    fight the governor's eviction decision.  The root equality check
    covers `LODESTAR_TPU_HTR=full` (which bypasses the engine and can
    leave it stale) and any engine fault that dropped it mid-call."""
    engine = getattr(state, "_root_engine", None)
    if engine is None or getattr(engine, "top", None) is None:
        return None
    try:
        root = state.hash_tree_root()
    except Exception:
        return None
    engine = getattr(state, "_root_engine", None)
    if engine is None or engine.top is None or engine.top.count == 0:
        return None
    if engine.top.root != root:
        return None
    return engine, root


def state_proof(
    state, path: Sequence, expected_root: Optional[bytes] = None
) -> Optional[Proof]:
    """Proof of `path` under `state`'s root, or None (fall through to
    container_branch).  Bit-identical to the host path when served."""
    snap = _warm_engine(state)
    if snap is None:
        return None
    engine, root = snap
    if expected_root is not None and bytes(expected_root) != root:
        return None
    return _proof_from_engine(engine, state, list(path))


def state_multiproof(
    state,
    paths: Sequence[Sequence],
    expected_root: Optional[bytes] = None,
) -> Optional[PyList[Proof]]:
    """Proofs for every path in `paths` (ONE engine sync), or None when
    ANY path cannot be served from planes — all-or-nothing so the
    caller's host fallback (container_branches) keeps its one-pass
    economics instead of splitting per path."""
    snap = _warm_engine(state)
    if snap is None:
        return None
    engine, root = snap
    if expected_root is not None and bytes(expected_root) != root:
        return None
    out: PyList[Proof] = []
    for path in paths:
        proof = _proof_from_engine(engine, state, list(path))
        if proof is None:
            return None
        out.append(proof)
    return out


def _proof_from_engine(engine, state, path: list) -> Optional[Proof]:
    container = state._container()
    names = [fname for fname, _ in container.fields]
    if not path:
        return engine.top.root, [], 0, 0
    name = str(path[0])
    if name not in names:
        return None  # unknown field: the host path raises the caller's 400
    idx = names.index(name)
    top = engine.top
    here_branch = top.branch(idx)
    here_depth = top.depth
    if len(path) == 1:
        return top.leaf(idx), here_branch, here_depth, idx
    sub = _sub_proof(engine, state, name, container.fields[idx][1], path[1:])
    if sub is None:
        return None
    leaf, sub_branch, sub_depth, sub_index = sub
    return (
        leaf,
        sub_branch + here_branch,
        sub_depth + here_depth,
        idx * (1 << sub_depth) + sub_index,
    )


def _sub_proof(engine, state, fname: str, ftype, rest: list):
    """Proof inside one field's subtree, anchored at the field root."""
    if len(rest) == 1 and _is_leaf_index(rest[0]):
        chunk_index = int(rest[0])
        cell = engine.leaf_cell(fname)
        if cell is not None:
            # ChunkTree-backed field: pure plane reads
            tree, length, mixin = cell
            if not (0 <= chunk_index < (1 << tree.depth)):
                return None
            branch = tree.branch(chunk_index)
            depth = tree.depth
            leaf = tree.leaf(chunk_index)
            if mixin:
                branch = branch + [length.to_bytes(32, "little")]
                depth += 1
            return leaf, branch, depth, chunk_index
        if isinstance(ftype, (SszList, Vector)):
            # memo-backed list/vector (historical_roots, eth1 votes):
            # small host oracle over the live value
            try:
                return leaf_chunk_branch(
                    ftype, getattr(state, fname), chunk_index
                )
            except (IndexError, TypeError, ValueError):
                return None
        return None
    if engine.leaf_cell(fname) is not None:
        return None  # deep paths into packed cells: host path owns these
    if not isinstance(ftype, Container):
        return None
    # memo-backed sub-container: its cached field chunk is current as of
    # the snapshot's hash_tree_root, and the sub-container is SMALL
    # (Checkpoint, BeaconBlockHeader, Eth1Data) — recursing the host
    # producer over it costs O(sub fields), never O(state)
    try:
        return container_branch(
            ftype, getattr(state, fname), [str(p) for p in rest]
        )
    except (KeyError, IndexError, TypeError, ValueError):
        return None


# -- descending multiproof ---------------------------------------------------


def pack_multiproof(proofs: Sequence[Proof]) -> dict:
    """Pack proofs that share ONE anchoring root into the descending
    multiproof form: every distinct tree node appears ONCE, helper
    nodes are exactly the siblings no proof path computes, and both
    sequences are sorted by DESCENDING generalized index (the order a
    verifier folds bottom-up in a single pass).

    Returns {"leaves": {gindex: node}, "helpers": [(gindex, node)...]}.
    Shared branch nodes across leaves are deduped — the whole point of
    multiproofs: k proofs of depth d cost well under k*d nodes when
    paths share ancestry."""
    leaves: Dict[int, bytes] = {}
    nodes: Dict[int, bytes] = {}
    for leaf, branch, depth, index in proofs:
        g = (1 << depth) + index
        leaves[g] = leaf
        for i, sibling in enumerate(branch):
            nodes[(g >> i) ^ 1] = sibling
    on_path = set()
    for g in leaves:
        while g >= 1:
            on_path.add(g)
            g >>= 1
    helper_g = sorted((g for g in nodes if g not in on_path), reverse=True)
    return {
        "leaves": {g: leaves[g] for g in sorted(leaves, reverse=True)},
        "helpers": [(g, nodes[g]) for g in helper_g],
    }


def verify_multiproof(leaves, helpers, root: bytes) -> bool:
    """Fold a packed multiproof bottom-up (descending gindex order) and
    compare against `root`.  False on a mismatch OR a malformed node
    set — never raises on malformed input.

    Fails CLOSED against helper placement attacks: a helper whose
    gindex sits ON any leaf's path to the root (including at a leaf's
    own gindex) would shadow the honest recomputation and let a forged
    leaf verify, so any such helper — or a duplicate, or one whose
    sibling is off every leaf path (it could never be consumed) — is
    rejected outright.  Every on-path internal node is recomputed from
    its two children, so each leaf is consumed by digests on an
    unbroken path to gindex 1; when one requested leaf is an ancestor
    of another, its claimed value must MATCH the value recomputed from
    below."""
    try:
        leaf_map = {int(g): bytes(n) for g, n in dict(leaves).items()}
        helper_list = [(int(g), bytes(n)) for g, n in helpers]
        want = bytes(root)
    except (TypeError, ValueError):
        return False
    if not leaf_map or any(g < 1 for g in leaf_map):
        return False
    on_path = set()
    for g in leaf_map:
        while g >= 1:
            on_path.add(g)
            g >>= 1
    nodes: Dict[int, bytes] = dict(leaf_map)
    for g, node in helper_list:
        if g in nodes or g in on_path or (g ^ 1) not in on_path:
            return False
        nodes[g] = node
    # descending gindex order: children always exceed their parent, so
    # both child values are final before the parent folds
    for parent in sorted({g >> 1 for g in on_path if g > 1}, reverse=True):
        left = nodes.get(2 * parent)
        right = nodes.get(2 * parent + 1)
        if left is None or right is None:
            return False
        node = digest(left + right)
        if parent in leaf_map and nodes[parent] != node:
            return False  # a claimed leaf that is another leaf's ancestor
        nodes[parent] = node
    return nodes.get(1) == want
