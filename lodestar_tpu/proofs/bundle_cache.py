"""Bounded LRU of per-checkpoint proof bundles.

Serving a light-client horde means answering the SAME few questions
thousands of times per head: the sync-committee update for a period,
the latest finality/optimistic proof, a handful of state-field proofs.
The bundle cache memoizes the fully rendered answers, keyed
(kind, key) — ("lc_update", period), ("finality", head), ("bootstrap",
block_root), ("state_proof", (head, paths)) — and is invalidated per
kind when the head moves or a better update lands.

Hygiene contract (tpulint cache-hygiene, which gates this package):
bounded by BOTH entry count and bytes, LRU-evicted at the bound,
invalidated on events, and DRAINABLE by the memory governor — under
squeeze `StateMemoryGovernor` empties this cache (cheap to rebuild,
one request each) before any live state demotes (expensive to replay).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional, Tuple


def estimate_bytes(payload, _depth: int = 0) -> int:
    """Rough deep byte estimate of a cached payload — the governor's
    accounting currency.  Exact footprints do not matter; RELATIVE
    drain pressure and a sane total do."""
    if _depth > 8:
        return 64
    if payload is None or isinstance(payload, (bool, int, float)):
        return 8
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return len(payload) + 32
    if isinstance(payload, str):
        return len(payload) + 48
    if isinstance(payload, dict):
        return 64 + sum(
            estimate_bytes(k, _depth + 1) + estimate_bytes(v, _depth + 1)
            for k, v in payload.items()
        )
    if isinstance(payload, (list, tuple, set, frozenset)):
        return 56 + sum(estimate_bytes(v, _depth + 1) for v in payload)
    d = getattr(payload, "__dict__", None)
    if d is not None:
        return 64 + estimate_bytes(d, _depth + 1)
    return 64


class ProofBundleCache:
    """LRU keyed (kind, key), bounded by entries AND bytes, thread-safe
    (the API server and the chain's event callbacks both touch it)."""

    def __init__(self, max_entries: int = 512, max_bytes: int = 16 << 20):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._map: "OrderedDict[Tuple[str, Any], Tuple[Any, int]]" = (
            OrderedDict()
        )
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.insertions = 0
        self.evicted = 0  # LRU-bound evictions
        self.invalidated = 0  # event-driven invalidations
        self.drained = 0  # governor-driven drops

    def get(self, kind: str, key) -> Optional[Any]:
        with self._lock:
            entry = self._map.get((kind, key))
            if entry is None:
                self.misses += 1
                return None
            self._map.move_to_end((kind, key))
            self.hits += 1
            return entry[0]

    def peek(self, kind: str, key) -> Optional[Any]:
        """get() without touching LRU order or hit/miss stats — the
        period-rollover warmer's presence check."""
        with self._lock:
            entry = self._map.get((kind, key))
            return None if entry is None else entry[0]

    def put(self, kind: str, key, payload, nbytes: Optional[int] = None):
        size = int(nbytes) if nbytes is not None else estimate_bytes(payload)
        with self._lock:
            old = self._map.pop((kind, key), None)
            if old is not None:
                self._bytes -= old[1]
            self._map[(kind, key)] = (payload, size)
            self._bytes += size
            self.insertions += 1
            while self._map and (
                len(self._map) > self.max_entries
                or self._bytes > self.max_bytes
            ):
                _, (_, freed) = self._map.popitem(last=False)
                self._bytes -= freed
                self.evicted += 1

    def invalidate(self, kind: Optional[str] = None, key=None) -> int:
        """Drop one entry (kind+key), every entry of `kind`, or
        everything (no arguments).  Returns entries dropped."""
        with self._lock:
            if kind is None:
                n = len(self._map)
                self._map.clear()
                self._bytes = 0
            elif key is not None:
                entry = self._map.pop((kind, key), None)
                n = 0 if entry is None else 1
                if entry is not None:
                    self._bytes -= entry[1]
            else:
                doomed = [k for k in self._map if k[0] == kind]
                for k in doomed:
                    self._bytes -= self._map.pop(k)[1]
                n = len(doomed)
            self.invalidated += n
            return n

    # -- governor seam (StateMemoryGovernor.register_aux) -------------------

    def resident_bytes(self) -> int:
        return self._bytes

    def drain(self, target_bytes: int = 0) -> int:
        """Evict LRU-first until resident bytes <= target — the squeeze
        hook: bundles are cheap to rebuild (one request each), so the
        cache empties before any live state demotes.  Returns bytes
        freed."""
        floor = max(0, int(target_bytes))
        freed = 0
        with self._lock:
            while self._map and self._bytes > floor:
                _, (_, size) = self._map.popitem(last=False)
                self._bytes -= size
                freed += size
                self.drained += 1
        return freed

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "entries": len(self._map),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else None,
                "insertions": self.insertions,
                "evicted": self.evicted,
                "invalidated": self.invalidated,
                "drained": self.drained,
            }
