"""Prover — verify execution-layer proofs against trusted roots.

Mirror of the reference's packages/prover (verified execution API: the
light-client-derived executionStateRoot anchors eth_getProof /
eth_getCode verification).  keccak256 and the MPT walk are implemented
from their specifications (no pycryptodome/@ethereumjs in this image).
"""

from .keccak import keccak256  # noqa: F401
from .mpt import (  # noqa: F401
    ProofError,
    rlp_decode,
    rlp_encode,
    verify_account_proof,
    verify_code,
    verify_proof,
    verify_storage_proof,
)
