"""RLP decoding + Merkle-Patricia-Trie proof verification.

Reference: packages/prover/src/ (verifyAccount/verifyCode against
eth_getProof responses) — the proof engine the reference delegates to
@ethereumjs/trie; implemented here from the MPT specification: RLP
items, hex-prefix encoded paths, branch/extension/leaf node walk
hashed with keccak256.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

from .keccak import keccak256

RlpItem = Union[bytes, List["RlpItem"]]


class ProofError(ValueError):
    pass


# -- RLP --------------------------------------------------------------------


def rlp_decode(data: bytes) -> RlpItem:
    item, rest = _rlp_decode_item(data)
    if rest:
        raise ProofError("trailing RLP bytes")
    return item


def _rlp_decode_item(data: bytes) -> Tuple[RlpItem, bytes]:
    if not data:
        raise ProofError("empty RLP")
    prefix = data[0]
    if prefix < 0x80:
        return bytes([prefix]), data[1:]
    if prefix < 0xB8:  # short string
        length = prefix - 0x80
        return data[1 : 1 + length], data[1 + length :]
    if prefix < 0xC0:  # long string
        len_len = prefix - 0xB7
        length = int.from_bytes(data[1 : 1 + len_len], "big")
        start = 1 + len_len
        return data[start : start + length], data[start + length :]
    if prefix < 0xF8:  # short list
        length = prefix - 0xC0
        return _rlp_decode_list(data[1 : 1 + length]), data[1 + length :]
    len_len = prefix - 0xF7
    length = int.from_bytes(data[1 : 1 + len_len], "big")
    start = 1 + len_len
    return (
        _rlp_decode_list(data[start : start + length]),
        data[start + length :],
    )


def _rlp_decode_list(data: bytes) -> List[RlpItem]:
    out = []
    while data:
        item, data = _rlp_decode_item(data)
        out.append(item)
    return out


def rlp_encode(item: RlpItem) -> bytes:
    if isinstance(item, (bytes, bytearray)):
        b = bytes(item)
        if len(b) == 1 and b[0] < 0x80:
            return b
        return _rlp_len(len(b), 0x80) + b
    body = b"".join(rlp_encode(x) for x in item)
    return _rlp_len(len(body), 0xC0) + body


def _rlp_len(n: int, offset: int) -> bytes:
    if n < 56:
        return bytes([offset + n])
    nb = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([offset + 55 + len(nb)]) + nb


# -- MPT proof walk ---------------------------------------------------------


def _nibbles(key: bytes) -> List[int]:
    out = []
    for b in key:
        out.append(b >> 4)
        out.append(b & 0x0F)
    return out


def _decode_hp(path: bytes) -> Tuple[List[int], bool]:
    """Hex-prefix: returns (nibbles, is_leaf)."""
    if not path:
        raise ProofError("empty hex-prefix path")
    flag = path[0] >> 4
    is_leaf = bool(flag & 2)
    nibs = []
    if flag & 1:  # odd length
        nibs.append(path[0] & 0x0F)
    for b in path[1:]:
        nibs.append(b >> 4)
        nibs.append(b & 0x0F)
    return nibs, is_leaf


def verify_proof(
    root: bytes, key: bytes, proof: Sequence[bytes]
) -> Optional[bytes]:
    """Walk `proof` (ordered RLP node list) from `root` along
    keccak(key)'s nibbles; returns the value, None for a proven
    absence, or raises ProofError on an invalid proof."""
    nodes = {keccak256(p): p for p in proof}
    nibbles = _nibbles(key)
    expected = root
    pos = 0
    while True:
        node_rlp = nodes.get(expected)
        if node_rlp is None:
            raise ProofError(f"missing proof node {expected.hex()[:16]}")
        node = rlp_decode(node_rlp)
        if not isinstance(node, list):
            raise ProofError("trie node is not a list")
        if len(node) == 17:  # branch
            if pos == len(nibbles):
                value = node[16]
                return bytes(value) if value else None
            child = node[nibbles[pos]]
            pos += 1
            if child == b"":
                return None  # proven absent
            if isinstance(child, list):  # embedded short node
                node_rlp_embedded = rlp_encode(child)
                nodes[keccak256(node_rlp_embedded)] = node_rlp_embedded
                expected = keccak256(node_rlp_embedded)
                continue
            if len(child) != 32:
                raise ProofError("branch child is not a hash")
            expected = bytes(child)
        elif len(node) == 2:  # extension or leaf
            path_nibs, is_leaf = _decode_hp(bytes(node[0]))
            if nibbles[pos : pos + len(path_nibs)] != path_nibs:
                return None  # path diverges: proven absent
            pos += len(path_nibs)
            if is_leaf:
                if pos != len(nibbles):
                    return None
                return bytes(node[1])
            nxt = node[1]
            if isinstance(nxt, list):
                emb = rlp_encode(nxt)
                nodes[keccak256(emb)] = emb
                expected = keccak256(emb)
                continue
            if len(nxt) != 32:
                raise ProofError("extension target is not a hash")
            expected = bytes(nxt)
        else:
            raise ProofError(f"bad trie node arity {len(node)}")


# -- the prover surface (reference: prover/src/verified_requests) -----------


def verify_account_proof(
    state_root: bytes, address: bytes, proof: Sequence[bytes]
) -> Optional[dict]:
    """eth_getProof account leg: returns {nonce, balance, storage_hash,
    code_hash} or None if the account is proven absent."""
    value = verify_proof(state_root, keccak256(address), proof)
    if value is None:
        return None
    fields = rlp_decode(value)
    if not isinstance(fields, list) or len(fields) != 4:
        raise ProofError("bad account RLP")
    nonce, balance, storage_hash, code_hash = fields
    return {
        "nonce": int.from_bytes(bytes(nonce), "big"),
        "balance": int.from_bytes(bytes(balance), "big"),
        "storage_hash": bytes(storage_hash),
        "code_hash": bytes(code_hash),
    }


def verify_storage_proof(
    storage_hash: bytes, slot: bytes, proof: Sequence[bytes]
) -> int:
    """eth_getProof storage leg: the slot's value (0 if absent)."""
    value = verify_proof(storage_hash, keccak256(slot), proof)
    if value is None:
        return 0
    inner = rlp_decode(value)
    return int.from_bytes(bytes(inner), "big")


def verify_code(code: bytes, code_hash: bytes) -> bool:
    """eth_getCode against the proven account code hash."""
    return keccak256(code) == code_hash
