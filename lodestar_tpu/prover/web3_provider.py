"""Verified web3 provider — the prover's user-facing surface.

Mirror of the reference's createVerifiedExecutionProvider (reference:
packages/prover/src/web3_provider.ts + verified_requests/*.ts): a
JSON-RPC proxy that answers account-state queries ONLY after verifying
merkle proofs (eth_getProof) against an execution state root obtained
from a trusted source — in the full stack, the light-client-verified
execution payload header; here an injectable `header_source` so any
verified-header feed plugs in.

Verified methods (the `_VERIFIED` dispatch table): eth_getBalance,
eth_getTransactionCount, eth_getCode, eth_getStorageAt.  Everything
else is rejected in strict mode or passed through UNVERIFIED
(the reference logs-and-passes for unhandled methods too).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from .keccak import keccak256
from .mpt import (
    ProofError,
    verify_account_proof,
    verify_code,
    verify_storage_proof,
)

# transport: (method, params) -> result (python-typed JSON-RPC values)
Transport = Callable[[str, list], object]


class VerificationError(Exception):
    """The EL's answer failed proof verification — NEVER return such a
    value to the caller (a lying provider is the threat model)."""


@dataclass
class ExecutionHeader:
    """The trusted anchor for one block: the verified state root."""

    block_number: int
    block_hash: bytes
    state_root: bytes


def _hx(data: bytes) -> str:
    return "0x" + bytes(data).hex()


def _unhex(v: str) -> bytes:
    s = v[2:] if v.startswith("0x") else v
    if len(s) % 2:
        s = "0" + s
    return bytes.fromhex(s)


def _unhex_int(v) -> int:
    if isinstance(v, int):
        return v
    return int(v, 16)


class VerifiedExecutionProvider:
    """`request(method, params)` with proof-verified account state.

    `header_source(block_tag) -> ExecutionHeader` supplies the verified
    state root for a block tag ("latest" or hex number) — the light
    client's finalized/optimistic execution headers in production.
    """

    def __init__(
        self,
        transport: Transport,
        header_source: Callable[[str], Optional[ExecutionHeader]],
        strict: bool = True,
    ):
        self.transport = transport
        self.header_source = header_source
        self.strict = strict

    # -- plumbing ----------------------------------------------------------

    def _header(self, block_tag) -> ExecutionHeader:
        header = self.header_source(block_tag)
        if header is None:
            raise VerificationError(
                f"no verified execution header for block {block_tag!r}"
            )
        return header

    def _get_proof(
        self, address: str, slots: Sequence[str], header: ExecutionHeader
    ) -> dict:
        return self.transport(
            "eth_getProof",
            [address, list(slots), hex(header.block_number)],
        )

    def _verified_account(
        self, address: str, header: ExecutionHeader, slots: Sequence[str] = ()
    ) -> tuple:
        """(account|None, proof_response) with the account leg verified
        against the trusted state root.  A structurally malformed
        response is the SAME threat as a failed proof — everything the
        EL sent is untrusted input."""
        resp = self._get_proof(address, slots, header)
        try:
            proof = [_unhex(p) for p in resp["accountProof"]]
        except (KeyError, TypeError, ValueError) as e:
            raise VerificationError(f"malformed eth_getProof response: {e}")
        try:
            account = verify_account_proof(
                header.state_root, _unhex(address), proof
            )
        except ProofError as e:
            raise VerificationError(f"account proof invalid: {e}")
        return account, resp

    # -- the verified methods (reference: verified_requests/*.ts) ----------

    def get_balance(self, address: str, block_tag="latest") -> int:
        header = self._header(block_tag)
        account, _ = self._verified_account(address, header)
        return 0 if account is None else account["balance"]

    def get_transaction_count(self, address: str, block_tag="latest") -> int:
        header = self._header(block_tag)
        account, _ = self._verified_account(address, header)
        return 0 if account is None else account["nonce"]

    def get_code(self, address: str, block_tag="latest") -> bytes:
        header = self._header(block_tag)
        account, _ = self._verified_account(address, header)
        code = _unhex(
            self.transport("eth_getCode", [address, hex(header.block_number)])
        )
        if account is None:
            if code:
                raise VerificationError("code returned for absent account")
            return b""
        if not verify_code(code, account["code_hash"]):
            raise VerificationError("code does not hash to proven code_hash")
        return code

    def get_storage_at(
        self, address: str, slot: str, block_tag="latest"
    ) -> int:
        header = self._header(block_tag)
        account, resp = self._verified_account(address, header, [slot])
        if account is None:
            return 0
        try:
            storage = resp["storageProof"][0]
            storage_proof = [_unhex(p) for p in storage["proof"]]
        except (KeyError, IndexError, TypeError, ValueError) as e:
            raise VerificationError(f"malformed storage proof response: {e}")
        try:
            value = verify_storage_proof(
                account["storage_hash"],
                _unhex(slot).rjust(32, b"\x00"),
                storage_proof,
            )
        except ProofError as e:
            raise VerificationError(f"storage proof invalid: {e}")
        claimed = _unhex_int(storage["value"])
        if claimed != value:
            raise VerificationError(
                f"EL claimed storage {claimed:#x} != proven {value:#x}"
            )
        return value

    # -- the JSON-RPC facade ----------------------------------------------

    def request(self, method: str, params: list):
        """JSON-RPC entry: verified methods verify; others pass through
        (strict mode rejects them instead)."""
        handler = self._VERIFIED.get(method)
        if handler is not None:
            return handler(self, *params)
        if self.strict:
            raise VerificationError(
                f"{method} cannot be verified (strict mode)"
            )
        return self.transport(method, params)


# method -> verified handler: request() dispatches from THIS table, so
# editing it is editing the dispatch (defined after the class body to
# reference the bound methods)
VerifiedExecutionProvider._VERIFIED = {
    "eth_getBalance": lambda self, *a: hex(self.get_balance(*a)),
    "eth_getTransactionCount": lambda self, *a: hex(
        self.get_transaction_count(*a)
    ),
    "eth_getCode": lambda self, *a: _hx(self.get_code(*a)),
    "eth_getStorageAt": lambda self, *a: "0x"
    + self.get_storage_at(*a).to_bytes(32, "big").hex(),
}
