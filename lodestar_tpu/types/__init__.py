"""Per-fork SSZ type definitions — the types layer.

Mirror of the reference's `@lodestar/types` (reference:
packages/types/src/phase0/sszTypes.ts, types/src/altair/sszTypes.ts,
types/src/sszTypes.ts for the per-fork `ssz.*` namespaces).  The subset
defined here is everything on the signature path: attestations, blocks
(phase0 + altair), slashings, exits, sync aggregates — enough to extract
and verify every block/gossip signature the reference's
getBlockSignatureSets covers (state-transition/src/signatureSets/).
"""

from types import SimpleNamespace

from .. import params
from ..ssz import (
    Bitlist,
    Bitvector,
    Boolean,
    ByteList,
    ByteVector,
    Bytes4,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    Vector,
    uint64,
    uint256,
)

P = params.ACTIVE_PRESET

# -- primitives (reference: types/src/primitive/sszTypes.ts) ----------------

Slot = uint64
Epoch = uint64
ValidatorIndex = uint64
CommitteeIndex = uint64
Gwei = uint64
Root = Bytes32
BLSPubkey = Bytes48
BLSSignature = Bytes96
Version = Bytes4

# -- phase0 (reference: types/src/phase0/sszTypes.ts) -----------------------

Checkpoint = Container(
    (("epoch", Epoch), ("root", Root)),
    name="Checkpoint",
)

Fork = Container(
    (
        ("previous_version", Version),
        ("current_version", Version),
        ("epoch", Epoch),
    ),
    name="Fork",
)

Validator = Container(
    (
        ("pubkey", BLSPubkey),
        ("withdrawal_credentials", Bytes32),
        ("effective_balance", Gwei),
        ("slashed", Boolean),
        ("activation_eligibility_epoch", Epoch),
        ("activation_epoch", Epoch),
        ("exit_epoch", Epoch),
        ("withdrawable_epoch", Epoch),
    ),
    name="Validator",
)

AttestationData = Container(
    (
        ("slot", Slot),
        ("index", CommitteeIndex),
        ("beacon_block_root", Root),
        ("source", Checkpoint),
        ("target", Checkpoint),
    ),
    name="AttestationData",
)

Attestation = Container(
    (
        ("aggregation_bits", Bitlist(P.MAX_VALIDATORS_PER_COMMITTEE)),
        ("data", AttestationData),
        ("signature", BLSSignature),
    ),
    name="Attestation",
)

IndexedAttestation = Container(
    (
        ("attesting_indices", List(ValidatorIndex, P.MAX_VALIDATORS_PER_COMMITTEE)),
        ("data", AttestationData),
        ("signature", BLSSignature),
    ),
    name="IndexedAttestation",
)

PendingAttestation = Container(
    (
        ("aggregation_bits", Bitlist(P.MAX_VALIDATORS_PER_COMMITTEE)),
        ("data", AttestationData),
        ("inclusion_delay", Slot),
        ("proposer_index", ValidatorIndex),
    ),
    name="PendingAttestation",
)

AggregateAndProof = Container(
    (
        ("aggregator_index", ValidatorIndex),
        ("aggregate", Attestation),
        ("selection_proof", BLSSignature),
    ),
    name="AggregateAndProof",
)

SignedAggregateAndProof = Container(
    (
        ("message", AggregateAndProof),
        ("signature", BLSSignature),
    ),
    name="SignedAggregateAndProof",
)

BeaconBlockHeader = Container(
    (
        ("slot", Slot),
        ("proposer_index", ValidatorIndex),
        ("parent_root", Root),
        ("state_root", Root),
        ("body_root", Root),
    ),
    name="BeaconBlockHeader",
)

SignedBeaconBlockHeader = Container(
    (
        ("message", BeaconBlockHeader),
        ("signature", BLSSignature),
    ),
    name="SignedBeaconBlockHeader",
)

ProposerSlashing = Container(
    (
        ("signed_header_1", SignedBeaconBlockHeader),
        ("signed_header_2", SignedBeaconBlockHeader),
    ),
    name="ProposerSlashing",
)

AttesterSlashing = Container(
    (
        ("attestation_1", IndexedAttestation),
        ("attestation_2", IndexedAttestation),
    ),
    name="AttesterSlashing",
)

DepositDataType = Container(
    (
        ("pubkey", BLSPubkey),
        ("withdrawal_credentials", Bytes32),
        ("amount", Gwei),
        ("signature", BLSSignature),
    ),
    name="DepositData",
)

DepositMessage = Container(
    (
        ("pubkey", BLSPubkey),
        ("withdrawal_credentials", Bytes32),
        ("amount", Gwei),
    ),
    name="DepositMessage",
)

Deposit = Container(
    (
        ("proof", Vector(Bytes32, params.DEPOSIT_CONTRACT_TREE_DEPTH + 1)),
        ("data", DepositDataType),
    ),
    name="Deposit",
)

VoluntaryExit = Container(
    (("epoch", Epoch), ("validator_index", ValidatorIndex)),
    name="VoluntaryExit",
)

SignedVoluntaryExit = Container(
    (("message", VoluntaryExit), ("signature", BLSSignature)),
    name="SignedVoluntaryExit",
)

HistoricalBatch = Container(
    (
        ("block_roots", Vector(Root, P.SLOTS_PER_HISTORICAL_ROOT)),
        ("state_roots", Vector(Root, P.SLOTS_PER_HISTORICAL_ROOT)),
    ),
    name="HistoricalBatch",
)

Eth1Data = Container(
    (
        ("deposit_root", Root),
        ("deposit_count", uint64),
        ("block_hash", Bytes32),
    ),
    name="Eth1Data",
)

_phase0_body_fields = (
    ("randao_reveal", BLSSignature),
    ("eth1_data", Eth1Data),
    ("graffiti", Bytes32),
    ("proposer_slashings", List(ProposerSlashing, P.MAX_PROPOSER_SLASHINGS)),
    ("attester_slashings", List(AttesterSlashing, P.MAX_ATTESTER_SLASHINGS)),
    ("attestations", List(Attestation, P.MAX_ATTESTATIONS)),
    ("deposits", List(Deposit, P.MAX_DEPOSITS)),
    ("voluntary_exits", List(SignedVoluntaryExit, P.MAX_VOLUNTARY_EXITS)),
)

BeaconBlockBody = Container(_phase0_body_fields, name="BeaconBlockBody")


def _block_types(body_type, suffix=""):
    block = Container(
        (
            ("slot", Slot),
            ("proposer_index", ValidatorIndex),
            ("parent_root", Root),
            ("state_root", Root),
            ("body", body_type),
        ),
        name=f"BeaconBlock{suffix}",
    )
    signed = Container(
        (("message", block), ("signature", BLSSignature)),
        name=f"SignedBeaconBlock{suffix}",
    )
    return block, signed


BeaconBlock, SignedBeaconBlock = _block_types(BeaconBlockBody)

# -- altair (reference: types/src/altair/sszTypes.ts) -----------------------

SyncAggregate = Container(
    (
        ("sync_committee_bits", Bitvector(P.SYNC_COMMITTEE_SIZE)),
        ("sync_committee_signature", BLSSignature),
    ),
    name="SyncAggregate",
)

SyncCommittee = Container(
    (
        ("pubkeys", Vector(BLSPubkey, P.SYNC_COMMITTEE_SIZE)),
        ("aggregate_pubkey", BLSPubkey),
    ),
    name="SyncCommittee",
)

SyncCommitteeMessage = Container(
    (
        ("slot", Slot),
        ("beacon_block_root", Root),
        ("validator_index", ValidatorIndex),
        ("signature", BLSSignature),
    ),
    name="SyncCommitteeMessage",
)

SyncCommitteeContribution = Container(
    (
        ("slot", Slot),
        ("beacon_block_root", Root),
        ("subcommittee_index", uint64),
        (
            "aggregation_bits",
            Bitvector(P.SYNC_COMMITTEE_SIZE // params.SYNC_COMMITTEE_SUBNET_COUNT),
        ),
        ("signature", BLSSignature),
    ),
    name="SyncCommitteeContribution",
)

SyncAggregatorSelectionData = Container(
    (
        ("slot", Slot),
        ("subcommittee_index", uint64),
    ),
    name="SyncAggregatorSelectionData",
)

ContributionAndProof = Container(
    (
        ("aggregator_index", ValidatorIndex),
        ("contribution", SyncCommitteeContribution),
        ("selection_proof", BLSSignature),
    ),
    name="ContributionAndProof",
)

SignedContributionAndProof = Container(
    (
        ("message", ContributionAndProof),
        ("signature", BLSSignature),
    ),
    name="SignedContributionAndProof",
)

BeaconBlockBodyAltair = Container(
    _phase0_body_fields + (("sync_aggregate", SyncAggregate),),
    name="BeaconBlockBodyAltair",
)

BeaconBlockAltair, SignedBeaconBlockAltair = _block_types(
    BeaconBlockBodyAltair, "Altair"
)

# BLSToExecutionChange (capella)
BLSToExecutionChange = Container(
    (
        ("validator_index", ValidatorIndex),
        ("from_bls_pubkey", BLSPubkey),
        ("to_execution_address", ByteList(20)),
    ),
    name="BLSToExecutionChange",
)

SignedBLSToExecutionChange = Container(
    (
        ("message", BLSToExecutionChange),
        ("signature", BLSSignature),
    ),
    name="SignedBLSToExecutionChange",
)

# Per-fork namespaces (the reference's `ssz.phase0`, `ssz.altair`)
ssz = SimpleNamespace(
    phase0=SimpleNamespace(
        Checkpoint=Checkpoint,
        AttestationData=AttestationData,
        Attestation=Attestation,
        IndexedAttestation=IndexedAttestation,
        AggregateAndProof=AggregateAndProof,
        SignedAggregateAndProof=SignedAggregateAndProof,
        BeaconBlockHeader=BeaconBlockHeader,
        SignedBeaconBlockHeader=SignedBeaconBlockHeader,
        ProposerSlashing=ProposerSlashing,
        AttesterSlashing=AttesterSlashing,
        VoluntaryExit=VoluntaryExit,
        SignedVoluntaryExit=SignedVoluntaryExit,
        BeaconBlock=BeaconBlock,
        SignedBeaconBlock=SignedBeaconBlock,
        BeaconBlockBody=BeaconBlockBody,
        Eth1Data=Eth1Data,
    ),
    altair=SimpleNamespace(
        SyncAggregate=SyncAggregate,
        SyncCommittee=SyncCommittee,
        SyncCommitteeMessage=SyncCommitteeMessage,
        SyncCommitteeContribution=SyncCommitteeContribution,
        ContributionAndProof=ContributionAndProof,
        SignedContributionAndProof=SignedContributionAndProof,
        BeaconBlock=BeaconBlockAltair,
        SignedBeaconBlock=SignedBeaconBlockAltair,
        BeaconBlockBody=BeaconBlockBodyAltair,
    ),
    Epoch=Epoch,
    Slot=Slot,
    Root=Root,
)


# -- bellatrix execution payload (reference: types/src/bellatrix/
# sszTypes.ts; consumed by the execution engine layer — the bellatrix
# state transition lands on top of these) -----------------------------------

Transaction = ByteList(1_073_741_824)  # MAX_BYTES_PER_TRANSACTION
_payload_header_fields = (
    ("parent_hash", Bytes32),
    ("fee_recipient", ByteVector(20)),
    ("state_root", Bytes32),
    ("receipts_root", Bytes32),
    ("logs_bloom", ByteVector(256)),
    ("prev_randao", Bytes32),
    ("block_number", uint64),
    ("gas_limit", uint64),
    ("gas_used", uint64),
    ("timestamp", uint64),
    ("extra_data", ByteList(32)),
    ("base_fee_per_gas", uint256),
)

ExecutionPayload = Container(
    _payload_header_fields
    + (
        ("block_hash", Bytes32),
        ("transactions", List(Transaction, 1_048_576)),
    ),
    name="ExecutionPayload",
)

ExecutionPayloadHeader = Container(
    _payload_header_fields
    + (
        ("block_hash", Bytes32),
        ("transactions_root", Bytes32),
    ),
    name="ExecutionPayloadHeader",
)


BeaconBlockBodyBellatrix = Container(
    _phase0_body_fields
    + (
        ("sync_aggregate", SyncAggregate),
        ("execution_payload", ExecutionPayload),
    ),
    name="BeaconBlockBodyBellatrix",
)

BeaconBlockBellatrix, SignedBeaconBlockBellatrix = _block_types(
    BeaconBlockBodyBellatrix, "Bellatrix"
)


# -- capella / deneb type layer (reference: types/src/{capella,deneb}/
# sszTypes.ts) — the containers the later forks add; their STF variants
# are future work (withdrawals + blobs are off the BLS path, BASELINE) --

Withdrawal = Container(
    (
        ("index", uint64),
        ("validator_index", ValidatorIndex),
        ("address", ByteVector(20)),
        ("amount", Gwei),
    ),
    name="Withdrawal",
)

MAX_WITHDRAWALS_PER_PAYLOAD = P.MAX_WITHDRAWALS_PER_PAYLOAD

ExecutionPayloadCapella = Container(
    _payload_header_fields
    + (
        ("block_hash", Bytes32),
        ("transactions", List(Transaction, 1_048_576)),
        ("withdrawals", List(Withdrawal, MAX_WITHDRAWALS_PER_PAYLOAD)),
    ),
    name="ExecutionPayloadCapella",
)

ExecutionPayloadHeaderCapella = Container(
    _payload_header_fields
    + (
        ("block_hash", Bytes32),
        ("transactions_root", Bytes32),
        ("withdrawals_root", Bytes32),
    ),
    name="ExecutionPayloadHeaderCapella",
)

BeaconBlockBodyCapella = Container(
    _phase0_body_fields
    + (
        ("sync_aggregate", SyncAggregate),
        ("execution_payload", ExecutionPayloadCapella),
        (
            "bls_to_execution_changes",
            List(SignedBLSToExecutionChange, 16),
        ),
    ),
    name="BeaconBlockBodyCapella",
)

BeaconBlockCapella, SignedBeaconBlockCapella = _block_types(
    BeaconBlockBodyCapella, "Capella"
)

# capella replaces the historical-roots accumulator entries
# (reference: types/src/capella/sszTypes.ts HistoricalSummary)
HistoricalSummary = Container(
    (
        ("block_summary_root", Bytes32),
        ("state_summary_root", Bytes32),
    ),
    name="HistoricalSummary",
)

# deneb: blob KZG commitments ride the block body (KZG verification is
# out of scope per BASELINE; the type layer carries the commitments).
# Spec field order appends blob_gas_used/excess_blob_gas AFTER the
# capella fields (consensus-specs deneb/beacon-chain.md ExecutionPayload).
KZGCommitment = Bytes48
MAX_BLOB_COMMITMENTS_PER_BLOCK = 4096

ExecutionPayloadDeneb = Container(
    _payload_header_fields
    + (
        ("block_hash", Bytes32),
        ("transactions", List(Transaction, 1_048_576)),
        ("withdrawals", List(Withdrawal, MAX_WITHDRAWALS_PER_PAYLOAD)),
        ("blob_gas_used", uint64),
        ("excess_blob_gas", uint64),
    ),
    name="ExecutionPayloadDeneb",
)

ExecutionPayloadHeaderDeneb = Container(
    _payload_header_fields
    + (
        ("block_hash", Bytes32),
        ("transactions_root", Bytes32),
        ("withdrawals_root", Bytes32),
        ("blob_gas_used", uint64),
        ("excess_blob_gas", uint64),
    ),
    name="ExecutionPayloadHeaderDeneb",
)

BeaconBlockBodyDeneb = Container(
    _phase0_body_fields
    + (
        ("sync_aggregate", SyncAggregate),
        ("execution_payload", ExecutionPayloadDeneb),
        (
            "bls_to_execution_changes",
            List(SignedBLSToExecutionChange, 16),
        ),
        (
            "blob_kzg_commitments",
            List(KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK),
        ),
    ),
    name="BeaconBlockBodyDeneb",
)

BeaconBlockDeneb, SignedBeaconBlockDeneb = _block_types(
    BeaconBlockBodyDeneb, "Deneb"
)

# -- blinded blocks + builder wire types (MEV flow) -------------------------
# reference: types/src/{bellatrix,capella,deneb}/sszTypes.ts
# BlindedBeaconBlockBody (execution_payload -> executionPayloadHeader;
# hash_tree_root is IDENTICAL to the full block's because the payload
# header's root equals the payload's root) and builder/registration
# containers (bellatrix/sszTypes.ts ValidatorRegistrationV1, BuilderBid).

BlindedBeaconBlockBodyBellatrix = Container(
    _phase0_body_fields
    + (
        ("sync_aggregate", SyncAggregate),
        ("execution_payload_header", ExecutionPayloadHeader),
    ),
    name="BlindedBeaconBlockBodyBellatrix",
)
BlindedBeaconBlockBellatrix, SignedBlindedBeaconBlockBellatrix = (
    _block_types(BlindedBeaconBlockBodyBellatrix, "BlindedBellatrix")
)

BlindedBeaconBlockBodyCapella = Container(
    _phase0_body_fields
    + (
        ("sync_aggregate", SyncAggregate),
        ("execution_payload_header", ExecutionPayloadHeaderCapella),
        (
            "bls_to_execution_changes",
            List(SignedBLSToExecutionChange, 16),
        ),
    ),
    name="BlindedBeaconBlockBodyCapella",
)
BlindedBeaconBlockCapella, SignedBlindedBeaconBlockCapella = _block_types(
    BlindedBeaconBlockBodyCapella, "BlindedCapella"
)

BlindedBeaconBlockBodyDeneb = Container(
    _phase0_body_fields
    + (
        ("sync_aggregate", SyncAggregate),
        ("execution_payload_header", ExecutionPayloadHeaderDeneb),
        (
            "bls_to_execution_changes",
            List(SignedBLSToExecutionChange, 16),
        ),
        (
            "blob_kzg_commitments",
            List(KZGCommitment, MAX_BLOB_COMMITMENTS_PER_BLOCK),
        ),
    ),
    name="BlindedBeaconBlockBodyDeneb",
)
BlindedBeaconBlockDeneb, SignedBlindedBeaconBlockDeneb = _block_types(
    BlindedBeaconBlockBodyDeneb, "BlindedDeneb"
)

ValidatorRegistrationV1 = Container(
    (
        ("fee_recipient", ByteVector(20)),
        ("gas_limit", uint64),
        ("timestamp", uint64),
        ("pubkey", BLSPubkey),
    ),
    name="ValidatorRegistrationV1",
)

SignedValidatorRegistrationV1 = Container(
    (
        ("message", ValidatorRegistrationV1),
        ("signature", BLSSignature),
    ),
    name="SignedValidatorRegistrationV1",
)


def builder_bid_types(header_type):
    """BuilderBid/SignedBuilderBid over a fork's payload-header type
    (reference: builder bids are fork-parameterized)."""
    bid = Container(
        (
            ("header", header_type),
            ("value", uint256),
            ("pubkey", BLSPubkey),
        ),
        name="BuilderBid",
    )
    signed = Container(
        (("message", bid), ("signature", BLSSignature)),
        name="SignedBuilderBid",
    )
    return bid, signed


BuilderBidBellatrix, SignedBuilderBidBellatrix = builder_bid_types(
    ExecutionPayloadHeader
)
BuilderBidCapella, SignedBuilderBidCapella = builder_bid_types(
    ExecutionPayloadHeaderCapella
)
BuilderBidDeneb, SignedBuilderBidDeneb = builder_bid_types(
    ExecutionPayloadHeaderDeneb
)


# Per-fork namespaces for the later forks (reference: types/src/sszTypes.ts
# `ssz.bellatrix` / `ssz.capella` / `ssz.deneb`)
ssz.bellatrix = SimpleNamespace(
    ExecutionPayload=ExecutionPayload,
    ExecutionPayloadHeader=ExecutionPayloadHeader,
    BeaconBlock=BeaconBlockBellatrix,
    SignedBeaconBlock=SignedBeaconBlockBellatrix,
    BeaconBlockBody=BeaconBlockBodyBellatrix,
    BlindedBeaconBlock=BlindedBeaconBlockBellatrix,
    SignedBlindedBeaconBlock=SignedBlindedBeaconBlockBellatrix,
    ValidatorRegistrationV1=ValidatorRegistrationV1,
    SignedValidatorRegistrationV1=SignedValidatorRegistrationV1,
    BuilderBid=BuilderBidBellatrix,
    SignedBuilderBid=SignedBuilderBidBellatrix,
)
ssz.capella = SimpleNamespace(
    Withdrawal=Withdrawal,
    HistoricalSummary=HistoricalSummary,
    BLSToExecutionChange=BLSToExecutionChange,
    SignedBLSToExecutionChange=SignedBLSToExecutionChange,
    ExecutionPayload=ExecutionPayloadCapella,
    ExecutionPayloadHeader=ExecutionPayloadHeaderCapella,
    BeaconBlock=BeaconBlockCapella,
    SignedBeaconBlock=SignedBeaconBlockCapella,
    BeaconBlockBody=BeaconBlockBodyCapella,
    BlindedBeaconBlock=BlindedBeaconBlockCapella,
    SignedBlindedBeaconBlock=SignedBlindedBeaconBlockCapella,
    BuilderBid=BuilderBidCapella,
    SignedBuilderBid=SignedBuilderBidCapella,
)
ssz.deneb = SimpleNamespace(
    KZGCommitment=KZGCommitment,
    ExecutionPayload=ExecutionPayloadDeneb,
    ExecutionPayloadHeader=ExecutionPayloadHeaderDeneb,
    BeaconBlock=BeaconBlockDeneb,
    SignedBeaconBlock=SignedBeaconBlockDeneb,
    BeaconBlockBody=BeaconBlockBodyDeneb,
    BlindedBeaconBlock=BlindedBeaconBlockDeneb,
    SignedBlindedBeaconBlock=SignedBlindedBeaconBlockDeneb,
    BuilderBid=BuilderBidDeneb,
    SignedBuilderBid=SignedBuilderBidDeneb,
)

# deneb blob sidecars (reference carried the earlier
# BeaconBlockAndBlobsSidecar shape, types/src/deneb/sszTypes.ts; this is
# the per-blob sidecar that shipped on mainnet deneb)
Blob = ByteVector(32 * P.FIELD_ELEMENTS_PER_BLOB)
KZGProof = Bytes48
KZG_COMMITMENT_INCLUSION_PROOF_DEPTH = 17

BlobSidecar = Container(
    (
        ("index", uint64),
        ("blob", Blob),
        ("kzg_commitment", KZGCommitment),
        ("kzg_proof", KZGProof),
        ("signed_block_header", SignedBeaconBlockHeader),
        (
            "kzg_commitment_inclusion_proof",
            Vector(Bytes32, KZG_COMMITMENT_INCLUSION_PROOF_DEPTH),
        ),
    ),
    name="BlobSidecar",
)
ssz.deneb.Blob = Blob
ssz.deneb.BlobSidecar = BlobSidecar
