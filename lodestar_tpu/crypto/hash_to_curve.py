"""Hash-to-G2 (and G1) for BLS signatures — CPU ground truth.

`hash_to_g2` implements the spec ciphersuite BLS12381G2_XMD:SHA-256_SSWU_RO_
(RFC 9380 section 8.8.2): `expand_message_xmd` (SHA-256) -> `hash_to_field`
(two Fp2 elements) -> simplified-SWU on the 3-isogenous curve -> 3-isogeny
back to E2 -> effective-cofactor clearing.  The isogeny coefficient table
and SSWU parameters are verified at import by polynomial identities (see
`_selfcheck_sswu`); byte-level known-answer vectors from
ethereum/bls12-381-tests additionally gate the suite when fixture files
are present (tests/test_hash_to_curve.py).

The earlier Shallue–van de Woestijne map is kept as `map_to_curve_svdw`
— tests use it as a source of on-curve but out-of-subgroup points.
The reference consumes hashing inside blst's `verify`
(packages/beacon-node/src/chain/bls/multithread/worker.ts:30-106).
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

from . import fields as F
from .curves import (
    FP2_OPS,
    FP_OPS,
    Affine,
    FieldOps,
    affine_add,
    g1_clear_cofactor,
    is_on_curve,
)

# The standard Ethereum beacon-chain ciphersuite DST.
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_HASH = hashlib.sha256
_B_IN_BYTES = 32  # sha256 output
_R_IN_BYTES = 64  # sha256 block size
_L = 64  # ceil((381 + 128) / 8)


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 section 5.3.1, H = SHA-256."""
    if len(dst) > 255:
        dst = _HASH(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = _HASH(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = _HASH(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        xored = bytes(x ^ y for x, y in zip(b0, prev))
        blocks.append(_HASH(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(blocks)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes) -> List[Tuple[int, int]]:
    """RFC 9380 hash_to_field with m=2 (Fp2), L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            offset = _L * (j + i * 2)
            tv = uniform[offset : offset + _L]
            coords.append(int.from_bytes(tv, "big") % F.P)
        out.append((coords[0], coords[1]))
    return out


def hash_to_field_fp(msg: bytes, count: int, dst: bytes) -> List[int]:
    len_in_bytes = count * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    return [
        int.from_bytes(uniform[_L * i : _L * (i + 1)], "big") % F.P
        for i in range(count)
    ]


# ---------------------------------------------------------------------------
# Shallue–van de Woestijne map for y^2 = x^3 + b  (j = 0)
# ---------------------------------------------------------------------------


def _g(fo: FieldOps, x):
    return fo.add(fo.mul(fo.sqr(x), x), fo.b_coeff)


def _sqrt(fo: FieldOps, a):
    if fo is FP_OPS:
        return F.fp_sqrt(a)
    return F.fp2_sqrt(a)


def _sgn(fo: FieldOps, a) -> int:
    if fo is FP_OPS:
        return F.fp_sgn(a)
    return F.fp2_sgn(a)


def _embed(fo: FieldOps, k: int):
    if fo is FP_OPS:
        return k % F.P
    return (k % F.P, 0)


def _sqrt_m3(fo: FieldOps):
    s = _sqrt(fo, _embed(fo, -3))
    assert s is not None, "-3 must be a QR (p = 1 mod 3)"
    return s


_SQRT_M3 = {id(FP_OPS): _sqrt_m3(FP_OPS), id(FP2_OPS): _sqrt_m3(FP2_OPS)}


def map_to_curve_svdw(fo: FieldOps, t) -> Affine:
    """Deterministic map K -> E(K) for E: y^2 = x^3 + b (char K != 2,3).

    Fouque–Tibouchi parameterisation of the Shallue–van de Woestijne
    construction; one of the three candidate x's is always on the curve.
    """
    s3 = _SQRT_M3[id(fo)]
    one = fo.one
    # degenerate inputs map to the curve point derived from t = 1
    if fo.is_zero(t):
        t = one
    denom = fo.add(fo.add(one, fo.b_coeff), fo.sqr(t))
    if fo.is_zero(denom):
        t = fo.add(t, one)
        denom = fo.add(fo.add(one, fo.b_coeff), fo.sqr(t))
    w = fo.mul(fo.mul(s3, t), fo.inv(denom))
    # x1 = (-1 + s3)/2 - t*w
    half = fo.inv(_embed(fo, 2))
    x1 = fo.sub(fo.mul(fo.sub(s3, one), half), fo.mul(t, w))
    # x2 = -1 - x1
    x2 = fo.sub(fo.neg(one), x1)
    # x3 = 1 + 1/w^2
    x3 = fo.add(one, fo.inv(fo.sqr(w)))
    sign = _sgn(fo, t)
    for x in (x1, x2, x3):
        y = _sqrt(fo, _g(fo, x))
        if y is not None:
            if _sgn(fo, y) != sign:
                y = fo.neg(y)
            return (x, y)
    raise AssertionError("SvdW: no candidate x was on the curve")


# ---------------------------------------------------------------------------
# RFC 9380 section 8.8.2: BLS12381G2_XMD:SHA-256_SSWU_RO_
#
# Simplified SWU on the 3-isogenous curve E2': y^2 = x^3 + A'x + B', then
# the 3-isogeny back to E2, then effective-cofactor clearing.  The isogeny
# coefficient table (appendix E.3) is verified at import time by a
# polynomial identity over random E2' points — any wrong constant makes
# mapped points miss E2, so the check is decisive.
# ---------------------------------------------------------------------------

_A2 = (0, 240)            # A' = 240 * I
_B2 = (1012, 1012)        # B' = 1012 * (1 + I)
_Z2 = F.fp2_neg((2, 1))   # Z  = -(2 + I)

# Effective cofactor for G2 (RFC 9380 section 8.8.2).
H_EFF_G2 = 0xBC69F08F2EE75B3584C6A0EA91B352888E2A8E9145AD7689986FF031508FFE1329C2F178731DB956D82BF015D1212B02EC0EC69D7477C1AE954CBC06689F6A359894C0ADEBBF6B4E8020005AAA95551

# 3-isogeny map coefficients (RFC 9380 appendix E.3), verified below.
_ISO3_XNUM = (
    (0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
     0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6),
    (0,
     0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D),
    (0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
     0),
)
_ISO3_XDEN = (
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63),
    (0xC,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F),
    (1, 0),
)
_ISO3_YNUM = (
    (0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
     0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706),
    (0,
     0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE),
    (0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
     0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F),
    (0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
     0),
)
_ISO3_YDEN = (
    (0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB),
    (0,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3),
    (0x12,
     0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99),
    (1, 0),
)


def _sgn0_fp2(x) -> int:
    """RFC 9380 section 4.1 sgn0 for m = 2."""
    x0, x1 = x
    sign_0 = x0 % 2
    zero_0 = x0 == 0
    return sign_0 | (zero_0 and (x1 % 2))


def _poly_eval(coeffs, x):
    acc = F.FP2_ZERO
    for c in reversed(coeffs):
        acc = F.fp2_add(F.fp2_mul(acc, x), c)
    return acc


def map_to_curve_sswu_g2(u) -> Affine:
    """Simplified SWU for E2' (RFC 9380 section 6.6.2, straight-line)."""
    A, B, Z = _A2, _B2, _Z2
    zu2 = F.fp2_mul(Z, F.fp2_sqr(u))
    tv1 = F.fp2_add(F.fp2_sqr(zu2), zu2)  # Z^2 u^4 + Z u^2
    if F.fp2_is_zero(tv1):
        # exceptional case: x1 = B / (Z A)
        x1 = F.fp2_mul(B, F.fp2_inv(F.fp2_mul(Z, A)))
    else:
        # x1 = (-B/A) * (1 + 1/tv1)
        x1 = F.fp2_mul(
            F.fp2_mul(F.fp2_neg(B), F.fp2_inv(A)),
            F.fp2_add((1, 0), F.fp2_inv(tv1)),
        )
    gx1 = F.fp2_add(F.fp2_mul(F.fp2_add(F.fp2_sqr(x1), A), x1), B)
    y1 = F.fp2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = F.fp2_mul(zu2, x1)
        gx2 = F.fp2_add(F.fp2_mul(F.fp2_add(F.fp2_sqr(x2), A), x2), B)
        y2 = F.fp2_sqrt(gx2)
        assert y2 is not None, "SSWU: neither gx1 nor gx2 is square"
        x, y = x2, y2
    if _sgn0_fp2(u) != _sgn0_fp2(y):
        y = F.fp2_neg(y)
    return (x, y)


def iso3_map(pt: Affine) -> Affine:
    """The 3-isogeny E2' -> E2 (appendix E.3)."""
    if pt is None:
        return None
    x, y = pt
    xden = _poly_eval(_ISO3_XDEN, x)
    yden = _poly_eval(_ISO3_YDEN, x)
    if F.fp2_is_zero(xden) or F.fp2_is_zero(yden):
        return None  # kernel points map to the identity
    xn = F.fp2_mul(_poly_eval(_ISO3_XNUM, x), F.fp2_inv(xden))
    yn = F.fp2_mul(
        F.fp2_mul(y, _poly_eval(_ISO3_YNUM, x)), F.fp2_inv(yden)
    )
    return (xn, yn)


def clear_cofactor_g2(q: Affine) -> Affine:
    """h_eff scalar multiplication (RFC 9380 section 8.8.2)."""
    from .curves import scalar_mul

    return scalar_mul(FP2_OPS, q, H_EFF_G2)


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> Affine:
    """BLS12381G2_XMD:SHA-256_SSWU_RO_ (RFC 9380) into the G2 subgroup."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = iso3_map(map_to_curve_sswu_g2(u0))
    q1 = iso3_map(map_to_curve_sswu_g2(u1))
    q = affine_add(FP2_OPS, q0, q1)
    p = clear_cofactor_g2(q)
    assert p is not None and is_on_curve(FP2_OPS, p)
    return p


# ---------------------------------------------------------------------------
# Import-time verification of the SSWU/isogeny constants: mapped points
# must satisfy both curve equations — a polynomial identity that any
# wrong coefficient breaks.
# ---------------------------------------------------------------------------


def _selfcheck_sswu() -> None:
    # One iteration suffices: the on-curve identities are polynomial in the
    # constants, so any wrong coefficient fails with probability ~1 on a
    # single pseudorandom point (more iterations live in the test suite).
    from .curves import g2_subgroup_check

    for i in range(1):
        (u,) = hash_to_field_fp2(b"sswu-selfcheck-%d" % i, 1, b"SELFTEST")
        xp, yp = map_to_curve_sswu_g2(u)
        # on E2': y^2 = x^3 + A'x + B'
        lhs = F.fp2_sqr(yp)
        rhs = F.fp2_add(
            F.fp2_mul(F.fp2_add(F.fp2_sqr(xp), _A2), xp), _B2
        )
        assert F.fp2_eq(lhs, rhs), "SSWU output not on E2'"
        pt = iso3_map((xp, yp))
        assert pt is not None and is_on_curve(FP2_OPS, pt), (
            "isogeny constants are wrong (mapped point off E2)"
        )
        cleared = clear_cofactor_g2(pt)
        assert cleared is not None and g2_subgroup_check(cleared), (
            "h_eff does not clear the G2 cofactor"
        )


_selfcheck_sswu()


def hash_to_g1(msg: bytes, dst: bytes) -> Affine:
    u0, u1 = hash_to_field_fp(msg, 2, dst)
    q0 = map_to_curve_svdw(FP_OPS, u0)
    q1 = map_to_curve_svdw(FP_OPS, u1)
    q = affine_add(FP_OPS, q0, q1)
    p = g1_clear_cofactor(q)
    assert p is not None and is_on_curve(FP_OPS, p)
    return p
