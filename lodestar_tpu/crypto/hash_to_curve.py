"""Hash-to-G2 (and G1) for BLS signatures — CPU ground truth.

Structure follows RFC 9380: `expand_message_xmd` (SHA-256) -> `hash_to_field`
(two Fp2 elements) -> map-to-curve -> add -> clear cofactor.  The
map-to-curve step uses the Shallue–van de Woestijne / Fouque–Tibouchi
construction for j-invariant-0 curves (y^2 = x^3 + b), which is fully
derivable from the curve constants — unlike the RFC's SSWU-on-isogeny
variant whose 3-isogeny coefficient tables cannot be re-derived offline.

NOTE: this makes the hash *internally consistent* (a deterministic,
well-distributed map onto the prime-order subgroup with the standard
Ethereum DST) but NOT bit-compatible with BLS12381G2_XMD:SHA-256_SSWU_RO_.
Signatures produced and verified inside this framework are sound; swapping
in the spec SSWU isogeny map is tracked as a later milestone (constants in
an offline-derivable form).  The reference consumes hashing inside blst's
`verify` (packages/beacon-node/src/chain/bls/multithread/worker.ts:30-106).
"""

from __future__ import annotations

import hashlib
from typing import List, Tuple

from . import fields as F
from .curves import (
    FP2_OPS,
    FP_OPS,
    Affine,
    FieldOps,
    affine_add,
    g1_clear_cofactor,
    g2_clear_cofactor,
    is_on_curve,
)

# The standard Ethereum beacon-chain ciphersuite DST.
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

_HASH = hashlib.sha256
_B_IN_BYTES = 32  # sha256 output
_R_IN_BYTES = 64  # sha256 block size
_L = 64  # ceil((381 + 128) / 8)


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    """RFC 9380 section 5.3.1, H = SHA-256."""
    if len(dst) > 255:
        dst = _HASH(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + len(dst).to_bytes(1, "big")
    z_pad = b"\x00" * _R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = _HASH(z_pad + msg + l_i_b_str + b"\x00" + dst_prime).digest()
    b1 = _HASH(b0 + b"\x01" + dst_prime).digest()
    blocks = [b1]
    for i in range(2, ell + 1):
        prev = blocks[-1]
        xored = bytes(x ^ y for x, y in zip(b0, prev))
        blocks.append(_HASH(xored + i.to_bytes(1, "big") + dst_prime).digest())
    return b"".join(blocks)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes) -> List[Tuple[int, int]]:
    """RFC 9380 hash_to_field with m=2 (Fp2), L=64."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            offset = _L * (j + i * 2)
            tv = uniform[offset : offset + _L]
            coords.append(int.from_bytes(tv, "big") % F.P)
        out.append((coords[0], coords[1]))
    return out


def hash_to_field_fp(msg: bytes, count: int, dst: bytes) -> List[int]:
    len_in_bytes = count * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    return [
        int.from_bytes(uniform[_L * i : _L * (i + 1)], "big") % F.P
        for i in range(count)
    ]


# ---------------------------------------------------------------------------
# Shallue–van de Woestijne map for y^2 = x^3 + b  (j = 0)
# ---------------------------------------------------------------------------


def _g(fo: FieldOps, x):
    return fo.add(fo.mul(fo.sqr(x), x), fo.b_coeff)


def _sqrt(fo: FieldOps, a):
    if fo is FP_OPS:
        return F.fp_sqrt(a)
    return F.fp2_sqrt(a)


def _sgn(fo: FieldOps, a) -> int:
    if fo is FP_OPS:
        return F.fp_sgn(a)
    return F.fp2_sgn(a)


def _embed(fo: FieldOps, k: int):
    if fo is FP_OPS:
        return k % F.P
    return (k % F.P, 0)


def _sqrt_m3(fo: FieldOps):
    s = _sqrt(fo, _embed(fo, -3))
    assert s is not None, "-3 must be a QR (p = 1 mod 3)"
    return s


_SQRT_M3 = {id(FP_OPS): _sqrt_m3(FP_OPS), id(FP2_OPS): _sqrt_m3(FP2_OPS)}


def map_to_curve_svdw(fo: FieldOps, t) -> Affine:
    """Deterministic map K -> E(K) for E: y^2 = x^3 + b (char K != 2,3).

    Fouque–Tibouchi parameterisation of the Shallue–van de Woestijne
    construction; one of the three candidate x's is always on the curve.
    """
    s3 = _SQRT_M3[id(fo)]
    one = fo.one
    # degenerate inputs map to the curve point derived from t = 1
    if fo.is_zero(t):
        t = one
    denom = fo.add(fo.add(one, fo.b_coeff), fo.sqr(t))
    if fo.is_zero(denom):
        t = fo.add(t, one)
        denom = fo.add(fo.add(one, fo.b_coeff), fo.sqr(t))
    w = fo.mul(fo.mul(s3, t), fo.inv(denom))
    # x1 = (-1 + s3)/2 - t*w
    half = fo.inv(_embed(fo, 2))
    x1 = fo.sub(fo.mul(fo.sub(s3, one), half), fo.mul(t, w))
    # x2 = -1 - x1
    x2 = fo.sub(fo.neg(one), x1)
    # x3 = 1 + 1/w^2
    x3 = fo.add(one, fo.inv(fo.sqr(w)))
    sign = _sgn(fo, t)
    for x in (x1, x2, x3):
        y = _sqrt(fo, _g(fo, x))
        if y is not None:
            if _sgn(fo, y) != sign:
                y = fo.neg(y)
            return (x, y)
    raise AssertionError("SvdW: no candidate x was on the curve")


def hash_to_g2(msg: bytes, dst: bytes = DST_G2) -> Affine:
    """Full hash-to-curve into the prime-order G2 subgroup."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = map_to_curve_svdw(FP2_OPS, u0)
    q1 = map_to_curve_svdw(FP2_OPS, u1)
    q = affine_add(FP2_OPS, q0, q1)
    p = g2_clear_cofactor(q)
    assert p is not None and is_on_curve(FP2_OPS, p)
    return p


def hash_to_g1(msg: bytes, dst: bytes) -> Affine:
    u0, u1 = hash_to_field_fp(msg, 2, dst)
    q0 = map_to_curve_svdw(FP_OPS, u0)
    q1 = map_to_curve_svdw(FP_OPS, u1)
    q = affine_add(FP_OPS, q0, q1)
    p = g1_clear_cofactor(q)
    assert p is not None and is_on_curve(FP_OPS, p)
    return p
