"""CPU ground-truth BLS12-381 cryptography (fields, curves, pairing, BLS).

The correctness oracle for the JAX/TPU kernels in `lodestar_tpu.ops`, and
the latency-critical CPU fallback verifier (the analog of the reference's
`BlsSingleThreadVerifier`, packages/beacon-node/src/chain/bls/singleThread.ts).
"""

from . import bls, curves, fields, hash_to_curve, pairing  # noqa: F401
