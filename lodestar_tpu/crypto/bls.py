"""BLS signatures over BLS12-381 (pubkeys in G1, signatures in G2) — CPU
ground truth, mirroring the blst API surface the reference consumes via
`@chainsafe/bls`:

  - `verify(pk, msg, sig)`          (blst one-shot verify)
  - `aggregate_pubkeys` / `aggregate_signatures`
        (reference: chain/bls/utils.ts:5-16 aggregates pubkeys on the main
         thread for `aggregate`-type signature sets)
  - `verify_multiple_signatures`    (random-linear-combination batch —
         reference: chain/bls/maybeBatch.ts:16-27 and multithread/worker.ts:52-87)

This CPU implementation is the correctness oracle and the small-batch /
latency-critical fallback path (the analog of the reference's
`verifyOnMainThread` option, chain/validation/block.ts:146).
"""

from __future__ import annotations

import hashlib
import os
from typing import List, Optional, Sequence, Tuple

from . import fields as F
from .curves import (
    FP2_OPS,
    FP_OPS,
    Affine,
    G1_GEN,
    affine_neg,
    g1_compress,
    g1_decompress,
    g2_compress,
    g2_decompress,
    g1_subgroup_check,
    g2_subgroup_check,
    is_on_curve,
    multi_add,
    scalar_mul,
)
from .hash_to_curve import DST_G2, hash_to_g2
from .pairing import multi_pairing_is_one

NEG_G1_GEN = affine_neg(FP_OPS, G1_GEN)

# Random coefficient width for batch verification.  The reference's blst
# backend uses 64-bit randomizers ("RAND_BITS" in blst); soundness error
# 2^-64 per batch.
RAND_BITS = 64


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


def keygen(ikm: bytes) -> int:
    """Deterministic test keygen (HKDF-free simplification): sk from hash."""
    h = hashlib.sha256(b"lodestar-tpu-keygen" + ikm).digest()
    sk = int.from_bytes(h, "big") % F.R
    return sk if sk != 0 else 1


def sk_to_pk(sk: int) -> Affine:
    return scalar_mul(FP_OPS, G1_GEN, sk % F.R)


# ---------------------------------------------------------------------------
# Core sign / verify
# ---------------------------------------------------------------------------


def sign(sk: int, msg: bytes, dst: bytes = DST_G2) -> Affine:
    return scalar_mul(FP2_OPS, hash_to_g2(msg, dst), sk % F.R)


def verify(pk: Affine, msg: bytes, sig: Affine, dst: bytes = DST_G2) -> bool:
    """e(pk, H(msg)) == e(G1, sig)  <=>  e(-G1, sig) * e(pk, H(msg)) == 1."""
    if pk is None or sig is None:
        return False
    if not (is_on_curve(FP_OPS, pk) and is_on_curve(FP2_OPS, sig)):
        return False
    # KeyValidate + signature subgroup check (IETF BLS / blst semantics)
    if not (g1_subgroup_check(pk) and g2_subgroup_check(sig)):
        return False
    return multi_pairing_is_one(
        [(NEG_G1_GEN, sig), (pk, hash_to_g2(msg, dst))]
    )


def aggregate_pubkeys(pks: Sequence[Affine]) -> Affine:
    return multi_add(FP_OPS, pks)


def aggregate_signatures(sigs: Sequence[Affine]) -> Affine:
    return multi_add(FP2_OPS, sigs)


def fast_aggregate_verify(
    pks: Sequence[Affine], msg: bytes, sig: Affine, dst: bytes = DST_G2
) -> bool:
    """n pubkeys, one message, one aggregate signature (sync-committee shape).

    KeyValidate (IETF BLS / blst) applies per pubkey: infinity, off-curve,
    or out-of-subgroup members fail the whole verification even when their
    torsion components would cancel in the aggregate.
    """
    if not pks or any(pk is None for pk in pks):
        return False
    for pk in pks:
        if not (is_on_curve(FP_OPS, pk) and g1_subgroup_check(pk)):
            return False
    return verify(aggregate_pubkeys(pks), msg, sig, dst)


# ---------------------------------------------------------------------------
# Batch verification (random linear combination)
# ---------------------------------------------------------------------------


def _rand_scalars(n: int, entropy: Optional[bytes] = None) -> List[int]:
    if entropy is None:
        entropy = os.urandom(32)
    out = []
    for i in range(n):
        h = hashlib.sha256(entropy + i.to_bytes(4, "big")).digest()
        r = int.from_bytes(h[: RAND_BITS // 8], "big") | 1  # nonzero, odd
        out.append(r)
    return out


def verify_multiple_signatures(
    sets: Sequence[Tuple[Affine, bytes, Affine]],
    dst: bytes = DST_G2,
    entropy: Optional[bytes] = None,
) -> bool:
    """Batch-verify [(pk, msg, sig)] with random linear combination.

    prod_i e(r_i * pk_i, H(m_i)) * e(-G1, sum_i r_i * sig_i) == 1

    One shared final exponentiation for n+1 Miller loops — the same
    amortization blst's `verifyMultipleSignatures` exploits (reference:
    chain/bls/multithread/worker.ts:52-66).
    """
    if not sets:
        return True
    for pk, _msg, sig in sets:
        if pk is None or sig is None:
            return False
        if not (is_on_curve(FP_OPS, pk) and is_on_curve(FP2_OPS, sig)):
            return False
        if not (g1_subgroup_check(pk) and g2_subgroup_check(sig)):
            return False
    rs = _rand_scalars(len(sets), entropy)
    pairs = []
    rsigs = []
    for (pk, msg, sig), r in zip(sets, rs):
        pairs.append((scalar_mul(FP_OPS, pk, r), hash_to_g2(msg, dst)))
        rsigs.append(scalar_mul(FP2_OPS, sig, r))
    agg_rsig = multi_add(FP2_OPS, rsigs)
    pairs.append((NEG_G1_GEN, agg_rsig))
    return multi_pairing_is_one(pairs)


# ---------------------------------------------------------------------------
# Byte-level convenience (compressed keys/signatures)
# ---------------------------------------------------------------------------


def sign_bytes(sk: int, msg: bytes) -> bytes:
    return g2_compress(sign(sk, msg))


def verify_bytes(pk48: bytes, msg: bytes, sig96: bytes) -> bool:
    try:
        pk = g1_decompress(pk48)
        sig = g2_decompress(sig96)
    except ValueError:
        return False
    # verify() performs KeyValidate (None / on-curve / subgroup) itself.
    return verify(pk, msg, sig)
