"""Optimal ate pairing on BLS12-381 — CPU ground truth.

e(P, Q) for P in G1(Fp), Q in G2(Fp2), computed the straightforward way:
untwist Q into E(Fp12) and run an *affine* Miller loop over |x| with generic
Fp12 arithmetic, then the full final exponentiation.  Slow (tens of ms per
pairing) but structurally simple — this is the oracle the optimized JAX
Miller loop (twisted line evaluation, shared final exp, 3d exponent trick)
is tested against.

The verification relation implemented on top (`lodestar_tpu.crypto.bls`)
mirrors blst's `verifyMultipleSignatures` random-linear-combination batch
(reference: packages/beacon-node/src/chain/bls/multithread/worker.ts:52-96,
maybeBatch.ts:16-27).
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence, Tuple

from . import fields as F
from .curves import FP2_OPS, FP_OPS, Affine, is_on_curve

# Miller loop runs over |x|; x < 0 is handled by a final conjugation.
ATE_LOOP = -F.X_PARAM
ATE_BITS = bin(ATE_LOOP)[2:]  # MSB first

# Hard-part exponent of the final exponentiation.
HARD_EXP = (F.P**4 - F.P**2 + 1) // F.R
assert (F.P**4 - F.P**2 + 1) % F.R == 0

# The (x-1)^2 * (x+p) * (x^2+p^2-1) + 3 identity used by the fast chain on
# the TPU side (which therefore computes e(P,Q)^3 — still a perfectly good
# pairing for equality-with-one checks since gcd(3, r) = 1).
assert 3 * HARD_EXP == (F.X_PARAM - 1) ** 2 * (F.X_PARAM + F.P) * (
    F.X_PARAM**2 + F.P**2 - 1
) + 3


# ---------------------------------------------------------------------------
# Untwist E'(Fp2) -> E(Fp12)
# ---------------------------------------------------------------------------

_XI_INV = F.fp2_inv(F.XI)


def untwist(q: Affine):
    """Map (x, y) on E'/Fp2 to E/Fp12 via X = x/w^2, Y = y/w^3.

    With the tower w^2 = v, v^3 = xi:  1/w^2 = v^2/xi and 1/w^3 = (v/xi)*w.
    """
    if q is None:
        return None
    x, y = q
    X = (
        (F.FP2_ZERO, F.FP2_ZERO, F.fp2_mul(x, _XI_INV)),
        F.FP6_ZERO,
    )
    Y = (
        F.FP6_ZERO,
        (F.FP2_ZERO, F.fp2_mul(y, _XI_INV), F.FP2_ZERO),
    )
    return (X, Y)


def embed_fp(a: int):
    """Embed an Fp scalar into Fp12."""
    return (((a % F.P, 0), F.FP2_ZERO, F.FP2_ZERO), F.FP6_ZERO)


# Self-check: the untwisted G2 generator satisfies Y^2 = X^3 + 4.
def _selfcheck_untwist() -> None:
    from .curves import G2_GEN

    X, Y = untwist(G2_GEN)
    lhs = F.fp12_sqr(Y)
    rhs = F.fp12_add(F.fp12_mul(F.fp12_sqr(X), X), embed_fp(4))
    assert F.fp12_eq(lhs, rhs), "untwist map is wrong"


_selfcheck_untwist()


# ---------------------------------------------------------------------------
# Affine Miller loop in Fp12
# ---------------------------------------------------------------------------


def _line(t, q, p_emb):
    """Evaluate the line through t and q (or tangent if t == q) at p_emb.

    All points are affine over Fp12.  Returns (value, t + q).
    """
    xt, yt = t
    xp, yp = p_emb
    if F.fp12_eq(xt, q[0]) and F.fp12_eq(yt, q[1]):
        # tangent: lambda = 3 x^2 / 2 y
        num = F.fp12_mul(embed_fp(3), F.fp12_sqr(xt))
        den = F.fp12_mul(embed_fp(2), yt)
    elif F.fp12_eq(xt, q[0]):
        # t == -q: the ate loop never reaches this for points in the proper
        # subgroups; reaching it means a bad input slipped past the callers.
        raise ValueError("degenerate line (t == -q): input not in G2 subgroup")
    else:
        num = F.fp12_sub(q[1], yt)
        den = F.fp12_sub(q[0], xt)
    lam = F.fp12_mul(num, F.fp12_inv(den))
    # l(P) = (y_p - y_t) - lambda * (x_p - x_t)
    val = F.fp12_sub(F.fp12_sub(yp, yt), F.fp12_mul(lam, F.fp12_sub(xp, xt)))
    # chord/tangent addition
    x3 = F.fp12_sub(F.fp12_sub(F.fp12_sqr(lam), xt), q[0])
    y3 = F.fp12_sub(F.fp12_mul(lam, F.fp12_sub(xt, x3)), yt)
    return val, (x3, y3)


def miller_loop(p: Affine, q: Affine):
    """f_{|x|,Q}(P), conjugated for the negative parameter.  Fp12 result."""
    if p is None or q is None:
        return F.FP12_ONE
    q_tw = untwist(q)
    p_emb = (embed_fp(p[0]), embed_fp(p[1]))
    f = F.FP12_ONE
    t = q_tw
    for bit in ATE_BITS[1:]:
        val, t = _line(t, t, p_emb)
        f = F.fp12_mul(F.fp12_sqr(f), val)
        if bit == "1":
            val, t = _line(t, q_tw, p_emb)
            f = F.fp12_mul(f, val)
    return F.fp12_conj(f)  # x < 0


def final_exponentiation(f):
    """f^((p^12 - 1)/r)."""
    # easy part: f^((p^6 - 1)(p^2 + 1))
    m = F.fp12_mul(F.fp12_conj(f), F.fp12_inv(f))
    m = F.fp12_mul(F.fp12_frobenius(m, 2), m)
    # hard part
    return F.fp12_pow(m, HARD_EXP)


def pairing(p: Affine, q: Affine, check: bool = True):
    if check:
        assert is_on_curve(FP_OPS, p), "P not on G1 curve"
        assert is_on_curve(FP2_OPS, q), "Q not on G2 curve"
    return final_exponentiation(miller_loop(p, q))


def multi_pairing(pairs: Sequence[Tuple[Affine, Affine]]):
    """prod_i e(P_i, Q_i) with a single shared final exponentiation."""
    f = F.FP12_ONE
    for p, q in pairs:
        f = F.fp12_mul(f, miller_loop(p, q))
    return final_exponentiation(f)


def multi_pairing_is_one(pairs: Sequence[Tuple[Affine, Affine]]) -> bool:
    return F.fp12_eq(multi_pairing(pairs), F.FP12_ONE)
