"""BLS12-381 field towers over Python ints — the CPU ground truth.

This module is the reference ("ground truth") arithmetic that the JAX/TPU
kernels in `lodestar_tpu.ops` are validated against.  It is written from
first principles (standard BLS12-381 parameters and tower construction):

    Fp   = GF(p)
    Fp2  = Fp[u]  / (u^2 + 1)
    Fp6  = Fp2[v] / (v^3 - xi),  xi = u + 1
    Fp12 = Fp6[w] / (w^2 - v)

Elements are represented as plain ints / nested tuples so the module has
zero dependencies and is trivially picklable:

    Fp   : int
    Fp2  : (int, int)                      # c0 + c1*u
    Fp6  : (Fp2, Fp2, Fp2)                 # a0 + a1*v + a2*v^2
    Fp12 : (Fp6, Fp6)                      # b0 + b1*w

Role in the reference architecture: this is the equivalent of the CPU
fallback implementation selected by the `@chainsafe/bls` facade
(reference: packages/beacon-node/src/chain/bls/multithread/index.ts:127-132
chooses blst-native vs herumi); the TPU build keeps a CPU path for ground
truth, decompression, and latency-critical small verifications.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# Parameters.  x is the BLS12-381 curve parameter; p and r derive from it.
# ---------------------------------------------------------------------------

X_PARAM = -0xD201000000010000  # "z", the BLS parameter (negative)

P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# Self-checks that the parameterisation is internally consistent.
_ax = -X_PARAM
assert R == X_PARAM**4 - X_PARAM**2 + 1
assert P == (X_PARAM - 1) ** 2 * R // 3 + X_PARAM
assert P % 4 == 3  # used by sqrt
H1_COFACTOR = (X_PARAM - 1) ** 2 // 3  # G1 cofactor

# ---------------------------------------------------------------------------
# Fp
# ---------------------------------------------------------------------------


def fp_add(a: int, b: int) -> int:
    return (a + b) % P


def fp_sub(a: int, b: int) -> int:
    return (a - b) % P


def fp_mul(a: int, b: int) -> int:
    return (a * b) % P


def fp_neg(a: int) -> int:
    return (-a) % P


def fp_inv(a: int) -> int:
    if a % P == 0:
        raise ZeroDivisionError("inverse of 0 in Fp")
    return pow(a, P - 2, P)


def fp_sqrt(a: int):
    """Square root in Fp (p % 4 == 3), or None if a is not a QR."""
    a %= P
    cand = pow(a, (P + 1) // 4, P)
    return cand if cand * cand % P == a else None


def fp_sgn(a: int) -> int:
    """1 if a > p - a (i.e. a is the 'larger' root), else 0.  a != 0."""
    return 1 if a > P - a else 0


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u^2+1)
# ---------------------------------------------------------------------------

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)
XI = (1, 1)  # the Fp6 non-residue, u + 1


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    # (a0+a1)(b0+b1) - t0 - t1 = a0*b1 + a1*b0  (Karatsuba)
    t2 = (a0 + a1) * (b0 + b1) - t0 - t1
    return ((t0 - t1) % P, t2 % P)


def fp2_sqr(a):
    a0, a1 = a
    # (a0+a1)(a0-a1), 2*a0*a1
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_mul_fp(a, k: int):
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a):
    """Frobenius x -> x^p on Fp2: conjugation."""
    return (a[0] % P, (-a[1]) % P)


def fp2_inv(a):
    a0, a1 = a
    n = (a0 * a0 + a1 * a1) % P
    ninv = fp_inv(n)
    return (a0 * ninv % P, (-a1) * ninv % P)


def fp2_mul_xi(a):
    """Multiply by xi = u + 1:  (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u."""
    a0, a1 = a
    return ((a0 - a1) % P, (a0 + a1) % P)


def fp2_eq(a, b) -> bool:
    return a[0] % P == b[0] % P and a[1] % P == b[1] % P


def fp2_is_zero(a) -> bool:
    return a[0] % P == 0 and a[1] % P == 0


def fp2_pow(a, e: int):
    result = FP2_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sqr(base)
        e >>= 1
    return result


def fp2_sqrt(a):
    """Square root in Fp2 via the norm ('complex') method, or None."""
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        # a0 is a non-residue in Fp; sqrt is of the form x1*u.
        s = fp_sqrt((-a0) % P)  # (x1*u)^2 = -x1^2  => x1^2 = -a0
        if s is None:
            return None
        return (0, s)
    n = (a0 * a0 + a1 * a1) % P  # norm, always a QR in Fp if a is a square
    d = fp_sqrt(n)
    if d is None:
        return None
    inv2 = fp_inv(2)
    x0sq = (a0 + d) * inv2 % P
    x0 = fp_sqrt(x0sq)
    if x0 is None:
        x0sq = (a0 - d) * inv2 % P
        x0 = fp_sqrt(x0sq)
        if x0 is None:
            return None
    x1 = a1 * fp_inv(2 * x0 % P) % P
    cand = (x0, x1)
    return cand if fp2_eq(fp2_sqr(cand), (a0, a1)) else None


def fp2_sgn(a) -> int:
    """Lexicographic 'is larger than its negation' flag, c1 compared first.

    Matches the ZCash compressed-point sort order used for G2 y-coordinates.
    """
    a0, a1 = a[0] % P, a[1] % P
    if a1 != 0:
        return fp_sgn(a1)
    if a0 != 0:
        return fp_sgn(a0)
    return 0


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v]/(v^3 - xi)
# ---------------------------------------------------------------------------

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a, b):
    return (fp2_add(a[0], b[0]), fp2_add(a[1], b[1]), fp2_add(a[2], b[2]))


def fp6_sub(a, b):
    return (fp2_sub(a[0], b[0]), fp2_sub(a[1], b[1]), fp2_sub(a[2], b[2]))


def fp6_neg(a):
    return (fp2_neg(a[0]), fp2_neg(a[1]), fp2_neg(a[2]))


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    # c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    c0 = fp2_add(
        t0,
        fp2_mul_xi(
            fp2_sub(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), t1), t2)
        ),
    )
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    c1 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), t0), t1),
        fp2_mul_xi(t2),
    )
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    """Multiply by v: (a0 + a1 v + a2 v^2) * v = xi*a2 + a0 v + a1 v^2."""
    return (fp2_mul_xi(a[2]), a[0], a[1])


def fp6_mul_fp2(a, k):
    return (fp2_mul(a[0], k), fp2_mul(a[1], k), fp2_mul(a[2], k))


def fp6_inv(a):
    a0, a1, a2 = a
    # Standard cubic-extension inversion.
    c0 = fp2_sub(fp2_sqr(a0), fp2_mul_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    t = fp2_add(
        fp2_mul_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))), fp2_mul(a0, c0)
    )
    tinv = fp2_inv(t)
    return (fp2_mul(c0, tinv), fp2_mul(c1, tinv), fp2_mul(c2, tinv))


def fp6_eq(a, b) -> bool:
    return all(fp2_eq(a[i], b[i]) for i in range(3))


def fp6_is_zero(a) -> bool:
    return all(fp2_is_zero(a[i]) for i in range(3))


# ---------------------------------------------------------------------------
# Fp12 = Fp6[w]/(w^2 - v)
# ---------------------------------------------------------------------------

FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_sub(a, b):
    return (fp6_sub(a[0], b[0]), fp6_sub(a[1], b[1]))


def fp12_neg(a):
    return (fp6_neg(a[0]), fp6_neg(a[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    # c0 = t0 + v*t1 ; c1 = (a0+a1)(b0+b1) - t0 - t1
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """x -> x^(p^6): the quadratic-twist conjugation (negate the w part)."""
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    t = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    tinv = fp6_inv(t)
    return (fp6_mul(a0, tinv), fp6_neg(fp6_mul(a1, tinv)))


def fp12_eq(a, b) -> bool:
    return fp6_eq(a[0], b[0]) and fp6_eq(a[1], b[1])


def fp12_pow(a, e: int):
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


# ---------------------------------------------------------------------------
# Frobenius endomorphism on Fp12 (x -> x^p), via precomputed constants.
#
# In the tower, Frobenius acts on an Fp2 coefficient c of v^i * w^j as
# conj(c) * gamma, with gamma = xi^((i*2 + j)*(p-1)/6) collected below.
# ---------------------------------------------------------------------------

# gamma_k = xi^(k*(p-1)/6) for k = 0..5; v^i w^j contributes k = 2i + j.
_GAMMA = [fp2_pow(XI, k * (P - 1) // 6) for k in range(6)]


def _frob_fp6(a, is_w_part: bool):
    """Frobenius of the Fp6 element `a` sitting on w^j, j = 1 if is_w_part."""
    j = 1 if is_w_part else 0
    out = []
    for i in range(3):
        k = 2 * i + j
        out.append(fp2_mul(fp2_conj(a[i]), _GAMMA[k]))
    return tuple(out)


def fp12_frobenius(a, power: int = 1):
    """x -> x^(p^power).  Applies single-power Frobenius `power` times."""
    result = a
    for _ in range(power % 12):
        result = (_frob_fp6(result[0], False), _frob_fp6(result[1], True))
    return result


# Sanity: Frobenius really is x -> x^p (checked once at import on a cheap case).
def _selfcheck_frobenius() -> None:
    a = ((( 3, 5), (7, 11), (13, 17)), ((19, 23), (29, 31), (37, 41)))
    lhs = fp12_frobenius(a)
    rhs = fp12_pow(a, P)
    assert fp12_eq(lhs, rhs), "Frobenius constants are wrong"


_selfcheck_frobenius()
