"""BLS12-381 curve groups G1 (over Fp) and G2 (over Fp2) — CPU ground truth.

G1:  E  /Fp : y^2 = x^3 + 4
G2:  E' /Fp2: y^2 = x^3 + 4*(u+1)     (sextic twist of E)

Points are affine tuples (x, y) with `None` as the point at infinity; the
internal fast paths use jacobian (X, Y, Z) with the usual x = X/Z^2,
y = Y/Z^3 convention.  Generic over the field via a tiny field-ops record so
G1/G2 share one implementation (the JAX ops mirror this structure in
`lodestar_tpu.ops.curve`).

Serialization follows the ZCash/ETH2 compressed format (48B G1 / 96B G2,
flag bits in the top 3 bits of the first byte) as consumed by the
reference's pubkey/signature byte surfaces (reference:
packages/state-transition/src/cache/pubkeyCache.ts:29-47 stores
deserialized pubkeys; packages/beacon-node/src/chain/bls/multithread/index.ts:177
ships {pubkey, signingRoot, signature} bytes per set).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from . import fields as F

# ---------------------------------------------------------------------------
# Field-ops records (duck-typed namespaces for generic EC formulas)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FieldOps:
    add: Callable
    sub: Callable
    mul: Callable
    sqr: Callable
    neg: Callable
    inv: Callable
    eq: Callable
    is_zero: Callable
    zero: Any
    one: Any
    mul_small: Callable  # multiply by a small Python int
    b_coeff: Any  # curve b coefficient in this field


def _fp_sqr(a):
    return a * a % F.P


def _fp_is_zero(a):
    return a % F.P == 0


def _fp_eq(a, b):
    return a % F.P == b % F.P


def _fp_mul_small(a, k):
    return a * k % F.P


def _fp2_mul_small(a, k):
    return (a[0] * k % F.P, a[1] * k % F.P)


FP_OPS = FieldOps(
    add=F.fp_add, sub=F.fp_sub, mul=F.fp_mul, sqr=_fp_sqr, neg=F.fp_neg,
    inv=F.fp_inv, eq=_fp_eq, is_zero=_fp_is_zero, zero=0, one=1,
    mul_small=_fp_mul_small, b_coeff=4,
)

FP2_OPS = FieldOps(
    add=F.fp2_add, sub=F.fp2_sub, mul=F.fp2_mul, sqr=F.fp2_sqr,
    neg=F.fp2_neg, inv=F.fp2_inv, eq=F.fp2_eq, is_zero=F.fp2_is_zero,
    zero=F.FP2_ZERO, one=F.FP2_ONE, mul_small=_fp2_mul_small,
    b_coeff=F.fp2_mul_fp(F.XI, 4),  # 4*(u+1)
)

# ---------------------------------------------------------------------------
# Generic affine/jacobian arithmetic
# ---------------------------------------------------------------------------

Affine = Optional[Tuple[Any, Any]]  # None = infinity


def is_on_curve(fo: FieldOps, pt: Affine) -> bool:
    if pt is None:
        return True
    x, y = pt
    return fo.eq(fo.sqr(y), fo.add(fo.mul(fo.sqr(x), x), fo.b_coeff))


def affine_neg(fo: FieldOps, pt: Affine) -> Affine:
    if pt is None:
        return None
    return (pt[0], fo.neg(pt[1]))


def affine_eq(fo: FieldOps, a: Affine, b: Affine) -> bool:
    if a is None or b is None:
        return a is None and b is None
    return fo.eq(a[0], b[0]) and fo.eq(a[1], b[1])


def _jac_dbl(fo: FieldOps, pt):
    X, Y, Z = pt
    if fo.is_zero(Z) or fo.is_zero(Y):
        return (fo.one, fo.one, fo.zero)
    A = fo.sqr(X)
    B = fo.sqr(Y)
    C = fo.sqr(B)
    # D = 2*((X+B)^2 - A - C)
    D = fo.mul_small(fo.sub(fo.sub(fo.sqr(fo.add(X, B)), A), C), 2)
    E = fo.mul_small(A, 3)
    Fv = fo.sqr(E)
    X3 = fo.sub(Fv, fo.mul_small(D, 2))
    Y3 = fo.sub(fo.mul(E, fo.sub(D, X3)), fo.mul_small(C, 8))
    Z3 = fo.mul_small(fo.mul(Y, Z), 2)
    return (X3, Y3, Z3)


def _jac_add(fo: FieldOps, a, b):
    X1, Y1, Z1 = a
    X2, Y2, Z2 = b
    if fo.is_zero(Z1):
        return b
    if fo.is_zero(Z2):
        return a
    Z1Z1 = fo.sqr(Z1)
    Z2Z2 = fo.sqr(Z2)
    U1 = fo.mul(X1, Z2Z2)
    U2 = fo.mul(X2, Z1Z1)
    S1 = fo.mul(fo.mul(Y1, Z2), Z2Z2)
    S2 = fo.mul(fo.mul(Y2, Z1), Z1Z1)
    if fo.eq(U1, U2):
        if fo.eq(S1, S2):
            return _jac_dbl(fo, a)
        return (fo.one, fo.one, fo.zero)  # P + (-P) = O
    H = fo.sub(U2, U1)
    I = fo.sqr(fo.mul_small(H, 2))
    J = fo.mul(H, I)
    Rv = fo.mul_small(fo.sub(S2, S1), 2)
    V = fo.mul(U1, I)
    X3 = fo.sub(fo.sub(fo.sqr(Rv), J), fo.mul_small(V, 2))
    Y3 = fo.sub(fo.mul(Rv, fo.sub(V, X3)), fo.mul_small(fo.mul(S1, J), 2))
    Z3 = fo.mul_small(fo.mul(fo.mul(Z1, Z2), H), 2)
    return (X3, Y3, Z3)


def _to_jac(fo: FieldOps, pt: Affine):
    if pt is None:
        return (fo.one, fo.one, fo.zero)
    return (pt[0], pt[1], fo.one)


def _to_affine(fo: FieldOps, pt) -> Affine:
    X, Y, Z = pt
    if fo.is_zero(Z):
        return None
    zinv = fo.inv(Z)
    zinv2 = fo.sqr(zinv)
    return (fo.mul(X, zinv2), fo.mul(Y, fo.mul(zinv2, zinv)))


def affine_add(fo: FieldOps, a: Affine, b: Affine) -> Affine:
    return _to_affine(fo, _jac_add(fo, _to_jac(fo, a), _to_jac(fo, b)))


def affine_dbl(fo: FieldOps, a: Affine) -> Affine:
    return _to_affine(fo, _jac_dbl(fo, _to_jac(fo, a)))


def scalar_mul(fo: FieldOps, pt: Affine, k: int) -> Affine:
    """k * pt via jacobian double-and-add (left-to-right)."""
    if k < 0:
        return scalar_mul(fo, affine_neg(fo, pt), -k)
    if k == 0 or pt is None:
        return None
    acc = (fo.one, fo.one, fo.zero)
    base = _to_jac(fo, pt)
    for bit in bin(k)[2:]:
        acc = _jac_dbl(fo, acc)
        if bit == "1":
            acc = _jac_add(fo, acc, base)
    return _to_affine(fo, acc)


def multi_add(fo: FieldOps, pts) -> Affine:
    """Sum of a list of affine points (jacobian accumulation)."""
    acc = (fo.one, fo.one, fo.zero)
    for pt in pts:
        if pt is not None:
            acc = _jac_add(fo, acc, _to_jac(fo, pt))
    return _to_affine(fo, acc)


# ---------------------------------------------------------------------------
# Generators (standard BLS12-381 constants)
# ---------------------------------------------------------------------------

G1_GEN: Affine = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)

G2_GEN: Affine = (
    (
        0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
    ),
    (
        0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
    ),
)

assert is_on_curve(FP_OPS, G1_GEN), "G1 generator not on curve"
assert is_on_curve(FP2_OPS, G2_GEN), "G2 generator not on curve"

# ---------------------------------------------------------------------------
# Twist order / G2 cofactor, derived (not hard-coded) from the parameters.
#
# t = x + 1 (trace of E/Fp); #E(Fp2) = p^2 + 1 - t2 with t2 = t^2 - 2p.
# The right sextic twist order among the candidates (p^2 + 1 - t') for
# t' in {(±3v ± t)/2, ±t2, ...} is the one divisible by r that kills the
# known generator; we find it by search once at import.
# ---------------------------------------------------------------------------


def _derive_g2_cofactor() -> int:
    t = F.X_PARAM + 1
    p = F.P
    t2 = t * t - 2 * p  # trace of Frobenius on E(Fp2)
    # t^2 - 4p = -3 v^2  over Fp; then t2^2 - 4p^2 = -3 (t*v)^2.
    vsq = (4 * p - t * t) // 3
    v = _isqrt(vsq)
    assert v * v == vsq, "v derivation failed"
    v2 = t * v
    candidates = []
    for tp in (
        t2,
        -t2,
        (t2 + 3 * v2) // 2,
        (t2 - 3 * v2) // 2,
        (-t2 + 3 * v2) // 2,
        (-t2 - 3 * v2) // 2,
    ):
        n = p * p + 1 - tp
        if n % F.R == 0:
            candidates.append(n)
    # G2_GEN has order r and r divides several candidates, so the
    # annihilation test must use a generic point of E'(Fp2): take the first
    # x = (k, 1) that lands on the curve via a y = sqrt(x^3 + b').
    probe = None
    k = 0
    while probe is None:
        k += 1
        x = (k, 1)
        y2 = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), FP2_OPS.b_coeff)
        y = F.fp2_sqrt(y2)
        if y is not None:
            probe = (x, y)
    for n in candidates:
        if scalar_mul(FP2_OPS, probe, n) is None:
            return n // F.R
    raise AssertionError("could not derive G2 cofactor")


def _isqrt(n: int) -> int:
    import math

    return math.isqrt(n)


H2_COFACTOR = _derive_g2_cofactor()

# r*G = O sanity for both groups
assert scalar_mul(FP_OPS, G1_GEN, F.R) is None
assert scalar_mul(FP2_OPS, G2_GEN, F.R) is None


def g1_subgroup_check(pt: Affine) -> bool:
    return scalar_mul(FP_OPS, pt, F.R) is None


def g2_subgroup_check(pt: Affine) -> bool:
    return scalar_mul(FP2_OPS, pt, F.R) is None


def g2_clear_cofactor(pt: Affine) -> Affine:
    return scalar_mul(FP2_OPS, pt, H2_COFACTOR)


def g1_clear_cofactor(pt: Affine) -> Affine:
    return scalar_mul(FP_OPS, pt, F.H1_COFACTOR)


# ---------------------------------------------------------------------------
# ZCash-format point compression
# ---------------------------------------------------------------------------

_COMP = 0x80
_INF = 0x40
_SIGN = 0x20


def g1_compress(pt: Affine) -> bytes:
    if pt is None:
        return bytes([_COMP | _INF]) + b"\x00" * 47
    x, y = pt
    flags = _COMP | (_SIGN if F.fp_sgn(y) else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_decompress(data: bytes) -> Affine:
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & _COMP:
        raise ValueError("uncompressed G1 not supported")
    if flags & _INF:
        if any(data[1:]) or flags & _SIGN or data[0] & 0x1F:
            raise ValueError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= F.P:
        raise ValueError("x not in field")
    y2 = (x * x % F.P * x + 4) % F.P
    y = F.fp_sqrt(y2)
    if y is None:
        raise ValueError("x not on curve")
    if F.fp_sgn(y) != (1 if flags & _SIGN else 0):
        y = F.fp_neg(y)
    return (x, y)


def g2_compress(pt: Affine) -> bytes:
    if pt is None:
        return bytes([_COMP | _INF]) + b"\x00" * 95
    (x0, x1), y = pt
    flags = _COMP | (_SIGN if F.fp2_sgn(y) else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_decompress(data: bytes) -> Affine:
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & _COMP:
        raise ValueError("uncompressed G2 not supported")
    if flags & _INF:
        if any(data[1:]) or flags & _SIGN or data[0] & 0x1F:
            raise ValueError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= F.P or x1 >= F.P:
        raise ValueError("x not in field")
    x = (x0, x1)
    y2 = F.fp2_add(F.fp2_mul(F.fp2_sqr(x), x), FP2_OPS.b_coeff)
    y = F.fp2_sqrt(y2)
    if y is None:
        raise ValueError("x not on curve")
    if F.fp2_sgn(y) != (1 if flags & _SIGN else 0):
        y = F.fp2_neg(y)
    return (x, y)
