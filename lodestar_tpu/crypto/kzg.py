"""KZG polynomial commitments for deneb blobs (EIP-4844).

Replaces the reference's `c-kzg` native dependency (reference:
packages/beacon-node/src/util/kzg.ts loads the c-kzg-4844 trusted setup
and exposes verifyBlobKzgProofBatch / blobToKzgCommitment).  The
algorithms follow the deneb polynomial-commitments spec: blobs are
polynomials in EVALUATION form over the bit-reversed roots-of-unity
domain; commitments/proofs are G1 MSMs over a Lagrange-form trusted
setup; verification is two pairings.

The production ceremony file cannot be fetched in this sealed
environment, so the module ships `insecure_dev_setup(n)` — a setup with
a KNOWN tau derived from a fixed seed.  It is cryptographically
USELESS for production (anyone knowing tau can forge proofs) but
byte-compatible in shape, which is exactly what dev networks and tests
need; dropping in the real `trusted_setup.json` points works unchanged
via `TrustedSetup.from_points`.

The MSM here runs on the CPU oracle (correctness path).  At mainnet
blob scale the MSM is the same gather + randomizer + jacobian-sum
machinery the TPU BLS pipeline already implements (kernels/verify.py
`_k_agg_pk` / `_j_seg_sum_g1`) — wiring blobs through it is the
device-acceleration path once blob throughput matters.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

from . import bls as B
from . import curves as C
from . import fields as F
from . import pairing as P

R = F.R  # the BLS12-381 scalar field modulus (Fr)

BYTES_PER_FIELD_ELEMENT = 32
# The full mainnet blob width is 4096; tests/dev nets use small widths
# (the consensus minimal preset also shrinks it).
FIELD_ELEMENTS_PER_BLOB = 4096

# 7 is a primitive root mod r; r - 1 = 2^32 * odd, so 2^i-th roots of
# unity exist for i <= 32
_PRIMITIVE_ROOT = 7
_TWO_ADICITY = 32

FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_DOMAIN = b"RCKZGBATCH___V1_"


class KzgError(ValueError):
    pass


def _inv(a: int) -> int:
    return pow(a, R - 2, R)


def compute_roots_of_unity(n: int) -> List[int]:
    """The n-th roots of unity in Fr, n a power of two <= 2^32."""
    assert n & (n - 1) == 0 and n <= (1 << _TWO_ADICITY)
    w = pow(_PRIMITIVE_ROOT, (R - 1) // n, R)
    out = [1]
    for _ in range(n - 1):
        out.append(out[-1] * w % R)
    return out


def bit_reversal_permutation(values: Sequence) -> List:
    n = len(values)
    assert n & (n - 1) == 0
    bits = n.bit_length() - 1
    return [
        values[int(format(i, f"0{bits}b")[::-1], 2) if bits else 0]
        for i in range(n)
    ]


@dataclass
class TrustedSetup:
    """Lagrange-form G1 points over the bit-reversed domain + the two
    monomial G2 points the pairing check needs."""

    g1_lagrange: List  # affine G1 points, one per field element
    g2_monomial: Tuple  # ([1]G2, [tau]G2)
    roots_brp: List[int]  # bit-reversed evaluation domain

    @property
    def width(self) -> int:
        return len(self.g1_lagrange)

    @classmethod
    def from_points(cls, g1_lagrange, g2_monomial):
        roots = bit_reversal_permutation(
            compute_roots_of_unity(len(g1_lagrange))
        )
        return cls(list(g1_lagrange), tuple(g2_monomial), roots)


def insecure_dev_setup(n: int = 16, seed: bytes = b"lodestar-tpu-dev-kzg") -> TrustedSetup:
    """A KNOWN-tau setup for dev/tests — see module docstring.  O(n)
    G1 scalar multiplications on the CPU oracle, so keep n small in
    tests (the math is width-independent)."""
    assert n & (n - 1) == 0
    tau = int.from_bytes(hashlib.sha256(seed).digest(), "big") % R
    roots = compute_roots_of_unity(n)
    # Lagrange basis at tau over the (natural-order) domain:
    #   L_i(tau) = w_i (tau^n - 1) / (n (tau - w_i))
    zn = (pow(tau, n, R) - 1) % R
    lagrange_nat = [
        C.scalar_mul(
            C.FP_OPS,
            C.G1_GEN,
            w * zn % R * _inv(n * (tau - w) % R) % R,
        )
        for w in roots
    ]
    g1_lagrange = bit_reversal_permutation(lagrange_nat)
    g2 = (C.G2_GEN, C.scalar_mul(C.FP2_OPS, C.G2_GEN, tau))
    return TrustedSetup.from_points(g1_lagrange, g2)


# -- blob <-> polynomial ----------------------------------------------------


def blob_to_polynomial(blob: bytes, width: int) -> List[int]:
    if len(blob) != width * BYTES_PER_FIELD_ELEMENT:
        raise KzgError(
            f"blob length {len(blob)} != {width * BYTES_PER_FIELD_ELEMENT}"
        )
    out = []
    for i in range(width):
        v = int.from_bytes(
            blob[i * 32 : (i + 1) * 32], "big"
        )
        if v >= R:
            raise KzgError(f"blob element {i} not canonical")
        out.append(v)
    return out


def polynomial_to_blob(evals: Sequence[int]) -> bytes:
    return b"".join(int(v).to_bytes(32, "big") for v in evals)


def _msm(points, scalars) -> Optional[tuple]:
    """sum_i scalars_i * points_i on the oracle (None = infinity)."""
    terms = []
    for pt, k in zip(points, scalars):
        k = k % R
        if k == 0 or pt is None:
            continue
        terms.append(C.scalar_mul(C.FP_OPS, pt, k))
    return C.multi_add(C.FP_OPS, [t for t in terms if t is not None])


def evaluate_polynomial_in_evaluation_form(
    evals: Sequence[int], z: int, setup: TrustedSetup
) -> int:
    """Barycentric evaluation at z over the bit-reversed domain."""
    n = setup.width
    roots = setup.roots_brp
    z %= R
    for i, w in enumerate(roots):
        if z == w:
            return evals[i] % R
    # p(z) = (z^n - 1)/n * sum_i e_i w_i / (z - w_i)
    total = 0
    for e, w in zip(evals, roots):
        total = (total + e * w % R * _inv((z - w) % R)) % R
    return total * (pow(z, n, R) - 1) % R * _inv(n) % R


# -- commitments + proofs ---------------------------------------------------


def blob_to_kzg_commitment(blob: bytes, setup: TrustedSetup) -> bytes:
    evals = blob_to_polynomial(blob, setup.width)
    return C.g1_compress(_msm(setup.g1_lagrange, evals))


def compute_kzg_proof(
    blob: bytes, z_bytes: bytes, setup: TrustedSetup
) -> Tuple[bytes, bytes]:
    """(proof, y): the quotient commitment for p(z) = y."""
    evals = blob_to_polynomial(blob, setup.width)
    z = int.from_bytes(z_bytes, "big")
    if z >= R:
        raise KzgError("z not canonical")
    y = evaluate_polynomial_in_evaluation_form(evals, z, setup)
    roots = setup.roots_brp
    # quotient in evaluation form: q_i = (e_i - y)/(w_i - z); at a
    # domain point use the spec's L'Hopital-style branch
    q = [0] * setup.width
    z_on_domain = None
    for i, w in enumerate(roots):
        if w == z:
            z_on_domain = i
            continue
        q[i] = (evals[i] - y) * _inv((w - z) % R) % R
    if z_on_domain is not None:
        i = z_on_domain
        acc = 0
        for j, w in enumerate(roots):
            if j == i:
                continue
            # q_i = sum_j (e_j - y) w_j / (z (z - w_j))
            acc = (
                acc
                + (evals[j] - y)
                * w
                % R
                * _inv(z * ((z - w) % R) % R)
            ) % R
        q[i] = acc
    proof_pt = _msm(setup.g1_lagrange, q)
    # infinity encodes as the compressed identity
    proof = (
        C.g1_compress(proof_pt)
        if proof_pt is not None
        else bytes([0xC0]) + b"\x00" * 47
    )
    return proof, int(y).to_bytes(32, "big")


def verify_kzg_proof(
    commitment: bytes, z_bytes: bytes, y_bytes: bytes, proof: bytes,
    setup: TrustedSetup,
) -> bool:
    """e(C - [y]G1, [1]G2) == e(pi, [tau - z]G2)."""
    try:
        c_pt = C.g1_decompress(commitment)
        pi = None if proof == bytes([0xC0]) + b"\x00" * 47 else C.g1_decompress(proof)
    except Exception:
        return False
    z = int.from_bytes(z_bytes, "big")
    y = int.from_bytes(y_bytes, "big")
    if z >= R or y >= R:
        return False
    g2_1, g2_tau = setup.g2_monomial
    # X2 = [tau]G2 - [z]G2
    x2 = C.multi_add(
        C.FP2_OPS,
        [g2_tau, C.affine_neg(C.FP2_OPS, C.scalar_mul(C.FP2_OPS, C.G2_GEN, z))],
    )
    p_minus_y = C.multi_add(
        C.FP_OPS,
        [c_pt, C.affine_neg(C.FP_OPS, C.scalar_mul(C.FP_OPS, C.G1_GEN, y))],
    )
    if p_minus_y is None and pi is None:
        return True
    if p_minus_y is None or pi is None or x2 is None:
        # degenerate inputs: fall back to the full identity via pairing
        # with explicit infinity handling (e(O, Q) = 1)
        lhs_one = p_minus_y is None
        rhs_one = pi is None or x2 is None
        return lhs_one and rhs_one
    return P.multi_pairing_is_one(
        [(p_minus_y, C.G2_GEN), (C.affine_neg(C.FP_OPS, pi), x2)]
    )


# -- blob-level API (what the beacon node consumes) -------------------------


def _compute_challenge(blob: bytes, commitment: bytes, setup: TrustedSetup) -> int:
    """Spec compute_challenge: hash(DOMAIN + degree_poly(16B) + blob +
    commitment) — byte-compatible with c-kzg so proofs interop once the
    real setup points are loaded."""
    h = hashlib.sha256()
    h.update(FIAT_SHAMIR_PROTOCOL_DOMAIN)
    h.update((setup.width).to_bytes(16, "big"))
    h.update(blob)
    h.update(commitment)
    return int.from_bytes(h.digest(), "big") % R


def compute_blob_kzg_proof(
    blob: bytes, commitment: bytes, setup: TrustedSetup
) -> bytes:
    z = _compute_challenge(blob, commitment, setup)
    proof, _y = compute_kzg_proof(blob, z.to_bytes(32, "big"), setup)
    return proof


def verify_blob_kzg_proof(
    blob: bytes, commitment: bytes, proof: bytes, setup: TrustedSetup
) -> bool:
    try:
        evals = blob_to_polynomial(blob, setup.width)
    except KzgError:
        return False
    z = _compute_challenge(blob, commitment, setup)
    y = evaluate_polynomial_in_evaluation_form(evals, z, setup)
    return verify_kzg_proof(
        commitment,
        z.to_bytes(32, "big"),
        int(y).to_bytes(32, "big"),
        proof,
        setup,
    )


def verify_blob_kzg_proof_batch(
    blobs: Sequence[bytes],
    commitments: Sequence[bytes],
    proofs: Sequence[bytes],
    setup: TrustedSetup,
) -> bool:
    """Per-blob verification (the RLC-batched pairing path is the TPU
    wiring noted in the module docstring; the reference's c-kzg batch
    is also sequential pairings under the hood for small counts)."""
    if not (len(blobs) == len(commitments) == len(proofs)):
        return False
    return all(
        verify_blob_kzg_proof(b, c, p, setup)
        for b, c, p in zip(blobs, commitments, proofs)
    )
