"""MonitoringService — push node stats to a remote collector.

Reference: packages/beacon-node/src/monitoring/service.ts
(MonitoringService: collect client/system/beacon stats on an interval,
POST JSON to the configured endpoint with a collect timeout) and
monitoring/clientStats.ts (the beaconnodestats/validatorstats shapes).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional

from ..utils.logger import get_logger

CLIENT_NAME = "lodestar-tpu"
CLIENT_VERSION = "0.3.0"


class MonitoringService:
    def __init__(
        self,
        endpoint: str,
        *,
        chain=None,
        bls_metrics=None,
        beacon_metrics=None,
        validator_monitor=None,
        slo=None,
        interval_s: float = 60.0,
        collect_system: bool = True,
        timeout_s: float = 10.0,
    ):
        self.endpoint = endpoint
        self.chain = chain
        self.bls_metrics = bls_metrics
        # utils/beacon_metrics.BeaconMetrics: import-phase breakdown
        self.beacon_metrics = beacon_metrics
        # utils/validator_monitor.ValidatorMonitor: duty performance
        self.validator_monitor = validator_monitor
        # observability/slo.SloEngine: per-objective breach counters
        self.slo = slo
        self.interval_s = interval_s
        self.collect_system = collect_system
        self.timeout_s = timeout_s
        self.log = get_logger("monitoring")
        self.sent = 0
        self.failures = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- stats collection (reference: clientStats.ts) ----------------------

    def collect(self) -> List[Dict]:
        now_ms = int(time.time() * 1000)
        common = {
            "version": 1,
            "timestamp": now_ms,
            "client_name": CLIENT_NAME,
            "client_version": CLIENT_VERSION,
        }
        beacon = dict(common, process="beaconnode")
        if self.chain is not None:
            try:
                head = self.chain.head_state
                beacon.update(
                    {
                        "head_slot": int(head.slot),
                        "finalized_epoch": int(
                            head.finalized_checkpoint["epoch"]
                        ),
                        "validators": int(head.num_validators),
                        "imported_blocks": int(self.chain.imported_blocks),
                    }
                )
            except Exception:  # noqa: BLE001 - stats are best-effort
                pass
        if self.bls_metrics is not None:
            m = self.bls_metrics
            beacon["bls_success_jobs"] = int(m.success_jobs.value)
            # hot-path shape observability (ISSUE 8): remote collectors
            # see the same lodestar_bls_batch_size/verify_seconds series
            # /metrics exposes, reduced to sums/counts
            beacon["bls_batch_size_count"] = int(m.batch_size.count)
            beacon["bls_batch_size_sum"] = float(m.batch_size.sum)
            beacon["bls_verify_seconds"] = {
                phase: float(m.verify_seconds.sum(phase))
                for phase in m.verify_seconds.label_values()
            }
        if self.slo is not None:
            # slot-anchored SLO health (ISSUE 12): remote collectors
            # see the same breach counters /eth/v1/lodestar/health
            # serves, reduced to per-objective totals
            try:
                status = self.slo.status()
                beacon["slo_status"] = status["status"]
                beacon["slo_breaches"] = {
                    obj: entry["breaches"]
                    for obj, entry in status["objectives"].items()
                }
                beacon["slo_last_breach_slot"] = status["last_breach_slot"]
            except Exception:  # noqa: BLE001 - stats are best-effort
                pass
        gov = getattr(self.chain, "memory_governor", None)
        if gov is not None:
            # state-plane residency governance (ISSUE 15): remote
            # collectors see the budget/ledger/ladder the health
            # endpoint's `memory` block serves, reduced to scalars
            try:
                mem = gov.status()
                beacon["state_memory"] = {
                    "budget_bytes": mem["budget_bytes"],
                    "resident_bytes": mem["resident_bytes"],
                    "spill_bytes": mem["spill_bytes"],
                    "pressure_active": mem["pressure_active"],
                    "pressure_events": mem["pressure_events"],
                    "evictions": mem["evictions"],
                }
            except Exception:  # noqa: BLE001 - stats are best-effort
                pass
        if self.beacon_metrics is not None:
            bm = self.beacon_metrics
            beacon["block_import_seconds_total"] = float(
                bm.block_import_time.sum
            )
            # the per-phase import breakdown, phase -> wall seconds
            beacon["block_import_phase_seconds"] = {
                phase: float(bm.block_import_phase.sum(phase))
                for phase in bm.block_import_phase.label_values()
            }
        stats = [beacon]
        if self.validator_monitor is not None:
            vm = self.validator_monitor
            stats.append(
                dict(
                    common,
                    process="validator",
                    validators=len(vm.tracked_indices),
                    attestations_included=int(vm.m_attestations.value),
                    blocks_proposed=int(vm.m_blocks.value),
                    sync_signals_included=int(vm.m_sync_signals.value),
                    attestations_missed=int(vm.m_missed.value),
                )
            )
        if self.collect_system:
            import resource

            ru = resource.getrusage(resource.RUSAGE_SELF)
            stats.append(
                dict(
                    common,
                    process="system",
                    cpu_process_seconds_total=ru.ru_utime + ru.ru_stime,
                    memory_process_bytes=ru.ru_maxrss * 1024,
                )
            )
        return stats

    def send(self) -> bool:
        data = json.dumps(self.collect()).encode()
        req = urllib.request.Request(
            self.endpoint,
            data=data,
            method="POST",
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                self.sent += 1
                return True
        except Exception as e:  # noqa: BLE001 - remote is best-effort
            self.failures += 1
            self.log.warn("monitoring send failed", error=str(e))
            return False

    # -- lifecycle (reference: service.ts start/stop) ----------------------

    def start(self) -> None:
        def _loop():
            while not self._stop.wait(self.interval_s):
                self.send()

        self._thread = threading.Thread(
            target=_loop, name="monitoring", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
