"""Remote monitoring — periodic metric snapshots to a collector URL.

Mirror of the reference's packages/beacon-node/src/monitoring/
(MonitoringService posting beaconnodestats to a remote endpoint).
"""

from .service import MonitoringService  # noqa: F401
