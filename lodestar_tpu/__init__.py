"""lodestar-tpu: a TPU-native framework with the capabilities of ChainSafe
Lodestar (Ethereum consensus client), centered on batched BLS12-381
signature-set verification on TPU via JAX.

Layout (mirrors SURVEY.md section 2's component inventory):
  params/    spec constants, presets, domains (the @lodestar/params layer)
  ssz/       SSZ serialization + merkleization (+ native batch hasher)
  types/     per-fork beacon SSZ types (phase0/altair signature path)
  config/    chain config: fork schedule, domains, digests
  crypto/    CPU ground-truth BLS12-381 (oracle + fallback verifier)
  kernels/   the pallas field/pairing engine (transposed signed-limb layout)
  ops/       JAX einsum-path kernels (correctness cross-check of kernels/)
  bls/       the IBlsVerifier boundary: signature sets, batch semantics, retry
  state_transition/  epoch cache, shuffling, signature-set extractors
  fork_choice/  proto-array LMD-GHOST + compute_deltas
  chain/     seen caches, clock, block import pipeline
  network/   gossip queues + NetworkProcessor scheduling/backpressure
  db/        bucketed repositories over the native ordered KV store
  api/       beacon REST routes + server + client
  validator/ duties, signing, slashing protection
  light_client/  sync-committee header tracking
  node.py    BeaconNode composition root
  utils/     queues, retry, logger, metrics (+ HTTP exposition server)
  native/    C++ runtime components (SHA-256 merkleizer, KV store)
"""

__version__ = "0.1.0"
