"""lodestar-tpu: a TPU-native framework with the capabilities of ChainSafe
Lodestar (Ethereum consensus client), centered on batched BLS12-381
signature-set verification on TPU via JAX.

Layout (mirrors SURVEY.md section 2's component inventory):
  crypto/    CPU ground-truth BLS12-381 (oracle + fallback verifier)
  kernels/   the pallas field/pairing engine (transposed signed-limb layout)
  ops/       JAX einsum-path kernels (correctness cross-check of kernels/)
  bls/       the IBlsVerifier boundary: signature sets, batch semantics, retry
  utils/     queues, backpressure, metrics (lodestar_bls_thread_pool_* compat)
"""

__version__ = "0.1.0"
