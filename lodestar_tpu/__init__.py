"""lodestar-tpu: a TPU-native framework with the capabilities of ChainSafe
Lodestar (Ethereum consensus client), centered on batched BLS12-381
signature-set verification on TPU via JAX.

Layout (mirrors SURVEY.md section 2's component inventory; subpackages land
incrementally — import errors on a listed name mean it is not built yet):
  crypto/    CPU ground-truth BLS12-381 (oracle + fallback verifier)
  ops/       JAX/TPU kernels: limb arithmetic, field towers, curves, pairing
  bls/       the IBlsVerifier boundary: signature sets, batch semantics, retry
  parallel/  device mesh sharding (data-parallel sets, sharded pubkey table)
  models/    verification pipelines (attestation gossip, block import)
  utils/     queues, backpressure, metrics (lodestar_bls_thread_pool_* compat)
"""

__version__ = "0.1.0"
