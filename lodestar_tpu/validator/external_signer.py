"""Remote signer client (Web3Signer API) + an in-repo signer server.

Mirror of the reference's external signer support (reference:
packages/validator/src/util/externalSignerClient.ts): validators whose
keys live in a separate signing service sign via REST —

    GET  /upcheck                      -> {"status": "OK"}
    GET  /api/v1/eth2/publicKeys       -> ["0x...", ...]
    POST /api/v1/eth2/sign/{pubkey}    {"signingRoot": "0x..."} ->
                                       {"signature": "0x..."}

The server half is the test/dev double (the reference tests against a
dockerized web3signer; this environment is sealed, so the double lives
in-repo and speaks the same wire shape).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List


class ExternalSignerError(Exception):
    pass


class ExternalSignerClient:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url.rstrip("/")
        self.timeout = timeout

    def _get(self, path: str):
        with urllib.request.urlopen(
            self.url + path, timeout=self.timeout
        ) as resp:
            return json.loads(resp.read())

    def upcheck(self) -> bool:
        try:
            return self._get("/upcheck").get("status") == "OK"
        except Exception:  # noqa: BLE001 — availability probe
            return False

    def public_keys(self) -> List[bytes]:
        return [
            bytes.fromhex(k[2:] if k.startswith("0x") else k)
            for k in self._get("/api/v1/eth2/publicKeys")
        ]

    def sign(self, pubkey: bytes, signing_root: bytes) -> bytes:
        body = json.dumps(
            {"signingRoot": "0x" + bytes(signing_root).hex()}
        ).encode()
        req = urllib.request.Request(
            f"{self.url}/api/v1/eth2/sign/0x{bytes(pubkey).hex()}",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                reply = json.loads(resp.read())
        except urllib.error.HTTPError as e:
            raise ExternalSignerError(
                f"signer HTTP {e.code}: {e.read().decode()[:200]}"
            )
        except (urllib.error.URLError, OSError, ValueError) as e:
            # connection refused / DNS / timeout / bad JSON — callers key
            # their handling on ExternalSignerError, never raw urllib
            raise ExternalSignerError(f"signer unreachable: {e}")
        sig = reply.get("signature", "")
        if not sig.startswith("0x") or len(sig) != 2 + 192:
            raise ExternalSignerError(f"malformed signature {sig[:20]}...")
        return bytes.fromhex(sig[2:])


class ExternalSignerServer:
    """The signing-service double: holds secret keys, signs any root.

    A REAL remote signer enforces its own slashing protection; this
    double exists to exercise the client + store wiring.
    """

    def __init__(self, secret_keys_by_pubkey: Dict[bytes, int], port: int = 0):
        from ..crypto import bls as B
        from ..crypto import curves as C

        keys = dict(secret_keys_by_pubkey)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _reply(self, code: int, obj) -> None:
                data = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if self.path == "/upcheck":
                    return self._reply(200, {"status": "OK"})
                if self.path == "/api/v1/eth2/publicKeys":
                    return self._reply(
                        200, ["0x" + pk.hex() for pk in keys]
                    )
                self._reply(404, {"error": "not found"})

            def do_POST(self):
                prefix = "/api/v1/eth2/sign/"
                if not self.path.startswith(prefix):
                    return self._reply(404, {"error": "not found"})
                pk = bytes.fromhex(self.path[len(prefix) + 2 :])
                sk = keys.get(pk)
                if sk is None:
                    return self._reply(404, {"error": "unknown key"})
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length))
                root = bytes.fromhex(body["signingRoot"][2:])
                sig = C.g2_compress(B.sign(sk, root))
                self._reply(200, {"signature": "0x" + sig.hex()})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
