"""SyncCommitteeService — sync-committee message + contribution duties.

Reference: packages/validator/src/services/syncCommittee.ts
(SyncCommitteeService: per-slot sign the head root, submit; aggregators
produce SignedContributionAndProof) and services/syncCommitteeDuties.ts
(per-period duty polling).  Aggregator selection follows the altair
is_sync_committee_aggregator rule: sha256(selection_proof)[:8] %
(subcommittee_size // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE) == 0.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List

from .. import params
from ..utils.logger import get_logger
from .doppelganger import DoppelgangerUnverified
from .store import ValidatorStore

TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16


def is_sync_committee_aggregator(selection_proof: bytes) -> bool:
    modulo = max(
        1,
        params.ACTIVE_PRESET.SYNC_COMMITTEE_SIZE
        // params.SYNC_COMMITTEE_SUBNET_COUNT
        // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    digest = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(digest[:8], "little") % modulo == 0


class SyncCommitteeService:
    def __init__(self, store: ValidatorStore, api, logger=None):
        self.store = store
        self.api = api
        self.log = logger or get_logger("validator/sync-committee")
        # period -> duties [{validator_index, positions: [committee pos]}]
        self._duties: Dict[int, List[dict]] = {}
        self.submitted_messages = 0
        self.submitted_contributions = 0

    @staticmethod
    def period_of(epoch: int) -> int:
        return epoch // params.ACTIVE_PRESET.EPOCHS_PER_SYNC_COMMITTEE_PERIOD

    def poll_duties(self, epoch: int) -> None:
        period = self.period_of(epoch)
        # ALL managed validators — remote-signer keys live in pubkeys
        # only (store.sks holds just the local ones)
        indices = sorted(self.store.pubkeys)
        self._duties[period] = self.api.get_sync_committee_duties(
            epoch, indices
        )
        for old in [p for p in self._duties if p < period - 1]:
            del self._duties[old]

    def run_sync_committee_tasks(self, epoch: int, slot: int) -> int:
        """Sign the head root with every duty; aggregators contribute."""
        duties = self._duties.get(self.period_of(epoch), [])
        if not duties:
            return 0
        head_root = self.api.get_head_root(slot)
        subnet_size = (
            params.ACTIVE_PRESET.SYNC_COMMITTEE_SIZE
            // params.SYNC_COMMITTEE_SUBNET_COUNT
        )
        n = 0
        for duty in duties:
            vindex = duty["validator_index"]
            try:
                message = self.store.sign_sync_committee_message(
                    vindex, slot, head_root
                )
            except DoppelgangerUnverified:
                continue  # no duty publishes during the watch window
            except Exception as e:  # noqa: BLE001 — signer outage for
                # one validator must not abort the others' duties
                self.log.warn(
                    "sync duty signing failed", validator=vindex, reason=str(e)
                )
                continue
            for position in duty["positions"]:
                subnet, index_in_subnet = divmod(position, subnet_size)
                self.api.submit_sync_committee_message(
                    subnet, message, index_in_subnet
                )
                n += 1
                self.submitted_messages += 1
                # aggregation duty (reference syncCommittee.ts aggregator leg)
                proof = self.store.sign_sync_selection_proof(
                    vindex, slot, subnet
                )
                if is_sync_committee_aggregator(proof):
                    contribution = self.api.produce_sync_contribution(
                        slot, head_root, subnet
                    )
                    if contribution is None:
                        continue
                    cap = {
                        "aggregator_index": vindex,
                        "contribution": contribution,
                        "selection_proof": proof,
                    }
                    sig = self.store.sign_contribution_and_proof(vindex, cap)
                    self.api.publish_contribution_and_proof(
                        {"message": cap, "signature": sig}
                    )
                    self.submitted_contributions += 1
        return n
