"""AttestationService — per-slot attestation + aggregation duties.

Reference: packages/validator/src/services/attestation.ts (produce at
slot/3, sign, submit; aggregate at 2/3 slot for selected aggregators) +
services/attestationDuties.ts (per-epoch duty polling with selection
proofs).  The api dependency is injected (any object with the
duty/produce/submit methods), so tests and the replay harness can drive
it without a live beacon node.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence

from .. import params
from ..types import AttestationData
from ..utils.logger import get_logger
from .doppelganger import DoppelgangerUnverified
from .store import SlashingError, ValidatorStore


def is_aggregator(committee_length: int, selection_proof: bytes) -> bool:
    """Spec is_aggregator: hash(slot signature) mod ceil(len/TARGET)."""
    modulo = max(
        1, committee_length // params.TARGET_AGGREGATORS_PER_COMMITTEE
    )
    digest = hashlib.sha256(selection_proof).digest()
    return int.from_bytes(digest[:8], "little") % modulo == 0


class AttestationService:
    def __init__(self, store: ValidatorStore, api, logger=None):
        self.store = store
        self.api = api
        self.log = logger or get_logger("validator/attestation")
        # epoch -> list of duty dicts {validator_index, committee_index, slot}
        self._duties: Dict[int, List[dict]] = {}
        # (slot, committee_index) -> AttestationData produced this slot
        self._produced_data: Dict[tuple, dict] = {}
        self.submitted = 0
        self.submitted_aggregates = 0
        self.skipped_slashable = 0

    # -- duties (reference: attestationDuties.ts pollBeaconAttesters) ------

    def poll_duties(self, epoch: int) -> None:
        # ALL managed validators — remote-signer keys live in pubkeys
        # only (store.sks holds just the local ones)
        indices = sorted(self.store.pubkeys)
        duties = self.api.get_attester_duties(epoch, indices)
        self._duties[epoch] = duties
        for old in [e for e in self._duties if e < epoch - 1]:
            del self._duties[old]

    def duties_at_slot(self, epoch: int, slot: int) -> List[dict]:
        return [d for d in self._duties.get(epoch, []) if d["slot"] == slot]

    # -- execution (reference: attestation.ts runAttestationTasks) ---------

    def run_attestation_tasks(self, epoch: int, slot: int) -> int:
        """Produce, sign, and submit for every duty at `slot`."""
        duties = self.duties_at_slot(epoch, slot)
        if not duties:
            return 0
        produced: Dict[int, dict] = {}
        submitted = []
        for duty in duties:
            ci = duty["committee_index"]
            if ci not in produced:
                # one AttestationData per committee (reference reuses the
                # produced data across that committee's duties)
                produced[ci] = self.api.produce_attestation_data(ci, slot)
            data = produced[ci]
            try:
                sig = self.store.sign_attestation(duty["validator_index"], data)
            except DoppelgangerUnverified as e:
                self.log.info(
                    "duty delayed: doppelganger watch", reason=str(e)
                )
                continue
            except SlashingError as e:
                self.skipped_slashable += 1
                self.log.warn(
                    "refusing slashable attestation",
                    validator=duty["validator_index"],
                    reason=str(e),
                )
                continue
            except Exception as e:  # noqa: BLE001 — one validator's
                # signer outage (e.g. remote signer down) must not
                # abort the remaining duties at this slot
                self.log.warn(
                    "duty signing failed",
                    validator=duty["validator_index"],
                    reason=str(e),
                )
                continue
            # single-attester bits at the duty's committee position
            length = duty.get("committee_length", 1)
            pos = duty.get("validator_committee_index", 0)
            bits = [i == pos for i in range(length)]
            submitted.append(
                {
                    "aggregation_bits": duty.get("aggregation_bits", bits),
                    "data": data,
                    "signature": sig,
                }
            )
        if submitted:
            self.api.submit_pool_attestations(submitted)
            self.submitted += len(submitted)
        for ci, data in produced.items():
            self._produced_data[(slot, ci)] = data
        for old in [k for k in self._produced_data if k[0] < slot - 2]:
            del self._produced_data[old]
        return len(submitted)

    # -- aggregation (reference: attestation.ts 2/3-slot aggregate leg) ----

    def run_aggregation_tasks(self, epoch: int, slot: int) -> int:
        """For duties whose selection proof elects them aggregator:
        fetch the pool aggregate, wrap + sign AggregateAndProof,
        publish."""
        published = []
        for duty in self.duties_at_slot(epoch, slot):
            vindex = duty["validator_index"]
            data = self._produced_data.get((slot, duty["committee_index"]))
            if data is None:
                continue
            try:
                proof = self.store.sign_selection_proof(vindex, slot)
            except DoppelgangerUnverified:
                continue  # no duty publishes during the watch window
            if not is_aggregator(duty.get("committee_length", 1), proof):
                continue
            data_root = AttestationData.hash_tree_root(data)
            # aggregate-forward (ISSUE 19): prefer the already-summed
            # verified layer from the node's forwarder — the pool path
            # re-aggregates raw entries with a G2 point-add per insert,
            # which the device already paid for once
            aggregate = None
            packed = getattr(self.api, "get_packed_aggregate", None)
            if packed is not None:
                try:
                    aggregate = packed(slot, data_root)
                except Exception:  # noqa: BLE001 — an optional-route
                    aggregate = None  # miss must not break the duty
            if aggregate is None:
                aggregate = self.api.get_aggregate_attestation(
                    slot, data_root
                )
            if aggregate is None:
                continue
            message = {
                "aggregator_index": vindex,
                "aggregate": aggregate,
                "selection_proof": proof,
            }
            signature = self.store.sign_aggregate_and_proof(vindex, message)
            published.append({"message": message, "signature": signature})
        if published:
            self.api.publish_aggregate_and_proofs(published)
            self.submitted_aggregates += len(published)
        return len(published)
