"""AttestationService — per-slot attestation duty execution.

Reference: packages/validator/src/services/attestation.ts (produce at
slot/3, sign, submit) + services/attestationDuties.ts (per-epoch duty
polling).  The api dependency is injected (any object with the
duty/produce/submit methods), so tests and the replay harness can drive
it without a live beacon node.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from ..utils.logger import get_logger
from .store import SlashingError, ValidatorStore


class AttestationService:
    def __init__(self, store: ValidatorStore, api, logger=None):
        self.store = store
        self.api = api
        self.log = logger or get_logger("validator/attestation")
        # epoch -> list of duty dicts {validator_index, committee_index, slot}
        self._duties: Dict[int, List[dict]] = {}
        self.submitted = 0
        self.skipped_slashable = 0

    # -- duties (reference: attestationDuties.ts pollBeaconAttesters) ------

    def poll_duties(self, epoch: int) -> None:
        indices = sorted(self.store.sks)
        duties = self.api.get_attester_duties(epoch, indices)
        self._duties[epoch] = duties
        for old in [e for e in self._duties if e < epoch - 1]:
            del self._duties[old]

    def duties_at_slot(self, epoch: int, slot: int) -> List[dict]:
        return [d for d in self._duties.get(epoch, []) if d["slot"] == slot]

    # -- execution (reference: attestation.ts runAttestationTasks) ---------

    def run_attestation_tasks(self, epoch: int, slot: int) -> int:
        """Produce, sign, and submit for every duty at `slot`."""
        duties = self.duties_at_slot(epoch, slot)
        if not duties:
            return 0
        produced: Dict[int, dict] = {}
        submitted = []
        for duty in duties:
            ci = duty["committee_index"]
            if ci not in produced:
                # one AttestationData per committee (reference reuses the
                # produced data across that committee's duties)
                produced[ci] = self.api.produce_attestation_data(ci, slot)
            data = produced[ci]
            try:
                sig = self.store.sign_attestation(duty["validator_index"], data)
            except SlashingError as e:
                self.skipped_slashable += 1
                self.log.warn(
                    "refusing slashable attestation",
                    validator=duty["validator_index"],
                    reason=str(e),
                )
                continue
            submitted.append(
                {
                    "aggregation_bits": duty.get("aggregation_bits", [True]),
                    "data": data,
                    "signature": "0x" + sig.hex(),
                }
            )
        if submitted:
            self.api.submit_pool_attestations(submitted)
            self.submitted += len(submitted)
        return len(submitted)
