"""EIP-2335 encrypted BLS keystores.

Mirror of the reference's keystore handling (reference:
packages/cli/src/cmds/validator/keymanager/importKeystores and the
@chainsafe/bls-keystore dependency): scrypt/pbkdf2 key derivation,
AES-128-CTR secret encryption, sha256 checksum binding the derived key
to the ciphertext.  The reference rides native crypto; here the cipher
is a self-contained AES-128 (keystore payloads are 32 bytes — one to
two blocks — so pure Python costs microseconds) and the KDFs come from
hashlib.  The format is byte-compatible with EIP-2335 so keystores made
by any client decrypt here and vice versa.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import unicodedata
import uuid
from typing import Dict, Optional

# ---------------------------------------------------------------------------
# AES-128 core, built from the algebraic definition (FIPS-197).  The
# S-box is COMPUTED (GF(2^8) inverse + affine map) rather than typed in
# as 256 literals, so the table is correct by construction; the FIPS-197
# appendix vector in tests/test_keystore.py seals the whole cipher.


def _gf_mul(a: int, b: int) -> int:
    r = 0
    for _ in range(8):
        if b & 1:
            r ^= a
        hi = a & 0x80
        a = (a << 1) & 0xFF
        if hi:
            a ^= 0x1B  # x^8 = x^4 + x^3 + x + 1
        b >>= 1
    return r


def _build_sbox():
    # inverse table via exp/log over the generator 3
    exp = [0] * 510
    log = [0] * 256
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x = _gf_mul(x, 3)
    for i in range(255, 510):
        exp[i] = exp[i - 255]
    sbox = [0] * 256
    for v in range(256):
        inv = 0 if v == 0 else exp[255 - log[v]]
        b = inv
        s = b
        for _ in range(4):
            b = ((b << 1) | (b >> 7)) & 0xFF
            s ^= b
        sbox[v] = s ^ 0x63
    return bytes(sbox)


_SBOX = _build_sbox()
_RCON = (0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40, 0x80, 0x1B, 0x36)


def _expand_key(key: bytes):
    """AES-128 key schedule: 11 round keys of 16 bytes."""
    w = [key[4 * i : 4 * i + 4] for i in range(4)]
    for i in range(4, 44):
        t = w[i - 1]
        if i % 4 == 0:
            t = bytes(
                _SBOX[t[(j + 1) % 4]] ^ (_RCON[i // 4 - 1] if j == 0 else 0)
                for j in range(4)
            )
        w.append(bytes(a ^ b for a, b in zip(w[i - 4], t)))
    return [b"".join(w[4 * r : 4 * r + 4]) for r in range(11)]


def _encrypt_block(rk, block: bytes) -> bytes:
    s = bytes(a ^ b for a, b in zip(block, rk[0]))
    for rnd in range(1, 11):
        # SubBytes + ShiftRows (column-major state: byte r + 4c)
        s = bytes(
            _SBOX[s[(r + 4 * ((c + r) % 4))]]
            for c in range(4)
            for r in range(4)
        )
        if rnd < 10:  # MixColumns
            out = bytearray(16)
            for c in range(4):
                a = s[4 * c : 4 * c + 4]
                out[4 * c + 0] = _gf_mul(a[0], 2) ^ _gf_mul(a[1], 3) ^ a[2] ^ a[3]
                out[4 * c + 1] = a[0] ^ _gf_mul(a[1], 2) ^ _gf_mul(a[2], 3) ^ a[3]
                out[4 * c + 2] = a[0] ^ a[1] ^ _gf_mul(a[2], 2) ^ _gf_mul(a[3], 3)
                out[4 * c + 3] = _gf_mul(a[0], 3) ^ a[1] ^ a[2] ^ _gf_mul(a[3], 2)
            s = bytes(out)
        s = bytes(a ^ b for a, b in zip(s, rk[rnd]))
    return s


def aes128_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    """CTR keystream xor (encrypt == decrypt); iv is the 16-byte initial
    counter block, incremented big-endian per block."""
    if len(key) != 16 or len(iv) != 16:
        raise ValueError("aes-128-ctr needs 16-byte key and iv")
    rk = _expand_key(key)
    ctr = int.from_bytes(iv, "big")
    out = bytearray()
    for off in range(0, len(data), 16):
        ks = _encrypt_block(rk, ctr.to_bytes(16, "big"))
        ctr = (ctr + 1) % (1 << 128)
        chunk = data[off : off + 16]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


# ---------------------------------------------------------------------------
# EIP-2335 container


class KeystoreError(Exception):
    pass


def normalize_password(password: str) -> bytes:
    """EIP-2335 password rules: NFKD normalize, strip C0/C1 control
    codes and DEL, encode UTF-8."""
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        ch
        for ch in norm
        if not (ord(ch) < 0x20 or 0x7F <= ord(ch) <= 0x9F)
    )
    return stripped.encode("utf-8")


def _derive_key(kdf: dict, password: bytes) -> bytes:
    fn = kdf["function"]
    p = kdf["params"]
    salt = bytes.fromhex(p["salt"])
    dklen = int(p["dklen"])
    if fn == "scrypt":
        n, r, rp = int(p["n"]), int(p["r"]), int(p["p"])
        return hashlib.scrypt(
            password,
            salt=salt,
            n=n,
            r=r,
            p=rp,
            dklen=dklen,
            # stdlib default maxmem (32MiB) rejects the EIP-2335
            # standard n=2^18,r=8 (needs 128*n*r = 256MiB)
            maxmem=128 * n * r + (1 << 20),
        )
    if fn == "pbkdf2":
        if p.get("prf", "hmac-sha256") != "hmac-sha256":
            raise KeystoreError(f"unsupported prf {p['prf']!r}")
        return hashlib.pbkdf2_hmac(
            "sha256", password, salt, int(p["c"]), dklen
        )
    raise KeystoreError(f"unsupported kdf {fn!r}")


def decrypt_keystore(keystore: dict, password: str) -> bytes:
    """Returns the secret (32-byte BLS sk).  Raises KeystoreError on a
    wrong password (checksum mismatch) or unsupported modules."""
    crypto = keystore["crypto"]
    dk = _derive_key(crypto["kdf"], normalize_password(password))
    if len(dk) < 32:
        raise KeystoreError("derived key shorter than 32 bytes")
    cipher = crypto["cipher"]
    if cipher["function"] != "aes-128-ctr":
        raise KeystoreError(f"unsupported cipher {cipher['function']!r}")
    ct = bytes.fromhex(cipher["message"])
    checksum = crypto["checksum"]
    if checksum["function"] != "sha256":
        raise KeystoreError(
            f"unsupported checksum {checksum['function']!r}"
        )
    want = bytes.fromhex(checksum["message"])
    got = hashlib.sha256(dk[16:32] + ct).digest()
    if not hmac.compare_digest(want, got):
        raise KeystoreError("checksum mismatch (wrong password?)")
    return aes128_ctr(dk[:16], bytes.fromhex(cipher["params"]["iv"]), ct)


def create_keystore(
    secret: bytes,
    password: str,
    pubkey: Optional[bytes] = None,
    path: str = "",
    kdf: str = "scrypt",
    kdf_params: Optional[Dict] = None,
    description: str = "",
) -> dict:
    """Encrypt `secret` into an EIP-2335 keystore dict.

    `kdf_params` overrides the cost parameters (tests use small ones;
    the defaults are the EIP-2335 standard costs)."""
    if kdf == "scrypt":
        params = dict(kdf_params or {"n": 262144, "r": 8, "p": 1})
        params.setdefault("dklen", 32)
        params["salt"] = os.urandom(32).hex()
        kdf_mod = {"function": "scrypt", "params": params}
    elif kdf == "pbkdf2":
        params = dict(kdf_params or {"c": 262144})
        params.setdefault("dklen", 32)
        params.setdefault("prf", "hmac-sha256")
        params["salt"] = os.urandom(32).hex()
        kdf_mod = {"function": "pbkdf2", "params": params}
    else:
        raise KeystoreError(f"unsupported kdf {kdf!r}")
    dk = _derive_key(kdf_mod, normalize_password(password))
    iv = os.urandom(16)
    ct = aes128_ctr(dk[:16], iv, secret)
    return {
        "version": 4,
        "uuid": str(uuid.uuid4()),
        "description": description,
        "path": path,
        "pubkey": pubkey.hex() if pubkey else "",
        "crypto": {
            "kdf": kdf_mod,
            "checksum": {
                "function": "sha256",
                "params": {},
                "message": hashlib.sha256(dk[16:32] + ct).digest().hex(),
            },
            "cipher": {
                "function": "aes-128-ctr",
                "params": {"iv": iv.hex()},
                "message": ct.hex(),
            },
        },
    }
